"""Compile stage graphs: the fusion pass and the staged reference executor.

Two ways to run a lowered :class:`~spfft_tpu.ir.graph.StageGraph`:

* **Fused** (the default, ``SPFFT_TPU_FUSE=1`` / ``fuse=True``):
  :func:`compose` folds the whole graph into one traceable function —
  topological order, each node wrapped in its canonical ``jax.named_scope``
  — and the builders emit **ONE** ``jax.jit``-compiled program per direction
  (wrapped in the engine's ``shard_map`` for mesh graphs). The sparse
  scatter/gather of decompress/compress fuse *inside* the program with the
  DFT stages: no materialized dense-stick intermediate ever crosses a
  dispatch boundary, and the local builders additionally emit a
  buffer-donating variant (``donate_argnums`` on the packed value pair) for
  the host-facing consuming flow.

* **Staged** (``SPFFT_TPU_FUSE=0``): every node becomes its own jitted
  dispatch with materialized intermediates — the debuggable reference path
  the fused program is parity-checked against (``tests/test_ir.py``), and
  the fallback rung when fusion fails to build (fault site ``ir.compile`` →
  ``fuse_compile_failed`` on the plan card — never a failed plan).

:class:`EngineIr` is the per-engine runtime router every engine constructs
(:func:`init_engine_ir`): it owns the lowering→validation→fusion ladder, the
degradation rungs (``ir_lower_failed`` falls back to the engine's legacy
monolithic jits, which remain the reference composition), the
``ir_dispatches_total{mode}`` accounting that the dispatch-count tests read,
and the schema-pinned ``ir`` plan-card section (stage lists, fusion
decision, donation map).
"""
from __future__ import annotations

import jax

from .. import knobs
from ..errors import InvalidParameterError

FUSE_ENV = "SPFFT_TPU_FUSE"
BATCH_FUSE_ENV = "SPFFT_TPU_BATCH_FUSE"

# plan-card ``ir`` section schema floor (obs.plancard pins it)
IR_KEYS = ("fused", "path", "requested", "stages", "donation")
# plan-card ``batch`` section schema floor (obs.plancard mirrors it; the
# vocabulary checker pins the two literals equal, like IR_KEYS)
BATCH_KEYS = ("enabled", "requested", "sizes", "failed")


def resolve_fuse(fuse=None):
    """Resolve the fusion knob: explicit ``fuse=`` kwarg wins, else
    ``SPFFT_TPU_FUSE`` (default fused). Returns ``(fused, source)`` with
    ``source`` in {"kwarg", "env", "default"}; a malformed env value raises
    typed (the knob-validation contract every SPFFT_TPU_* knob follows)."""
    if fuse is not None:
        if not isinstance(fuse, (bool, int)) or fuse not in (0, 1):
            raise InvalidParameterError(
                f"fuse= must be a bool (or 0/1), got {fuse!r}"
            )
        return bool(fuse), "kwarg"
    raw = knobs.raw(FUSE_ENV)
    if raw is None or raw == "":
        return True, "default"
    if raw not in ("0", "1"):
        raise InvalidParameterError(
            f"{FUSE_ENV} must be 0 or 1, got {raw!r}"
        )
    return raw == "1", "env"


def resolve_batch_fuse():
    """Resolve the batch-fusion knob (``SPFFT_TPU_BATCH_FUSE``, default on).
    Returns ``(enabled, source)`` with ``source`` in {"env", "default"};
    read at call time (not plan construction) so a serving A/B flips without
    rebuilding plans. A malformed value raises typed like every knob."""
    raw = knobs.raw(BATCH_FUSE_ENV)
    if raw is None or raw == "":
        return True, "default"
    if raw not in ("0", "1"):
        raise InvalidParameterError(
            f"{BATCH_FUSE_ENV} must be 0 or 1, got {raw!r}"
        )
    return raw == "1", "env"


def compose(graph):
    """Fold a validated graph into one traceable function.

    The returned ``fn(*args)`` binds ``args`` to the graph's declared input
    edges in order (a trailing varargs edge — ``graph.varargs`` — collects
    the rest as a tuple: the local MXU engine's threaded plan operands),
    executes nodes topologically with each body under its canonical
    ``jax.named_scope``, and returns the declared output edge values (a bare
    value for a single output, a tuple otherwise). Tracing ``fn`` once under
    ``jax.jit`` IS the fusion pass: XLA sees the whole direction as one
    program."""
    order = graph.toposort()
    names = list(graph.inputs)
    varargs = bool(getattr(graph, "varargs", False))

    def fn(*args):
        if varargs:
            fixed = names[:-1]
            if len(args) < len(fixed):
                raise InvalidParameterError(
                    f"ir[{graph.direction}]: expected at least {len(fixed)} "
                    f"inputs ({fixed} + *{names[-1]}), got {len(args)}"
                )
            env = dict(zip(fixed, args[: len(fixed)]))
            env[names[-1]] = tuple(args[len(fixed) :])
        else:
            if len(args) != len(names):
                raise InvalidParameterError(
                    f"ir[{graph.direction}]: expected {len(names)} inputs "
                    f"({names}), got {len(args)}"
                )
            env = dict(zip(names, args))
        for node in order:
            ins = [env[e] for e in node.inputs]
            with jax.named_scope(node.stage):
                out = node.fn(*ins)
            if len(node.outputs) == 1:
                env[node.outputs[0]] = out
            else:
                for e, v in zip(node.outputs, out):
                    env[e] = v
        outs = tuple(env[e] for e in graph.outputs)
        return outs[0] if len(outs) == 1 else outs

    return fn


def _mesh_spec(meta, axes):
    """Partition spec of one distributed edge: sharded over ``axes`` on the
    (implicit) leading block dimension, replicated elsewhere — derived from
    the edge's per-shard rank, the single rule every mesh edge follows."""
    from jax.sharding import PartitionSpec as P

    rank = meta.rank()
    if rank is None:
        raise InvalidParameterError(
            "ir: mesh graphs need shaped edges to derive partition specs"
        )
    ax = axes[0] if len(axes) == 1 else tuple(axes)
    return P(ax, *([None] * rank))


def _block_adapter(fn, n_out):
    """Mesh boundary adapter: strip the per-shard block dim off every input,
    restore it on every output — node fns and composed graphs are written in
    per-shard terms, while ``shard_map`` blocks carry the leading axis."""

    def wrapped(*args):
        out = fn(*[a[0] for a in args])
        if n_out == 1:
            return out[None]
        return tuple(o[None] for o in out)

    return wrapped


def build_fused(graph, spec):
    """The fusion pass: one jitted program for ``graph``.

    Local graphs jit the composition directly and (when ``spec`` names
    donatable inputs) also build the buffer-donating variant. Mesh graphs
    wrap the composition in the engine's ``shard_map`` with specs derived
    from edge metadata. Returns ``{"call", "consuming"|None}``."""
    fn = compose(graph)
    if spec["kind"] == "local":
        call = jax.jit(fn)
        donate = spec.get("donate") if graph.direction == "backward" else None
        consuming = (
            jax.jit(fn, donate_argnums=tuple(donate)) if donate else None
        )
        return {"call": call, "consuming": consuming}
    axes = spec["axes"]
    in_specs = tuple(_mesh_spec(graph.meta[e], axes) for e in graph.inputs)
    outs = tuple(_mesh_spec(graph.meta[e], axes) for e in graph.outputs)
    out_specs = outs[0] if len(outs) == 1 else outs
    mapped = spec["sm"](
        _block_adapter(fn, len(graph.outputs)),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    return {"call": jax.jit(mapped), "consuming": None}


def _batched_compose(graph, fn):
    """Vmap the composed graph over a leading batch axis on the graph's
    declared ``batch_inputs`` (stacked per-request values/space), keeping
    every other input — index tables, threaded plan operands (the trailing
    varargs tuple included) — a plan constant shared by the whole batch.
    Returns the batched traceable; tracing it under ``jax.jit`` IS the
    batch-fusion pass: one program computes B transforms per direction."""
    names = list(graph.inputs)
    fixed = names[:-1] if getattr(graph, "varargs", False) else names
    batched = tuple(getattr(graph, "batch_inputs", ()) or ())
    if not batched:
        raise InvalidParameterError(
            f"ir[{graph.direction}]: graph declares no batchable inputs"
        )
    idx = tuple(i for i, n in enumerate(fixed) if n in batched)

    def bfn(*args):
        stacked = [args[i] for i in idx]

        def per_item(*items):
            full = list(args)
            for i, v in zip(idx, items):
                full[i] = v
            return fn(*full)

        return jax.vmap(per_item)(*stacked)

    return bfn


def build_batched(graph, spec):
    """The batch-fusion pass: ONE jitted program running a whole stacked
    batch of same-geometry transforms through ``graph``.

    Local graphs jit the vmapped composition (and, when ``spec`` names
    donatable inputs, a donating variant over the STACKED value pair — the
    per-request donation rule lifted to the batch axis). Mesh graphs wrap it
    in the engine's ``shard_map`` with the batch axis replicated (arrays are
    ``(P, B, *per_shard)``: sharded over the mesh on the block dim, every
    shard holding its own slice of all B requests). The program is
    batch-size-polymorphic — ``jax.jit`` specializes per distinct B.
    Returns ``{"call", "consuming"|None}`` like :func:`build_fused`."""
    fn = compose(graph)
    bfn = _batched_compose(graph, fn)
    if spec["kind"] == "local":
        call = jax.jit(bfn)
        donate = spec.get("donate") if graph.direction == "backward" else None
        consuming = (
            jax.jit(bfn, donate_argnums=tuple(donate)) if donate else None
        )
        return {"call": call, "consuming": consuming}
    axes = spec["axes"]
    batched = set(graph.batch_inputs)

    def espec(e, with_batch):
        base = _mesh_spec(graph.meta[e], axes)
        if not with_batch:
            return base
        from jax.sharding import PartitionSpec as P

        return P(base[0], None, *base[1:])

    in_specs = tuple(espec(e, e in batched) for e in graph.inputs)
    outs = tuple(espec(e, True) for e in graph.outputs)
    out_specs = outs[0] if len(outs) == 1 else outs
    mapped = spec["sm"](
        _block_adapter(bfn, len(graph.outputs)),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    return {"call": jax.jit(mapped), "consuming": None}


class StagedProgram:
    """The per-stage reference executor: every node is its own jitted
    dispatch (its own ``shard_map`` program on mesh graphs), intermediates
    materialize between dispatches. Slower by construction — its value is
    being the debuggable, fusion-free reference the fused program must match
    bit-for-bit-modulo-reassociation, and the fallback when fusion cannot
    compile."""

    def __init__(self, graph, spec):
        self.graph = graph
        self.order = graph.toposort()
        self._calls = {}
        for node in self.order:
            body = self._scoped(node)
            if spec["kind"] == "local":
                self._calls[node.name] = jax.jit(body)
            else:
                axes = spec["axes"]
                in_specs = tuple(
                    _mesh_spec(graph.meta[e], axes) for e in node.inputs
                )
                outs = tuple(
                    _mesh_spec(graph.meta[e], axes) for e in node.outputs
                )
                out_specs = outs[0] if len(outs) == 1 else outs
                self._calls[node.name] = jax.jit(
                    spec["sm"](
                        _block_adapter(body, len(node.outputs)),
                        in_specs=in_specs,
                        out_specs=out_specs,
                    )
                )

    @staticmethod
    def _scoped(node):
        def body(*args, _node=node):
            with jax.named_scope(_node.stage):
                return _node.fn(*args)

        return body

    @property
    def num_dispatches(self) -> int:
        return len(self.order)

    def __call__(self, *args):
        from .. import obs

        g = self.graph
        names = list(g.inputs)
        if getattr(g, "varargs", False):
            fixed = names[:-1]
            if len(args) < len(fixed):
                raise InvalidParameterError(
                    f"ir[{g.direction}]: expected at least {len(fixed)} "
                    f"inputs ({fixed} + *{names[-1]}), got {len(args)}"
                )
            env = dict(zip(fixed, args[: len(fixed)]))
            env[names[-1]] = tuple(args[len(fixed) :])
        else:
            if len(args) != len(names):
                raise InvalidParameterError(
                    f"ir[{g.direction}]: expected {len(names)} inputs "
                    f"({names}), got {len(args)}"
                )
            env = dict(zip(names, args))
        counter = obs.counter(
            "ir_dispatches_total", mode="staged", direction=g.direction
        )
        for node in self.order:
            ins = [env[e] for e in node.inputs]
            out = self._calls[node.name](*ins)
            counter.inc()
            if len(node.outputs) == 1:
                env[node.outputs[0]] = out
            else:
                for e, v in zip(node.outputs, out):
                    env[e] = v
        outs = tuple(env[e] for e in g.outputs)
        return outs[0] if len(outs) == 1 else outs


class EngineIr:
    """Per-engine IR runtime: graphs, compiled programs, and the routing +
    accounting for ``backward_pair``/``forward_pair``. Built by
    :func:`init_engine_ir`; engines delegate their device-side entry points
    here with their canonical operand tuples."""

    def __init__(self, spec, graphs, *, path, requested, sink=None):
        self.spec = spec
        self.graphs = graphs  # {"backward": g, "forward": {ScalingType: g}}
        self.path = path  # "fused" | "staged" | "legacy"
        self.requested = requested
        # the plan's live degradations list (captured from the collecting
        # scope the engine was built under) so the first-dispatch rung below
        # still lands on the plan card after construction has returned
        self._sink = sink
        self._compiled = set()  # fused programs that have run once
        self._backward = None
        self._backward_consuming = None
        self._forward = {}
        # batch-fused programs (SPFFT_TPU_BATCH_FUSE): built lazily per
        # (direction[, scaling]) on the first batched dispatch, jit-
        # specialized per batch size; a build/compile failure records ONE
        # batch_fuse_failed rung and disables the axis for this plan (the
        # caller's split-phase loop is the rung — never a failed batch)
        self._batched = {}
        self._batch_compiled = set()  # (key, B) pairs that have run once
        self._batch_failed = False
        self._batch_sizes = set()  # distinct B values dispatched (card)
        if graphs is not None:
            if path == "fused":
                built = build_fused(graphs["backward"], spec)
                self._backward = built["call"]
                self._backward_consuming = built["consuming"]
                self._forward = {
                    s: build_fused(g, spec)["call"]
                    for s, g in graphs["forward"].items()
                }
            else:
                self._backward = StagedProgram(graphs["backward"], spec)
                self._forward = {
                    s: StagedProgram(g, spec)
                    for s, g in graphs["forward"].items()
                }

    @property
    def fused(self) -> bool:
        return self.path == "fused"

    def _count(self, direction: str) -> None:
        from .. import obs

        if self.path != "staged":  # staged counts per node itself
            obs.counter(
                "ir_dispatches_total", mode=self.path, direction=direction
            ).inc()

    def _degrade_to_staged(self, exc) -> None:
        """The first-dispatch compile rung: ``jax.jit`` compiles lazily, so
        a fused program whose XLA compile genuinely fails (e.g. compile-
        memory exhaustion on an enormous fused program) surfaces at the
        first call, not inside :func:`init_engine_ir`'s try (which sees
        only the armed fault site and build-time spec errors). Same
        contract as the build-time rung: ``fuse_compile_failed`` on the
        plan card (via the captured sink — ``report()`` re-reads the live
        list), staged reference path, never a failed dispatch."""
        from .. import faults

        entry = faults.record_degradation(
            "fuse_compile_failed", faults.summarize(exc)
        )
        if self._sink is not None and (
            not self._sink or self._sink[-1] is not entry
        ):
            self._sink.append(entry)
        self.path = "staged"
        self._backward = StagedProgram(self.graphs["backward"], self.spec)
        self._backward_consuming = None
        self._forward = {
            s: StagedProgram(g, self.spec)
            for s, g in self.graphs["forward"].items()
        }

    def _attempt_fused(self, key, call, direction, args):
        """One fused dispatch with the first-call rung: until a program has
        succeeded once, a compile-class failure degrades to staged and
        re-dispatches there; after that, errors propagate untouched (an
        execution failure is the ``typed_execution`` ladder's job, not a
        fusion rung)."""
        from .. import faults

        if key in self._compiled:
            out = call(*args)
        else:
            try:
                out = call(*args)
            except faults.ENGINE_BUILD_ERRORS as e:
                self._degrade_to_staged(e)
                if direction == "backward":
                    return self._backward(*args)
                return self._forward[key[1]](*args)
            self._compiled.add(key)
        self._count(direction)
        return out

    def run_backward(self, *args):
        if self.path == "legacy":
            self._count("backward")
            return self.spec["legacy_backward"](*args)
        if self.path == "fused":
            return self._attempt_fused(
                ("backward",), self._backward, "backward", args
            )
        return self._backward(*args)

    def run_backward_consuming(self, *args):
        """Donating backward for the host-facing consuming flow: the fused
        donating jit when available, else the plain route (staged programs
        materialize intermediates and cannot donate; legacy falls back to
        the engine's own consuming jit)."""
        if self.path == "fused" and self._backward_consuming is not None:
            return self._attempt_fused(
                ("backward", "consuming"),
                self._backward_consuming,
                "backward",
                args,
            )
        if self.path == "legacy":
            legacy = self.spec.get("legacy_backward_consuming")
            if legacy is not None:
                self._count("backward")
                return legacy(*args)
        return self.run_backward(*args)

    def run_forward(self, scaling, *args):
        if self.path == "legacy":
            self._count("forward")
            return self.spec["legacy_forward"][scaling](*args)
        if self.path == "fused":
            return self._attempt_fused(
                ("forward", scaling), self._forward[scaling], "forward", args
            )
        return self._forward[scaling](*args)

    # ---- batch-fused dispatch (SPFFT_TPU_BATCH_FUSE) --------------------------

    def batch_available(self) -> bool:
        """Whether the batch-fused path may be attempted: knob on, plan
        running the fused path (the staged/legacy rungs have no composition
        to vmap), graphs declaring a batch axis, and no earlier batched
        build having failed. Read at call time — a serving A/B flips the
        knob without rebuilding plans."""
        enabled, _ = resolve_batch_fuse()
        return (
            enabled
            and self.path == "fused"
            and self.graphs is not None
            and bool(getattr(self.graphs["backward"], "batch_inputs", ()))
            and not self._batch_failed
        )

    def _batch_degrade(self, exc) -> None:
        """The batch rung: a batched build/first-dispatch compile failure
        records ``batch_fuse_failed`` on the plan card (via the captured
        sink, like the fused first-dispatch rung) and disables the axis for
        this plan — callers fall back to their split-phase per-request loop;
        the plan itself stays healthy."""
        from .. import faults

        entry = faults.record_degradation(
            "batch_fuse_failed", faults.summarize(exc)
        )
        if self._sink is not None and (
            not self._sink or self._sink[-1] is not entry
        ):
            self._sink.append(entry)
        self._batch_failed = True
        self._batched = {}

    def _batch_program(self, key):
        """Build (or fetch) the batched program for ``key`` =
        ``("backward",)`` / ``("forward", scaling)``; returns the
        ``{"call", "consuming"}`` dict or ``None`` after taking the rung.
        The ``ir.batch`` fault site models this layer refusing to build."""
        prog = self._batched.get(key)
        if prog is not None:
            return prog
        from .. import faults

        graph = (
            self.graphs["backward"]
            if key[0] == "backward"
            else self.graphs["forward"][key[1]]
        )
        try:
            faults.site("ir.batch")
            prog = build_batched(graph, self.spec)
        except faults.ENGINE_BUILD_ERRORS + (InvalidParameterError,) as e:
            self._batch_degrade(e)
            return None
        self._batched[key] = prog
        return prog

    def _run_batch(self, key, args, *, consuming=False):
        """One batched dispatch: returns the stacked result, or ``None``
        after recording the rung (build failure, or a compile-class failure
        at a program's first call for this batch size — ``jax.jit`` is
        lazy, the fused-path rule). Once a (program, B) pair has succeeded,
        errors propagate untouched to the typed-execution ladder."""
        from .. import faults

        if not self.batch_available():
            return None
        prog = self._batch_program(key)
        if prog is None:
            return None
        call = prog["consuming"] if consuming else prog["call"]
        if call is None:
            call = prog["call"]
        # the stacked batch extent: leading axis on local arrays, second
        # axis (after the mesh block dim) on sharded ones
        batch = int(
            args[0].shape[0] if self.spec["kind"] == "local"
            else args[0].shape[1]
        )
        ckey = (key, consuming, batch)
        if ckey in self._batch_compiled:
            out = call(*args)
        else:
            try:
                out = call(*args)
            except faults.ENGINE_BUILD_ERRORS as e:
                self._batch_degrade(e)
                return None
            self._batch_compiled.add(ckey)
        self._batch_sizes.add(batch)
        from .. import obs

        obs.counter(
            "ir_dispatches_total", mode="batched", direction=key[0]
        ).inc()
        return out

    def run_backward_batch(self, *args):
        """Batched backward: stacked value pairs in, stacked space out —
        ONE dispatch for the whole batch. ``None`` = batch fusion is
        unavailable/degraded; the caller runs its per-request loop."""
        return self._run_batch(("backward",), args)

    def run_backward_batch_consuming(self, *args):
        """Batched backward donating the STACKED value pair (the consuming
        host-facing flow's donation rule lifted to the batch axis)."""
        return self._run_batch(("backward",), args, consuming=True)

    def run_forward_batch(self, scaling, *args):
        """Batched forward: stacked space in, stacked packed pairs out."""
        return self._run_batch(("forward", scaling), args)

    # ---- plan-card provenance (obs.plancard pins IR_KEYS) ---------------------

    def describe_batch(self) -> dict:
        """The plan card's schema-pinned ``batch`` section (BATCH_KEYS):
        whether the batch-fused path is live, where the knob came from, the
        distinct batch sizes dispatched so far, and whether the axis took
        the ``batch_fuse_failed`` rung."""
        _, requested = resolve_batch_fuse()
        return {
            "enabled": bool(self.batch_available()),
            "requested": requested,
            "sizes": sorted(int(b) for b in self._batch_sizes),
            "failed": bool(self._batch_failed),
        }

    def describe(self) -> dict:
        from ..types import ScalingType

        stages = None
        if self.graphs is not None:
            stages = {
                "backward": self.graphs["backward"].stage_list(),
                "forward": self.graphs["forward"][
                    ScalingType.NONE
                ].stage_list(),
            }
        donated = (
            list(self.graphs["backward"].inputs[i] for i in self.spec["donate"])
            if (
                self.path == "fused"
                and self.graphs is not None
                and self.spec.get("donate")
                and self._backward_consuming is not None
            )
            else []
        )
        return {
            "fused": self.path == "fused",
            "path": self.path,
            "requested": self.requested,
            "stages": stages,
            # donated inputs of the consuming fused backward; forward
            # retains its inputs (the space buffer is the plan's retained
            # state), so its map is always empty
            "donation": {"backward": donated, "forward": []},
        }


def init_engine_ir(engine, fuse=None):
    """The lowering→validation→fusion ladder every engine runs at
    construction (module docstring). Degradations land on the plan being
    built via the ambient :func:`spfft_tpu.faults.collecting` sink:

    * fault site ``ir.lower`` / a lowering or validation failure →
      ``ir_lower_failed``, engine runs its legacy monolithic jits,
    * fault site ``ir.compile`` / a fusion build failure →
      ``fuse_compile_failed``, engine runs the staged reference path.

    ``jax.jit`` compiles lazily, so a fused program whose XLA compile
    genuinely fails takes the same ``fuse_compile_failed`` rung at its
    first dispatch instead (:meth:`EngineIr._degrade_to_staged`).

    Never a failed plan."""
    from .. import faults, obs
    from . import lower

    fused, requested = resolve_fuse(fuse)
    spec = engine._ir_spec()
    # the plan's degradations list: captured so EngineIr's first-dispatch
    # rung (lazy jit — see _degrade_to_staged) lands on the same card
    sink = faults.current_sink()
    # the IR's own refusals (graph validation, unregistered lowering,
    # _mesh_spec) raise typed InvalidParameterError — a rung, not a failed
    # plan, same as the build-error classes
    rung_errors = faults.ENGINE_BUILD_ERRORS + (InvalidParameterError,)
    try:
        faults.site("ir.lower")
        graphs = lower.lower_engine(engine)
        graphs["backward"].validate()
        for g in graphs["forward"].values():
            g.validate()
    except rung_errors as e:
        faults.record_degradation("ir_lower_failed", faults.summarize(e))
        return EngineIr(spec, None, path="legacy", requested=requested)
    if fused:
        try:
            faults.site("ir.compile")
            ir = EngineIr(
                spec, graphs, path="fused", requested=requested, sink=sink
            )
        except rung_errors as e:
            faults.record_degradation("fuse_compile_failed", faults.summarize(e))
            ir = EngineIr(spec, graphs, path="staged", requested=requested)
    else:
        ir = EngineIr(spec, graphs, path="staged", requested=requested)
    obs.trace.event("decision", what="fuse", choice=ir.path)
    return ir
