"""spfft_tpu.ir — stage-graph IR with per-direction fusion.

The pipeline-structure layer between the engines and XLA (ROADMAP item 3):

1. **Graph** (:mod:`.graph`): a small typed stage-graph IR whose nodes are
   the canonical pipeline stages (:data:`NODES` — the engine subset of
   ``obs.STAGES``, lint-enforced both ways against the profiler and perf
   vocabularies) with dtype/shape metadata on edges and typed validation —
   unknown stage, dangling edge, dtype mismatch, and cycles raise before
   anything compiles.
2. **Lowering** (:mod:`.lower`): all six engines describe their
   per-direction pipelines as stage graphs built from the same extracted
   stage bodies their monolithic impls call; the OVERLAPPED exchange
   discipline is applied as a *graph rewrite* (split the exchange node into
   C chunk chains pipelined against the neighbor FFT nodes) instead of
   hand-threaded loop code.
3. **Compile** (:mod:`.compile`): the fusion pass emits ONE jitted program
   per direction (donated value buffers on the local consuming flow;
   decompress/compress scatter-gathers fused inside — no materialized
   dense-stick intermediate crosses a dispatch boundary), selectable via
   ``SPFFT_TPU_FUSE=0|1`` / ``fuse=`` kwarg with the staged per-node
   dispatch path as the reference and fallback. Fault sites ``ir.lower`` /
   ``ir.compile`` feed the degradation ladder: a failed lowering runs the
   legacy monolithic jits, a failed fusion compile runs the staged path —
   never a failed plan.

Plan cards carry a schema-pinned ``ir`` section (stage lists per direction,
fusion decision, donation map); the ``fused`` vs ``staged`` (and
bf16-twiddle mixed-precision) variants are autotuner candidates under
``policy="tuned"`` (:mod:`spfft_tpu.tuning.candidates`).

4. **Batch fusion** (``SPFFT_TPU_BATCH_FUSE``, :func:`build_batched`): a
   same-geometry batch of B transforms lowers to ONE jitted program per
   direction — the composed stage graph vmapped over a leading batch axis
   on the stacked per-request inputs (values/space), with index tables and
   threaded plan operands staying shared plan constants and the stacked
   value pair donated on the consuming backward. Fault site ``ir.batch``
   feeds the ladder: a failed batched build records ``batch_fuse_failed``
   and callers run their split-phase per-request loop — never a failed
   batch. Batch size is a tuner-owned axis (``fused/bN`` candidates,
   :func:`spfft_tpu.tuning.tuned_batch`) persisted in wisdom.
"""
from .graph import NODES, EdgeMeta, Node, StageGraph  # noqa: F401
from .compile import (  # noqa: F401
    BATCH_FUSE_ENV,
    BATCH_KEYS,
    FUSE_ENV,
    IR_KEYS,
    EngineIr,
    StagedProgram,
    build_batched,
    compose,
    init_engine_ir,
    resolve_batch_fuse,
    resolve_fuse,
)
from .lower import lower_engine  # noqa: F401
