"""Stage-graph IR: the typed pipeline representation every engine lowers to.

A :class:`StageGraph` describes one *direction* of a transform pipeline
(backward: decompress -> ... -> space; forward: space -> ... -> compress) as a
DAG of stage nodes connected by named edges. Nodes carry a canonical stage
label from :data:`NODES` — the engine-pipeline subset of ``obs.STAGES``, the
same vocabulary profiler scopes and perf attribution use, enforced both ways
by ``programs/lint.py`` check 9 — plus a traceable ``fn`` computing the
node's outputs from its input edges. Edges carry dtype/shape/"what kind of
value" metadata (:class:`EdgeMeta`), so a graph is validated *before* it is
compiled: an unknown stage label, a dangling edge (consumed but never
produced), a doubly-produced edge, a dtype mismatch across an edge, or a
cycle all raise typed :class:`~spfft_tpu.errors.InvalidParameterError` at
plan-construction time — never a cryptic trace-time failure inside XLA.

The graph is deliberately *small*: it is a scheduling/fusion representation,
not a tensor IR. Stage bodies stay ordinary traceable JAX callables (closures
over engine constants); what the IR adds is that the pipeline's *structure*
— which stages exist, what flows between them, what is safe to fuse or split
— is data that passes (:mod:`spfft_tpu.ir.compile` fuses a graph into ONE
jitted program per direction; :mod:`spfft_tpu.ir.lower` rewrites the
exchange node into overlap chunks) can manipulate, instead of hand-ordered
method calls frozen inside six engine bodies.

Distributed graphs describe the PER-SHARD pipeline: edge shapes are
per-shard block shapes (no leading mesh dimension), node fns run under
``shard_map``, and collective stages (``exchange*``) call the engine's
exchange machinery directly. The compile layer owns the block-dim adapters
and partition specs (:mod:`spfft_tpu.ir.compile`).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..errors import InvalidParameterError

# Canonical IR node vocabulary: exactly the engine-pipeline stages of
# ``obs.STAGES`` (the autotuner's "tune warmup"/"tune trial" phases are trial
# harness stages, never pipeline nodes). Pure literal tuple —
# ``programs/lint.py`` check 9 enforces it both ways against ``obs.STAGES``
# AND ``obs.perf.MODELED_STAGES``, so an IR stage can never silently escape
# profiler attribution or the perf flop/byte model.
NODES = (
    "compression",
    "stick symmetry",
    "plane symmetry",
    "z transform",
    "y transform",
    "y transform sparse",
    "y transform blocked",
    "x transform",
    "expand",
    "pack",
    "exchange",
    "unpack",
    "pack A",
    "exchange A",
    "unpack A",
    "pack B",
    "exchange B",
    "unpack B",
    "exchange overlapped",
    "exchange A overlapped",
    "exchange B overlapped",
)


@dataclass(frozen=True)
class EdgeMeta:
    """Metadata of one edge (a value flowing between stages).

    ``dtype``: numpy-comparable dtype of the edge's array, or ``None`` for
    opaque values (e.g. the local MXU engine's threaded plan-operand tuple).
    ``shape``: per-shard array shape (no leading mesh/block dimension for
    distributed graphs), or ``None`` when unknown/opaque. The compile layer
    derives per-node partition specs from ``len(shape)``."""

    dtype: object = None
    shape: tuple | None = None

    def rank(self) -> int | None:
        return None if self.shape is None else len(self.shape)


@dataclass(frozen=True)
class Node:
    """One pipeline stage: a canonical label, a traceable body, and the
    edges it consumes/produces. ``name`` is unique per graph (several nodes
    may share one ``stage`` label — e.g. the C chunk exchanges of the
    OVERLAPPED rewrite); ``fn(*inputs)`` returns the single output value when
    ``len(outputs) == 1``, else a sequence of ``len(outputs)`` values."""

    name: str
    stage: str
    fn: object
    inputs: tuple
    outputs: tuple


@dataclass
class StageGraph:
    """A validated, topologically-orderable pipeline DAG for one direction."""

    direction: str  # "backward" | "forward"
    nodes: list = field(default_factory=list)
    inputs: list = field(default_factory=list)  # ordered input edge names
    outputs: list = field(default_factory=list)  # ordered output edge names
    meta: dict = field(default_factory=dict)  # edge name -> EdgeMeta
    # Input edges that carry PER-REQUEST data (packed values / space slabs):
    # the batch-fused compile (spfft_tpu.ir.compile.build_batched) vmaps the
    # composed graph over a leading batch axis on exactly these inputs, while
    # the rest (index tables, threaded plan operands) stay plan constants
    # shared by the whole batch. Every graph output is per-request. Empty =
    # the graph declares no batch axis and cannot batch-fuse.
    batch_inputs: tuple = ()

    def add_input(self, name: str, *, dtype=None, shape=None) -> None:
        """Declare a graph input edge (caller-supplied value)."""
        if name in self.meta:
            raise InvalidParameterError(f"ir: duplicate edge {name!r}")
        self.inputs.append(name)
        self.meta[name] = EdgeMeta(dtype, None if shape is None else tuple(shape))

    def add(
        self,
        stage: str,
        fn,
        inputs,
        outputs,
        *,
        name: str | None = None,
        out_meta: dict | None = None,
    ) -> None:
        """Append a stage node. ``out_meta`` maps produced edge names to
        :class:`EdgeMeta` (missing entries default to untyped edges)."""
        if stage not in NODES:
            raise InvalidParameterError(
                f"ir: unknown stage {stage!r}: not in the canonical node "
                f"vocabulary (spfft_tpu/ir/graph.py NODES)"
            )
        name = name or stage
        if any(n.name == name for n in self.nodes):
            raise InvalidParameterError(f"ir: duplicate node name {name!r}")
        inputs = tuple(inputs)
        outputs = tuple(outputs)
        for e in outputs:
            if e in self.meta:
                raise InvalidParameterError(
                    f"ir: edge {e!r} produced more than once (node {name!r})"
                )
            m = (out_meta or {}).get(e)
            self.meta[e] = m if m is not None else EdgeMeta()
        self.nodes.append(Node(name, stage, fn, inputs, outputs))

    def set_outputs(self, names) -> None:
        self.outputs = list(names)

    def remove(self, name: str) -> None:
        """Remove node ``name`` and unregister its produced edges — the
        surgery primitive graph rewrites build on (the OVERLAPPED rewrite in
        :mod:`spfft_tpu.ir.lower` removes the bulk z/pack/exchange segment
        and re-adds per-chunk nodes between the same boundary edges)."""
        for node in self.nodes:
            if node.name == name:
                for e in node.outputs:
                    self.meta.pop(e, None)
                self.nodes.remove(node)
                return
        raise InvalidParameterError(f"ir: no node named {name!r} to remove")

    # ---- validation ------------------------------------------------------------

    def validate(self) -> None:
        """Typed pre-compile validation (module docstring): raises
        :class:`~spfft_tpu.errors.InvalidParameterError` on the first
        structural defect; returns None on a well-formed graph."""
        produced = set(self.inputs)
        for node in self.nodes:
            produced.update(node.outputs)
        for node in self.nodes:
            for e in node.inputs:
                if e not in produced:
                    raise InvalidParameterError(
                        f"ir[{self.direction}]: dangling edge {e!r} consumed "
                        f"by node {node.name!r} but produced by no node or "
                        f"graph input"
                    )
        for e in self.outputs:
            if e not in produced:
                raise InvalidParameterError(
                    f"ir[{self.direction}]: graph output {e!r} is produced "
                    f"by no node"
                )
        # dtype agreement: a consumer that declares an expected dtype via
        # its node's input-edge metadata must match the producer's declared
        # dtype. (Both come from self.meta — one table — so the check is
        # producer-declared dtype vs consumer expectation recorded by
        # expect_dtype(); None on either side means "unchecked".)
        for (edge, want), have in self._expectations.items():
            m = self.meta.get(edge)
            if m is None or m.dtype is None or want is None:
                continue
            import numpy as np

            if np.dtype(m.dtype) != np.dtype(want):
                raise InvalidParameterError(
                    f"ir[{self.direction}]: dtype mismatch at edge {edge!r}: "
                    f"produced {np.dtype(m.dtype)} but {have!r} expects "
                    f"{np.dtype(want)}"
                )
        self.toposort()  # raises on cycles

    # consumer dtype expectations: (edge, dtype) -> consumer node name
    @property
    def _expectations(self) -> dict:
        return getattr(self, "_expect", {})

    def expect_dtype(self, node_name: str, edge: str, dtype) -> None:
        """Record that ``node_name`` expects ``edge`` to carry ``dtype`` —
        checked against the producer's declared metadata in
        :meth:`validate`."""
        if not hasattr(self, "_expect"):
            self._expect = {}
        self._expect[(edge, dtype)] = node_name

    def toposort(self) -> list:
        """Nodes in dependency order; raises typed on cycles."""
        ready = set(self.inputs)
        remaining = list(self.nodes)
        order = []
        while remaining:
            progressed = False
            for node in list(remaining):
                if all(e in ready for e in node.inputs):
                    order.append(node)
                    ready.update(node.outputs)
                    remaining.remove(node)
                    progressed = True
            if not progressed:
                names = [n.name for n in remaining]
                raise InvalidParameterError(
                    f"ir[{self.direction}]: cycle or unsatisfiable "
                    f"dependency among nodes {names}"
                )
        return order

    # ---- introspection ---------------------------------------------------------

    def stage_list(self) -> list:
        """Stage labels in topological order — the plan card's ``ir``
        provenance section embeds this per direction."""
        return [n.stage for n in self.toposort()]
