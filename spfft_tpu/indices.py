"""Sparse frequency index handling.

Converts caller-supplied frequency index triplets into the internal z-stick layout.
Behavioral parity with the reference's index conversion
(reference: src/compression/indices.hpp:49-186), re-expressed as vectorized numpy:

* a value's storage slot is ``stick_id * dim_z + z_storage``   (z-sticks contiguous in z)
* stick ids are assigned in ascending order of the xy key ``x_storage * dim_y + y_storage``
* negative ("centered") indices wrap modulo the dimension
* bounds are validated against either the non-negative or the centered interval,
  with the hermitian (R2C) restriction ``0 <= x <= dim_x // 2``

All of this is host-side plan construction — it runs once per Transform creation, in
numpy, and produces static device-constant index arrays (the analogue of
CompressionGPU uploading its indices once, reference: src/compression/compression_gpu.hpp:54-57).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from .errors import (
    DuplicateIndicesError,
    InvalidIndicesError,
    InvalidParameterError,
    OverflowError_,
)


def to_storage_index(dim: int, index: np.ndarray) -> np.ndarray:
    """Map centered indices [-floor(dim/2)+..., floor(dim/2)] into storage [0, dim).

    Reference semantics: src/compression/indices.hpp:49-55.
    """
    return np.where(index < 0, index + dim, index)


def _validate_bounds(
    idx: np.ndarray, lo: int, hi: int
) -> None:
    if idx.size and (int(idx.min()) < lo or int(idx.max()) > hi):
        raise InvalidIndicesError(
            f"frequency index out of bounds: allowed [{lo}, {hi}], "
            f"got [{int(idx.min())}, {int(idx.max())}]"
        )


def convert_index_triplets(
    hermitian_symmetry: bool,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    indices: np.ndarray | Sequence[int],
) -> tuple[np.ndarray, np.ndarray]:
    """Convert interleaved (x, y, z) triplets to (value_indices, stick_xy_indices).

    Returns:
      value_indices: int32 array of length num_values; flat slot of each caller value in
        the local stick array, ``stick_id * dim_z + z``.
      stick_xy_indices: int32 sorted array of unique xy keys (``x * dim_y + y``), one per
        local z-stick; position == stick id.

    Behavior parity: src/compression/indices.hpp:120-186. Bounds / duplicate-triplet
    validation matches the reference: centered indexing is auto-detected from any
    negative index; hermitian symmetry restricts x to [0, dim_x//2].
    """
    triplets = np.asarray(indices, dtype=np.int64)
    if triplets.ndim == 1:
        if triplets.size % 3 != 0:
            raise InvalidParameterError("index triplet array length must be a multiple of 3")
        triplets = triplets.reshape(-1, 3)
    if triplets.ndim != 2 or triplets.shape[1] != 3:
        raise InvalidParameterError("indices must be (N, 3) or interleaved flat triplets")

    num_values = triplets.shape[0]
    if num_values > dim_x * dim_y * dim_z:
        raise InvalidParameterError("more values than grid points")

    x, y, z = triplets[:, 0], triplets[:, 1], triplets[:, 2]

    centered = bool(num_values) and bool((triplets < 0).any())

    # Allowed intervals; reference: src/compression/indices.hpp:137-148.
    max_x = (dim_x // 2 + 1 if (hermitian_symmetry or centered) else dim_x) - 1
    max_y = (dim_y // 2 + 1 if centered else dim_y) - 1
    max_z = (dim_z // 2 + 1 if centered else dim_z) - 1
    min_x = 0 if hermitian_symmetry else max_x - dim_x + 1
    min_y = max_y - dim_y + 1
    min_z = max_z - dim_z + 1
    _validate_bounds(x, min_x, max_x)
    _validate_bounds(y, min_y, max_y)
    _validate_bounds(z, min_z, max_z)

    xs = to_storage_index(dim_x, x)
    ys = to_storage_index(dim_y, y)
    zs = to_storage_index(dim_z, z)

    xy_keys = xs * dim_y + ys
    stick_xy_indices, stick_of_value = np.unique(xy_keys, return_inverse=True)

    value_indices = stick_of_value.astype(np.int64) * dim_z + zs

    # Index arrays are int32 on device; reject plans whose stick array exceeds the
    # int32 range (reference raises SPFFT_OVERFLOW_ERROR on similar size overflows).
    if stick_xy_indices.size * dim_z >= 2**31 or dim_x * dim_y >= 2**31:
        raise OverflowError_("transform too large for 32-bit index arrays")

    # Reject duplicate triplets (same slot claimed twice). The reference detects this
    # lazily through cross-rank stick checks; here a direct check is cheap.
    if num_values and np.unique(value_indices).size != num_values:
        raise DuplicateIndicesError("duplicate frequency index triplets")

    return value_indices.astype(np.int32), stick_xy_indices.astype(np.int32)


def check_stick_duplicates(indices_per_shard: Sequence[np.ndarray]) -> None:
    """Raise if any z-stick (xy key) appears on more than one shard.

    Reference semantics: src/compression/indices.hpp:105-117.
    """
    all_sticks = np.concatenate([np.asarray(s) for s in indices_per_shard]) if indices_per_shard else np.array([])
    if all_sticks.size and np.unique(all_sticks).size != all_sticks.size:
        raise DuplicateIndicesError("a z-stick is owned by more than one shard")


def stick_xy_to_xy(stick_xy: np.ndarray, dim_y: int) -> tuple[np.ndarray, np.ndarray]:
    """Split packed xy keys into (x, y) storage coordinates."""
    stick_xy = np.asarray(stick_xy)
    return stick_xy // dim_y, stick_xy % dim_y


def spherical_radius_for_fraction(fraction: float) -> float:
    """Radius fraction whose ball holds ``fraction`` of the cube's grid points
    (normalized ball volume pi f^3 / 6 = fraction). Beyond fraction = pi/6 the
    ball is clipped by the cube, so the effective nonzero fraction saturates
    below the request — callers should warn (benchmark.py and profile.py do)."""
    return float((6.0 * fraction / np.pi) ** (1.0 / 3.0))


def create_spherical_cutoff_triplets(
    dim_x: int, dim_y: int, dim_z: int, radius_fraction: float,
    hermitian_symmetry: bool = False,
) -> np.ndarray:
    """Generate centered index triplets inside a sphere of radius
    ``radius_fraction * dim/2`` — the plane-wave-DFT-style workload used for
    benchmarks (sparsity model analogous to tests/programs/benchmark.cpp:177-205).
    """
    hx = dim_x // 2
    hy = dim_y // 2
    hz = dim_z // 2
    xs = np.arange(0 if hermitian_symmetry else -((dim_x - 1) // 2), hx + 1)
    ys = np.arange(-((dim_y - 1) // 2), hy + 1)
    zs = np.arange(-((dim_z - 1) // 2), hz + 1)
    gx, gy, gz = np.meshgrid(xs, ys, zs, indexing="ij")
    r2 = (gx / max(hx, 1)) ** 2 + (gy / max(hy, 1)) ** 2 + (gz / max(hz, 1)) ** 2
    mask = r2 <= radius_fraction**2
    return np.stack([gx[mask], gy[mask], gz[mask]], axis=1).astype(np.int32)
