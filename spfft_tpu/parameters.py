"""Transform metadata ("the plan").

The analogue of the reference's ``Parameters`` object
(reference: src/parameters/parameters.hpp:48-156, src/parameters/parameters.cpp:43-180):
converts user index triplets into the internal z-stick layout, derives all static
shapes, and (in the distributed case) the per-shard stick/plane bookkeeping.

Everything here is host-side numpy computed once at Transform creation; the resulting
index arrays become device-resident constants closed over by the jitted pipelines
(static shapes are what XLA needs — the reference freezes the same quantities at plan
creation time).
"""
from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from . import indices as _indices
from .errors import InvalidParameterError, MPIParameterMismatchError
from .types import TransformType


@dataclasses.dataclass(frozen=True)
class DistributedParameters:
    """Metadata for a mesh-distributed transform.

    The analogue of the reference's MPI ``Parameters`` constructor
    (reference: src/parameters/parameters.cpp:43-140): per-shard stick sets, slab
    lengths/offsets, global stick tables (the reference allgathers these via
    point-to-point exchange, src/compression/indices.hpp:58-102 — here the single
    controller simply concatenates), plus the padded-uniform ("BUFFERED") exchange
    geometry. All arrays are host numpy; sharded ones are stacked over axis 0.
    """

    transform_type: TransformType
    dim_x: int
    dim_y: int
    dim_z: int
    num_shards: int

    # -- per-shard (axis 0 == shard) --
    num_values_per_shard: np.ndarray  # (P,)
    num_sticks_per_shard: np.ndarray  # (P,)
    value_indices: np.ndarray  # (P, V_max) int32, padded with OOB sentinel
    local_z_lengths: np.ndarray  # (P,)
    z_offsets: np.ndarray  # (P,)

    # -- global stick tables, identical on every shard --
    stick_x_all: np.ndarray  # (P, S_max) int32, padded with dim_x_freq (OOB -> drop)
    stick_y_all: np.ndarray  # (P, S_max) int32, padded with 0
    stick_xy_per_shard: tuple  # tuple of per-shard unpadded xy key arrays

    # -- zero-stick ownership (R2C stick symmetry) --
    zero_stick_shard: int  # -1 if no (0,0) stick exists
    zero_stick_row: int

    @property
    def dim_x_freq(self) -> int:
        if self.transform_type == TransformType.R2C:
            return self.dim_x // 2 + 1
        return self.dim_x

    @property
    def max_num_sticks(self) -> int:
        return int(self.stick_x_all.shape[1])

    @property
    def max_num_values(self) -> int:
        return int(self.value_indices.shape[1])

    @property
    def max_local_z_length(self) -> int:
        return int(self.local_z_lengths.max()) if self.num_shards else 0

    @property
    def total_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z

    def pack_z_map(self) -> np.ndarray:
        """(P * L_max,) map from packed exchange-plane slot to global z index, with
        out-of-range sentinel (dim_z) on padding slots (take -> fill 0)."""
        L = self.max_local_z_length
        out = np.full(self.num_shards * L, self.dim_z, dtype=np.int32)
        for r in range(self.num_shards):
            l, o = int(self.local_z_lengths[r]), int(self.z_offsets[r])
            out[r * L : r * L + l] = np.arange(o, o + l)
        return out

    def unpack_z_map(self) -> np.ndarray:
        """(dim_z,) map from global z index to packed exchange-plane slot."""
        L = self.max_local_z_length
        out = np.zeros(self.dim_z, dtype=np.int32)
        for r in range(self.num_shards):
            l, o = int(self.local_z_lengths[r]), int(self.z_offsets[r])
            out[o : o + l] = r * L + np.arange(l)
        return out


@dataclasses.dataclass(frozen=True)
class LocalParameters:
    """Metadata for a single-device transform."""

    transform_type: TransformType
    dim_x: int
    dim_y: int
    dim_z: int
    num_values: int
    # Flat slot of each packed caller value inside the stick array (stick*dim_z + z).
    value_indices: np.ndarray
    # Sorted unique xy keys (x*dim_y + y); position == stick id.
    stick_xy_indices: np.ndarray

    @property
    def dim_x_freq(self) -> int:
        """Frequency-domain x extent (hermitian-reduced for R2C)."""
        if self.transform_type == TransformType.R2C:
            return self.dim_x // 2 + 1
        return self.dim_x

    @property
    def num_sticks(self) -> int:
        return int(self.stick_xy_indices.size)

    @property
    def stick_x(self) -> np.ndarray:
        return self.stick_xy_indices // self.dim_y

    @property
    def stick_y(self) -> np.ndarray:
        return self.stick_xy_indices % self.dim_y

    @property
    def total_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z


def make_distributed_parameters(
    transform_type: TransformType,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    indices_per_shard: Sequence[np.ndarray],
    local_z_lengths: Sequence[int] | None = None,
) -> DistributedParameters:
    """Build distributed metadata from per-shard index triplets.

    ``indices_per_shard[r]`` are the triplets whose values shard r owns (whole
    z-sticks per shard, validated). ``local_z_lengths`` gives the slab split; default
    is the balanced split ceil/floor split of dim_z (the reference leaves the split to
    the caller; SIRIUS-style callers use near-uniform slabs).

    Performs the reference's collective validation steps single-controller-side:
    cross-shard stick duplicate detection (reference: src/compression/indices.hpp:105-117)
    and global count checks (reference: src/parameters/parameters.cpp:93-109).
    """
    if dim_x <= 0 or dim_y <= 0 or dim_z <= 0:
        raise InvalidParameterError("transform dimensions must be positive")
    num_shards = len(indices_per_shard)
    if num_shards < 1:
        raise InvalidParameterError("need at least one shard")

    hermitian = TransformType(transform_type) == TransformType.R2C
    per_shard = [
        _indices.convert_index_triplets(hermitian, dim_x, dim_y, dim_z, trip)
        for trip in indices_per_shard
    ]
    stick_xy_per_shard = tuple(sticks for _, sticks in per_shard)
    _indices.check_stick_duplicates(stick_xy_per_shard)

    if local_z_lengths is None:
        base, rem = divmod(dim_z, num_shards)
        local_z_lengths = np.asarray(
            [base + (1 if r < rem else 0) for r in range(num_shards)], dtype=np.int64
        )
    else:
        local_z_lengths = np.asarray(local_z_lengths, dtype=np.int64)
        if local_z_lengths.size != num_shards:
            raise MPIParameterMismatchError("one local_z_length per shard required")
        if local_z_lengths.sum() != dim_z or (local_z_lengths < 0).any():
            raise MPIParameterMismatchError("local_z_lengths must partition dim_z")
    z_offsets = np.concatenate([[0], np.cumsum(local_z_lengths)[:-1]])

    num_values = np.asarray([vi.size for vi, _ in per_shard], dtype=np.int64)
    num_sticks = np.asarray([s.size for _, s in per_shard], dtype=np.int64)
    s_max = max(1, int(num_sticks.max()))
    v_max = max(1, int(num_values.max()))

    dim_x_freq = dim_x // 2 + 1 if hermitian else dim_x
    oob_value = s_max * dim_z  # past the padded stick array -> dropped/filled
    value_indices = np.full((num_shards, v_max), oob_value, dtype=np.int32)
    stick_x_all = np.full((num_shards, s_max), dim_x_freq, dtype=np.int32)
    stick_y_all = np.zeros((num_shards, s_max), dtype=np.int32)
    zero_stick_shard, zero_stick_row = -1, 0
    for r, (vi, sticks) in enumerate(per_shard):
        value_indices[r, : vi.size] = vi
        stick_x_all[r, : sticks.size] = sticks // dim_y
        stick_y_all[r, : sticks.size] = sticks % dim_y
        if sticks.size and int(sticks[0]) == 0:
            zero_stick_shard, zero_stick_row = r, 0

    return DistributedParameters(
        transform_type=TransformType(transform_type),
        dim_x=int(dim_x),
        dim_y=int(dim_y),
        dim_z=int(dim_z),
        num_shards=num_shards,
        num_values_per_shard=num_values,
        num_sticks_per_shard=num_sticks,
        value_indices=value_indices,
        local_z_lengths=local_z_lengths,
        z_offsets=z_offsets,
        stick_x_all=stick_x_all,
        stick_y_all=stick_y_all,
        stick_xy_per_shard=stick_xy_per_shard,
        zero_stick_shard=zero_stick_shard,
        zero_stick_row=zero_stick_row,
    )


def stick_keys(triplets, dim_y: int) -> np.ndarray:
    """Sign-safe composite (x, y) stick identity key for each value triplet.

    Groups values by stick in *caller* index space (sign-sensitive keys map to
    the same storage stick after conversion); the single definition shared by
    the partitioner and the benchmark's stick accounting.
    """
    t = np.asarray(triplets).reshape(-1, 3).astype(np.int64)
    return t[:, 0] * (4 * dim_y) + t[:, 1]


def distribute_triplets(
    triplets: np.ndarray,
    num_shards: int,
    dim_y: int,
    weights: Sequence[float] | None = None,
    *,
    layout: tuple[int, int] | None = None,
    dim_x: int | None = None,
) -> list[np.ndarray]:
    """Partition global triplets into per-shard lists, keeping z-sticks whole
    (the hard constraint, reference: docs/source/details.rst:50-53) and balancing
    value counts across shards (optionally by weight, mirroring the reference tests'
    ``zStickDistribution`` weight vectors, tests/test_util/generate_indices.hpp:39-100).

    ``layout=(P1, P2)`` requests an x-column-local split for a 2-D pencil mesh
    (``dim_x`` required, for centered-index folding): the x-sorted stick list
    is cut into P1 contiguous column groups balanced by value counts, then each
    group is split over its column's P2 shards (shard = a*P2 + b). Every stick
    of column group ``a`` lands on a shard of column ``a``, so the pencil
    engines' ownership-aligned x-grouping makes exchange A column-diagonal —
    only the z-chunk redistribution inside each column crosses the wire,
    (P2-1)/P2 of the stick data instead of (P-1)/P. For the 1-D slab engine
    the stick->shard map has no wire effect, so the default (greedy
    largest-first) stays; ``weights`` are unsupported with ``layout``.
    """
    t = np.asarray(triplets).reshape(-1, 3)
    if num_shards < 1:
        raise InvalidParameterError("num_shards must be >= 1")
    keys = stick_keys(t, dim_y)
    uniq, inverse, counts = np.unique(keys, return_inverse=True, return_counts=True)

    if layout is not None:
        P1, P2 = int(layout[0]), int(layout[1])
        if P1 * P2 != num_shards:
            raise InvalidParameterError("layout does not match num_shards")
        if weights is not None:
            raise InvalidParameterError("weights are unsupported with layout")
        if dim_x is None:
            raise InvalidParameterError("layout requires dim_x")
        # storage-x of each unique stick (centered callers fold negatives onto
        # the same physical column), then sort sticks x-major. Nearest-int
        # recovery: |y| <= dim_y/2 < (4*dim_y)/2, so rounding key/(4*dim_y)
        # yields the signed x exactly even when floor division would not.
        raw_x = np.rint(uniq / (4 * dim_y)).astype(np.int64)
        storage_x = np.where(raw_x < 0, raw_x + dim_x, raw_x)
        xorder = np.argsort(storage_x, kind="stable")
        # 1) contiguous column groups balanced by value counts; a group
        # boundary never splits one x column (column-local is the point)
        csum = np.cumsum(counts[xorder])
        total = int(csum[-1])
        group_of_sorted = np.minimum(
            (csum - 1) * P1 // max(1, total), P1 - 1
        )
        # snap each column's sticks to the group of its first stick
        sx_sorted = storage_x[xorder]
        first_of_col = np.concatenate([[True], sx_sorted[1:] != sx_sorted[:-1]])
        col_sizes = np.diff(
            np.concatenate([np.flatnonzero(first_of_col), [sx_sorted.size]])
        )
        col_group = group_of_sorted[np.flatnonzero(first_of_col)]
        # Snapping can starve later groups when one column dominates the
        # value counts (advisor r4): if any group came out empty, fall back
        # to an even split over column boundaries — whole columns stay
        # together and every group gets at least one column whenever
        # P1 <= #columns (a dominant column forces load imbalance either
        # way; starving whole shard columns of ALL sticks is the part this
        # prevents).
        if not np.isin(np.arange(P1), col_group).all():
            n_cols = col_group.size
            col_group = np.minimum(
                np.arange(n_cols) * P1 // max(1, n_cols), P1 - 1
            )
        group_of_sorted = np.repeat(col_group, col_sizes)
        # 2) greedy largest-first within each column group over its P2 shards
        stick_shard = np.zeros(uniq.size, dtype=np.int64)
        for a in range(P1):
            members = xorder[group_of_sorted == a]
            load = np.zeros(P2)
            for s in members[np.argsort(-counts[members], kind="stable")]:
                b = int(np.argmin(load))
                stick_shard[s] = a * P2 + b
                load[b] += counts[s]
        value_shard = stick_shard[inverse]
        return [t[value_shard == r] for r in range(num_shards)]

    order = np.argsort(-counts)  # largest sticks first
    if weights is None:
        weights = np.ones(num_shards)
    weights = np.asarray(weights, dtype=np.float64)
    if weights.size != num_shards or (weights < 0).any() or weights.sum() == 0:
        raise InvalidParameterError("invalid shard weights")
    load = np.zeros(num_shards)
    stick_shard = np.zeros(uniq.size, dtype=np.int64)
    for s in order:
        # zero-weight shards receive nothing (reference parity: a zero entry in the
        # zStickDistribution weight vector draws no sticks,
        # tests/test_util/generate_indices.hpp:39-100)
        ratio = np.where(weights > 0, load / np.maximum(weights, 1e-300), np.inf)
        r = int(np.argmin(ratio))
        stick_shard[s] = r
        load[r] += counts[s]
    value_shard = stick_shard[inverse]
    return [t[value_shard == r] for r in range(num_shards)]


def make_local_parameters(
    transform_type: TransformType,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    indices: np.ndarray | Sequence[int],
) -> LocalParameters:
    """Build local transform metadata from index triplets.

    Parity with the reference's local Parameters constructor
    (reference: src/parameters/parameters.cpp:143-180).
    """
    if dim_x <= 0 or dim_y <= 0 or dim_z <= 0:
        raise InvalidParameterError("transform dimensions must be positive")
    hermitian = transform_type == TransformType.R2C
    value_indices, stick_xy = _indices.convert_index_triplets(
        hermitian, dim_x, dim_y, dim_z, indices
    )
    return LocalParameters(
        transform_type=TransformType(transform_type),
        dim_x=int(dim_x),
        dim_y=int(dim_y),
        dim_z=int(dim_z),
        num_values=int(value_indices.size),
        value_indices=value_indices,
        stick_xy_indices=stick_xy,
    )
