"""Transform metadata ("the plan").

The analogue of the reference's ``Parameters`` object
(reference: src/parameters/parameters.hpp:48-156, src/parameters/parameters.cpp:43-180):
converts user index triplets into the internal z-stick layout, derives all static
shapes, and (in the distributed case) the per-shard stick/plane bookkeeping.

Everything here is host-side numpy computed once at Transform creation; the resulting
index arrays become device-resident constants closed over by the jitted pipelines
(static shapes are what XLA needs — the reference freezes the same quantities at plan
creation time).
"""
from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from . import indices as _indices
from .errors import InvalidParameterError, MPIParameterMismatchError
from .types import TransformType


@dataclasses.dataclass(frozen=True)
class LocalParameters:
    """Metadata for a single-device transform."""

    transform_type: TransformType
    dim_x: int
    dim_y: int
    dim_z: int
    num_values: int
    # Flat slot of each packed caller value inside the stick array (stick*dim_z + z).
    value_indices: np.ndarray
    # Sorted unique xy keys (x*dim_y + y); position == stick id.
    stick_xy_indices: np.ndarray

    @property
    def dim_x_freq(self) -> int:
        """Frequency-domain x extent (hermitian-reduced for R2C)."""
        if self.transform_type == TransformType.R2C:
            return self.dim_x // 2 + 1
        return self.dim_x

    @property
    def num_sticks(self) -> int:
        return int(self.stick_xy_indices.size)

    @property
    def stick_x(self) -> np.ndarray:
        return self.stick_xy_indices // self.dim_y

    @property
    def stick_y(self) -> np.ndarray:
        return self.stick_xy_indices % self.dim_y

    @property
    def total_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z


def make_local_parameters(
    transform_type: TransformType,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    indices: np.ndarray | Sequence[int],
) -> LocalParameters:
    """Build local transform metadata from index triplets.

    Parity with the reference's local Parameters constructor
    (reference: src/parameters/parameters.cpp:143-180).
    """
    if dim_x <= 0 or dim_y <= 0 or dim_z <= 0:
        raise InvalidParameterError("transform dimensions must be positive")
    hermitian = transform_type == TransformType.R2C
    value_indices, stick_xy = _indices.convert_index_triplets(
        hermitian, dim_x, dim_y, dim_z, indices
    )
    return LocalParameters(
        transform_type=TransformType(transform_type),
        dim_x=int(dim_x),
        dim_y=int(dim_y),
        dim_z=int(dim_z),
        num_values=int(value_indices.size),
        value_indices=value_indices,
        stick_xy_indices=stick_xy,
    )
