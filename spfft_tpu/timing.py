"""Nested host-side timing tree — the analogue of the reference's embedded
``rt_graph`` profiler (reference: src/timing/rt_graph.hpp:44-95, rt_graph.cpp, 755 LoC)
and its ``HOST_TIMING_*`` macro layer (reference: src/timing/timing.hpp:34-62).

Design differences forced by the TPU execution model:

* The reference wraps every pipeline stage (x/y/z transform, pack, exchange,
  compression) in host timers because stages are separate host calls. Under XLA the
  whole pipeline is one compiled program, so intra-program stages are invisible to
  host timers — per-stage attribution comes from ``jax.profiler`` traces instead
  (:func:`trace_annotation` emits named scopes for that). What the host timing tree
  *can* see — and what this module measures — are the host-visible phases: plan
  creation/compilation, input staging (host->device), dispatch, and the blocking wait.
* The reference gates timing at compile time (SPFFT_TIMING -> no-op macros). Here the
  gate is runtime: :func:`enable`/:func:`disable`; when disabled, :func:`scoped` is a
  shared no-op context manager (no allocation per call).

The processed tree reports the same statistics as rt_graph: count, total, mean,
median, quartiles, min, max, percentage of the top-level total and of the parent
(reference: src/timing/rt_graph.hpp:44-56), printable or exportable as JSON in the
shape the reference benchmark embeds in its report
(reference: tests/programs/benchmark.cpp:283-289).

This is layer 1 of the five observability layers (docs/details.md
"Observability"): the timing tree measures what the host *paid*;
:mod:`spfft_tpu.obs` records what the plan *decided* (plan cards) and counts
what ran (run-metrics registry, gated by ``SPFFT_TPU_METRICS`` with the same
shared-no-op pattern as :func:`enable`/:func:`disable` here); the flight
recorder (:mod:`spfft_tpu.obs.trace`) keeps the per-execution event log —
every :func:`scoped` phase below doubles as a run-ID-stamped trace span when
tracing is armed, so the nested timing nodes appear as Chrome-trace duration
slices instead of living in a separate report-only tree; ``jax.profiler``
traces show what the device *executed*, stage-tagged via ``obs.STAGES``;
performance reports (:mod:`spfft_tpu.obs.perf`) say how *fast* it was,
attributing fenced pair time to those same stages.
"""
from __future__ import annotations

import json as _json
import time

from .errors import InvalidParameterError
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from .obs import trace


class _Node:
    __slots__ = ("label", "timings", "children", "order")

    def __init__(self, label: str):
        self.label = label
        self.timings: list[float] = []
        self.children: dict[str, "_Node"] = {}
        self.order: list[str] = []

    def child(self, label: str) -> "_Node":
        node = self.children.get(label)
        if node is None:
            node = _Node(label)
            self.children[label] = node
            self.order.append(label)
        return node


def _quantile(sorted_vals, q: float) -> float:
    return float(np.quantile(sorted_vals, q))


@dataclass
class TimingResult:
    """Processed statistics for one timing node (reference: rt_graph.hpp:44-56)."""

    label: str
    count: int
    total: float
    mean: float
    median: float
    min: float
    max: float
    lower_quartile: float
    upper_quartile: float
    percentage: float
    parent_percentage: float
    sub: list["TimingResult"] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "count": self.count,
            "total": self.total,
            "mean": self.mean,
            "median": self.median,
            "min": self.min,
            "max": self.max,
            "lower_quartile": self.lower_quartile,
            "upper_quartile": self.upper_quartile,
            "percentage": self.percentage,
            "parent_percentage": self.parent_percentage,
            "sub": [s.to_dict() for s in self.sub],
        }

    def json(self, indent: int | None = 2) -> str:
        return _json.dumps(self.to_dict(), indent=indent)

    def flat(self) -> list["TimingResult"]:
        out = [self]
        for s in self.sub:
            out.extend(s.flat())
        return out

    def find(self, label: str) -> "TimingResult | None":
        for node in self.flat():
            if node.label == label:
                return node
        return None

    def _format_lines(self, depth: int, lines: list[str]) -> None:
        indent = "  " * depth
        lines.append(
            f"{indent}{self.label:<{max(1, 34 - 2 * depth)}} "
            f"n={self.count:<5d} total={_fmt_s(self.total):>10} "
            f"mean={_fmt_s(self.mean):>10} median={_fmt_s(self.median):>10} "
            f"min={_fmt_s(self.min):>10} max={_fmt_s(self.max):>10} "
            f"{self.percentage:6.2f}% (parent {self.parent_percentage:6.2f}%)"
        )
        for s in self.sub:
            s._format_lines(depth + 1, lines)

    def __str__(self) -> str:
        lines: list[str] = []
        for s in self.sub if self.label == "" else [self]:
            s._format_lines(0, lines)
        return "\n".join(lines)


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.3f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.3f} ms"
    return f"{seconds * 1e6:.3f} us"


class Timer:
    """Collects nested scoped timings into a tree.

    Unlike rt_graph — which logs raw start/stop events and reconstructs the nesting in
    ``process()`` (reference: rt_graph.hpp:60-95) — the tree is built live via an
    explicit scope stack; ``process()`` only computes statistics. Same output, no
    event-log replay, and mismatched stop labels are detected immediately.
    """

    def __init__(self):
        self._root = _Node("")
        self._stack: list[_Node] = [self._root]
        self._starts: list[float] = []

    def start(self, label: str) -> None:
        node = self._stack[-1].child(label)
        self._stack.append(node)
        self._starts.append(time.perf_counter())

    def stop(self, label: str) -> None:
        stop_time = time.perf_counter()
        if len(self._stack) <= 1:
            # typed-error discipline (analysis SA010): scope misuse is a
            # caller contract violation, surfaced as taxonomy
            raise InvalidParameterError(
                f"Timer.stop({label!r}) without matching start"
            )
        node = self._stack[-1]
        if node.label != label:
            raise InvalidParameterError(
                f"Timer.stop({label!r}) does not match open scope {node.label!r}"
            )
        self._stack.pop()
        node.timings.append(stop_time - self._starts.pop())

    @contextmanager
    def scoped(self, label: str):
        self.start(label)
        try:
            yield
        finally:
            self.stop(label)

    def clear(self) -> None:
        self._root = _Node("")
        self._stack = [self._root]
        self._starts = []

    def process(self) -> TimingResult:
        """Compute the statistics tree over everything recorded so far."""
        top_total = sum(sum(c.timings) for c in self._root.children.values())

        def build(node: _Node, parent_total: float) -> TimingResult:
            vals = sorted(node.timings) or [0.0]
            total = sum(node.timings)
            res = TimingResult(
                label=node.label,
                count=len(node.timings),
                total=total,
                mean=total / max(1, len(node.timings)),
                median=_quantile(vals, 0.5),
                min=vals[0],
                max=vals[-1],
                lower_quartile=_quantile(vals, 0.25),
                upper_quartile=_quantile(vals, 0.75),
                percentage=100.0 * total / top_total if top_total else 0.0,
                parent_percentage=100.0 * total / parent_total if parent_total else 0.0,
                sub=[],
            )
            for label in node.order:
                res.sub.append(build(node.children[label], total))
            return res

        root = TimingResult(
            label="",
            count=0,
            total=top_total,
            mean=0.0,
            median=0.0,
            min=0.0,
            max=0.0,
            lower_quartile=0.0,
            upper_quartile=0.0,
            percentage=100.0,
            parent_percentage=100.0,
            sub=[build(self._root.children[l], top_total) for l in self._root.order],
        )
        return root


class _NoopScope:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopScope()

# Process-global timer, the analogue of rt_graph's GlobalTimer
# (reference: src/timing/timing.cpp:34-36). Disabled by default like the
# SPFFT_TIMING=OFF build.
global_timer = Timer()
_enabled = False


def enable() -> None:
    """Turn on timing collection (the SPFFT_TIMING=ON build of the reference)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


class _JoinedScope:
    """Compose the timing-tree scope with the trace phase span, so one
    :func:`scoped` call feeds both layers (timing report AND flight
    recorder) without the call sites knowing which are armed."""

    __slots__ = ("_scopes",)

    def __init__(self, *scopes):
        self._scopes = scopes

    def __enter__(self):
        for s in self._scopes:
            s.__enter__()
        return self

    def __exit__(self, *exc):
        for s in reversed(self._scopes):
            s.__exit__(*exc)
        return False


def scoped(label: str):
    """Scoped timing region (the HOST_TIMING_SCOPED macro,
    reference: src/timing/timing.hpp:34-62). No-op when disabled. When the
    flight recorder is armed (:mod:`spfft_tpu.obs.trace`), the same scope
    additionally emits a run-ID-stamped ``phase`` begin/end span — the host
    timing tree and the execution trace share one instrumentation point."""
    tspan = trace.span("phase", label=label) if trace.enabled() else None
    if not _enabled:
        return _NOOP if tspan is None else tspan
    scope = global_timer.scoped(label)
    return scope if tspan is None else _JoinedScope(scope, tspan)


# Each start() records whether it actually opened a scope, so a stop() after an
# enable/disable toggle stays balanced instead of corrupting the global tree.
# The parallel _trace_spans stack keeps the flight-recorder phase spans
# balanced across toggles the same way.
_start_flags: list[bool] = []
_trace_spans: list = []


def start(label: str) -> None:
    _start_flags.append(_enabled)
    if _enabled:
        global_timer.start(label)
    if trace.enabled():
        tspan = trace.span("phase", label=label)
        tspan.__enter__()
        _trace_spans.append(tspan)
    else:
        _trace_spans.append(None)


def stop(label: str) -> None:
    tspan = _trace_spans.pop() if _trace_spans else None
    if tspan is not None:
        tspan.__exit__(None, None, None)
    if _start_flags.pop() if _start_flags else False:
        global_timer.stop(label)


def clear() -> None:
    global_timer.clear()
    _start_flags.clear()


def process() -> TimingResult:
    return global_timer.process()


def trace_annotation(label: str):
    """Device-side named scope for ``jax.profiler`` traces — the stage-level
    attribution that host timers cannot see under XLA (module docstring)."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(label)
