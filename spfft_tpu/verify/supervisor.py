"""Recovery supervisor: turn detection into bounded, observable self-healing.

The supervisor wraps a plan's host-facing ``backward``/``forward`` execution
(engine dispatch, exchange collectives, fence, staging — the whole attempt)
in a recovery ladder. Each rung is recorded in the plan's ``degradations``
(plan card), the run-metrics registry and the flight recorder, so a recovered
transform is diagnosable after the fact:

1. **Verify** — run the ABFT checks (:mod:`.checks`) on the attempt's result.
   All pass -> return it (and close/reset the engine's circuit breaker).
2. **Retry** — on a failed check, a detector fault, or a typed execution
   error, re-execute up to ``SPFFT_TPU_VERIFY_RETRIES`` more times with
   exponential backoff (``SPFFT_TPU_VERIFY_BACKOFF_S`` base; the sleep holds
   no locks, mirroring ``tuning/wisdom.py``'s retry discipline). A transient
   flip heals here; ``verify_retries_total`` counts the budget spent.
3. **Demote** — retries exhausted (or the engine's breaker already open):
   recompute through the ``jnp.fft`` reference engine — a freshly built
   :class:`~spfft_tpu.execution.LocalExecution` pipeline on a disjoint code
   path from the primary engine's dispatch — and verify *that*. A verified
   reference result returns to the caller (``verify_recoveries_total``, a
   ``verify_demoted`` degradation rung) and feeds the breaker's
   consecutive-failure count.
4. **Raise** — the reference fails verification too (or is unavailable):
   typed :class:`~spfft_tpu.errors.VerificationError`, round-tripped to the
   C error surface by ``capi.error_code`` like every other member of the
   taxonomy. A silently wrong result is never returned.

``strict`` mode (``SPFFT_TPU_VERIFY=strict``) is the fail-fast variant for
debugging: the first failed check raises immediately, no retry or demotion —
and no breaker short-circuit either (the primary engine is always attempted;
strict episodes still feed the breaker's shared failure count).

The per-process **circuit breaker** (:mod:`.breaker`) sits above rung 2: an
engine with K consecutive verified-failure episodes is open for the whole
process — verified calls skip the primary attempt entirely (a
``verify_breaker_open`` degradation rung) until a half-open probe heals it.
"""
from __future__ import annotations

import random
import time

import numpy as np

from .. import faults, knobs, obs
from ..errors import (
    FFTWError,
    GPUFFTError,
    HostExecutionError,
    MPIError,
    VerificationError,
)
from . import breaker, checks

VERIFY_RETRIES_ENV = "SPFFT_TPU_VERIFY_RETRIES"
VERIFY_BACKOFF_ENV = "SPFFT_TPU_VERIFY_BACKOFF_S"
VERIFY_JITTER_SEED_ENV = "SPFFT_TPU_VERIFY_JITTER_SEED"

DEFAULT_RETRIES = knobs.default(VERIFY_RETRIES_ENV)
DEFAULT_BACKOFF_S = knobs.default(VERIFY_BACKOFF_ENV)

# Execution-level typed failures the retry rung may absorb: the dual error
# surface's dispatch/fence conversions plus the distributed collective layer.
# Deliberately excludes parameter/index errors (user errors must surface
# immediately) and raw backend exceptions (faults.typed_execution already
# converted anything retryable by the time it reaches the supervisor).
RETRYABLE_ERRORS = (HostExecutionError, GPUFFTError, MPIError, FFTWError)

# Failure classes tolerated from the *detector* itself (the verify.check
# fault site raises InjectedFault, a RuntimeError): a broken checker means
# the result is unverifiable, which the ladder treats as a failed episode —
# fail closed, never "checker died so assume the data is fine".
CHECKER_ERRORS = (RuntimeError,)


def resolve_retries() -> int:
    """Re-executions after the first attempt (``SPFFT_TPU_VERIFY_RETRIES``,
    floor 0)."""
    return knobs.get_int(VERIFY_RETRIES_ENV)


def resolve_backoff_s() -> float:
    """Base of the exponential retry backoff (``SPFFT_TPU_VERIFY_BACKOFF_S``)."""
    return knobs.get_float(VERIFY_BACKOFF_ENV)


def jitter_rng() -> random.Random:
    """Per-supervisor jitter stream for the retry backoff
    (:func:`spfft_tpu.faults.backoff_s`): concurrent callers retrying the
    same failed engine must not thundering-herd it on a synchronized
    schedule. Seeded from ``SPFFT_TPU_VERIFY_JITTER_SEED`` when set (a chaos
    run's sleep sequence replays exactly), system entropy otherwise."""
    seed = knobs.get_int(VERIFY_JITTER_SEED_ENV)
    return random.Random(seed) if seed is not None else random.Random()


class Supervisor:
    """Per-plan recovery supervisor (created only when verification is armed,
    so the disarmed hot path stays one falsy attribute check).

    The owning transform provides the engine-specific pieces: the attempt
    callable (its full dispatch path, fault sites included) and the
    reference callables (``_reference_backward`` / ``_reference_forward`` —
    the ``jnp.fft`` rung). The supervisor owns policy: check selection,
    retry budget, breaker interaction, recovery bookkeeping."""

    def __init__(self, transform, mode: str):
        self._t = transform
        self.mode = mode
        self.rtol = checks.resolve_rtol(transform.dtype)
        self.retries = resolve_retries()
        self._jitter = jitter_rng()
        self._triplets = None  # lazy: storage-order rows aligned with packing

    # ---- plan-facing entry points ------------------------------------------

    def backward(self, values):
        """Supervised backward: ``values`` (packed array, or per-shard list
        for distributed plans) -> verified ``(Z, Y, X)`` space slab."""
        freq = self._flat_values(values)
        return self._supervise(
            direction="backward",
            attempt=lambda: self._t._backward_attempt(values),
            reference=lambda: self._t._reference_backward(values),
            check=lambda result: self._run_checks(
                "backward", freq=freq, space=result, scale=1.0
            ),
        )

    def forward(self, space, scaling):
        """Supervised forward: space slab (or ``None`` for the retained
        buffer) -> verified packed values (per-shard list for distributed
        plans)."""
        from ..types import ScalingType

        t = self._t
        if space is None:
            # retries and the reference rung need the input host-side; the
            # retained device buffer is fetched once through the plan's own
            # accessor (engine-native relayout included)
            space_host = np.asarray(t.space_domain_data())
        else:
            space_host = np.asarray(space).reshape(
                t.dim_z, t.dim_y, t.dim_x
            )
        scale = (
            1.0 / float(t.global_size)
            if ScalingType(scaling) == ScalingType.FULL
            else 1.0
        )
        return self._supervise(
            direction="forward",
            attempt=lambda: t._forward_attempt(space, scaling),
            reference=lambda: t._reference_forward(space_host, scaling),
            check=lambda result: self._run_checks(
                "forward",
                freq=self._flat_values(result),
                space=space_host,
                scale=scale,
            ),
        )

    # ---- the ladder ---------------------------------------------------------

    def _supervise(self, *, direction, attempt, reference, check):
        t = self._t
        engine = t._engine
        strict = self.mode == "strict"
        failures: list = []
        # strict mode bypasses the breaker's short-circuit: its contract is
        # "attempt the primary engine, fail fast on the first bad verdict" —
        # a silent demotion to the reference would be exactly the recovery
        # strict exists to forbid (it still FEEDS the breaker below, so
        # strict episodes count toward the shared engine-health state)
        if strict or breaker.allow(engine):
            budget = 1 if strict else 1 + self.retries
            backoff = resolve_backoff_s()
            for i in range(budget):
                if i:
                    obs.counter("verify_retries_total", direction=direction).inc()
                    obs.trace.event(
                        "verify", what="retry", direction=direction, attempt=i
                    )
                    # backoff OUTSIDE any lock (the wisdom.py retry rule): a
                    # backing-off transform must not serialize other threads;
                    # jittered so concurrent retriers of one failed engine
                    # spread out instead of re-hitting it in lockstep
                    time.sleep(faults.backoff_s(backoff, i, self._jitter))
                bad = None
                try:
                    result = attempt()
                except RETRYABLE_ERRORS as e:
                    bad = f"execution: {faults.summarize(e)}"
                if bad is None:
                    try:
                        verdicts = check(result)
                    except CHECKER_ERRORS as e:
                        bad = f"checker: {faults.summarize(e)}"
                    else:
                        failed = [v for v in verdicts if v["verdict"] != "pass"]
                        if not failed:
                            breaker.record_success(engine)
                            return result
                        bad = "; ".join(
                            f"{v['check']} rel={v['rel']:.3g} > rtol={v['rtol']:.3g}"
                            for v in failed
                        )
                failures.append(bad)
                if strict:
                    obs.counter("verify_failures_total", direction=direction).inc()
                    breaker.record_failure(engine)
                    raise VerificationError(
                        f"strict verification failed on {direction}: {bad}"
                    )
            breaker.record_failure(engine)
            reason = failures[-1]
        else:
            reason = f"engine {engine!r} circuit breaker open"
            with faults.collecting(t._degradations):
                faults.record_degradation(
                    "verify_breaker_open",
                    reason,
                    engine=engine,
                    direction=direction,
                )
        # rung 3: the jnp.fft reference engine, itself verified
        obs.trace.event("verify", what="demote", direction=direction, engine=engine)
        try:
            result = reference()
            verdicts = check(result)
        except CHECKER_ERRORS + RETRYABLE_ERRORS as e:
            obs.counter("verify_failures_total", direction=direction).inc()
            raise VerificationError(
                f"{direction} failed verification and the reference rung could "
                f"not verify either ({faults.summarize(e)}); attempts: "
                f"{failures or [reason]}"
            ) from e
        failed = [v for v in verdicts if v["verdict"] != "pass"]
        if failed:
            obs.counter("verify_failures_total", direction=direction).inc()
            raise VerificationError(
                f"{direction} failed verification on engine {engine!r} AND on "
                f"the jnp.fft reference: "
                + "; ".join(f"{v['check']} rel={v['rel']:.3g}" for v in failed)
            )
        obs.counter("verify_recoveries_total", direction=direction).inc()
        with faults.collecting(t._degradations):
            faults.record_degradation(
                "verify_demoted",
                f"recovered via jnp.fft reference after: {reason}",
                engine=engine,
                direction=direction,
            )
        if direction == "backward":
            # the retained space buffer holds the PRIMARY engine's (failed)
            # result; a later forward(space=None) must not read it — replace
            # it with the verified recovery so the backward-then-forward(None)
            # idiom keeps working through a recovery
            t._retain_space(result)
        return result

    # ---- helpers ------------------------------------------------------------

    def _run_checks(self, direction, *, freq, space, scale):
        return checks.run_checks(
            direction=direction,
            freq=freq,
            space=space,
            triplets=self.triplets(),
            transform_type=self._t.transform_type,
            scale=scale,
            rtol=self.rtol,
        )

    def _flat_values(self, values):
        """Packed complex vector in triplet order: per-shard lists
        (distributed plans) concatenate in shard order, matching
        :meth:`triplets`."""
        if isinstance(values, (list, tuple)):
            return np.concatenate([np.asarray(v).reshape(-1) for v in values])
        return np.asarray(values).reshape(-1)

    def triplets(self):
        """Storage-order index rows aligned with the packed value order
        (concatenated across shards for distributed plans); cached — the
        decode is plan-constant."""
        if self._triplets is None:
            self._triplets = self._t._verify_triplets()
        return self._triplets

    def describe(self) -> dict:
        """JSON-plain record for the plan card's ``verification`` section."""
        return {
            "mode": self.mode,
            "checks": sorted(
                set(
                    checks.applicable_checks("backward", self._t.transform_type)
                )
                | set(checks.applicable_checks("forward", self._t.transform_type))
            ),
            "rtol": float(self.rtol),
            "retries": int(self.retries),
            "breaker": breaker.describe(self._t._engine),
        }
