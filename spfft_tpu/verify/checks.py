"""Algebraic self-verification checks (ABFT) for sparse-FFT results.

The transforms in this package are linear maps with cheap algebraic
invariants — exactly the property algorithm-based fault tolerance exploits
(AccFFT-style distributed FFT stacks lean on the same identities). PR 3's
guard mode only detects *non-finite* corruption; these checks close the
remaining hole: an accelerator or exchange that returns finite-but-wrong
data. Every check recomputes an invariant on the host (numpy, accumulated in
double precision) from the transform's *inputs* and compares it against the
engine's *output*:

- ``parseval`` — energy conservation: an unnormalized inverse DFT satisfies
  ``sum|space|^2 == N * sum|freq|^2`` because the space array's full spectrum
  is exactly the sparse value set (backward direction, C2C plans).
- ``dc`` — DC-component consistency: only the zero-frequency term survives
  summation over the grid, so ``sum(space) == N * F_(0,0,0)`` (backward) and
  ``F_(0,0,0) == scale * sum(space)`` (forward, when the plan's index set
  contains the origin).
- ``probe`` — random-probe linearity: the output at one randomly chosen site
  is a known linear functional of the input, recomputed directly from the DFT
  definition — ``O(num_values)`` host work for a backward probe,
  one separable ``O(N)`` contraction for a forward probe. The probe site is
  drawn deterministically from ``SPFFT_TPU_VERIFY_SEED`` and the plan
  geometry, so a failing run replays exactly.

Applicability (:func:`applicable_checks`): C2C plans verify both directions;
R2C plans verify the forward direction only — the backward R2C engine
*completes* the hermitian-redundant half-spectrum internally, so the supplied
values alone do not determine the invariants (documented in docs/details.md
"Silent-data-corruption detection & recovery").

Tolerances are relative (``SPFFT_TPU_VERIFY_RTOL``; default per dtype —
:func:`resolve_rtol`), normalized by the natural magnitude of each invariant
(the cancellation mass of a sum, not the possibly-tiny result), so the checks
flag corruption rather than benign floating-point noise.

The canonical check vocabulary (:data:`CHECKS`) is enforced both ways by
``programs/lint.py`` — every registered name implemented and documented, same
contract as ``obs.STAGES`` / ``faults.SITES`` / ``trace.EVENTS``. Fault site
``verify.check`` fires at the top of :func:`run_checks`, so the detector
itself is chaos-testable.
"""
from __future__ import annotations

import numpy as np

from .. import faults, knobs, obs
from ..errors import InvalidParameterError

VERIFY_ENV = "SPFFT_TPU_VERIFY"
VERIFY_RTOL_ENV = "SPFFT_TPU_VERIFY_RTOL"
VERIFY_SEED_ENV = "SPFFT_TPU_VERIFY_SEED"

# Canonical check vocabulary. Pure literal tuple (programs/lint.py reads it
# with ast.literal_eval, import-free) enforced both ways: every entry has an
# implementation registered in CHECK_FNS below and a row in docs/details.md.
CHECKS = (
    "parseval",
    "dc",
    "probe",
)

_TINY = 1e-300  # denominator floor: never divide by an exactly-zero scale


def resolve_mode(explicit=None) -> str:
    """The active verification mode: ``"off"``, ``"on"`` or ``"strict"``.

    An explicit ``verify=`` plan argument wins (``True``/``"1"``/``"on"`` ->
    on, ``"strict"`` -> strict, ``False``/``"0"``/``"off"``/``None``-env ->
    off), else the ``SPFFT_TPU_VERIFY`` env knob with the same values. An
    unrecognized value raises :class:`InvalidParameterError` naming it — a
    verification request must never be silently dropped."""
    value = knobs.get_str(VERIFY_ENV) if explicit is None else explicit
    if value in (False, None, "0", "off", ""):
        return "off"
    if value in (True, "1", "on"):
        return "on"
    if value == "strict":
        return "strict"
    raise InvalidParameterError(
        f"invalid verification mode {value!r}: expected 0/off, 1/on, or strict"
    )


def resolve_rtol(real_dtype) -> float:
    """Relative check tolerance: ``SPFFT_TPU_VERIFY_RTOL`` when set, else a
    default keyed on the *effective* execution precision — far above the
    engines' parity error (f32 transforms land ~1e-6 relative; f64 ~1e-14)
    and far below any real corruption. A plan declared ``float64`` while
    ``jax_enable_x64`` is off actually executes in f32 (JAX silently
    truncates), so it gets the f32 tolerance — a correct-but-f32 result must
    not be condemned as corruption."""
    rtol = knobs.get_float(VERIFY_RTOL_ENV)
    if rtol is not None:
        if rtol <= 0:
            raise InvalidParameterError(
                f"{VERIFY_RTOL_ENV} must be positive, got {rtol}"
            )
        return rtol
    if np.dtype(real_dtype) == np.dtype(np.float64):
        import jax

        if jax.config.read("jax_enable_x64"):
            return 1e-9
    return 1e-4


def applicable_checks(direction: str, transform_type) -> tuple:
    """The subset of :data:`CHECKS` valid for one host-facing call. C2C
    backward verifies all three; forward drops ``parseval`` (the space
    input's spectrum is not generally contained in the sparse index set);
    R2C backward verifies none (hermitian completion — module docstring)."""
    from ..types import TransformType

    r2c = TransformType(transform_type) == TransformType.R2C
    if direction == "backward":
        return () if r2c else ("parseval", "dc", "probe")
    return ("dc", "probe")


def _probe_rng(dims, num_values, direction: str):
    """Deterministic probe-site stream: seeded by ``SPFFT_TPU_VERIFY_SEED``
    plus the plan geometry and direction, so one plan's probe site is stable
    across calls and a failure replays exactly."""
    seed = knobs.get_int(VERIFY_SEED_ENV)
    return np.random.default_rng(
        [seed, *(int(d) for d in dims), int(num_values), direction == "forward"]
    )


def _verdict(check, measured, expected, denom, rtol):
    rel = abs(measured - expected) / max(float(denom), _TINY)
    return {
        "check": check,
        "verdict": "pass" if rel <= rtol else "fail",
        "rel": float(rel),
        "rtol": float(rtol),
        "measured": str(measured),
        "expected": str(expected),
    }


def _check_parseval(ctx):
    """Backward energy conservation: ``sum|space|^2 == N * sum|freq|^2``."""
    space, freq = ctx["space"], ctx["freq"]
    measured = float(np.sum(np.abs(space) ** 2))
    expected = float(space.size) * float(np.sum(np.abs(freq) ** 2))
    return _verdict("parseval", measured, expected, expected, ctx["rtol"])


def _origin_index(triplets) -> int | None:
    hit = np.where(~triplets.any(axis=1))[0]
    return int(hit[0]) if hit.size else None


def _check_dc(ctx):
    """DC consistency: only the zero-frequency term survives a grid sum."""
    space, freq, triplets = ctx["space"], ctx["freq"], ctx["triplets"]
    j = _origin_index(triplets)
    # tolerance scale: the cancellation mass of the grid sum (sqrt(N) * l2 ==
    # N * rms), not the possibly-zero DC value itself
    mass = np.sqrt(space.size) * float(np.linalg.norm(space.reshape(-1)))
    if ctx["direction"] == "backward":
        f0 = complex(freq[j]) if j is not None else 0.0
        measured = complex(np.sum(space))
        expected = float(space.size) * f0
        denom = max(abs(expected), mass)
    else:
        if j is None:
            return None  # origin not in the sparse set: nothing to compare
        scale = ctx["scale"]
        measured = complex(freq[j])
        expected = scale * complex(np.sum(space))
        denom = max(abs(expected), scale * mass)
    return _verdict("dc", measured, expected, denom, ctx["rtol"])


def _check_probe(ctx):
    """Random-probe linearity: recompute one output element from the DFT
    definition (backward: ``O(num_values)`` phase sum at one space site;
    forward: one separable contraction over the space grid)."""
    space, freq, triplets = ctx["space"], ctx["freq"], ctx["triplets"]
    if not len(freq):
        return None
    dz, dy, dx = space.shape
    rng = _probe_rng((dx, dy, dz), len(freq), ctx["direction"])
    kx = triplets[:, 0].astype(np.float64)
    ky = triplets[:, 1].astype(np.float64)
    kz = triplets[:, 2].astype(np.float64)
    if ctx["direction"] == "backward":
        zs, ys, xs = (
            int(rng.integers(dz)),
            int(rng.integers(dy)),
            int(rng.integers(dx)),
        )
        phase = 2j * np.pi * (kx * xs / dx + ky * ys / dy + kz * zs / dz)
        expected = complex(np.sum(freq * np.exp(phase)))
        measured = complex(space[zs, ys, xs])
        denom = max(abs(expected), float(np.sum(np.abs(freq))))
    else:
        j = int(rng.integers(len(freq)))
        scale = ctx["scale"]
        ex = np.exp(-2j * np.pi * kx[j] * np.arange(dx) / dx)
        ey = np.exp(-2j * np.pi * ky[j] * np.arange(dy) / dy)
        ez = np.exp(-2j * np.pi * kz[j] * np.arange(dz) / dz)
        expected = scale * complex(ez @ ((space @ ex) @ ey))
        measured = complex(freq[j])
        denom = max(abs(expected), scale * float(np.sum(np.abs(space))))
    return _verdict("probe", measured, expected, denom, ctx["rtol"])


# name -> implementation; programs/lint.py pins CHECKS == CHECK_FNS keys, the
# registry half of the both-ways vocabulary contract
CHECK_FNS = {
    "parseval": _check_parseval,
    "dc": _check_dc,
    "probe": _check_probe,
}


def run_checks(
    *,
    direction: str,
    freq,
    space,
    triplets,
    transform_type,
    scale: float = 1.0,
    rtol: float,
) -> list:
    """Run every applicable check for one host-facing call; returns the
    verdict rows (``check``/``verdict``/``rel``/``rtol``, JSON-plain).

    ``freq`` is the packed sparse value vector (input for backward, output
    for forward), ``space`` the ``(Z, Y, X)`` slab (output for backward,
    input for forward), ``triplets`` the storage-order index rows aligned
    with ``freq``'s packing order, ``scale`` the forward scaling factor
    (1/N under ``ScalingType.FULL``).

    Every verdict counts ``verify_checks_total{check,verdict}`` and lands as
    a ``verify`` flight-recorder event. Fault site ``verify.check`` fires
    first: a ``raise`` injection models the detector itself dying — the
    supervisor treats that as a failed verification episode (fail closed),
    never as a pass."""
    faults.site("verify.check")
    freq = np.asarray(freq).reshape(-1).astype(np.complex128)
    space = np.asarray(space).astype(np.complex128)
    triplets = np.asarray(triplets).reshape(-1, 3)
    ctx = {
        "direction": direction,
        "freq": freq,
        "space": space,
        "triplets": triplets,
        "scale": float(scale),
        "rtol": float(rtol),
    }
    verdicts = []
    for name in applicable_checks(direction, transform_type):
        row = CHECK_FNS[name](ctx)
        if row is None:
            continue
        obs.counter("verify_checks_total", check=name, verdict=row["verdict"]).inc()
        obs.trace.event(
            "verify",
            what="check",
            check=name,
            verdict=row["verdict"],
            direction=direction,
            rel=row["rel"],
        )
        verdicts.append(row)
    return verdicts
