"""spfft_tpu.verify — self-verifying transforms (ABFT) with recovery.

The layer that closes the loop from *observe* (:mod:`spfft_tpu.obs`) and
*inject* (:mod:`spfft_tpu.faults`) to *recover*. Three pieces:

1. **Checks** (:mod:`.checks`): opt-in per-transform algebraic verification
   — Parseval energy conservation, DC-component consistency, and a
   deterministic random-probe linearity check — armed via
   ``SPFFT_TPU_VERIFY=1|strict`` or ``verify=`` on any
   Transform/DistributedTransform/``Grid.create_transform``. The canonical
   :data:`CHECKS` vocabulary is enforced both ways by ``programs/lint.py``.
2. **Supervisor** (:mod:`.supervisor`): a retry -> demote-to-``jnp.fft``
   -> typed-:class:`~spfft_tpu.errors.VerificationError` recovery ladder
   around every verified ``backward``/``forward``, with every rung recorded
   in the plan card, the run metrics and the flight recorder.
3. **Circuit breaker** (:mod:`.breaker`): a process-global breaker that
   stops burning retry budget on an engine with K consecutive verified
   failures (half-open probe after a cooldown).

Guarantee (tested by ``tests/test_verify.py`` and ``./ci.sh verify``): with
verification armed, a transform either returns a result consistent with the
``jnp.fft`` reference or raises typed ``VerificationError`` — a silently
corrupted output is impossible. Disarmed (the default), the whole layer is
one falsy attribute check per call.
"""
from . import breaker  # noqa: F401
from .checks import (  # noqa: F401
    CHECK_FNS,
    CHECKS,
    VERIFY_ENV,
    VERIFY_RTOL_ENV,
    VERIFY_SEED_ENV,
    applicable_checks,
    resolve_mode,
    resolve_rtol,
    run_checks,
)
from .supervisor import (  # noqa: F401
    DEFAULT_BACKOFF_S,
    DEFAULT_RETRIES,
    RETRYABLE_ERRORS,
    VERIFY_BACKOFF_ENV,
    VERIFY_JITTER_SEED_ENV,
    VERIFY_RETRIES_ENV,
    Supervisor,
    jitter_rng,
    resolve_backoff_s,
    resolve_retries,
)
