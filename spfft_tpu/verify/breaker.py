"""Process-global engine circuit breaker for verified execution.

A dying accelerator path does not fail once — it fails every call, and
without a breaker every verified transform on it would burn the full
retry-then-demote ladder (N re-executions plus a reference run) before
recovering. The breaker bounds that: after ``SPFFT_TPU_VERIFY_BREAKER_K``
*consecutive* verified-failure episodes on one engine, the engine is **open**
for the whole process — verified transforms skip the primary engine entirely
and go straight to the ``jnp.fft`` reference rung. After
``SPFFT_TPU_VERIFY_BREAKER_COOLDOWN_S`` the breaker moves to **half-open**
and admits a single probe execution: a verified success closes it again
(transient fault healed), a failure re-opens it and restarts the cooldown.

State is per engine name (``mxu``, ``xla``, ``pencil2-mxu``, ...) and
process-global like the fault plane and the metrics registry: one wedged MXU
path should stop burning retry budget for *every* plan in the process, not
per plan object. Exposure: the ``verify_breaker_state{engine}`` gauge
(0 closed / 1 open / 2 half-open) rides in ``obs.snapshot()``,
``verify_breaker_trips_total{engine}`` counts trips, every transition lands
as a ``verify`` flight-recorder event, and :func:`describe` feeds the plan
card's schema-pinned ``verification.breaker`` section.
"""
from __future__ import annotations

import threading
import time

from .. import knobs, obs

BREAKER_K_ENV = "SPFFT_TPU_VERIFY_BREAKER_K"
BREAKER_COOLDOWN_ENV = "SPFFT_TPU_VERIFY_BREAKER_COOLDOWN_S"

DEFAULT_K = knobs.default(BREAKER_K_ENV)
DEFAULT_COOLDOWN_S = knobs.default(BREAKER_COOLDOWN_ENV)

_STATE_CODES = {"closed": 0, "open": 1, "half_open": 2}

_lock = threading.Lock()
_states: dict = {}  # engine -> {"state", "consecutive_failures", "opened_at", "trips"}


def threshold() -> int:
    """Consecutive verified failures that trip the breaker (floor 1)."""
    return knobs.get_int(BREAKER_K_ENV)


def cooldown_s() -> float:
    """Open -> half-open probe delay in seconds (0 probes immediately)."""
    return knobs.get_float(BREAKER_COOLDOWN_ENV)


def _entry(engine: str) -> dict:
    entry = _states.get(engine)
    if entry is None:
        entry = _states[engine] = {
            "state": "closed",
            "consecutive_failures": 0,
            "opened_at": 0.0,
            "trips": 0,
            # half-open admits exactly ONE in-flight probe: concurrent
            # verified callers racing the cooldown must not all hammer a
            # possibly-still-bad engine at once — losers fail fast to the
            # reference rung while the winner's verdict settles the state
            "probing": False,
            "probe_at": 0.0,
        }
    return entry


def _probe_takeover_s() -> float:
    """How long an in-flight half-open probe may go verdict-less before
    another caller may take over the slot. A probe whose carrier died
    without reporting (a non-retryable escape, a killed thread) must not
    wedge the breaker in half-open forever — the slot self-heals after the
    cooldown (floored at 1 s so a zero cooldown still admits exactly one
    probe per instant under a thread race)."""
    return max(1.0, cooldown_s())


def _transition(engine: str, entry: dict, state: str) -> None:
    entry["state"] = state
    obs.gauge("verify_breaker_state", engine=engine).set(_STATE_CODES[state])
    obs.trace.event("verify", what="breaker", engine=engine, state=state)


def allow(engine: str) -> bool:
    """Whether a verified transform may attempt the primary engine now.

    Closed -> yes. Open -> no until the cooldown elapses, then the breaker
    moves to half-open and THIS caller carries the probe. Half-open -> yes
    for exactly ONE caller at a time: while a probe is in flight every other
    caller is refused (straight to the reference rung) — N threads racing an
    elapsed cooldown must not multiply the probe load on an engine the
    breaker just declared unhealthy. The probe's verdict
    (:func:`record_success` / :func:`record_failure`) settles the state and
    releases the probe slot."""
    with _lock:
        entry = _entry(engine)
        now = time.monotonic()
        if entry["state"] == "open":
            if now - entry["opened_at"] >= cooldown_s():
                _transition(engine, entry, "half_open")
                entry["probing"] = True
                entry["probe_at"] = now
                return True
            return False
        if entry["state"] == "half_open":
            # a verdict-less probe (carrier escaped without record_*) frees
            # its slot after the takeover interval — see _probe_takeover_s
            if entry["probing"] and now - entry["probe_at"] < _probe_takeover_s():
                return False
            entry["probing"] = True
            entry["probe_at"] = now
            return True
        return True


def release_probe(engine: str) -> None:
    """Release a held half-open probe slot WITHOUT a verdict — the probe
    never actually executed (e.g. the serving layer's probe batch was fully
    deadline-shed before dispatch). The state stays half-open and the next
    :func:`allow` grants a fresh probe immediately instead of waiting out
    the takeover interval. No-op when no probe is held."""
    with _lock:
        _entry(engine)["probing"] = False


def record_success(engine: str) -> None:
    """A verified execution on ``engine`` passed its checks: reset the
    consecutive-failure count and close the breaker (half-open probe healed)."""
    with _lock:
        entry = _entry(engine)
        entry["consecutive_failures"] = 0
        entry["probing"] = False
        if entry["state"] != "closed":
            _transition(engine, entry, "closed")


def record_failure(engine: str) -> None:
    """One verified-failure episode (retries exhausted or a half-open probe
    failed): trips the breaker at :func:`threshold` consecutive failures —
    immediately when half-open, since the probe just proved the engine is
    still bad."""
    with _lock:
        entry = _entry(engine)
        entry["consecutive_failures"] += 1
        entry["probing"] = False
        tripped = (
            entry["state"] == "half_open"
            or entry["consecutive_failures"] >= threshold()
        )
        if tripped and entry["state"] != "open":
            entry["opened_at"] = time.monotonic()
            entry["trips"] += 1
            obs.counter("verify_breaker_trips_total", engine=engine).inc()
            _transition(engine, entry, "open")


def describe(engine: str) -> dict:
    """JSON-plain state of one engine's breaker (the plan card's
    ``verification.breaker`` section)."""
    with _lock:
        entry = _entry(engine)
        return {
            "engine": engine,
            "state": entry["state"],
            "consecutive_failures": int(entry["consecutive_failures"]),
            "trips": int(entry["trips"]),
            "threshold": threshold(),
        }


def snapshot() -> dict:
    """JSON-plain state of every engine the process has verified."""
    with _lock:
        return {engine: dict(entry) for engine, entry in _states.items()}


def reset() -> None:
    """Close every breaker and drop all counts (tests / fresh processes).
    The ``verify_breaker_state`` gauges are zeroed too, so a metrics
    snapshot never shows a tripped breaker that no longer exists."""
    with _lock:
        for engine in _states:
            obs.gauge("verify_breaker_state", engine=engine).set(
                _STATE_CODES["closed"]
            )
        _states.clear()
