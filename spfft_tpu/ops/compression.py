"""Sparse value <-> dense z-stick packing.

The analogue of the reference's compression component
(reference: src/compression/compression_host.hpp:50-92 and the CUDA kernels in
src/compression/gpu_kernels/compression_kernels.cu:40-130): *decompress* scatters the
caller's packed sparse values into a zeroed dense stick array, *compress* gathers them
back out with optional 1/(NxNyNz) scaling fused in.

Index arrays are static device constants (uploaded once at plan creation, like
CompressionGPU does, reference: src/compression/compression_gpu.hpp:54-57); the
scatter/gather itself is a single XLA op that fuses with neighbouring stages.
"""
from __future__ import annotations

import jax.numpy as jnp


def decompress(values, value_indices, num_sticks: int, dim_z: int):
    """Scatter packed values into a zeroed (num_sticks, dim_z) stick array.

    Zero-fill first is semantically load-bearing: slots without a caller value must be
    zero (reference zero-fills before scattering,
    src/compression/compression_host.hpp:76-92).
    """
    flat = jnp.zeros(num_sticks * dim_z, dtype=values.dtype)
    flat = flat.at[value_indices].set(values, mode="drop", unique_indices=True)
    return flat.reshape(num_sticks, dim_z)


def compress(sticks, value_indices, scale: float | None = None):
    """Gather packed values out of the stick array, optionally scaling.

    Reference: src/compression/compression_host.hpp:50-74 (compress with optional
    scaling fused into the gather loop).
    """
    values = sticks.reshape(-1).at[value_indices].get(mode="promise_in_bounds")
    if scale is not None and scale != 1.0:
        values = values * jnp.asarray(scale, dtype=sticks.real.dtype)
    return values
