"""Hermitian symmetry completion for R2C transforms.

For R2C the caller only supplies non-redundant frequencies (x restricted to
[0, Nx/2]); the omitted mirror values must be reconstructed before the backward
transform. Two completions exist, exactly as in the reference
(reference: src/symmetry/symmetry_host.hpp:40-97, docs/source/details.rst:31-40):

* *stick symmetry*: the z-column at (x=0, y=0) is self-mirrored along z.
* *plane symmetry*: the x=0 plane is mirrored along y (applied after the z transform,
  where the mirror relation is a plain pointwise conjugate in the space-z coordinate).

Both use the reference's nonzero-guarded two-pass discipline ("data may be conjugated
twice, but this way symmetry is applied independent of positive or negative
frequencies provided", src/symmetry/symmetry_host.hpp:49-50 / :74-75): an entry is only
written where its mirror source is nonzero, lower half first, then upper half reading
possibly-updated values.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _mirror(a, axis: int):
    """m[..., j, ...] = a[..., (n-j) % n, ...] along ``axis``."""
    n = a.shape[axis]
    idx = (-np.arange(n)) % n
    return jnp.take(a, jnp.asarray(idx), axis=axis)


def hermitian_fill_1d(a, axis: int):
    """Two-pass nonzero-guarded hermitian completion along ``axis``.

    Pass 1 writes targets [ceil(n/2), n-1] from sources in the lower half; pass 2
    writes targets [1, ceil(n/2)-1] from the (possibly updated) upper half. Index 0 is
    its own mirror and is never written. Matches the sequential in-place semantics of
    StickSymmetryHost / PlaneSymmetryHost (reference: src/symmetry/symmetry_host.hpp:47-90).
    """
    n = a.shape[axis]
    if n <= 1:
        return a
    shape = [1] * a.ndim
    shape[axis] = n
    j = jnp.arange(n).reshape(shape)
    upper_targets = j >= (n - n // 2)  # ceil(n/2) .. n-1 (incl. Nyquist for even n)
    lower_targets = (j >= 1) & (j < (n - n // 2))

    m = _mirror(a, axis)
    a = jnp.where(upper_targets & (m != 0), jnp.conj(m), a)
    m = _mirror(a, axis)
    a = jnp.where(lower_targets & (m != 0), jnp.conj(m), a)
    return a


def hermitian_fill_1d_pair(re, im, axis: int):
    """Pair-form (re, im) variant of :func:`hermitian_fill_1d` for engines that keep
    complex data as two real arrays (conj == negate imag; nonzero == either part)."""
    n = re.shape[axis]
    if n <= 1:
        return re, im
    shape = [1] * re.ndim
    shape[axis] = n
    j = jnp.arange(n).reshape(shape)
    upper_targets = j >= (n - n // 2)
    lower_targets = (j >= 1) & (j < (n - n // 2))

    for targets in (upper_targets, lower_targets):
        mre, mim = _mirror(re, axis), _mirror(im, axis)
        write = targets & ((mre != 0) | (mim != 0))
        re = jnp.where(write, mre, re)
        im = jnp.where(write, -mim, im)
    return re, im


def apply_stick_symmetry(sticks, zero_stick_id: int | None):
    """Complete the (0,0) z-stick along z, in the frequency domain before the z-FFT.

    ``sticks`` is (num_sticks, dim_z); ``zero_stick_id`` is the row holding xy key 0,
    or None if the transform has no (0,0) stick.
    Reference call site: src/execution/execution_host.cpp backward_z stage.
    """
    if zero_stick_id is None:
        return sticks
    row = hermitian_fill_1d(sticks[zero_stick_id], axis=0)
    return sticks.at[zero_stick_id].set(row)


def apply_plane_symmetry(grid):
    """Complete the x=0 plane along y, after the z transform.

    ``grid`` is (dim_z_local, dim_y, dim_x_freq) with z in space domain, x/y in
    frequency domain. After the z-FFT the 3D hermitian relation restricted to x=0
    reduces to ``g(z, -y, 0) = conj(g(z, y, 0))`` pointwise in z, which is what the
    reference exploits by applying plane symmetry post-exchange
    (reference: src/execution/execution_host.cpp backward_xy stage).
    """
    plane = hermitian_fill_1d(grid[:, :, 0], axis=1)
    return grid.at[:, :, 0].set(plane)
