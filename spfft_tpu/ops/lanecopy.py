"""Lane-aligned static copy plans: TPU-fast sparse pack/unpack.

The reference moves packed sparse values with per-element scatter/gather loops
(reference: src/compression/compression_host.hpp:50-92 and CUDA grid-stride kernels,
src/compression/gpu_kernels/compression_kernels.cu:40-130). Per-element dynamic
addressing is the one thing a TPU cannot do fast: XLA lowers it to a serialized
element gather (~20ns/element measured). What a TPU *can* do fast is gather whole
128-lane rows (~0.01ns/element measured, vectorized DMA path).

This module compiles an arbitrary static injective map ``dst[i] = src[m[i]]`` (with
holes) into row-granular work, exploiting that sparse-FFT value orders are
*piecewise contiguous* (values grouped by z-stick in z order — the layout plane-wave
callers use, reference: docs/source/details.rst:53):

1. each 128-lane destination block is decomposed into affine runs
   (``src - lane == const``); the k-th run of every block goes to pipe k, and
   pipe k only covers the blocks that *have* a k-th run (so fragmented tails cost
   work proportional to the total number of runs, not max-runs x blocks),
2. per run: the source window ``src0 .. src0+127`` is fetched by TWO whole-row
   gathers (rows ``src0//128`` and ``+1``),
3. lane alignment (``src0 % 128``) is resolved by grouping blocks by shift and
   taking one *static* 128-wide slice per shift group (<=128 static slices),
4. block order is restored with one more row-gather, and holes/run boundaries
   are applied with a static 0/1 mask; pipes covering at least
   ``SPFFT_TPU_COPY_DENSE_FRAC`` of the blocks are padded to FULL coverage
   (zero-row dummies) and combine by direct write / dense array add — the
   row-scatter-add lowering costs ~70 ns per covered row on TPU — while
   genuinely sparse tail pipes keep the row-granular scatter-add.

Everything is planned host-side at Transform creation; at runtime the copy is a
handful of fused row-gathers, slices, multiplies and row-granular scatter-adds —
no element scatter, no element gather. Falls back to ``None`` only when the order
is pathologically fragmented (> ``max_runs`` runs in one block; caller then uses
the plain scatter path).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs

LANE = 128


def _barrier_batching() -> None:
    """jax 0.4.3x compat shim: ``lax.optimization_barrier`` has no vmap
    batching rule there, so vmapping the copy pipelines (the batch-fused
    programs of :mod:`spfft_tpu.ir` vmap the composed stage graph) fails
    with ``NotImplementedError`` even though the barrier is semantically the
    identity. Register the identity rule once — per-operand batch dims pass
    through untouched, exactly what later jax versions ship upstream."""
    try:
        from jax._src.lax import lax as _lax
        from jax.interpreters import batching

        prim = _lax.optimization_barrier_p
    except (ImportError, AttributeError):  # newer jax moved it: rule ships
        return
    if prim in batching.primitive_batchers:
        return

    def _rule(args, dims):
        return prim.bind(*args), list(dims)

    batching.primitive_batchers[prim] = _rule


_barrier_batching()


@dataclasses.dataclass(frozen=True)
class _RunPipe:
    """One affine-run pipeline over a subset of destination blocks: row indices
    (shift-sorted), shift group sizes, inverse row order, the 0/1 mask, and the
    destination block ids this pipe covers (None = all blocks, in order).

    When every row's valid lanes form one contiguous range, the mask is stored
    as (starts, ends) int32 vectors and generated in-register at apply time
    (iota compares) instead of as a (Rk, LANE) f32 constant — the constant
    costs ~0.5 KB/row of HBM read traffic on every apply (~23 MB per part at
    256^3/15%). ``mask`` is None in that case."""

    rows_sorted: np.ndarray  # (Rk,) int32 source row per covered block, shift-sorted
    shift_counts: tuple  # len-128 tuple of group sizes
    inv_order: np.ndarray  # (Rk,) int32 restoring natural covered-block order
    mask: np.ndarray | None  # (Rk, LANE) float32 0/1, or None = use starts/ends
    block_ids: np.ndarray | None  # (Rk,) int32 destination blocks, or None = all
    mask_starts: np.ndarray | None = None  # (Rk,) int32 first valid lane
    mask_ends: np.ndarray | None = None  # (Rk,) int32 one past last valid lane


@dataclasses.dataclass(frozen=True)
class CopyPlan:
    """Compiled plan for ``out[i] = src[m[i]]`` (holes -> 0) with out length D."""

    num_dst: int  # D (padded to LANE multiple)
    num_src: int  # logical source length
    src_rows: int  # rows in the padded (src_rows, LANE) source view
    pipes: tuple  # tuple of _RunPipe

    @staticmethod
    def build(src_of_dst: np.ndarray, num_src: int, max_runs: int = 64):
        """Build a plan from the per-destination source index (-1 = hole), or return
        None if any destination block needs more than ``max_runs`` affine runs
        (work scales with the *total* run count, so the cap is just a sanity bound
        against pathological per-element fragmentation)."""
        m = np.asarray(src_of_dst, dtype=np.int64)
        D = ((m.size + LANE - 1) // LANE) * LANE
        pad = np.full(D - m.size, -1, dtype=np.int64)
        m = np.concatenate([m, pad])
        R = D // LANE
        blocks = m.reshape(R, LANE)
        lanes = np.arange(LANE)

        base = blocks - lanes[None, :]
        filled = blocks >= 0

        # per-pipe sparse assembly: pipe k holds the k-th run of each block that
        # has one — (block id, run base, lane mask) triples
        per_pipe: list[list] = []
        for r in range(R):
            if not filled[r].any():
                continue
            vals = np.unique(base[r][filled[r]])
            if vals.size > max_runs:
                return None
            while len(per_pipe) < vals.size:
                per_pipe.append([])
            for k, v in enumerate(vals):
                per_pipe[k].append((r, v, (base[r] == v) & filled[r]))

        # Pad well-covered pipes to FULL block coverage: a full pipe combines
        # by direct write / dense array add, while a partial pipe needs the
        # row-scatter-add path, whose TPU lowering is catastrophically slower
        # per covered row (measured ~70 ns/row at 512^3/15%, where pipe 0's
        # 69% coverage made decompress alone cost 19.3 ms of a 56 ms
        # backward; the padded direct write moves the same data at ~row-gather
        # bandwidth). Dummy entries gather the zero lead row under an all-zero
        # mask, so padding costs one extra gathered row each — worth it down
        # to low coverage fractions (``SPFFT_TPU_COPY_DENSE_FRAC``, default
        # 0.1); genuinely sparse tail pipes keep the scatter-add.
        dense_frac = knobs.get_float("SPFFT_TPU_COPY_DENSE_FRAC")
        no_lanes = np.zeros(LANE, dtype=bool)
        for k, entries in enumerate(per_pipe):
            covered = {e[0] for e in entries}
            if len(covered) == R or len(covered) < dense_frac * R:
                continue
            entries.extend((r, -LANE, no_lanes) for r in range(R) if r not in covered)
            entries.sort(key=lambda e: e[0])

        pipes = []
        # source view: one zero lead row (handles negative run bases: a run that
        # starts mid-block has base in (-LANE, 0)), the data, two zero tail rows
        # (window overhang); mask guards every out-of-run lane.
        src_rows = 1 + (num_src + LANE - 1) // LANE + 2
        for k, entries in enumerate(per_pipe):
            block_ids = np.asarray([e[0] for e in entries], dtype=np.int32)
            start = np.asarray([e[1] for e in entries], dtype=np.int64) + LANE
            mask = np.stack([e[2] for e in entries])
            assert (start >= 0).all()
            rowA = (start // LANE).astype(np.int32)
            shift = (start % LANE).astype(np.int32)
            order = np.argsort(shift, kind="stable").astype(np.int32)
            counts = tuple(int((shift == t).sum()) for t in range(LANE))
            full = block_ids.size == R and (block_ids == np.arange(R)).all()
            # range-form mask when every row's valid lanes are one contiguous
            # run (the common case; disjoint same-base segments are rare)
            nval = mask.sum(axis=1)
            firsts = mask.argmax(axis=1)
            lasts = LANE - 1 - mask[:, ::-1].argmax(axis=1)
            contiguous = bool(((lasts - firsts + 1 == nval) | (nval == 0)).all())
            if contiguous:
                starts = np.where(nval > 0, firsts, 0).astype(np.int32)
                ends = np.where(nval > 0, lasts + 1, 0).astype(np.int32)
                mask_arr, mstarts, mends = None, starts, ends
            else:
                mask_arr, mstarts, mends = mask.astype(np.float32), None, None
            pipes.append(
                _RunPipe(
                    rows_sorted=rowA[order],
                    shift_counts=counts,
                    inv_order=np.argsort(order).astype(np.int32),
                    mask=mask_arr,
                    block_ids=None if full else block_ids,
                    mask_starts=mstarts,
                    mask_ends=mends,
                )
            )
        return CopyPlan(num_dst=D, num_src=num_src, src_rows=src_rows, pipes=tuple(pipes))

    # -- runtime -----------------------------------------------------------------

    def source_view(self, flat):
        """Pad a flat (num_src,) array into the (src_rows, LANE) gatherable view:
        one zero lead row, the data, zero tail rows."""
        tail = (self.src_rows - 1) * LANE - flat.shape[0]
        return jnp.concatenate(
            [
                jnp.zeros(LANE, dtype=flat.dtype),
                flat,
                jnp.zeros(tail, dtype=flat.dtype),
            ]
        ).reshape(self.src_rows, LANE)

    def _apply_stacked(self, src3, dtype):
        """The copy pipeline on a stacked (B, src_rows, LANE) source ->
        (B, num_dst/LANE, LANE). Used only by the opt-in pair-copy path;
        the default single-part path is :meth:`_apply_single`, an axis-shifted
        twin (B=1 batch dims penalize the TPU gather lowering ~36%). ANY
        change to the miscompile barrier or mask semantics MUST be mirrored
        between the two bodies — tests/test_lanecopy_shapes.py pins both."""
        B = src3.shape[0]
        out = None
        for pipe in self.pipes:
            rows = jnp.asarray(pipe.rows_sorted)
            if pipe.shift_counts[0] == pipe.rows_sorted.size:
                # All runs lane-aligned (shift 0, the layout plan-time stick
                # rotation engineers — see execution_mxu's alignment rotations):
                # the whole shift machinery collapses to ONE row gather — no
                # second-window concat, no per-shift slices, no barrier, no
                # reorder (shift-sort of all-zeros is the natural order).
                aligned = jnp.take(src3, rows, axis=1)
            else:
                w = jnp.concatenate(
                    [jnp.take(src3, rows, axis=1), jnp.take(src3, rows + 1, axis=1)],
                    axis=2,
                )  # (B, Rk, 2*LANE), covered blocks in shift order
                pieces = []
                off = 0
                for t, c in enumerate(pipe.shift_counts):
                    if c == 0:
                        continue
                    pieces.append(
                        jax.lax.slice(w, (0, off, t), (B, off + c, t + LANE))
                    )
                    off += c
                # The barrier is a MISCOMPILE workaround, not an optimization: on
                # (vmap support for it is registered below — _barrier_batching)
                # the TPU backend (v5e, 2026-07), fusing the concat of >= 2 pieces
                # lane-shifted by different amounts out of one buffer produces
                # wrong values when the piece sublane counts are below the 8-row
                # f32 tile (observed at Rk=2: two (1, 128) slices at shifts 5/77
                # of a (2, 256) buffer concat to garbage; each slice alone is
                # correct). Keeping the pieces materialized before the concat
                # sidesteps the bad fusion on every backend at negligible cost.
                if len(pieces) > 1:
                    pieces = list(jax.lax.optimization_barrier(tuple(pieces)))
                aligned = jnp.concatenate(pieces, axis=1)
                aligned = jnp.take(aligned, jnp.asarray(pipe.inv_order), axis=1)
            if pipe.mask is None:
                # in-register range mask: two compares against iota instead of
                # reading a (Rk, LANE) f32 constant from HBM
                lane = jnp.arange(LANE, dtype=jnp.int32)[None, None, :]
                lo = jnp.asarray(pipe.mask_starts)[None, :, None]
                hi = jnp.asarray(pipe.mask_ends)[None, :, None]
                contrib = jnp.where((lane >= lo) & (lane < hi), aligned, 0)
            else:
                # where (not multiply): holes must be exact zeros even when the
                # source carries inf/NaN next to a run boundary, matching the
                # range path's semantics
                contrib = jnp.where(jnp.asarray(pipe.mask > 0)[None], aligned, 0)
            if pipe.block_ids is None:
                out = contrib if out is None else out + contrib
            else:
                if out is None:
                    out = jnp.zeros((B, self.num_dst // LANE, LANE), dtype=dtype)
                # row-granular scatter-add into the covered blocks (unique ids)
                out = out.at[:, jnp.asarray(pipe.block_ids)].add(
                    contrib, unique_indices=True, mode="drop"
                )
        if out is None:
            out = jnp.zeros((B, self.num_dst // LANE, LANE), dtype=dtype)
        return out

    def _apply_single(self, src2, dtype):
        """The copy pipeline on an unbatched (src_rows, LANE) source ->
        (num_dst/LANE, LANE). Same stages as :meth:`_apply_stacked` minus the
        leading batch dim, which XLA:TPU's gather lowering penalizes ~36%
        even at B=1 (measured at 512^3 row counts, BASELINE.md round 4 —
        the same slow-lowering class as the rejected pair-copy stacking)."""
        out = None
        for pipe in self.pipes:
            rows = jnp.asarray(pipe.rows_sorted)
            if pipe.shift_counts[0] == pipe.rows_sorted.size:
                aligned = jnp.take(src2, rows, axis=0)
            else:
                w = jnp.concatenate(
                    [jnp.take(src2, rows, axis=0), jnp.take(src2, rows + 1, axis=0)],
                    axis=1,
                )  # (Rk, 2*LANE), covered blocks in shift order
                pieces = []
                off = 0
                for t, c in enumerate(pipe.shift_counts):
                    if c == 0:
                        continue
                    pieces.append(jax.lax.slice(w, (off, t), (off + c, t + LANE)))
                    off += c
                # miscompile workaround — see _apply_stacked
                if len(pieces) > 1:
                    pieces = list(jax.lax.optimization_barrier(tuple(pieces)))
                aligned = jnp.concatenate(pieces, axis=0)
                aligned = jnp.take(aligned, jnp.asarray(pipe.inv_order), axis=0)
            if pipe.mask is None:
                lane = jnp.arange(LANE, dtype=jnp.int32)[None, :]
                lo = jnp.asarray(pipe.mask_starts)[:, None]
                hi = jnp.asarray(pipe.mask_ends)[:, None]
                contrib = jnp.where((lane >= lo) & (lane < hi), aligned, 0)
            else:
                contrib = jnp.where(jnp.asarray(pipe.mask > 0), aligned, 0)
            if pipe.block_ids is None:
                out = contrib if out is None else out + contrib
            else:
                if out is None:
                    out = jnp.zeros((self.num_dst // LANE, LANE), dtype=dtype)
                out = out.at[jnp.asarray(pipe.block_ids)].add(
                    contrib, unique_indices=True, mode="drop"
                )
        if out is None:
            out = jnp.zeros((self.num_dst // LANE, LANE), dtype=dtype)
        return out

    def apply(self, flat):
        """Execute the copy: flat (num_src,) -> (num_dst/LANE, LANE)."""
        return self._apply_single(self.source_view(flat), flat.dtype)

    def apply_pair(self, flat_a, flat_b):
        """Execute the copy on two same-shaped flats with ONE gather per pipe.

        The parts ride as a stacked (2, src_rows, LANE) source, so every row
        gather, lane-shift slice, mask and scatter-add is issued once for
        both, halving the copy's descriptor count vs two :meth:`apply` calls.
        Measured SLOWER on chip despite that (8.44 vs 6.88 ms/pair at the
        256^3/15% headline, bench_results/round3_onchip.json): the leading
        batch dim pushes XLA:TPU off its fast whole-row-gather lowering —
        the same failure mode as the earlier vmap-batched probe
        (docs/ROADMAP.md item 1a). Hence OFF by default; semantics are
        exactly two independent applies either way, and
        ``SPFFT_TPU_PAIR_COPY=1`` (read at trace time) opts back in for A/B.
        Returns the pair of (num_dst/LANE, LANE) outputs.
        """
        if not pair_copy_enabled():
            return self.apply(flat_a), self.apply(flat_b)
        src3 = jnp.stack([self.source_view(flat_a), self.source_view(flat_b)])
        out = self._apply_stacked(src3, flat_a.dtype)
        return out[0], out[1]


def pair_copy_enabled() -> bool:
    """Whether :meth:`CopyPlan.apply_pair` stacks the parts into one gather
    per pipe. Default OFF — measured ~23% slower end-to-end on chip (see
    :meth:`CopyPlan.apply_pair`); ``SPFFT_TPU_PAIR_COPY=1`` opts in for A/B.
    Semantics are identical either way."""
    return knobs.get_bool("SPFFT_TPU_PAIR_COPY")


def build_decompress_plan(value_indices: np.ndarray, num_slots: int, num_values: int, max_runs: int = 64):
    """Plan scattering packed values into stick slots: dst = slot, src = value pos."""
    src_of_dst = np.full(num_slots, -1, dtype=np.int64)
    src_of_dst[np.asarray(value_indices, dtype=np.int64)] = np.arange(num_values)
    return CopyPlan.build(src_of_dst, num_values, max_runs)


def build_compress_plan(value_indices: np.ndarray, num_slots: int, max_runs: int = 64):
    """Plan gathering packed values out of stick slots: dst = value pos, src = slot."""
    return CopyPlan.build(np.asarray(value_indices, dtype=np.int64), num_slots, max_runs)


def plan_alignment_rotations(value_indices, num_sticks: int, dim_z: int, keep_zero=()):
    """Per-stick cyclic z-rotations that lane-align the packed-value layout.

    The engine's internal stick table may hold stick s's frequency-z axis under
    any cyclic rotation ``delta_s``: by the DFT rotation theorem this only costs
    a unit-magnitude per-(stick, k) phase on the space side of the z-DFT, one
    fused elementwise multiply. Choosing ``delta_s`` so the stick's first
    packed value lands at a slot congruent to its value position mod LANE makes
    every affine run of BOTH copy plans lane-aligned (shift 0) whenever the
    caller's per-stick z order is cyclically contiguous (the plane-wave layout,
    reference: docs/source/details.rst:53) — ``CopyPlan.apply`` then collapses
    to single row gathers (measured 5.7 ms -> ~1 ms pack/unpack at 256^3/15%
    spherical, BASELINE.md).

    Returns ``(delta, rotated_indices)`` — the (num_sticks,) rotation table and
    the value->slot map under the rotated layout — or ``None`` when alignment
    cannot help: ``dim_z`` not a LANE multiple (run bases shift at the stick
    wrap), empty plan, or a caller order that is not predominantly
    stick-contiguous (>= 90% of adjacent value pairs must advance z by one
    within a stick; otherwise runs fragment regardless of rotation and the
    phase multiply would be pure cost). Sticks in ``keep_zero`` (the hermitian
    (0, 0) stick, whose in-place frequency-domain fill assumes the standard
    layout) stay unrotated.
    """
    vi = np.asarray(value_indices, dtype=np.int64)
    Z, S = int(dim_z), int(num_sticks)
    if S == 0 or vi.size == 0 or Z % LANE != 0:
        return None
    stick = vi // Z
    z = vi % Z
    # alignment-benefit predictor: fraction of adjacent pairs that continue a
    # cyclically ascending run within one stick
    same = stick[1:] == stick[:-1]
    if same.sum() == 0:
        return None
    steps = ((z[1:] - z[:-1]) % Z == 1) & same
    if 10 * int(steps.sum()) < 9 * int(same.sum()):
        return None
    uniq, first_idx = np.unique(stick, return_index=True)
    target = first_idx % LANE  # slot offset making run bases ≡ 0 (mod LANE)
    delta = np.zeros(S, dtype=np.int64)
    delta[uniq] = (target - z[first_idx]) % Z
    for s in keep_zero:
        if s is not None and 0 <= int(s) < S:
            delta[int(s)] = 0
    if not delta.any():
        return None
    rotated = stick * Z + (z + delta[stick]) % Z
    return delta, rotated.astype(np.int64)


def alignment_phase_tables(deltas, dim_z: int, real_dtype):
    """(cos, sin) tables for the alignment rotations: shape ``deltas.shape +
    (dim_z,)`` with ``theta[..., s, k] = 2 pi delta_s k / Z``. Single source
    for every engine's table build (the sign convention lives in
    :func:`apply_alignment_phase`)."""
    deltas = np.asarray(deltas)
    theta = 2.0 * np.pi * deltas[..., None] * np.arange(int(dim_z)) / int(dim_z)
    return np.cos(theta).astype(real_dtype), np.sin(theta).astype(real_dtype)


PHASE_TABLE_LIMIT_MB_ENV = "SPFFT_TPU_PHASE_TABLE_MB"


def alignment_phase_rep(deltas, dim_z: int, real_dtype):
    """Size-aware phase representation for a plan's rotation vector.

    Below the budget (``SPFFT_TPU_PHASE_TABLE_MB``, default 64): ``("table",
    cos, sin)`` with host-precomputed f64-accurate numpy tables — the fast
    path, embedded once per program. Above it: ``("delta", deltas_i32,
    dim_z)`` and the tables are generated in-trace at apply time — a (S, Z)
    cos/sin table pair at 512^3 C2C is 366 MB of embedded HLO constants,
    which overflowed the tunnel's compile transport (HTTP 413) and costs a
    full HBM read per apply; the in-trace form embeds only the (S,) rotation
    vector. :func:`phase_rep_tables` consumes either form.
    """
    deltas = np.asarray(deltas)
    bytes_ = 2 * deltas.size * int(dim_z) * np.dtype(real_dtype).itemsize
    limit = knobs.get_int(PHASE_TABLE_LIMIT_MB_ENV) * (1 << 20)
    # the in-trace form's exactness requires delta*k < 2^31 (int32 products)
    if bytes_ <= limit or int(dim_z) * int(dim_z) >= 2**31:
        return ("table", *alignment_phase_tables(deltas, dim_z, real_dtype))
    return ("delta", deltas.astype(np.int32), int(dim_z))


PHASE_DEVICE_LIMIT_MB_ENV = "SPFFT_TPU_PHASE_DEVICE_MB"


def phase_rep_operands(rep, real_dtype, put):
    """Device-resident (cos, sin) operand pair for a phase rep, or ``()``.

    Operands enter the jitted programs as ARGUMENTS, not embedded constants,
    so they inflate neither the compiled program nor its compile transport —
    the 512^3 table pair (366 MB) that overflowed the tunnel as an HLO
    constant is one ``device_put`` here, and the per-apply in-trace cos/sin
    regeneration it forced disappears. Table reps convert directly; delta
    reps materialize their tables up to the HBM budget
    (``SPFFT_TPU_PHASE_DEVICE_MB``, default 2048) and keep the in-trace
    fallback above it. Callers pass the pair through their jit boundary
    (``phase=`` on the engine's trace entry points) and
    :func:`phase_rep_tables` stays the closure fallback for paths that do
    not thread operands.
    """
    if rep is None:
        return ()
    limit = knobs.get_int(PHASE_DEVICE_LIMIT_MB_ENV) * (1 << 20)
    if limit <= 0:  # <= 0 disables operands entirely (A/B escape hatch)
        return ()
    if rep[0] == "table":
        return (put(rep[1]), put(rep[2]))
    _, deltas, dim_z = rep
    bytes_ = 2 * deltas.size * int(dim_z) * np.dtype(real_dtype).itemsize
    if bytes_ > limit:
        return ()
    cos, sin = alignment_phase_tables(deltas, dim_z, real_dtype)
    return (put(cos), put(sin))


def phase_rep_tables(rep, real_dtype):
    """Traced (cos, sin) tables from an :func:`alignment_phase_rep` value.

    The in-trace form reduces ``delta * k`` mod Z in exact int32 arithmetic
    BEFORE the float cast, so theta stays in [0, 2 pi) and f32 cos/sin keep
    full precision (naive f32 ``cos(2 pi delta k / Z)`` at delta*k ~ 2.6e5
    rad loses ~4 digits). Exactness bound: delta, k < Z and Z^2 < 2^31.
    """
    if rep[0] == "table":
        return jnp.asarray(rep[1]), jnp.asarray(rep[2])
    _, deltas, dim_z = rep
    k = jnp.arange(dim_z, dtype=jnp.int32)
    m = (jnp.asarray(deltas)[:, None] * k[None, :]) % dim_z
    theta = (2.0 * np.pi / dim_z) * m.astype(real_dtype)
    return jnp.cos(theta), jnp.sin(theta)


def phase_rep_tables_at(rep, idx, real_dtype):
    """Per-shard (cos, sin) from a rep whose leading axis is the shard: the
    table form indexes the stacked tables at (traced) ``idx``; the compact
    form slices the (P, S) rotation matrix and generates that shard's tables
    in-trace. Used by SPMD engines that close over the full rep and resolve
    their shard inside the traced program (the pencil engines)."""
    if rep[0] == "table":
        return jnp.asarray(rep[1])[idx], jnp.asarray(rep[2])[idx]
    _, deltas, dim_z = rep
    return phase_rep_tables(("delta", jnp.asarray(deltas)[idx], dim_z), real_dtype)


def apply_alignment_phase(re, im, cos_t, sin_t, sign: int):
    """Fused multiply of the (re, im) pair by ``e^{sign * i theta}``.

    ``sign=-1`` after the backward z matmul (undo the rotation on the space
    side), ``sign=+1`` before the forward z matmul (enter the rotated layout).
    THE sign convention for the whole rotation scheme — every engine calls
    this instead of hand-writing the complex multiply, so a convention change
    is one edit."""
    if sign < 0:
        return re * cos_t + im * sin_t, im * cos_t - re * sin_t
    return re * cos_t - im * sin_t, im * cos_t + re * sin_t
