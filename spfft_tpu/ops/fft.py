"""DFT stages for the MXU engine.

The reference computes its 1D FFT batches with FFTW/cuFFT plans
(reference: src/fft/transform_1d_host.hpp:50-235, src/fft/transform_1d_gpu.hpp,
src/fft/transform_2d_gpu.hpp). On TPU the systolic array (MXU) turns a batched
length-N DFT into a single (batch, N) @ (N, N) matmul — O(N^2) flops instead of
O(N log N), but at 1-2 orders of magnitude higher flop rate than XLA's generic FFT,
a net win for the N <= ~1024 extents plane-wave grids use. Two further MXU-only
tricks this module exploits:

* **permutation folding**: any static permutation / padding of the input axis can be
  folded into the DFT matrix rows for free (the ``row_perm``/``num_rows`` hook on
  :func:`c2c_matrix` — the designed fusion point for the distributed exchange unpack,
  the analogue of the reference's unpack kernels,
  reference: src/transpose/gpu_kernels/buffered_kernels.cu),
* **scale folding**: the forward 1/(NxNyNz) scaling rides the matrix constants
  (reference applies it in the compress loop, src/compression/compression_host.hpp:63).

Complex data is carried as (re, im) pairs of real arrays; each complex DFT contraction
runs as 3 real matmuls by default (Gauss's trick, see :func:`complex_matmul`; R2C/C2R: 2).
Matmul precision is a plan-level knob (``resolve_precision``):
``"highest"`` (default, 6-pass bf16, ~2e-7 single-pair oracle error — the 1e-6
parity bar) or ``"high"`` (3-pass bf16, ~3e-5, measured 16% faster end-to-end at
the 256^3/15% headline — the accuracy/speed dial analogous to the reference's
*_FLOAT exchange variants, reference: include/spfft/types.h:41-47; full matrix
in BASELINE.md ``precision_oracle_matrix_128``).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import knobs

_PRECISION = jax.lax.Precision.HIGHEST


def resolve_precision(precision) -> jax.lax.Precision:
    """Map a user-facing precision name to a lax.Precision."""
    if isinstance(precision, jax.lax.Precision):
        return precision
    table = {
        "highest": jax.lax.Precision.HIGHEST,
        "high": jax.lax.Precision.HIGH,
        "default": jax.lax.Precision.DEFAULT,
    }
    key = str(precision).lower()
    if key not in table:
        from ..errors import InvalidParameterError

        raise InvalidParameterError(
            f"unknown matmul precision {precision!r} (expected one of {sorted(table)})"
        )
    return table[key]


def c2c_matrix(n: int, sign: int, scale: float = 1.0, row_perm=None, num_rows=None):
    """(rows, n) DFT matrix W[j, k] = scale * exp(sign * 2i pi p(j) k / n).

    ``row_perm`` (optional) maps matrix row j to logical input index p(j); entries
    < 0 produce zero rows (padding slots). This is the permutation-folding hook.
    """
    if row_perm is None:
        row_perm = np.arange(n)
    row_perm = np.asarray(row_perm, dtype=np.int64)
    if num_rows is not None and num_rows != row_perm.size:
        if num_rows < row_perm.size:
            from ..errors import InvalidParameterError

            raise InvalidParameterError("num_rows smaller than row_perm")
        row_perm = np.concatenate(
            [row_perm, np.full(num_rows - row_perm.size, -1, dtype=np.int64)]
        )
    k = np.arange(n)
    w = scale * np.exp(sign * 2j * np.pi * np.outer(row_perm, k) / n)
    w[row_perm < 0] = 0.0
    return w


def r2c_matrices(n: int, scale: float = 1.0):
    """Real matrix pair (A, B) for the forward R2C x-stage: F = f@A + i f@B,
    F[k] = scale * sum_l f[l] exp(-2i pi k l / n), k in [0, n//2]."""
    nf = n // 2 + 1
    l, k = np.arange(n), np.arange(nf)
    theta = 2 * np.pi * np.outer(l, k) / n
    return scale * np.cos(theta), -scale * np.sin(theta)


def c2r_matrices(n: int, scale: float = 1.0):
    """Real matrix pair (A, B) for the backward C2R x-stage:
    f = Fr@A - Fi@B, the unnormalized inverse of the half spectrum with hermitian
    weights c_k (1 for k=0 and the even-n Nyquist bin, else 2)."""
    nf = n // 2 + 1
    k, l = np.arange(nf), np.arange(n)
    c = np.full(nf, 2.0)
    c[0] = 1.0
    if n % 2 == 0:
        c[-1] = 1.0
    theta = 2 * np.pi * np.outer(k, l) / n
    return scale * (c[:, None] * np.cos(theta)), scale * (c[:, None] * np.sin(theta))


TWIDDLE_BF16_ENV = "SPFFT_TPU_TWIDDLE_BF16"


def twiddle_bf16_enabled() -> bool:
    """The bf16-twiddle mixed-precision knob: store the MXU engines' DFT
    stage matrices in bfloat16 (halving their HBM footprint and letting the
    MXU run mixed bf16xf32 contractions) while activations stay f32.
    f32 plans only — f64 plans ignore the knob (a bf16 twiddle under an f64
    contract would silently discard the precision the caller asked for).
    Off by default; under ``policy="tuned"`` the variant is an autotuner
    candidate (``tuning/candidates.py`` ``mxu/bf16-twiddle``) so the
    accuracy/speed trade is measured, not guessed."""
    return knobs.get_bool(TWIDDLE_BF16_ENV)


def twiddle_dtype(real_dtype):
    """The storage dtype of DFT stage matrices for an engine running at
    ``real_dtype`` — bfloat16 under the bf16-twiddle knob (f32 plans only),
    else the engine dtype."""
    if np.dtype(real_dtype) == np.dtype(np.float32) and twiddle_bf16_enabled():
        return jnp.bfloat16
    return real_dtype


def matrix_pair(w, real_dtype):
    """Complex matrix -> (re, im) real pair in the engine's twiddle dtype
    (the engine dtype, or bfloat16 under SPFFT_TPU_TWIDDLE_BF16)."""
    dt = twiddle_dtype(real_dtype)
    return w.real.astype(dt), w.imag.astype(dt)


def zy_stage_matrices(dim_z: int, dim_y: int, total_size: int, real_dtype):
    """The z/y DFT matrix constants every MXU engine needs: backward z and y,
    forward y, and the forward-z table with the FULL 1/(NxNyNz) scaling folded
    in (reference applies it in the compress loop,
    src/compression/compression_host.hpp:63). Returns (wz_b, wy_b, wy_f, wz_f)."""
    from ..types import ScalingType

    rt = real_dtype
    wz_f = {
        ScalingType.NONE: matrix_pair(c2c_matrix(dim_z, -1), rt),
        ScalingType.FULL: matrix_pair(c2c_matrix(dim_z, -1, scale=1.0 / total_size), rt),
    }
    return (
        matrix_pair(c2c_matrix(dim_z, +1), rt),
        matrix_pair(c2c_matrix(dim_y, +1), rt),
        matrix_pair(c2c_matrix(dim_y, -1), rt),
        wz_f,
    )


def compact_x_extent(num_unique: int, dim_x_freq: int) -> int:
    """Padded active-x extent for the uniqueXIndices compaction.

    Pads to the ``SPFFT_TPU_XPAD`` quantum (default 8, the f32 sublane tile —
    ragged extents defeat XLA's tiling, measured 2.7x slower at 256^3/15%),
    capped at the full extent. Compaction is applied even for near-dense active
    sets: measured on v5e, A=176 beats the full 256 extent by 12% at 256^3/15%
    spherical and A=88 beats 128 at 128^3 (the intermediate-plane HBM traffic
    shrinks with A; an earlier full-extent fallback predated the run-subset
    copy plans and no longer wins). Shared by the local and distributed MXU
    engines; a huge SPFFT_TPU_XPAD still disables compaction.
    """
    quantum = knobs.get_int("SPFFT_TPU_XPAD")
    a = -(-max(1, int(num_unique)) // quantum) * quantum
    return min(a, dim_x_freq)


def x_stage_matrices(dim_x: int, ux, num_rows: int, r2c: bool, real_dtype):
    """(backward, forward) x-stage matrix pairs over the active-x subset.

    Backward maps the ``num_rows``-padded active x-frequency extent to the full
    ``dim_x`` space extent ((A, X), zero rows on padding slots); forward is the
    transposed selection ((X, A)). For R2C the pairs are the real c2r/r2c
    matrices restricted the same way. ``ux`` entries may be -1 (interior
    padding slots — the 2-D pencil engines' slot layout interleaves them);
    those produce zero rows, folding the slot->x scatter into the matmul.
    """
    ux = np.asarray(ux, dtype=np.int64)
    rt = real_dtype

    def pad_rows(m):
        out = np.zeros((num_rows, m.shape[1]), m.dtype)
        valid = np.flatnonzero(ux >= 0)
        out[valid] = m[ux[valid]]
        return out

    if r2c:
        dt = twiddle_dtype(rt)
        a, b = c2r_matrices(dim_x)  # (Xf, X)
        wx_b = (pad_rows(a).astype(dt), pad_rows(b).astype(dt))  # (A, X)
        a, b = r2c_matrices(dim_x)  # (X, Xf)
        wx_f = (pad_rows(a.T).T.astype(dt), pad_rows(b.T).T.astype(dt))  # (X, A)
        return wx_b, wx_f

    wx_b = matrix_pair(c2c_matrix(dim_x, +1, row_perm=ux, num_rows=num_rows), rt)
    # the DFT matrix is symmetric, so the column-subset forward matrix is the
    # transpose of the row-subset one
    wx_f = matrix_pair(c2c_matrix(dim_x, -1, row_perm=ux, num_rows=num_rows).T, rt)
    return wx_b, wx_f


# Measured per-slot sparse-y engagement crossover: the variant wins below
# Sy/Y = 0.6 (BASELINE.md `sparse_y_crossover_256`). The engagement test in
# plan_sparse_y uses the exact integer form (5 * Sy < 3 * Y); this constant is
# the documented value plan cards report (obs.plancard).
SPARSE_Y_CROSSOVER = 0.6


def sparse_y_blocked_frac() -> float:
    """Blocked sparse-y engagement threshold: engage when padded bucket rows
    stay under this fraction of the dense extent
    (``SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC``, default 0.8 — measured sweep in
    BASELINE.md). Single source for plan_sparse_y_blocked and plan cards."""
    return knobs.get_float("SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC")


def describe_sparse_y(per_slot: bool, blocked_buckets, sy: int = 0) -> dict:
    """Sparse-y fragment of the MXU engine plan cards (obs.plancard): the
    engaged variant plus the measured thresholds that selected it. ONE home
    shared by the local and distributed engines so their cards cannot drift.
    """
    if per_slot:
        card = {"variant": "per-slot", "sy": int(sy)}
    elif blocked_buckets is not None:
        card = {"variant": "blocked", "num_buckets": len(blocked_buckets)}
    else:
        card = {"variant": "dense"}
    card["crossover_sy_over_y"] = SPARSE_Y_CROSSOVER
    card["blocked_engage_frac"] = sparse_y_blocked_frac()
    return card


def plan_sparse_y(xslot, ys, num_x_active: int, dim_y: int, real_dtype):
    """Shared sparse-y planning for the MXU engines (C2C only — callers gate).

    Groups sticks by active-x slot into an (A, Sy, *) table so the y-DFT
    contracts only each slot's sticks. ONE home for the engagement policy:
    ``SPFFT_TPU_SPARSE_Y`` = ``0`` (off) / ``1`` (forced) / unset ("auto" —
    engage below the measured Sy/Y < 0.6 crossover, BASELINE.md
    `sparse_y_crossover_256`; also measured on the distributed engine,
    `dist1_5pct_sparse_y_*`). Returns ``None`` when disengaged, else
    ``(Sy, row_of_stick, wy_backward_pair, wy_forward_pair)`` where
    ``row_of_stick[i] = slot_a * Sy + j`` is stick i's table row and the
    matrix pairs are the (A, Sy, Y) per-slot gathered DFT constants
    (padding rows zero).
    """
    # empty string = unset; out-of-vocabulary values raise typed (the
    # registry's choices — spfft_tpu.knobs — own the validation)
    mode = knobs.get_str("SPFFT_TPU_SPARSE_Y")
    xslot = np.asarray(xslot, dtype=np.int64)
    if mode == "0" or xslot.size == 0:
        return None
    A, Y = int(num_x_active), int(dim_y)
    cnt = np.bincount(xslot, minlength=A)
    sy_max = compact_x_extent(int(cnt.max()), Y)
    if sy_max >= Y or (mode != "1" and not (5 * sy_max < 3 * Y)):
        return None
    order = np.argsort(xslot, kind="stable")
    j = np.empty(xslot.size, dtype=np.int64)
    j[order] = np.arange(xslot.size) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    row_of = xslot * sy_max + j
    y_flat = np.full(A * sy_max, -1, dtype=np.int64)
    y_flat[row_of] = np.asarray(ys, dtype=np.int64)
    wyb = matrix_pair(c2c_matrix(Y, +1, row_perm=y_flat).reshape(A, sy_max, Y), real_dtype)
    wyf = matrix_pair(c2c_matrix(Y, -1, row_perm=y_flat).reshape(A, sy_max, Y), real_dtype)
    return sy_max, row_of, wyb, wyf


def plan_sparse_y_blocked(
    xslot, ys, dim_y: int, real_dtype, num_sticks: int, dense_rows: int,
    matrix_budget_mb: int | None = None, dense_slots=(),
):
    """Blocked (two-level) sparse-y planning — the win region ABOVE the
    per-slot crossover (``plan_sparse_y`` auto-disengages at Sy/Y >= 0.6,
    where its single (A, Sy_max) padding inflates the stick table and with it
    the z matmuls and copy plans — measured 1.28x slower at the 256^3/15%
    headline, BASELINE.md). This variant keeps the stick table EXACT:

    - active-x slots are sorted by stick count and cut into ``G`` buckets
      (``SPFFT_TPU_SPARSE_Y_BLOCKS``; auto picks G=4), each padded only to
      its own bucket maximum (8-sublane quantum),
    - each bucket's y-DFT runs as a batched (Ag, Syg, Z) x (Ag, Syg, Y)
      contraction; bucket outputs concatenate into the (Y, A, Z) grid in
      bucket-major slot order (the x-stage matrices fold the slot
      permutation, ops/fft.x_stage_matrices),
    - the bucket gathers replace the dense path's expand/pack gathers
      one-for-one, so the z/copy stages are untouched.

    Total y flops drop from ``A * Y`` rows to ``sum_g Ag * Syg`` —
    ~2x at the 15% spherical headline. Engages when the padded row total is
    under ``SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC`` (default 0.8) of the dense
    extent. Returns ``None`` when disengaged, else a dict with:

    - ``slot_perm``: original slot index per new (bucket-major) position,
    - ``buckets``: list of ``(row_idx (Ag, Syg) int32 into the
      (num_sticks+1)-padded stick table, wyb pair (Ag, Syg, Y), wyf pair)``,
    - ``row_of_stick``: (S,) int32 — each stick's row in the concatenation of
      the bucket flats (the forward regather map),
    - ``dense_flat``: {original slot: flat row offset} for ``dense_slots``.

    ``dense_slots`` (R2C support): original slot indices to DENSIFY — each
    becomes its own trailing bucket of shape (1, dim_y) whose rows are the
    full y extent (stick rows where sticks exist, zero rows elsewhere) with
    the plain dense y-DFT matrices. The x == 0 plane rides this way so its
    hermitian fill has every y row available inside the blocked stage
    (reference wiring being out-done: src/execution/execution_host.cpp:185-191
    applies sticks-only-y in R2C but this build had fallen back to the dense
    y stage for R2C entirely).

    Reference being out-done: the y-FFT-only-on-stick-bearing-rows idea of
    ``src/fft/transform_1d_host.hpp:155-235``, which skips empty x-rows but
    still transforms every y column of occupied ones.
    """
    mode = knobs.get_str("SPFFT_TPU_SPARSE_Y_BLOCKS")
    if mode == "0":
        return None
    if mode != "auto":
        # validated like SPFFT_TPU_SPARSE_Y: 'auto'/'0'/positive int only
        try:
            forced_g = int(mode)
        except ValueError:
            forced_g = -1
        if forced_g < 1:
            from ..errors import InvalidParameterError

            raise InvalidParameterError(
                f"SPFFT_TPU_SPARSE_Y_BLOCKS={mode!r}: expected 'auto', '0' "
                "(disable), or a positive bucket count"
            )
    xslot = np.asarray(xslot, dtype=np.int64)
    ys = np.asarray(ys, dtype=np.int64)
    if xslot.size == 0:
        return None
    n_slots = int(xslot.max()) + 1
    counts = np.bincount(xslot, minlength=n_slots)
    dense_slots = tuple(int(s) for s in dense_slots if 0 <= int(s) < n_slots)
    sortable = np.asarray(
        [s for s in range(n_slots) if s not in set(dense_slots)], dtype=np.int64
    )
    # measured bucket-count sweep (bench_results/round4_onchip{,2}.json):
    # G=4 best at 256^3 (5.893 vs 5.979/6.031 ms), G=8 best at 512^3
    # (76.3 vs 77.0 ms) — larger grids profit from tighter padding
    G = (4 if dim_y <= 256 else 8) if mode == "auto" else forced_g
    G = min(G, sortable.size) if sortable.size else 0
    # slots by stick count, desc (dense slots excluded — they bucket alone)
    order = sortable[np.argsort(-counts[sortable], kind="stable")]
    bounds = np.linspace(0, order.size, G + 1).astype(np.int64)
    sy_of = lambda c: min(dim_y, -(-max(1, int(c)) // 8) * 8)
    padded_rows = sum(
        (bounds[g + 1] - bounds[g]) * sy_of(counts[order[bounds[g]]])
        for g in range(G)
        if bounds[g + 1] > bounds[g]
    ) + len(dense_slots) * dim_y
    # engagement: blocked y flops ~ padded_rows * Y * Z vs dense ~ A * Y * Y * Z,
    # so the row totals compare directly (dense_rows = A * dim_y)
    frac = sparse_y_blocked_frac()
    if mode == "auto" and padded_rows >= frac * dense_rows:
        return None
    # callers that EMBED the bucket matrices as program constants (the SPMD
    # engines' shard_map closures) bound them here; the local engine threads
    # them as jit operands instead and passes no budget (at 512^3 the
    # matrices are ~800 MB — measured overflowing the tunnel compile
    # transport as constants, round 4)
    if matrix_budget_mb is not None:
        mat_bytes = (
            4 * int(padded_rows) * dim_y * np.dtype(real_dtype).itemsize
        )
        if mat_bytes > matrix_budget_mb * (1 << 20):
            if mode != "auto":
                import warnings

                warnings.warn(
                    f"SPFFT_TPU_SPARSE_Y_BLOCKS={mode} forced the blocked "
                    f"sparse-y stage, but its {mat_bytes >> 20} MB of bucket "
                    f"matrices exceed this engine's embedded-constant budget "
                    f"(SPFFT_TPU_SPARSE_Y_MATRIX_MB={matrix_budget_mb}); "
                    "falling back to the dense y stage",
                    stacklevel=3,
                )
            return None
    # stable per-slot stick enumeration (same j-ordering as plan_sparse_y)
    by_slot = np.argsort(xslot, kind="stable")
    cum = np.cumsum(counts) - counts
    j_of = np.empty(xslot.size, dtype=np.int64)
    j_of[by_slot] = np.arange(xslot.size) - cum[xslot[by_slot]]
    buckets = []
    offsets = np.zeros(n_slots, dtype=np.int64)  # per-slot flat offset
    flat_off = 0
    for g in range(G):
        lo, hi = int(bounds[g]), int(bounds[g + 1])
        if hi <= lo:
            continue
        slots_g = order[lo:hi]
        Ag = hi - lo
        Syg = sy_of(counts[slots_g].max() if Ag else 1)
        row_idx = np.full((Ag, Syg), num_sticks, dtype=np.int64)
        y_flat = np.full(Ag * Syg, -1, dtype=np.int64)
        for a_local, s in enumerate(slots_g):
            members = by_slot[cum[s] : cum[s] + counts[s]]
            row_idx[a_local, : counts[s]] = members
            y_flat[a_local * Syg : a_local * Syg + counts[s]] = ys[members]
            offsets[s] = flat_off + a_local * Syg
        wyb = matrix_pair(
            c2c_matrix(dim_y, +1, row_perm=y_flat).reshape(Ag, Syg, dim_y),
            real_dtype,
        )
        wyf = matrix_pair(
            c2c_matrix(dim_y, -1, row_perm=y_flat).reshape(Ag, Syg, dim_y),
            real_dtype,
        )
        buckets.append((row_idx.astype(np.int32), wyb, wyf))
        flat_off += Ag * Syg
    # dense trailing buckets (R2C x == 0 plane): full y extent, plain dense
    # y-DFT matrices; member sticks sit at their natural y row so the
    # hermitian fill sees the whole plane
    dense_flat = {}
    for s in dense_slots:
        row_idx = np.full((1, dim_y), num_sticks, dtype=np.int64)
        members = by_slot[cum[s] : cum[s] + counts[s]]
        row_idx[0, ys[members]] = members
        wyb = matrix_pair(
            c2c_matrix(dim_y, +1).reshape(1, dim_y, dim_y), real_dtype
        )
        wyf = matrix_pair(
            c2c_matrix(dim_y, -1).reshape(1, dim_y, dim_y), real_dtype
        )
        buckets.append((row_idx.astype(np.int32), wyb, wyf))
        dense_flat[s] = flat_off
        flat_off += dim_y
    row_of_stick = offsets[xslot] + j_of
    for s in dense_slots:
        members = by_slot[cum[s] : cum[s] + counts[s]]
        row_of_stick[members] = dense_flat[s] + ys[members]
    return {
        "slot_perm": np.concatenate(
            [order, np.asarray(dense_slots, dtype=np.int64)]
        ),
        "buckets": buckets,
        "row_of_stick": row_of_stick.astype(np.int32),
        "dense_flat": dense_flat,
    }


SPARSE_Y_MATRIX_MB_ENV = "SPFFT_TPU_SPARSE_Y_MATRIX_MB"


def sparse_y_matrix_budget_bytes() -> int:
    """Blocked-y bucket-matrix budget (bytes): above it the local engine
    threads the matrices as jit operands and the SPMD engines (which embed
    constants in their shard_map closures) veto engagement. One definition
    so the two engines' thresholds cannot desynchronize."""
    return knobs.get_int(SPARSE_Y_MATRIX_MB_ENV) << 20


F64_STAGE_MB_ENV = "SPFFT_TPU_F64_STAGE_MB"


def f64_stage_chunks(batch: int, *operand_elems: int) -> int:
    """Batch-axis chunk count bounding an f64 matmul stage's emulation temps.

    XLA:TPU emulates f64 matmuls with multi-component f32 arithmetic whose HLO
    temporaries are ~8 f32 components per element with several alive at once —
    measured: the single 512^3 R2C f64 backward x-stage held three
    ``f32[8,512,512,512]`` temps (12 GB) and OOM'd a 15.75 GB chip
    (BASELINE.md). Splitting the batch axis into chunks bounds each temp to
    ``32 * max(operand_elems) / n`` bytes (default budget 256 MB, override via
    ``SPFFT_TPU_F64_STAGE_MB``). Returns the smallest divisor of ``batch``
    meeting the budget (1 = no chunking; ``batch`` if no smaller divisor fits).
    """
    budget = knobs.get_int(F64_STAGE_MB_ENV) * (1 << 20)
    temp_bytes = 32 * max(operand_elems)
    if temp_bytes <= budget or batch <= 1:
        return 1
    # map_chunked zero-pads the batch axis to a chunk multiple, so any count
    # works — no divisor search (a prime batch must not serialize per-row)
    return min(int(-(-temp_bytes // budget)), batch)


def map_chunked(fn, arrs, nchunks: int):
    """Apply ``fn`` over leading-axis chunks of ``arrs`` via ``lax.map``.

    Sequentializes the stage into ``nchunks`` pieces (each a full-width matmul
    over a batch slice) so XLA's per-step temporaries shrink by ``nchunks``;
    results are concatenated back along the leading axis. The batch axis is
    zero-padded up to a chunk multiple (padding rows flow through the stage as
    zeros and are sliced off), so ``nchunks`` need not divide the extent.
    ``fn`` may return one array or a tuple.
    """
    if nchunks <= 1:
        return fn(*arrs)
    n0 = arrs[0].shape[0]
    b = -(-n0 // nchunks)
    padded = nchunks * b
    if padded != n0:
        arrs = tuple(
            jnp.concatenate(
                [a, jnp.zeros((padded - n0, *a.shape[1:]), dtype=a.dtype)]
            )
            for a in arrs
        )
    stacked = tuple(a.reshape(nchunks, b, *a.shape[1:]) for a in arrs)
    out = jax.lax.map(lambda chunk: fn(*chunk), stacked)

    def unstack(o):
        return o.reshape(o.shape[0] * o.shape[1], *o.shape[2:])[:n0]

    if isinstance(out, tuple):
        return tuple(unstack(o) for o in out)
    return unstack(out)


def gauss_matmul_enabled() -> bool:
    """Whether :func:`complex_matmul` uses Gauss's 3-multiplication form.
    Read at trace time; ``SPFFT_TPU_GAUSS_MM=0`` restores the 4-matmul form
    (the A/B escape hatch)."""
    return knobs.get_bool("SPFFT_TPU_GAUSS_MM")


def complex_matmul(xr, xi, wr, wi, spec: str, precision=_PRECISION):
    """(xr + i xi) contracted with (wr + i wi) via einsum ``spec``.

    Default is Gauss's 3-multiplication form: with t1 = xr@wr, t2 = xi@wi,
    t3 = (xr + xi)@(wr + wi), the product is (t1 - t2, t3 - t1 - t2) — 25%
    fewer MXU flops than the textbook 4-matmul form, and since the DFT
    matrices are static constants, (wr + wi) folds at compile time; the only
    runtime additions are one input-sized add and two output subtracts.
    Measured 6.88 -> 6.15 ms/pair (585 -> 655 GFLOP/s) at the 256^3/15%
    headline with roundtrip error unchanged (~7e-5 f32) and dense-oracle
    relative error 2.6e-7 vs 1.6e-7 — the subtraction cancellation is benign
    at DFT value scales, still well under the 1e-6 parity bar
    (bench_results/round3_onchip.json ``gauss_3mm`` arms).
    ``SPFFT_TPU_GAUSS_MM=0`` restores the 4-matmul form.
    """
    if gauss_matmul_enabled():
        t1 = jnp.einsum(spec, xr, wr, precision=precision)
        t2 = jnp.einsum(spec, xi, wi, precision=precision)
        t3 = jnp.einsum(spec, xr + xi, wr + wi, precision=precision)
        return t1 - t2, t3 - t1 - t2
    yr = jnp.einsum(spec, xr, wr, precision=precision) - jnp.einsum(
        spec, xi, wi, precision=precision
    )
    yi = jnp.einsum(spec, xr, wi, precision=precision) + jnp.einsum(
        spec, xi, wr, precision=precision
    )
    return yr, yi


def real_in_matmul(x, wr, wi, spec: str, precision=_PRECISION):
    """Real input x contracted with complex matrix: 2 real matmuls."""
    return (
        jnp.einsum(spec, x, wr, precision=precision),
        jnp.einsum(spec, x, wi, precision=precision),
    )


def real_out_matmul(xr, xi, a, b, spec: str, precision=_PRECISION):
    """Real output xr@A - xi@B (the C2R stage): 2 real matmuls."""
    return jnp.einsum(spec, xr, a, precision=precision) - jnp.einsum(
        spec, xi, b, precision=precision
    )
