"""Device-side building blocks of the transform pipelines."""

from . import compression, symmetry  # noqa: F401
