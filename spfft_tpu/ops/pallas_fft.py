"""Pallas TPU kernels for the MXU DFT stages.

The MXU engine's complex DFT stage is 3-4 real matmuls (ops/fft.complex_matmul;
Gauss's 3-multiplication form is the default since round 3);
XLA compiles them as separate fusions, so the (re, im) operand pair is read from
HBM twice and intermediate products round-trip once more. This module fuses the
whole complex contraction into ONE Pallas kernel: each (re, im) input tile is
loaded into VMEM once, both DFT matrix parts stay VMEM-resident across the batch,
and both outputs are produced in the same pass — halving operand traffic for the
bandwidth-bound stages (small-N DFTs over large batches).

The kernel is shape-restricted (operands tiled on (8, 128) f32 boundaries).
Reference analogue: the fused cuFFT 2D plans of the GPU backend (reference:
src/fft/transform_2d_gpu.hpp:47-149) — one fused pass where the host path does
separate ones.

Measured on TPU v5e at the 256^3/15%-spherical plan shapes
(programs/microbench_pallas.py; scan-loop timing, scalar-fetch fence): the
fused kernel does NOT beat XLA's einsum lowering — z-stage (1160x256 @
256x256) 0.57 ms fused vs 0.47 ms einsum; y-stage (10240x256 @ 256x256)
0.65 ms vs 0.43 ms. XLA already fuses the 4-matmul complex product well. The
einsum path (ops/fft.complex_matmul) therefore stays the engine default; this
kernel is kept as a building block for shapes where manual VMEM residency wins
(re-measure before wiring in).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(xr_ref, xi_ref, wr_ref, wi_ref, yr_ref, yi_ref, *, precision):
    xr, xi = xr_ref[:], xi_ref[:]
    wr, wi = wr_ref[:], wi_ref[:]
    dot = functools.partial(
        jnp.dot, preferred_element_type=jnp.float32, precision=precision
    )
    yr_ref[:] = dot(xr, wr) - dot(xi, wi)
    yi_ref[:] = dot(xr, wi) + dot(xi, wr)


def supports(m: int, k: int, n: int, dtype, block_m: int = 256) -> bool:
    """True if the fused kernel handles an (m, k) @ (k, n) complex contraction.

    VMEM budget: both W parts stay resident for the whole grid, and each grid
    step double-buffers a (block_m, k) x-tile pair and a (block_m, n) y-tile
    pair. Keep the total under ~12 MB of the ~16 MB per-core VMEM.
    """
    bm = min(block_m, m)
    tiles = 2 * 2 * bm * (k + n) * 4  # double-buffered (re, im) x/y tiles
    return (
        np.dtype(dtype) == np.float32
        and m % 8 == 0
        and k % 128 == 0
        and n % 128 == 0
        and k * n * 4 * 2 + tiles <= 12 * 1024 * 1024
    )


@functools.partial(jax.jit, static_argnames=("block_m", "precision", "interpret"))
def complex_matmul_fused(
    xr,
    xi,
    wr,
    wi,
    *,
    block_m: int = 256,
    precision=jax.lax.Precision.HIGHEST,
    interpret: bool | None = None,
):
    """(xr + i xi) @ (wr + i wi) -> (yr, yi), one fused Pallas pass.

    x: (M, K) f32 pair, w: (K, N) f32 pair, M % 8 == 0, K/N % 128 == 0.
    Grid tiles the batch dimension; the DFT matrix stays resident.
    ``interpret`` defaults to True off-TPU so tests exercise the kernel on the
    virtual CPU mesh (the same build-only-CI compromise as the reference's GPU
    kernels, reference: .github/workflows/ci.yml:89-130).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = xr.shape
    n = wr.shape[1]
    bm = min(block_m, m)
    while m % bm:
        bm //= 2
    grid = (m // bm,)
    x_spec = pl.BlockSpec((bm, k), lambda i: (i, 0), memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM)
    y_spec = pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM)
    yr, yi = pl.pallas_call(
        functools.partial(_kernel, precision=precision),
        grid=grid,
        in_specs=[x_spec, x_spec, w_spec, w_spec],
        out_specs=[y_spec, y_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), jnp.float32),
            jax.ShapeDtypeStruct((m, n), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * m * k * n, transcendentals=0,
            bytes_accessed=4 * (2 * m * k + 2 * k * n + 2 * m * n),
        ),
        interpret=interpret,
    )(xr, xi, wr, wi)
    return yr, yi
