"""Batched execution of independent transforms with pipelined dispatch.

Parity with the reference's ``multi_transform_{forward,backward}`` free functions
(reference: include/spfft/multi_transform.hpp:48-95) and the pipelining semantics of
``MultiTransformInternal`` (reference: src/spfft/multi_transform_internal.hpp:48-176):
the reference interleaves CPU and GPU transform stages by hand (queue all GPU xy
stages, run CPU stages while GPU works, nonblocking MPI exchanges) so communication
and computation of independent transforms overlap.

TPU-first rebuild: JAX dispatch is asynchronous, so the same overlap falls out of
dispatch ordering — *all* transforms are staged and enqueued first (device programs
queue back-to-back without host round-trips, and host-side staging of transform i+1
overlaps device execution of transform i), then results are waited on and fetched in
order. One function handles local and distributed transforms alike; both expose the
same split-phase ``_dispatch_* / _finalize_*`` hooks.

The reference rejects transforms created from the same Grid because they would share
scratch buffers mid-flight (reference: multi_transform_internal.hpp:67-73). Plans
here own their buffers, so sharing a Grid is safe and no such restriction applies —
duplicate *transform objects* in one batch are still rejected, since the retained
space-domain buffer of a transform is per-object state.
"""
from __future__ import annotations

from . import timing
from .errors import InvalidParameterError
from .types import ScalingType


def _check_batch(transforms, inputs, name):
    if len(transforms) != len(inputs):
        raise InvalidParameterError(
            f"{name}: got {len(transforms)} transforms but {len(inputs)} inputs"
        )
    if len(set(map(id, transforms))) != len(transforms):
        raise InvalidParameterError(
            f"{name}: the same transform object appears more than once in the batch"
        )


def _broadcast_scaling(scaling_types, n):
    if scaling_types is None:
        return [ScalingType.NONE] * n
    try:
        if isinstance(scaling_types, (int, ScalingType)):
            return [ScalingType(scaling_types)] * n
        scaling_types = [ScalingType(s) for s in scaling_types]
    except (ValueError, TypeError) as e:
        raise InvalidParameterError(f"invalid scaling type: {e}") from e
    if len(scaling_types) != n:
        raise InvalidParameterError(
            f"got {n} transforms but {len(scaling_types)} scaling types"
        )
    return scaling_types


def dispatch_backward(transforms, values_list):
    """Stage and enqueue every backward without waiting; returns the list of
    device-resident pending results (finalize with :func:`finalize_backward`).

    The split-phase half of :func:`multi_transform_backward`, exposed so
    batch owners that interleave work between dispatch and finalize — the
    serving layer sheds deadline-expired requests pre-dispatch and resolves
    tickets per-request (:mod:`spfft_tpu.serve`) — share the exact pipelined
    dispatch path instead of reimplementing it. Validates like the one-shot
    form: a length mismatch or a duplicate transform object raises typed
    (silent zip truncation would drop work)."""
    transforms, values_list = list(transforms), list(values_list)
    _check_batch(transforms, values_list, "dispatch_backward")
    return [t._dispatch_backward(v) for t, v in zip(transforms, values_list)]


def finalize_backward(transforms, pending):
    """Wait for and fetch the results of a :func:`dispatch_backward` batch,
    in order (host staging of result i overlaps device execution of i+1)."""
    return [t._finalize_backward(o) for t, o in zip(transforms, pending)]


def dispatch_forward(transforms, spaces_list, scalings):
    """Split-phase forward dispatch (counterpart of :func:`dispatch_backward`;
    ``scalings`` must already be one :class:`ScalingType` per transform —
    length-checked, like the batch itself)."""
    transforms, spaces_list = list(transforms), list(spaces_list)
    scalings = list(scalings)
    _check_batch(transforms, spaces_list, "dispatch_forward")
    if len(scalings) != len(transforms):
        raise InvalidParameterError(
            f"dispatch_forward: got {len(transforms)} transforms but "
            f"{len(scalings)} scaling types"
        )
    return [
        t._dispatch_forward(s, sc)
        for t, s, sc in zip(transforms, spaces_list, scalings)
    ]


def finalize_forward(transforms, pending):
    """Wait for and fetch the packed results of a :func:`dispatch_forward`
    batch, in order."""
    return [t._finalize_forward(p) for t, p in zip(transforms, pending)]


def multi_transform_backward(transforms, values_list):
    """Execute independent backward transforms with pipelined dispatch.

    ``values_list[i]`` is the packed frequency input of ``transforms[i]`` (for
    distributed transforms: the per-shard list). Returns the list of space-domain
    results, in order. Reference: include/spfft/multi_transform.hpp:72-95.
    """
    transforms = list(transforms)
    values_list = list(values_list)
    # validation (lengths, duplicate transform objects) lives in the
    # split-phase halves — one rule for both entry forms
    with timing.scoped("multi backward"):
        with timing.scoped("dispatch all"):
            pending = dispatch_backward(transforms, values_list)
        with timing.scoped("finalize all"):
            return finalize_backward(transforms, pending)


def multi_transform_forward(transforms, spaces_list=None, scaling_types=None):
    """Execute independent forward transforms with pipelined dispatch.

    ``spaces_list[i]`` is the space-domain input of ``transforms[i]`` (``None``
    reuses that transform's retained space buffer, e.g. right after a backward —
    the pointer-free overload of the reference). Returns the list of packed
    frequency results. Reference: include/spfft/multi_transform.hpp:48-70.
    """
    transforms = list(transforms)
    if spaces_list is None:
        spaces_list = [None] * len(transforms)
    else:
        spaces_list = list(spaces_list)
    # batch validation lives in dispatch_forward (one rule for both forms)
    scalings = _broadcast_scaling(scaling_types, len(transforms))
    with timing.scoped("multi forward"):
        with timing.scoped("dispatch all"):
            pending = dispatch_forward(transforms, spaces_list, scalings)
        with timing.scoped("finalize all"):
            return finalize_forward(transforms, pending)
