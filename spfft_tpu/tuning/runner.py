"""On-device trial runner: measure candidates on the caller's real plan.

Each trial builds a full transform for one candidate (same geometry, mesh,
dtype, precision as the plan being tuned — the trial IS the plan, not a
proxy), runs warmup dispatches to absorb compilation, then timed
backward+forward roundtrips fenced with the platform-correct completion fence
(:mod:`spfft_tpu.sync`). Best-of-repeats is reported, matching every
measurement harness in this repo (bench.py, programs/benchmark.py).

Budget knobs: ``SPFFT_TPU_TUNE_WARMUP`` (default 1 untimed roundtrip) and
``SPFFT_TPU_TUNE_REPEATS`` (default 5 timed roundtrips) per candidate.
Trials never run on CPU-only hosts unless ``SPFFT_TPU_TUNE_CPU=1`` — CPU
"collectives" are memory copies, so CPU timings would poison wisdom that a
TPU plan later reads; the tuned policy falls back to the model there
(``trials_allowed``). CI and the tests set the override, with a tmp wisdom
file, to exercise the whole loop hardware-free.

Instrumentation reuses the obs layers: each trial dispatch is wrapped in the
canonical ``tune warmup`` / ``tune trial`` stage scopes (``obs.STAGES`` —
``programs/lint.py`` enforces the vocabulary), and the run registry counts
``tuning_trials_total`` per candidate label plus a ``tuning_trial_seconds``
histogram, so a metrics snapshot shows exactly what tuning cost.
"""
from __future__ import annotations

import threading
import time

from .. import faults, knobs, obs
from ..errors import GenericError
from ..sync import FENCE_BUDGET_ENV, _fence_budget_s

TUNE_REPEATS_ENV = "SPFFT_TPU_TUNE_REPEATS"
TUNE_WARMUP_ENV = "SPFFT_TPU_TUNE_WARMUP"
TUNE_CPU_ENV = "SPFFT_TPU_TUNE_CPU"

# Failure classes a trial may swallow into an ``error`` row: the typed
# spfft_tpu.errors surface (a candidate whose geometry the engine rejects),
# backend/compile blowups (XLA runtime errors are RuntimeError subclasses;
# InjectedFault deliberately is too), missing lowerings, host OOM and I/O.
# Anything else — TypeError, AttributeError, KeyboardInterrupt — is a bug or
# an interrupt and must propagate, not become a quiet trial failure.
TRIAL_ERRORS = (
    GenericError,
    RuntimeError,
    NotImplementedError,
    ValueError,
    MemoryError,
    OSError,
)


class TrialTimeout(RuntimeError):
    """A tuning trial exceeded its wall-clock deadline (the
    ``SPFFT_TPU_FENCE_BUDGET_S`` discipline extended over the whole trial —
    build + warmup + timed repeats). A ``RuntimeError`` subclass on purpose:
    it is a member of :data:`TRIAL_ERRORS`, so a hung candidate becomes an
    honest ``error`` row and ``policy="tuned"`` planning degrades to the
    model instead of stalling forever on one wedged compile or dispatch."""


class TrialDegradedError(RuntimeError):
    """A trial plan silently degraded away from its candidate (the engine
    fallback rung fired inside the trial build): its timing would measure the
    *fallback*, not the candidate, and persisting it would poison wisdom with
    a mislabeled number. Raised inside the isolation scope so the candidate
    becomes an honest ``error`` row instead."""


def trial_budget() -> tuple:
    """(warmup, repeats) per candidate from the env knobs (floors: 0, 1)."""
    return knobs.get_int(TUNE_WARMUP_ENV), knobs.get_int(TUNE_REPEATS_ENV)


def trial_deadline_s() -> float:
    """Wall-clock budget for ONE whole candidate trial (build + warmup +
    timed repeats), derived from the fence deadline discipline:
    ``SPFFT_TPU_FENCE_BUDGET_S x (warmup + repeats + 1)`` — each roundtrip
    gets one fence budget's worth, plus one for the trial plan's build.
    0 (the default, budget unset) means no deadline."""
    budget = _fence_budget_s()
    if budget <= 0:
        return 0.0
    warmup, repeats = trial_budget()
    return budget * (warmup + repeats + 1)


def _run_deadlined(fn, budget_s: float, label: str):
    """Run ``fn`` under a wall-clock deadline in a worker thread (the
    ``sync.fence`` budget pattern): a wedge — hung compile, dead dispatch —
    raises :class:`TrialTimeout` after ``budget_s`` instead of stalling the
    tuned-policy plan construction. The worker re-enters the caller's trace
    run so the trial's events keep their run-ID join; it stays parked on the
    dead call (daemon, reclaimed at exit) if the deadline fires."""
    if budget_s <= 0:
        return fn()
    done = threading.Event()
    result: list = []
    err: list = []
    run = obs.trace.current_run_id()

    def _work():
        try:
            # re-enter the caller's run ID AND its dump suppression: both
            # are thread-local, and a failing candidate is an EXPECTED,
            # isolated error row — it must not flood SPFFT_TPU_TRACE_DUMP
            # with dumps of handled errors just because a deadline is set
            with obs.trace.with_run(run), obs.trace.suppressed_dumps():
                result.append(fn())
        except BaseException as e:  # noqa: SA010 — re-raised in the caller
            # thread (cross-thread re-raise, nothing swallowed)
            err.append(e)
        finally:
            done.set()

    worker = threading.Thread(target=_work, daemon=True)
    worker.start()
    if not done.wait(budget_s):
        raise TrialTimeout(
            f"tuning trial {label!r} exceeded its {budget_s:.3g}s deadline "
            f"({FENCE_BUDGET_ENV} x (warmup + repeats + 1)); candidate "
            "recorded as an error row, planning falls back"
        )
    if err:
        raise err[0]
    return result[0]


def trials_allowed(platform: str) -> bool:
    """Whether on-device trials may run for a plan on ``platform`` (see
    module docstring — CPU-only hosts skip to the model fallback unless
    ``SPFFT_TPU_TUNE_CPU=1``)."""
    return platform != "cpu" or knobs.get_bool(TUNE_CPU_ENV)


def _roundtrip(transform, staged):
    """One backward+forward device roundtrip over pre-staged inputs,
    fenced to completion; returns the fenced result for reuse."""
    from ..sync import fence
    from ..types import ScalingType

    transform.backward_pair(staged[0], staged[1])
    out = transform.forward_pair(ScalingType.FULL)
    fence(out)
    return out


def _stage_inputs(transform):
    """Random frequency values of the plan's exact shape, staged on device
    (trial timings must not bill host staging — the tuned decision is about
    the device pipeline)."""
    import numpy as np

    from ..execution import as_pair

    rng = np.random.default_rng(0)
    if getattr(transform, "_mesh", None) is not None:
        vps = [
            rng.standard_normal(transform.num_local_elements(r))
            + 1j * rng.standard_normal(transform.num_local_elements(r))
            for r in range(transform.num_shards)
        ]
        return transform._exec.pad_values(vps)
    n = transform.num_local_elements
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    re, im = as_pair(values, transform.dtype)
    return transform._exec.put(re), transform._exec.put(im)


def measure_candidate(transform) -> float:
    """Best-of-repeats seconds per backward+forward pair for one built
    trial transform."""
    import jax

    warmup, repeats = trial_budget()
    staged = _stage_inputs(transform)
    with jax.named_scope("tune warmup"):
        # warmup 0 is honored: compilation then bills to the first timed
        # repeat (acceptable for smoke runs; best-of still softens it)
        for _ in range(warmup):
            _roundtrip(transform, staged)
    best = float("inf")
    for _ in range(repeats):
        with jax.named_scope("tune trial"), obs.phase_timer(
            "tuning_trial_seconds"
        ):
            t0 = time.perf_counter()
            _roundtrip(transform, staged)
            best = min(best, time.perf_counter() - t0)
    return best


def run_trials(build, candidates: list) -> list:
    """Measure every candidate; returns the trial table (one row per
    candidate: its label, constructor facts, and best-of ms), measured rows
    sorted fastest-first. ``build(candidate)`` constructs the trial
    transform — the closure lives with the caller (transform.py /
    distributed.py), which knows its own constructor; trial plans are built
    with the model policy so tuning cannot recurse.

    Per-candidate failures are isolated, not raised: a candidate that fails
    to build, compile, or run (e.g. BUFFERED's padded blocks OOM-ing on the
    imbalanced geometry the model rejects it for) yields an ``error`` row
    instead of an ``ms`` row and sorts last — tuning degrades, never fails
    plan construction (the caller falls back to the model policy when NO
    candidate measured). Only the failure classes in :data:`TRIAL_ERRORS`
    are isolated (counted via ``tuning_trial_failures_total``); programming
    errors propagate. Fault site ``tuning.trial`` fires inside the isolation
    scope, so chaos runs prove the all-candidates-failed fallback."""
    rows, failed = [], []
    for cand in candidates:
        try:
            # each trial is its own "tune.trial" operation (child run of the
            # plan construction being tuned — spfft_tpu.obs.trace), so a
            # trace shows which candidate's build/roundtrips cost what;
            # dumps are suppressed inside: a failing candidate is an
            # EXPECTED, isolated error row, not a crash worth a dump file
            with obs.trace.operation(
                "tune.trial", label=cand["label"]
            ), obs.trace.suppressed_dumps():

                def _trial(cand=cand):
                    faults.site("tuning.trial")
                    trial = build(cand)
                    degraded = [
                        d["event"]
                        for d in getattr(trial, "_degradations", ())
                        if d.get("event") == "engine_fallback"
                    ]
                    if degraded:
                        raise TrialDegradedError(
                            f"trial plan fell back ({degraded[0]}): timing "
                            "would not measure the candidate"
                        )
                    return measure_candidate(trial)

                # the whole trial runs under the SPFFT_TPU_FENCE_BUDGET_S
                # deadline discipline (see trial_deadline_s): a hung
                # candidate fails typed into TRIAL_ERRORS instead of
                # stalling policy="tuned" planning forever
                seconds = _run_deadlined(
                    _trial, trial_deadline_s(), cand["label"]
                )
        except TRIAL_ERRORS as e:
            obs.counter("tuning_trial_failures_total", candidate=cand["label"]).inc()
            failed.append(dict(cand, error=faults.summarize(e)))
            continue
        obs.counter("tuning_trials_total", candidate=cand["label"]).inc()
        row = dict(cand)
        row["ms"] = round(seconds * 1e3, 4)
        rows.append(row)
    return sorted(rows, key=lambda r: r["ms"]) + failed


def _stage_batch_inputs(transform, batch: int):
    """The plan's exact-shape trial inputs stacked ``batch`` times along the
    batch axis, staged on device (local plans — the batch axis the serving
    layer tunes is a local-plan surface)."""
    import jax.numpy as jnp

    re, im = _stage_inputs(transform)
    return (
        jnp.stack([re] * max(1, int(batch))),
        jnp.stack([im] * max(1, int(batch))),
    )


def measure_batch_seconds(transform, batch: int) -> float:
    """Best-of-repeats seconds per TRANSFORM (wall / batch) through the
    batch-fused dispatch path: one stacked backward+forward program dispatch
    per roundtrip. Raises :class:`TrialDegradedError` when the batched path
    is unavailable or takes its rung mid-trial — timing the per-request
    fallback loop under a ``fused/bN`` label would poison wisdom with a
    mislabeled number (the ``TrialDegradedError`` rule)."""
    import jax

    from ..sync import fence
    from ..types import ScalingType

    batch = max(1, int(batch))
    warmup, repeats = trial_budget()
    re, im = _stage_batch_inputs(transform, batch)
    ex = transform._exec

    def roundtrip():
        out = ex.backward_pair_batch(re, im)
        if out is None:
            raise TrialDegradedError(
                "batch-fused path unavailable: timing would measure the "
                "per-request loop, not the fused/bN candidate"
            )
        if transform._is_r2c:
            space_re, space_im = out, None
        else:
            space_re, space_im = out
        pair = ex.forward_pair_batch(space_re, space_im, ScalingType.FULL)
        if pair is None:
            raise TrialDegradedError(
                "batch-fused forward unavailable mid-trial"
            )
        fence(pair)
        return pair

    with jax.named_scope("tune warmup"):
        for _ in range(warmup):
            roundtrip()
    best = float("inf")
    for _ in range(repeats):
        with jax.named_scope("tune trial"), obs.phase_timer(
            "tuning_trial_seconds"
        ):
            t0 = time.perf_counter()
            roundtrip()
            best = min(best, time.perf_counter() - t0)
    return best / batch


def run_batch_trials(transform, candidates: list) -> list:
    """Measure the ``fused/bN`` batch-size candidates on ``transform``'s OWN
    batched programs (no trial plan builds — a batched program is per-plan
    state, so the plan being tuned IS the trial vehicle). Same isolation
    contract as :func:`run_trials`: per-candidate failures become ``error``
    rows (``TRIAL_ERRORS`` only), measured rows sort fastest-first, fault
    site ``tuning.trial`` fires inside the scope."""
    rows, failed = [], []
    for cand in candidates:
        try:
            with obs.trace.operation(
                "tune.trial", label=cand["label"]
            ), obs.trace.suppressed_dumps():

                def _trial(cand=cand):
                    faults.site("tuning.trial")
                    return measure_batch_seconds(transform, cand["batch"])

                seconds = _run_deadlined(
                    _trial, trial_deadline_s(), cand["label"]
                )
        except TRIAL_ERRORS as e:
            obs.counter(
                "tuning_trial_failures_total", candidate=cand["label"]
            ).inc()
            failed.append(dict(cand, error=faults.summarize(e)))
            continue
        obs.counter("tuning_trials_total", candidate=cand["label"]).inc()
        row = dict(cand)
        row["ms"] = round(seconds * 1e3, 4)
        rows.append(row)
    return sorted(rows, key=lambda r: r["ms"]) + failed
