"""spfft_tpu.tuning — empirical autotuner with persistent plan wisdom.

Closes the loop the model-based ``ExchangeType.DEFAULT`` policy leaves open:
instead of trusting analytic cost guesses (``parallel/policy.py``), a plan
constructed with ``policy="tuned"`` (or ``SPFFT_TPU_POLICY=tuned``) measures
the real alternatives on its own geometry/mesh/dtype and remembers the winner
— the FFTW planner/wisdom shape, rebuilt for this system:

1. **Candidates** (:mod:`.candidates`): the exchange disciplines the DEFAULT
   cost model already tabulates, and the local engine axis (MXU vs ``jnp.fft``
   with the sparse-y knob variants).
2. **Trials** (:mod:`.runner`): each candidate built as a full transform and
   timed on device (warmup + best-of repeats, fenced), instrumented through
   the obs stage scopes and run-metrics registry.
3. **Wisdom** (:mod:`.wisdom`): the measured choice persists in a JSON store
   (``SPFFT_TPU_WISDOM``; process-memory fallback when unset) keyed by every
   decision-relevant plan property, so the same plan constructed again runs
   ZERO trials.

Safety contract: tuning degrades, never fails — wisdom miss on a CPU-only
host (trials skipped unless ``SPFFT_TPU_TUNE_CPU=1``), a corrupt store, or a
schema-version mismatch all fall back to the model policy, and the plan card
records the provenance either way (``plan.report()["tuning"]``: ``wisdom``
vs ``model``, hit/miss, per-candidate trial timings).
"""
from __future__ import annotations

import contextlib
import os

from .. import knobs
from .wisdom import (  # noqa: F401
    PERF_ENV_KNOBS,
    WISDOM_ENV,
    WISDOM_SCHEMA,
    MemoryStore,
    WisdomStore,
    active_store,
    best_measured_ms,
    clear_memory,
    env_signature,
    key_digest,
    make_entry,
    merge_entries,
    sparsity_signature,
)
from .runner import (  # noqa: F401
    TUNE_CPU_ENV,
    TUNE_REPEATS_ENV,
    TUNE_WARMUP_ENV,
    TrialTimeout,
    measure_batch_seconds,
    run_batch_trials,
    run_trials,
    trial_budget,
    trial_deadline_s,
    trials_allowed,
)
from .candidates import (  # noqa: F401
    batch_candidates,
    exchange_candidates,
    local_candidates,
    sched_candidates,
)


@contextlib.contextmanager
def env_overrides(overrides: dict):
    """Temporarily apply a candidate's env knob overrides (sparse-y variants
    etc.) around a trial or chosen-plan engine construction. The knobs are
    read at plan-construction time only, so scoping the mutation to the
    construction is exact. Empty overrides never touch ``os.environ``.

    CAVEAT — process-global state: while a non-empty override is active,
    concurrent plan construction in *other threads* would read the
    overridden knobs (and ``env_signature`` would key wisdom under them).
    Tuned plan construction is therefore NOT thread-safe against concurrent
    plan construction — serialize plan creation when using
    ``policy="tuned"`` (the documented exception to the otherwise lock-free
    plan creation, docs/details.md "Thread safety")."""
    if not overrides:
        yield
        return
    # The trial isolation scope is the package's ONE deliberate raw env
    # path (noqa: SA014): it saves/restores ambient values VERBATIM — typed
    # parsing here would destroy the "unset stays unset" round-trip.
    saved = {k: os.environ.get(k) for k in overrides}  # noqa: SA014
    try:
        os.environ.update({k: str(v) for k, v in overrides.items()})
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)  # noqa: SA014 — verbatim restore
            else:
                os.environ[k] = old  # noqa: SA014 — verbatim restore


def _record(provenance, *, hit, store, choice, trials, reason, key):
    """The JSON-plain tuning record a transform retains (``_tuning``) and
    plan cards embed verbatim (obs.plancard TUNING_KEYS pins the shape)."""
    return {
        "policy": "tuned",
        "provenance": provenance,  # "wisdom" (measured) | "model" (fallback)
        "hit": bool(hit),
        "wisdom_path": getattr(store, "path", None),
        "key_digest": key_digest(key),
        "reason": reason,
        "choice": choice,
        "trials": trials,
    }


def _base_key(kind, transform_type, dims, dtype, engine, precision) -> dict:
    import jax

    return {
        "kind": kind,
        "transform_type": transform_type.name,
        "dims": [int(d) for d in dims],
        "dtype": str(dtype),
        "engine": str(engine),
        "precision": str(precision),
        "jax": jax.__version__,
        # ambient perf knobs trials ran under (wisdom.PERF_ENV_KNOBS):
        # changing a knob lands in a different entry instead of aliasing
        "env": env_signature(),
    }


def exchange_key(params, mesh, dtype, engine, precision, pencil2) -> dict:
    """Wisdom key for a distributed plan's exchange decision: geometry and
    per-shard layout exactly (they set the wire volumes), mesh shape, dtype
    and wire width, the requested engine, the platform the MESH lives on
    (engine availability — CPU wisdom never answers for TPU plans), and the
    jax version (a collective-lowering change invalidates timings)."""
    key = _base_key(
        "exchange",
        params.transform_type,
        (params.dim_x, params.dim_y, params.dim_z),
        dtype,
        engine,
        precision,
    )
    key.update(
        {
            "decomposition": "pencil2" if pencil2 else "slab",
            "mesh": {
                str(name): int(size)
                for name, size in zip(mesh.axis_names, mesh.devices.shape)
            },
            "platform": str(mesh.devices.flat[0].platform),
            "sticks_per_shard": [int(n) for n in params.num_sticks_per_shard],
            "local_z_lengths": [int(n) for n in params.local_z_lengths],
            "values_per_shard": [int(n) for n in params.num_values_per_shard],
        }
    )
    return key


def local_key(params, device, dtype, precision) -> dict:
    """Wisdom key for a local plan's engine decision: dims, the full stick
    layout (hashed — it drives sparse-y engagement), value count, dtype,
    precision, platform, jax version."""
    key = _base_key(
        "local",
        params.transform_type,
        (params.dim_x, params.dim_y, params.dim_z),
        dtype,
        "auto",
        precision,
    )
    key.update(
        {
            "platform": str(device.platform),
            "num_sticks": int(params.num_sticks),
            "num_elements": int(params.num_values),
            "sparsity_signature": sparsity_signature(
                params.stick_x, params.stick_y, params.value_indices
            ),
        }
    )
    return key


def tuned_exchange(params, mesh, dtype, engine, precision, pencil2, build,
                   overlap=None):
    """Resolve ``ExchangeType.DEFAULT`` under the TUNED policy.

    Returns ``(ExchangeType, overlap_chunks, record)``. Wisdom hit -> the
    stored choice, zero trials. Miss with trials allowed -> measure the
    candidate disciplines via ``build`` (a caller closure constructing
    explicit-discipline trial plans with the model policy), persist, return
    the winner. Miss with trials skipped (CPU-only host,
    ``runner.trials_allowed``) -> the model policy's pick (1-D slab:
    ``policy.resolve_default_for_plan``; 2-D pencil: DEFAULT is left for the
    engine's internal model resolver), recorded as ``provenance="model"``
    with the skip reason.

    ``overlap``: the caller's explicit exchange-overlap chunk count, or
    ``None`` to hand the knob to the tuner — candidates then include the
    OVERLAPPED chunk variants (``candidates.exchange_candidates``) and the
    measured chunk count persists in wisdom alongside the discipline. The
    model fallbacks resolve an unset knob through
    ``policy.resolve_overlap_chunks`` (the env default), never a constant
    the tuner cannot revisit.
    """
    from ..parallel.execution import mesh_process_span
    from ..parallel.policy import (
        resolve_default_for_plan,
        resolve_overlap_chunks,
    )
    from ..types import ExchangeType

    key = exchange_key(params, mesh, dtype, engine, precision, pencil2)
    # an explicit pin and the tuner-owned axis are different decision
    # problems — keying them apart stops a tuner-resolved entry from
    # answering (and silently overriding) a pinned construction
    key["overlap"] = "tuned" if overlap is None else int(overlap)
    store = active_store()
    fallback_overlap = resolve_overlap_chunks(overlap)

    def model(pick, trials, reason):
        return pick, fallback_overlap, _record(
            "model",
            hit=False,
            store=store,
            choice={"exchange_type": pick.name, "overlap": fallback_overlap},
            trials=trials,
            reason=reason,
            key=key,
        )

    if params.num_shards <= 1:
        # no exchange happens on a single shard — the decision has zero
        # effect, so never pay trials for it (mirrors the model path's
        # num_shards <= 1 shortcut in resolve_default_for_plan)
        pick = (
            ExchangeType.DEFAULT if pencil2 else ExchangeType.BUFFERED
        )
        return model(pick, [], "single shard: exchange discipline has no effect")
    if mesh_process_span(mesh) > 1:
        # Multi-host meshes: tuning is per-process, so one host hitting
        # wisdom while another runs trial collectives — or two hosts'
        # best-of-repeats disagreeing — would compile mismatched collective
        # programs and deadlock the mesh. Every process must reach the same
        # answer deterministically: the model policy (which depends only on
        # replicated plan geometry), never wisdom or trials.
        pick = (
            ExchangeType.DEFAULT  # engine-internal model resolution
            if pencil2
            else resolve_default_for_plan(params, mesh, dtype)
        )
        return model(
            pick, [], "multi-host mesh: tuning requires cross-process agreement"
        )
    entry = store.lookup(key)
    if entry is not None:
        choice = entry["choice"]
        return (
            ExchangeType[choice["exchange_type"]],
            # the key separates pinned and tuner-owned entries, so the
            # stored count matches this construction's pin context; the
            # explicit pin still wins outright for defense in depth
            int(choice.get("overlap", 1)) if overlap is None
            else fallback_overlap,
            _record(
                "wisdom",
                hit=True,
                store=store,
                choice=choice,
                trials=entry.get("trials", []),
                reason="wisdom hit",
                key=key,
            ),
        )
    platform = str(mesh.devices.flat[0].platform)
    if not trials_allowed(platform):
        reason = store.fallback_reason or (
            f"trials skipped on CPU-only host (set {TUNE_CPU_ENV}=1 to allow)"
        )
        if pencil2:
            pick = ExchangeType.DEFAULT  # engine-internal model resolution
        else:
            pick = resolve_default_for_plan(params, mesh, dtype)
        return model(pick, [], reason)
    if pencil2:
        cands = exchange_candidates(pencil2=True, overlap=overlap)
    else:
        from ..parallel.ragged import _ragged_a2a_supported
        from ..types import wire_scalar_bytes

        cands = exchange_candidates(
            params.num_sticks_per_shard,
            params.local_z_lengths,
            one_shot_supported=params.num_shards > 1
            and _ragged_a2a_supported(mesh),
            wire_scalar_bytes=wire_scalar_bytes(ExchangeType.DEFAULT, dtype),
            overlap=overlap,
        )
    trials = run_trials(build, cands)
    measured = [row for row in trials if "ms" in row]
    if not measured:
        # every candidate failed to build/compile/run: degrade to the model
        # policy (tuning never fails plan construction); nothing is persisted
        pick = (
            ExchangeType.DEFAULT
            if pencil2
            else resolve_default_for_plan(params, mesh, dtype)
        )
        return model(pick, trials, "all trial candidates failed")
    choice = {
        "exchange_type": measured[0]["exchange_type"],
        "overlap": int(measured[0].get("overlap", 1)),
    }
    store.record(key, make_entry(key, choice, trials))
    return ExchangeType[choice["exchange_type"]], choice["overlap"], _record(
        "wisdom",
        hit=False,
        store=store,
        choice=choice,
        trials=trials,
        reason=store.fallback_reason or "measured",
        key=key,
    )


def tuned_local(params, device, dtype, precision, build, fuse=None):
    """Resolve a local plan's ``engine="auto"`` under the TUNED policy.

    Returns ``(choice, record)`` where ``choice`` is a local candidate dict
    (``engine`` + ``env`` overrides the caller applies around its engine
    construction). Same hit/trial/model-fallback ladder as
    :func:`tuned_exchange`; the model fallback is the static auto rule
    (XLA on CPU, MXU elsewhere).

    ``fuse``: the caller's explicit ``fuse=`` kwarg, or None when the tuner
    owns the fusion axis (same contract as ``tuned_exchange``'s ``overlap``).
    The pin is part of the wisdom key — a pinned plan's winner (measured at
    the pinned state, see ``local_candidates``) never answers a tuner-owned
    lookup, whose ``*/staged``-labeled envs would otherwise be overridden by
    the kwarg while the provenance claims the trialed variant ran."""
    key = local_key(params, device, dtype, precision)
    key["fuse"] = "tuned" if fuse is None else int(bool(fuse))
    store = active_store()
    entry = store.lookup(key)
    if entry is not None:
        return dict(entry["choice"]), _record(
            "wisdom",
            hit=True,
            store=store,
            choice=entry["choice"],
            trials=entry.get("trials", []),
            reason="wisdom hit",
            key=key,
        )
    platform = str(device.platform)
    if not trials_allowed(platform):
        reason = store.fallback_reason or (
            f"trials skipped on CPU-only host (set {TUNE_CPU_ENV}=1 to allow)"
        )
        choice = {
            "label": "xla" if platform == "cpu" else "mxu",
            "engine": "xla" if platform == "cpu" else "mxu",
            "env": {},
        }
        return choice, _record(
            "model",
            hit=False,
            store=store,
            choice=choice,
            trials=[],
            reason=reason,
            key=key,
        )
    trials = run_trials(build, local_candidates(platform, dtype, fuse=fuse))
    measured = [row for row in trials if "ms" in row]
    if not measured:
        choice = {
            "label": "xla" if platform == "cpu" else "mxu",
            "engine": "xla" if platform == "cpu" else "mxu",
            "env": {},
        }
        return choice, _record(
            "model",
            hit=False,
            store=store,
            choice=choice,
            trials=trials,
            reason="all trial candidates failed",
            key=key,
        )
    best = measured[0]
    choice = {"label": best["label"], "engine": best["engine"], "env": best["env"]}
    store.record(key, make_entry(key, choice, trials))
    return dict(choice), _record(
        "wisdom",
        hit=False,
        store=store,
        choice=choice,
        trials=trials,
        reason=store.fallback_reason or "measured",
        key=key,
    )


def batch_key(params, device, dtype, precision, batch_max) -> dict:
    """Wisdom key for the fused batch-size axis: the local-plan decision
    key plus the batcher's coalescing bound (it caps the candidate list, so
    a cap change is a different decision problem — the ``overlap`` pin
    rule)."""
    key = _base_key(
        "batch",
        params.transform_type,
        (params.dim_x, params.dim_y, params.dim_z),
        dtype,
        "auto",
        precision,
    )
    key.update(
        {
            "platform": str(device.platform),
            "num_sticks": int(params.num_sticks),
            "num_elements": int(params.num_values),
            "sparsity_signature": sparsity_signature(
                params.stick_x, params.stick_y, params.value_indices
            ),
            "batch_max": None if batch_max is None else int(batch_max),
        }
    )
    return key


def tuned_batch(transform, batch_max=None):
    """Resolve the fused batch-size axis (``fused/bN``) for ``transform``.

    Returns ``(choice, record)``: ``choice["batch"]`` is the measured batch
    size the serving batcher chunks coalesced batches to, or ``None`` for
    uncapped (every model fallback — trials skipped on CPU-only hosts,
    batch fusion unavailable, all candidates failed — keeps today's
    whole-batch behavior). Same hit/trial/model ladder as
    :func:`tuned_local`; trials run on the plan's OWN batched programs
    (:func:`spfft_tpu.tuning.runner.run_batch_trials` — seconds per
    transform, wall / B), and the winner persists in wisdom so a warm store
    reproduces the cap with zero trials."""
    key = batch_key(
        transform._params, transform.device, transform.dtype,
        transform._precision, batch_max,
    )
    store = active_store()

    def model(reason, trials=()):
        choice = {"label": "fused/uncapped", "batch": None}
        return choice, _record(
            "model",
            hit=False,
            store=store,
            choice=choice,
            trials=list(trials),
            reason=reason,
            key=key,
        )

    entry = store.lookup(key)
    if entry is not None:
        return dict(entry["choice"]), _record(
            "wisdom",
            hit=True,
            store=store,
            choice=entry["choice"],
            trials=entry.get("trials", []),
            reason="wisdom hit",
            key=key,
        )
    platform = str(transform.device.platform)
    if not trials_allowed(platform):
        return model(
            store.fallback_reason
            or f"trials skipped on CPU-only host (set {TUNE_CPU_ENV}=1 to allow)"
        )
    if not transform._exec._ir.batch_available():
        return model("batch fusion unavailable on this plan")
    trials = run_batch_trials(transform, batch_candidates(batch_max))
    measured = [row for row in trials if "ms" in row]
    if not measured:
        return model("all trial candidates failed", trials)
    best = measured[0]
    choice = {"label": best["label"], "batch": int(best["batch"])}
    store.record(key, make_entry(key, choice, trials))
    return dict(choice), _record(
        "wisdom",
        hit=False,
        store=store,
        choice=choice,
        trials=trials,
        reason=store.fallback_reason or "measured",
        key=key,
    )


def wisdom_state(transform=None) -> dict:
    """Reproducibility stamp for benchmark JSON: where wisdom lives and what
    the given plan's decision provenance was (bench.py /
    programs/benchmark.py embed this so perf numbers are diffable against
    HOW the plan was decided)."""
    path = knobs.get_str(WISDOM_ENV)
    state = {"path": path, "configured": path is not None}
    if transform is not None:
        state["policy"] = getattr(transform, "_policy", "default")
        rec = getattr(transform, "_tuning", None)
        state["provenance"] = rec["provenance"] if rec else "model"
        state["hit"] = rec["hit"] if rec else None
    return state
