"""Persistent plan wisdom — the FFTW-wisdom analogue for this build.

A wisdom store maps a *tuning key* (the plan properties that determine which
candidate wins: grid dims, sparsity signature, mesh shape, dtype, requested
engine, platform, jax version) to the measured choice and its trial table.
Two stores exist behind one interface:

- :class:`WisdomStore` — JSON on disk at the path named by the
  ``SPFFT_TPU_WISDOM`` env knob. Versioned schema (:data:`WISDOM_SCHEMA`);
  a corrupted file or a schema-version mismatch degrades to an empty store
  (every lookup misses, ``fallback_reason`` says why) instead of raising —
  plan construction must never fail because wisdom rotted. A corrupt file is
  additionally *quarantined*: renamed to ``*.corrupt`` and warned about once
  per process (``wisdom_quarantined_total`` metric), so the broken JSON is
  parsed once, not on every plan construction. Writes are atomic (tempfile +
  ``os.replace``) so concurrent tuners cannot tear the file, and transient
  write failures get bounded retry with exponential backoff
  (``wisdom_retries_total``; exhausted retries degrade to a recorded
  ``wisdom_save_failed`` event — the plan keeps its measured choice, only
  persistence is lost). Fault sites ``wisdom.load`` / ``wisdom.save``
  (:mod:`spfft_tpu.faults`) make both paths chaos-testable.
- :class:`MemoryStore` — the process-global fallback when ``SPFFT_TPU_WISDOM``
  is unset: repeated constructions in one process still reuse trials, nothing
  persists.

Keying doubles as invalidation: any change to the key fields — including the
jax version and the platform the mesh lives on — lands in a different entry,
so stale wisdom is never *applied*, only bypassed (docs/details.md
"Autotuning & wisdom").
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time
import warnings

from .. import faults, knobs, obs

WISDOM_ENV = "SPFFT_TPU_WISDOM"
WISDOM_SCHEMA = "spfft_tpu.tuning.wisdom/1"

# Bounded retry for transient wisdom-write failures (NFS hiccups, lock
# contention): attempts and base backoff of the exponential ladder
# (0.01 s, 0.02 s between the three attempts).
WISDOM_SAVE_ATTEMPTS = 3
WISDOM_SAVE_BACKOFF_S = 0.01

# Ambient engine/exchange env knobs that change measured performance (the
# docs/details.md engine-knob table, minus pure model/docs knobs). Their
# values at tuning time ride in every wisdom key (:func:`env_signature`) —
# trials run UNDER these settings, so an entry measured with, say,
# SPFFT_TPU_ONESHOT_TRANSPORT=chain must not answer for a run without it.
# Candidate-level overrides (tuning/candidates.py) sit on top of this ambient
# state and are recorded in the choice itself.
PERF_ENV_KNOBS = (
    "SPFFT_TPU_GAUSS_MM",
    "SPFFT_TPU_PAIR_COPY",
    "SPFFT_TPU_SPARSE_Y",
    "SPFFT_TPU_SPARSE_Y_BLOCKS",
    "SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC",
    "SPFFT_TPU_SPARSE_Y_MATRIX_MB",
    "SPFFT_TPU_COPY_DENSE_FRAC",
    "SPFFT_TPU_XPAD",
    "SPFFT_TPU_F64_STAGE_MB",
    "SPFFT_TPU_PHASE_TABLE_MB",
    "SPFFT_TPU_PHASE_DEVICE_MB",
    "SPFFT_TPU_ONESHOT_TRANSPORT",
)

_lock = threading.Lock()
_warn_lock = threading.Lock()  # guards _quarantine_warned (NOT _lock: the
# quarantine path runs inside _load, which record() calls under _lock)
_quarantine_warned: set = set()  # paths already warned about (once/process)


def env_signature() -> dict:
    """The ambient values of :data:`PERF_ENV_KNOBS` (None = unset/default),
    embedded in every tuning key so knob changes invalidate instead of
    aliasing (kept inline, not hashed — small and debuggable)."""
    return {k: knobs.raw(k) for k in PERF_ENV_KNOBS}


def sparsity_signature(*arrays) -> str:
    """Stable 16-hex digest of the stick/value layout arrays — the sparsity
    part of a tuning key. Hashed (not stored raw) because a 512^3-class plan
    carries millions of indices; two plans with the same digest share the
    same measured trade-offs."""
    import numpy as np

    h = hashlib.sha1()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a, dtype=np.int64))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory exclusive lock on a sidecar file for cross-process
    read-modify-write safety; degrades to no lock where ``fcntl`` is
    unavailable (non-POSIX) — the module lock still covers threads."""
    try:
        import fcntl
    except ImportError:
        yield
        return
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)  # closing drops the flock


def key_digest(key: dict) -> str:
    """Canonical entry id of a tuning key (sorted-JSON sha256, 24 hex)."""
    return hashlib.sha256(
        json.dumps(key, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:24]


def make_entry(key: dict, choice: dict, trials: list) -> dict:
    """A store entry: the full key (debuggability — digests are one-way),
    the winning candidate, and the measured trial table that picked it."""
    return {
        "key": key,
        "choice": choice,
        "trials": trials,
        "created_unix": time.time(),
    }


def best_measured_ms(entry: dict) -> float | None:
    """The fastest measured trial of a store entry (None when the entry
    carries no measured rows — e.g. a hand-written or model-derived one).
    The merge tie-breaker: between two entries for one key, the one whose
    winning choice was backed by the better measurement is the one a fleet
    should keep."""
    times = []
    for row in entry.get("trials", ()):
        if not (isinstance(row, dict) and "ms" in row):
            continue
        try:
            times.append(float(row["ms"]))
        except (TypeError, ValueError):
            continue  # malformed trial row (hand-edited bundle): not measured
    return min(times) if times else None


def merge_entries(existing: dict, incoming: dict) -> tuple:
    """Merge ``incoming`` bundle entries into ``existing`` (in place),
    best-measured-wins on key conflict; returns ``(added, replaced)``.

    An incoming entry replaces an existing one only when it is strictly
    better measured (lower best trial ms, or measured at all where the
    existing one is not); ties and unmeasured-vs-unmeasured keep the
    existing entry — merging the same bundle twice is a no-op, so fleet
    bundle distribution is idempotent."""
    added = replaced = 0
    for digest, entry in incoming.items():
        if not isinstance(entry, dict) or not isinstance(
            entry.get("choice"), dict
        ):
            continue  # malformed rows never displace measured wisdom
        current = existing.get(digest)
        if current is None:
            existing[digest] = entry
            added += 1
            continue
        new_ms = best_measured_ms(entry)
        cur_ms = best_measured_ms(current)
        if new_ms is not None and (cur_ms is None or new_ms < cur_ms):
            existing[digest] = entry
            replaced += 1
    return added, replaced


def quarantine_file(path: str, why: str) -> None:
    """Rename a corrupt wisdom file/bundle to ``<path>.corrupt`` (parsed
    once, not repeatedly), warn once per process and count
    ``wisdom_quarantined_total`` — the one corruption treatment shared by
    store loads and bundle merges. A failing rename (permissions, races)
    degrades silently; the caller's degrade-to-empty behavior stands."""
    path = str(path)
    target = path + ".corrupt"
    try:
        os.replace(path, target)
    except OSError:
        return
    obs.counter("wisdom_quarantined_total").inc()
    faults.record_degradation(
        "wisdom_quarantined", why, path=path, quarantined_to=target
    )
    with _warn_lock:
        first = path not in _quarantine_warned
        _quarantine_warned.add(path)
    if first:
        warnings.warn(
            f"corrupt wisdom store {path!r} quarantined to {target!r}: {why}",
            RuntimeWarning,
            stacklevel=4,
        )


def _write_bundle(path: str, entries: dict, *, dir: str) -> None:
    """Atomic write of a ``{schema, entries}`` wisdom document (tempfile +
    ``os.replace`` — the store's torn-write rule, shared with bundles)."""
    doc = {"schema": WISDOM_SCHEMA, "entries": entries}
    fd, tmp = tempfile.mkstemp(prefix=".wisdom.", dir=dir)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, str(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _load_bundle(path: str) -> dict:
    """Entries of a fleet bundle for merging. Every bad bundle raises typed
    (a merge is an explicit operator action — unlike plan-time loads it
    must fail loudly, not degrade): unreadable file, schema mismatch, and
    corruption — the last ALSO gets the store's quarantine treatment
    (renamed ``*.corrupt``, warned once, counted) before raising, so the
    broken file is parsed once and the operator is told both facts."""
    from ..errors import InvalidParameterError

    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise InvalidParameterError(
            f"wisdom bundle {str(path)!r} is unreadable: {e}"
        ) from e
    try:
        doc = json.loads(text)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        quarantine_file(path, faults.summarize(e))
        raise InvalidParameterError(
            f"wisdom bundle {str(path)!r} is corrupt "
            f"(quarantined to {str(path) + '.corrupt'!r}): "
            f"{faults.summarize(e)}"
        ) from e
    if not isinstance(doc, dict) or doc.get("schema") != WISDOM_SCHEMA:
        got = doc.get("schema") if isinstance(doc, dict) else type(doc).__name__
        raise InvalidParameterError(
            f"wisdom bundle {str(path)!r} schema mismatch: "
            f"{got!r} != {WISDOM_SCHEMA!r}"
        )
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


class WisdomStore:
    """JSON-file wisdom store (see module docstring for the contract)."""

    def __init__(self, path: str):
        self.path = str(path)
        self.fallback_reason: str | None = None

    def _quarantine(self, why: str) -> None:
        """Rename a corrupt store to ``<path>.corrupt`` so it is parsed once,
        not on every plan construction; warn once per process and count
        ``wisdom_quarantined_total``. A failing rename (permissions, races)
        keeps the degrade-to-empty behavior without quarantine."""
        quarantine_file(self.path, why)

    def _load(self) -> dict:
        """Parse the file into ``{digest: entry}``; empty on absence,
        corruption (which also quarantines the file — see
        :meth:`_quarantine`), or schema mismatch (recording
        ``fallback_reason``)."""
        self.fallback_reason = None
        try:
            with open(self.path) as f:
                text = f.read()
            # fault site wisdom.load: `corrupt` mangles the text (exercising
            # the quarantine below), `raise` models an unreadable store
            text = faults.site("wisdom.load", payload=text)
            doc = json.loads(text)
        except FileNotFoundError:
            return {}
        except faults.InjectedFault as e:
            self.fallback_reason = f"wisdom load fault: {e}"
            faults.record_degradation("wisdom_load_failed", str(e), path=self.path)
            return {}
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self.fallback_reason = f"corrupt wisdom file: {faults.summarize(e)}"
            self._quarantine(faults.summarize(e))
            return {}
        except OSError as e:
            self.fallback_reason = f"corrupt wisdom file: {faults.summarize(e)}"
            return {}
        if not isinstance(doc, dict) or doc.get("schema") != WISDOM_SCHEMA:
            self.fallback_reason = (
                f"wisdom schema mismatch: {doc.get('schema') if isinstance(doc, dict) else type(doc).__name__!s}"
                f" != {WISDOM_SCHEMA}"
            )
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}

    def lookup(self, key: dict) -> dict | None:
        entry = self._load().get(key_digest(key))
        # entries written by hand/a future version must at least carry a choice
        if entry is not None and not isinstance(entry.get("choice"), dict):
            entry = None
        obs.trace.event(
            "wisdom.load",
            path=self.path,
            outcome=self.fallback_reason or "ok",
            hit=entry is not None,
        )
        return entry

    def record(self, key: dict, entry: dict) -> None:
        """Read-modify-write under the module lock (threads) plus an
        advisory ``flock`` on a sidecar lockfile (concurrent processes
        sharing one wisdom file — without it, two tuners' load/replace
        cycles would silently drop each other's entries), finished with an
        atomic replace. A corrupt existing file is overwritten with a fresh
        store — the FFTW-wisdom behavior (re-measure and move on, never
        wedge). Transient failures anywhere in the attempt — directory
        creation, lockfile acquisition, the write itself (fault site
        ``wisdom.save``) — are retried :data:`WISDOM_SAVE_ATTEMPTS` times
        with exponential backoff (``wisdom_retries_total``); both locks are
        re-acquired per attempt and the backoff sleeps OUTSIDE them, so a
        failing saver never serializes other savers behind its backoff.
        Exhausted retries degrade to a recorded ``wisdom_save_failed`` event
        instead of raising — the caller's plan keeps its measured choice,
        only persistence is lost."""

        def mutate(entries):
            entries[key_digest(key)] = entry

        self._update(mutate)

    def _update(self, mutate) -> bool:
        """One atomic read-modify-write of the store file (``mutate`` edits
        the ``{digest: entry}`` table in place) under the module lock, the
        advisory flock, and the bounded-retry/backoff ladder — the single
        write discipline shared by :meth:`record` and :meth:`merge`.
        Returns whether the write landed (False = recorded save failure)."""
        last: Exception | None = None
        for attempt in range(WISDOM_SAVE_ATTEMPTS):
            try:
                faults.site("wisdom.save")
                with _lock:
                    d = os.path.dirname(os.path.abspath(self.path)) or "."
                    os.makedirs(d, exist_ok=True)
                    with _file_lock(self.path + ".lock"):
                        entries = self._load()
                        mutate(entries)
                        _write_bundle(self.path, entries, dir=d)
                obs.trace.event(
                    "wisdom.save", path=self.path, outcome="ok",
                    attempt=attempt + 1,
                )
                return True
            except (OSError, faults.InjectedFault) as e:
                last = e
                obs.counter("wisdom_retries_total").inc()
                if attempt < WISDOM_SAVE_ATTEMPTS - 1:
                    time.sleep(WISDOM_SAVE_BACKOFF_S * (2**attempt))
        self._save_failed(last)
        return False

    def entries(self) -> dict:
        """Copy of the store's ``{digest: entry}`` table."""
        with _lock:
            return dict(self._load())

    def export(self, path: str) -> int:
        """Write the store's entries as a fleet bundle at ``path`` (atomic;
        the bundle IS a wisdom file — same schema, loadable as a store or
        mergeable into one). Returns the number of entries exported.

        The fleet-bundle half of ROADMAP item 5: one tuned host exports, a
        new host merges (or just points ``SPFFT_TPU_WISDOM`` at the bundle)
        and boots pre-tuned instead of re-measuring per machine."""
        entries = self.entries()
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        _write_bundle(path, entries, dir=d)
        obs.trace.event(
            "wisdom.save", path=str(path), outcome="ok", attempt=1
        )
        return len(entries)

    def merge(self, bundle_path: str) -> tuple:
        """Merge a fleet bundle into this store, best-measured-wins on key
        conflict (:func:`merge_entries`); returns ``(added, replaced)``.

        Version-checked: a bundle with a mismatched schema raises typed
        :class:`InvalidParameterError` (silently merging entries whose key
        semantics changed would poison every host it touches). A corrupt
        bundle gets exactly the store's own corruption treatment —
        quarantined to ``*.corrupt``, warned once, counted — and merges
        nothing."""
        incoming = _load_bundle(bundle_path)
        if not incoming:
            return (0, 0)
        counts = []

        def mutate(entries):
            counts.clear()
            counts.append(merge_entries(entries, incoming))

        if not self._update(mutate):
            return (0, 0)
        return counts[0]

    def _save_failed(self, exc) -> None:
        """Exhausted-retry terminal: count and record, never raise (ladder
        rung 2 — a dead store must not fail plan construction)."""
        obs.counter("wisdom_save_failures_total").inc()
        obs.trace.event(
            "wisdom.save", path=self.path, outcome="failed", reason=str(exc)
        )
        faults.record_degradation(
            "wisdom_save_failed", str(exc), path=self.path
        )


class MemoryStore:
    """Process-global in-memory store (``SPFFT_TPU_WISDOM`` unset)."""

    path = None
    fallback_reason = None
    _entries: dict = {}

    def lookup(self, key: dict) -> dict | None:
        entry = MemoryStore._entries.get(key_digest(key))
        obs.trace.event(
            "wisdom.load", path=None, outcome="ok", hit=entry is not None
        )
        return entry

    def record(self, key: dict, entry: dict) -> None:
        with _lock:
            MemoryStore._entries[key_digest(key)] = entry
        obs.trace.event("wisdom.save", path=None, outcome="ok", attempt=1)

    def entries(self) -> dict:
        with _lock:
            return dict(MemoryStore._entries)

    def export(self, path: str) -> int:
        """Write the process memory store as a fleet bundle (same format as
        :meth:`WisdomStore.export` — a host tuned without a configured
        ``SPFFT_TPU_WISDOM`` can still hand its wisdom to the fleet)."""
        entries = self.entries()
        d = os.path.dirname(os.path.abspath(str(path))) or "."
        os.makedirs(d, exist_ok=True)
        _write_bundle(path, entries, dir=d)
        obs.trace.event("wisdom.save", path=str(path), outcome="ok", attempt=1)
        return len(entries)

    def merge(self, bundle_path: str) -> tuple:
        """Merge a fleet bundle into process memory (same rules as
        :meth:`WisdomStore.merge`: best-measured-wins, version-checked,
        corrupt bundles quarantined)."""
        incoming = _load_bundle(bundle_path)
        if not incoming:
            return (0, 0)
        with _lock:
            return merge_entries(MemoryStore._entries, incoming)


def active_store():
    """The store tuned plans consult: the file store at ``SPFFT_TPU_WISDOM``
    when set, else the process-global memory store."""
    path = knobs.get_str(WISDOM_ENV)
    return WisdomStore(path) if path else MemoryStore()


def clear_memory() -> None:
    """Drop the process-global memory store (tests / fresh windows)."""
    with _lock:
        MemoryStore._entries.clear()
