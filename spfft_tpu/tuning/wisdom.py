"""Persistent plan wisdom — the FFTW-wisdom analogue for this build.

A wisdom store maps a *tuning key* (the plan properties that determine which
candidate wins: grid dims, sparsity signature, mesh shape, dtype, requested
engine, platform, jax version) to the measured choice and its trial table.
Two stores exist behind one interface:

- :class:`WisdomStore` — JSON on disk at the path named by the
  ``SPFFT_TPU_WISDOM`` env knob. Versioned schema (:data:`WISDOM_SCHEMA`);
  a corrupted file or a schema-version mismatch degrades to an empty store
  (every lookup misses, ``fallback_reason`` says why) instead of raising —
  plan construction must never fail because wisdom rotted. A corrupt file is
  additionally *quarantined*: renamed to ``*.corrupt`` and warned about once
  per process (``wisdom_quarantined_total`` metric), so the broken JSON is
  parsed once, not on every plan construction. Writes are atomic (tempfile +
  ``os.replace``) so concurrent tuners cannot tear the file, and transient
  write failures get bounded retry with exponential backoff
  (``wisdom_retries_total``; exhausted retries degrade to a recorded
  ``wisdom_save_failed`` event — the plan keeps its measured choice, only
  persistence is lost). Fault sites ``wisdom.load`` / ``wisdom.save``
  (:mod:`spfft_tpu.faults`) make both paths chaos-testable.
- :class:`MemoryStore` — the process-global fallback when ``SPFFT_TPU_WISDOM``
  is unset: repeated constructions in one process still reuse trials, nothing
  persists.

Keying doubles as invalidation: any change to the key fields — including the
jax version and the platform the mesh lives on — lands in a different entry,
so stale wisdom is never *applied*, only bypassed (docs/details.md
"Autotuning & wisdom").
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time
import warnings

from .. import faults, obs

WISDOM_ENV = "SPFFT_TPU_WISDOM"
WISDOM_SCHEMA = "spfft_tpu.tuning.wisdom/1"

# Bounded retry for transient wisdom-write failures (NFS hiccups, lock
# contention): attempts and base backoff of the exponential ladder
# (0.01 s, 0.02 s between the three attempts).
WISDOM_SAVE_ATTEMPTS = 3
WISDOM_SAVE_BACKOFF_S = 0.01

# Ambient engine/exchange env knobs that change measured performance (the
# docs/details.md engine-knob table, minus pure model/docs knobs). Their
# values at tuning time ride in every wisdom key (:func:`env_signature`) —
# trials run UNDER these settings, so an entry measured with, say,
# SPFFT_TPU_ONESHOT_TRANSPORT=chain must not answer for a run without it.
# Candidate-level overrides (tuning/candidates.py) sit on top of this ambient
# state and are recorded in the choice itself.
PERF_ENV_KNOBS = (
    "SPFFT_TPU_GAUSS_MM",
    "SPFFT_TPU_PAIR_COPY",
    "SPFFT_TPU_SPARSE_Y",
    "SPFFT_TPU_SPARSE_Y_BLOCKS",
    "SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC",
    "SPFFT_TPU_SPARSE_Y_MATRIX_MB",
    "SPFFT_TPU_COPY_DENSE_FRAC",
    "SPFFT_TPU_XPAD",
    "SPFFT_TPU_F64_STAGE_MB",
    "SPFFT_TPU_PHASE_TABLE_MB",
    "SPFFT_TPU_PHASE_DEVICE_MB",
    "SPFFT_TPU_ONESHOT_TRANSPORT",
)

_lock = threading.Lock()
_warn_lock = threading.Lock()  # guards _quarantine_warned (NOT _lock: the
# quarantine path runs inside _load, which record() calls under _lock)
_quarantine_warned: set = set()  # paths already warned about (once/process)


def env_signature() -> dict:
    """The ambient values of :data:`PERF_ENV_KNOBS` (None = unset/default),
    embedded in every tuning key so knob changes invalidate instead of
    aliasing (kept inline, not hashed — small and debuggable)."""
    return {k: os.environ.get(k) for k in PERF_ENV_KNOBS}


def sparsity_signature(*arrays) -> str:
    """Stable 16-hex digest of the stick/value layout arrays — the sparsity
    part of a tuning key. Hashed (not stored raw) because a 512^3-class plan
    carries millions of indices; two plans with the same digest share the
    same measured trade-offs."""
    import numpy as np

    h = hashlib.sha1()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a, dtype=np.int64))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory exclusive lock on a sidecar file for cross-process
    read-modify-write safety; degrades to no lock where ``fcntl`` is
    unavailable (non-POSIX) — the module lock still covers threads."""
    try:
        import fcntl
    except ImportError:
        yield
        return
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)  # closing drops the flock


def key_digest(key: dict) -> str:
    """Canonical entry id of a tuning key (sorted-JSON sha256, 24 hex)."""
    return hashlib.sha256(
        json.dumps(key, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:24]


def make_entry(key: dict, choice: dict, trials: list) -> dict:
    """A store entry: the full key (debuggability — digests are one-way),
    the winning candidate, and the measured trial table that picked it."""
    return {
        "key": key,
        "choice": choice,
        "trials": trials,
        "created_unix": time.time(),
    }


class WisdomStore:
    """JSON-file wisdom store (see module docstring for the contract)."""

    def __init__(self, path: str):
        self.path = str(path)
        self.fallback_reason: str | None = None

    def _quarantine(self, why: str) -> None:
        """Rename a corrupt store to ``<path>.corrupt`` so it is parsed once,
        not on every plan construction; warn once per process and count
        ``wisdom_quarantined_total``. A failing rename (permissions, races)
        keeps the degrade-to-empty behavior without quarantine."""
        target = self.path + ".corrupt"
        try:
            os.replace(self.path, target)
        except OSError:
            return
        obs.counter("wisdom_quarantined_total").inc()
        faults.record_degradation(
            "wisdom_quarantined", why, path=self.path, quarantined_to=target
        )
        with _warn_lock:
            first = self.path not in _quarantine_warned
            _quarantine_warned.add(self.path)
        if first:
            warnings.warn(
                f"corrupt wisdom store {self.path!r} quarantined to "
                f"{target!r}: {why}",
                RuntimeWarning,
                stacklevel=4,
            )

    def _load(self) -> dict:
        """Parse the file into ``{digest: entry}``; empty on absence,
        corruption (which also quarantines the file — see
        :meth:`_quarantine`), or schema mismatch (recording
        ``fallback_reason``)."""
        self.fallback_reason = None
        try:
            with open(self.path) as f:
                text = f.read()
            # fault site wisdom.load: `corrupt` mangles the text (exercising
            # the quarantine below), `raise` models an unreadable store
            text = faults.site("wisdom.load", payload=text)
            doc = json.loads(text)
        except FileNotFoundError:
            return {}
        except faults.InjectedFault as e:
            self.fallback_reason = f"wisdom load fault: {e}"
            faults.record_degradation("wisdom_load_failed", str(e), path=self.path)
            return {}
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self.fallback_reason = f"corrupt wisdom file: {faults.summarize(e)}"
            self._quarantine(faults.summarize(e))
            return {}
        except OSError as e:
            self.fallback_reason = f"corrupt wisdom file: {faults.summarize(e)}"
            return {}
        if not isinstance(doc, dict) or doc.get("schema") != WISDOM_SCHEMA:
            self.fallback_reason = (
                f"wisdom schema mismatch: {doc.get('schema') if isinstance(doc, dict) else type(doc).__name__!s}"
                f" != {WISDOM_SCHEMA}"
            )
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}

    def lookup(self, key: dict) -> dict | None:
        entry = self._load().get(key_digest(key))
        # entries written by hand/a future version must at least carry a choice
        if entry is not None and not isinstance(entry.get("choice"), dict):
            entry = None
        obs.trace.event(
            "wisdom.load",
            path=self.path,
            outcome=self.fallback_reason or "ok",
            hit=entry is not None,
        )
        return entry

    def record(self, key: dict, entry: dict) -> None:
        """Read-modify-write under the module lock (threads) plus an
        advisory ``flock`` on a sidecar lockfile (concurrent processes
        sharing one wisdom file — without it, two tuners' load/replace
        cycles would silently drop each other's entries), finished with an
        atomic replace. A corrupt existing file is overwritten with a fresh
        store — the FFTW-wisdom behavior (re-measure and move on, never
        wedge). Transient failures anywhere in the attempt — directory
        creation, lockfile acquisition, the write itself (fault site
        ``wisdom.save``) — are retried :data:`WISDOM_SAVE_ATTEMPTS` times
        with exponential backoff (``wisdom_retries_total``); both locks are
        re-acquired per attempt and the backoff sleeps OUTSIDE them, so a
        failing saver never serializes other savers behind its backoff.
        Exhausted retries degrade to a recorded ``wisdom_save_failed`` event
        instead of raising — the caller's plan keeps its measured choice,
        only persistence is lost."""
        last: Exception | None = None
        for attempt in range(WISDOM_SAVE_ATTEMPTS):
            try:
                faults.site("wisdom.save")
                with _lock:
                    d = os.path.dirname(os.path.abspath(self.path)) or "."
                    os.makedirs(d, exist_ok=True)
                    with _file_lock(self.path + ".lock"):
                        entries = self._load()
                        entries[key_digest(key)] = entry
                        doc = {"schema": WISDOM_SCHEMA, "entries": entries}
                        fd, tmp = tempfile.mkstemp(prefix=".wisdom.", dir=d)
                        try:
                            with os.fdopen(fd, "w") as f:
                                json.dump(doc, f, indent=1, sort_keys=True)
                            os.replace(tmp, self.path)
                        except BaseException:
                            try:
                                os.unlink(tmp)
                            except OSError:
                                pass
                            raise
                obs.trace.event(
                    "wisdom.save", path=self.path, outcome="ok",
                    attempt=attempt + 1,
                )
                return
            except (OSError, faults.InjectedFault) as e:
                last = e
                obs.counter("wisdom_retries_total").inc()
                if attempt < WISDOM_SAVE_ATTEMPTS - 1:
                    time.sleep(WISDOM_SAVE_BACKOFF_S * (2**attempt))
        self._save_failed(last)

    def _save_failed(self, exc) -> None:
        """Exhausted-retry terminal: count and record, never raise (ladder
        rung 2 — a dead store must not fail plan construction)."""
        obs.counter("wisdom_save_failures_total").inc()
        obs.trace.event(
            "wisdom.save", path=self.path, outcome="failed", reason=str(exc)
        )
        faults.record_degradation(
            "wisdom_save_failed", str(exc), path=self.path
        )


class MemoryStore:
    """Process-global in-memory store (``SPFFT_TPU_WISDOM`` unset)."""

    path = None
    fallback_reason = None
    _entries: dict = {}

    def lookup(self, key: dict) -> dict | None:
        entry = MemoryStore._entries.get(key_digest(key))
        obs.trace.event(
            "wisdom.load", path=None, outcome="ok", hit=entry is not None
        )
        return entry

    def record(self, key: dict, entry: dict) -> None:
        with _lock:
            MemoryStore._entries[key_digest(key)] = entry
        obs.trace.event("wisdom.save", path=None, outcome="ok", attempt=1)


def active_store():
    """The store tuned plans consult: the file store at ``SPFFT_TPU_WISDOM``
    when set, else the process-global memory store."""
    path = os.environ.get(WISDOM_ENV)
    return WisdomStore(path) if path else MemoryStore()


def clear_memory() -> None:
    """Drop the process-global memory store (tests / fresh windows)."""
    with _lock:
        MemoryStore._entries.clear()
