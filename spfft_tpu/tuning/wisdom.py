"""Persistent plan wisdom — the FFTW-wisdom analogue for this build.

A wisdom store maps a *tuning key* (the plan properties that determine which
candidate wins: grid dims, sparsity signature, mesh shape, dtype, requested
engine, platform, jax version) to the measured choice and its trial table.
Two stores exist behind one interface:

- :class:`WisdomStore` — JSON on disk at the path named by the
  ``SPFFT_TPU_WISDOM`` env knob. Versioned schema (:data:`WISDOM_SCHEMA`);
  a corrupted file or a schema-version mismatch degrades to an empty store
  (every lookup misses, ``fallback_reason`` says why) instead of raising —
  plan construction must never fail because wisdom rotted. Writes are atomic
  (tempfile + ``os.replace``) so concurrent tuners cannot tear the file.
- :class:`MemoryStore` — the process-global fallback when ``SPFFT_TPU_WISDOM``
  is unset: repeated constructions in one process still reuse trials, nothing
  persists.

Keying doubles as invalidation: any change to the key fields — including the
jax version and the platform the mesh lives on — lands in a different entry,
so stale wisdom is never *applied*, only bypassed (docs/details.md
"Autotuning & wisdom").
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time

WISDOM_ENV = "SPFFT_TPU_WISDOM"
WISDOM_SCHEMA = "spfft_tpu.tuning.wisdom/1"

# Ambient engine/exchange env knobs that change measured performance (the
# docs/details.md engine-knob table, minus pure model/docs knobs). Their
# values at tuning time ride in every wisdom key (:func:`env_signature`) —
# trials run UNDER these settings, so an entry measured with, say,
# SPFFT_TPU_ONESHOT_TRANSPORT=chain must not answer for a run without it.
# Candidate-level overrides (tuning/candidates.py) sit on top of this ambient
# state and are recorded in the choice itself.
PERF_ENV_KNOBS = (
    "SPFFT_TPU_GAUSS_MM",
    "SPFFT_TPU_PAIR_COPY",
    "SPFFT_TPU_SPARSE_Y",
    "SPFFT_TPU_SPARSE_Y_BLOCKS",
    "SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC",
    "SPFFT_TPU_SPARSE_Y_MATRIX_MB",
    "SPFFT_TPU_COPY_DENSE_FRAC",
    "SPFFT_TPU_XPAD",
    "SPFFT_TPU_F64_STAGE_MB",
    "SPFFT_TPU_PHASE_TABLE_MB",
    "SPFFT_TPU_PHASE_DEVICE_MB",
    "SPFFT_TPU_ONESHOT_TRANSPORT",
)

_lock = threading.Lock()


def env_signature() -> dict:
    """The ambient values of :data:`PERF_ENV_KNOBS` (None = unset/default),
    embedded in every tuning key so knob changes invalidate instead of
    aliasing (kept inline, not hashed — small and debuggable)."""
    return {k: os.environ.get(k) for k in PERF_ENV_KNOBS}


def sparsity_signature(*arrays) -> str:
    """Stable 16-hex digest of the stick/value layout arrays — the sparsity
    part of a tuning key. Hashed (not stored raw) because a 512^3-class plan
    carries millions of indices; two plans with the same digest share the
    same measured trade-offs."""
    import numpy as np

    h = hashlib.sha1()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a, dtype=np.int64))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory exclusive lock on a sidecar file for cross-process
    read-modify-write safety; degrades to no lock where ``fcntl`` is
    unavailable (non-POSIX) — the module lock still covers threads."""
    try:
        import fcntl
    except ImportError:
        yield
        return
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)  # closing drops the flock


def key_digest(key: dict) -> str:
    """Canonical entry id of a tuning key (sorted-JSON sha256, 24 hex)."""
    return hashlib.sha256(
        json.dumps(key, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:24]


def make_entry(key: dict, choice: dict, trials: list) -> dict:
    """A store entry: the full key (debuggability — digests are one-way),
    the winning candidate, and the measured trial table that picked it."""
    return {
        "key": key,
        "choice": choice,
        "trials": trials,
        "created_unix": time.time(),
    }


class WisdomStore:
    """JSON-file wisdom store (see module docstring for the contract)."""

    def __init__(self, path: str):
        self.path = str(path)
        self.fallback_reason: str | None = None

    def _load(self) -> dict:
        """Parse the file into ``{digest: entry}``; empty on absence,
        corruption, or schema mismatch (recording ``fallback_reason``)."""
        self.fallback_reason = None
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return {}
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            self.fallback_reason = f"corrupt wisdom file: {str(e).splitlines()[0]}"
            return {}
        if not isinstance(doc, dict) or doc.get("schema") != WISDOM_SCHEMA:
            self.fallback_reason = (
                f"wisdom schema mismatch: {doc.get('schema') if isinstance(doc, dict) else type(doc).__name__!s}"
                f" != {WISDOM_SCHEMA}"
            )
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}

    def lookup(self, key: dict) -> dict | None:
        entry = self._load().get(key_digest(key))
        # entries written by hand/a future version must at least carry a choice
        if entry is not None and not isinstance(entry.get("choice"), dict):
            return None
        return entry

    def record(self, key: dict, entry: dict) -> None:
        """Read-modify-write under the module lock (threads) plus an
        advisory ``flock`` on a sidecar lockfile (concurrent processes
        sharing one wisdom file — without it, two tuners' load/replace
        cycles would silently drop each other's entries), finished with an
        atomic replace. A corrupt existing file is overwritten with a fresh
        store — the FFTW-wisdom behavior (re-measure and move on, never
        wedge)."""
        with _lock:
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            os.makedirs(d, exist_ok=True)
            with _file_lock(self.path + ".lock"):
                entries = self._load()
                entries[key_digest(key)] = entry
                doc = {"schema": WISDOM_SCHEMA, "entries": entries}
                fd, tmp = tempfile.mkstemp(prefix=".wisdom.", dir=d)
                try:
                    with os.fdopen(fd, "w") as f:
                        json.dump(doc, f, indent=1, sort_keys=True)
                    os.replace(tmp, self.path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise


class MemoryStore:
    """Process-global in-memory store (``SPFFT_TPU_WISDOM`` unset)."""

    path = None
    fallback_reason = None
    _entries: dict = {}

    def lookup(self, key: dict) -> dict | None:
        return MemoryStore._entries.get(key_digest(key))

    def record(self, key: dict, entry: dict) -> None:
        with _lock:
            MemoryStore._entries[key_digest(key)] = entry


def active_store():
    """The store tuned plans consult: the file store at ``SPFFT_TPU_WISDOM``
    when set, else the process-global memory store."""
    path = os.environ.get(WISDOM_ENV)
    return WisdomStore(path) if path else MemoryStore()


def clear_memory() -> None:
    """Drop the process-global memory store (tests / fresh windows)."""
    with _lock:
        MemoryStore._entries.clear()
