"""Candidate enumeration: what the autotuner is allowed to try.

The candidate set is not invented here — it lifts the alternatives the system
already structures elsewhere into trial plans:

- **Exchange candidates** (distributed plans): the disciplines of the DEFAULT
  cost model's table (``parallel/policy.alternative_costs`` — the same
  accounting plan cards embed), ordered by model cost so the trial log reads
  model-first and an early-exit budget would try the model's pick first.
- **Overlap candidates** (distributed plans): the chunk counts of the
  OVERLAPPED exchange discipline (chunked, double-buffered padded
  collectives — parallel/execution.py) as ``BUFFERED/ovC`` variants of the
  padded discipline, so the autotuner — not a constant — owns the
  communication/compute-overlap knob. An explicit ``overlap=`` pin disables
  the axis (every candidate is then trialed at the pinned chunk count).
- **Local candidates**: the local engine axis — the MXU matmul-DFT engine
  under its measured sparse-y auto knobs, the same engine with the sparse-y
  variants forced dense (the regime where the auto thresholds mis-predict),
  and the XLA engine (``jnp.fft``; pocketfft on CPU).

Every candidate is a plain JSON-stable dict: ``label`` (stable id, what
wisdom/trial tables store), plus the constructor-level facts a builder needs
(``exchange_type`` + ``overlap`` for distributed, ``engine`` + ``env``
overrides for local).
"""
from __future__ import annotations

import numpy as np

# Chunk counts the OVERLAPPED-discipline axis trials when the caller leaves
# the knob to the tuner (overlap=None). Small powers of two: chunking past
# a handful of chunks trades per-collective efficiency for no extra hiding
# (the hideable wire time saturates at (C-1)/C of min(exchange, compute)).
OVERLAP_CANDIDATE_CHUNKS = (2, 4)


def exchange_candidates(
    num_sticks_per_shard=None,
    local_z_lengths=None,
    *,
    one_shot_supported: bool = False,
    wire_scalar_bytes: int = 4,
    pencil2: bool = False,
    overlap=None,
) -> list:
    """Exchange-discipline candidates for a distributed plan.

    For 1-D slab geometry the model's cost table orders the list (cheapest
    modeled cost first) and each candidate carries its ``model_cost_bytes``
    so tuned plan cards can show model-vs-measured side by side. 2-D pencil
    plans get the same three base disciplines in enum order (their model
    table lives inside the engine, ``pencil2._resolve_pencil2_default``).
    ``one_shot_supported`` feeds the model table exactly as in
    ``resolve_default_exchange`` (the caller probes the backend once before
    trials — parallel/ragged.py ``_ragged_a2a_supported``).

    ``overlap=None`` adds the OVERLAPPED chunk variants of the padded
    discipline (``BUFFERED/ovC`` for C in :data:`OVERLAP_CANDIDATE_CHUNKS`)
    — modeled cost = the padded wire bytes plus C collective rounds, so the
    model ranks them behind plain BUFFERED and the measurement decides
    whether the hiding wins. An explicit integer pins every candidate at
    that chunk count instead (the caller fixed the knob; only the
    discipline axis is trialed)."""
    from ..types import ExchangeType

    disciplines = (
        ExchangeType.BUFFERED,
        ExchangeType.COMPACT_BUFFERED,
        ExchangeType.UNBUFFERED,
    )
    pinned = int(overlap) if overlap is not None else None
    if pencil2 or num_sticks_per_shard is None:
        cands = [
            {"label": d.name, "exchange_type": d.name, "overlap": pinned or 1}
            for d in disciplines
        ]
        if pinned is None:
            cands.extend(
                {
                    "label": f"BUFFERED/ov{c}",
                    "exchange_type": ExchangeType.BUFFERED.name,
                    "overlap": int(c),
                }
                for c in OVERLAP_CANDIDATE_CHUNKS
            )
        return cands
    from ..parallel.policy import alternative_costs, round_cost_bytes

    table = alternative_costs(
        num_sticks_per_shard,
        local_z_lengths,
        one_shot_supported=one_shot_supported,
        wire_scalar_bytes=wire_scalar_bytes,
    )
    cands = [
        {
            "label": d.name,
            "exchange_type": d.name,
            "overlap": pinned or 1,
            "model_cost_bytes": int(table[d]["cost_bytes"]),
        }
        for d in disciplines
    ]
    if pinned is None:
        wire = int(table[ExchangeType.BUFFERED]["wire_bytes"])
        per_round = round_cost_bytes()
        cands.extend(
            {
                "label": f"BUFFERED/ov{c}",
                "exchange_type": ExchangeType.BUFFERED.name,
                "overlap": int(c),
                "model_cost_bytes": int(wire + c * per_round),
            }
            for c in OVERLAP_CANDIDATE_CHUNKS
        )
    return sorted(cands, key=lambda c: c["model_cost_bytes"])


def sched_candidates(num_devices: int) -> list:
    """Placement-width candidates for the task-graph scheduler
    (:mod:`spfft_tpu.sched.placement`): how many devices the round-robin
    placement pass spreads independent transforms over.

    Powers of two up to the device count (plus the full count itself):
    width 1 is the everything-on-one-device pipeline (dispatch overlap
    only), the full width is the DaggerFFT-style spread (one transform's
    exchange/fence hides another's FFTs on a different device), and the
    measurement decides where the host's dispatch threads and memory
    bandwidth actually peak — on CPU meshes the devices share cores, so
    wider is routinely slower and the tuner must be allowed to say so."""
    n = max(1, int(num_devices))
    widths = []
    w = 1
    while w <= n:
        widths.append(w)
        w *= 2
    if widths[-1] != n:
        widths.append(n)
    return [{"label": f"rr{w}", "width": int(w)} for w in widths]


# Fused batch sizes the batch axis trials when the caller leaves the knob
# to the tuner: 1 is the per-request dispatch shape (the tuner must be
# allowed to say batching loses — on core-shared CPU meshes it sometimes
# does), small powers of two amortize per-dispatch overhead, and the
# serving batcher's batch_max bounds the list from above.
BATCH_CANDIDATE_SIZES = (1, 4, 8)


def batch_candidates(batch_max=None) -> list:
    """Fused-batch-size candidates (``fused/bN``) for the batch-fused
    dispatch axis (:func:`spfft_tpu.tuning.tuned_batch`): how many
    same-geometry transforms one stacked program runs per dispatch. The
    measurement unit is seconds per TRANSFORM (wall / B), so candidates
    compare like for like; the winner persists in wisdom next to the
    fused/staged axis and the serving batcher chunks its coalesced batches
    to it. ``batch_max`` (the batcher's coalescing bound) caps the list —
    a batch the batcher can never assemble is not worth a trial."""
    sizes = [
        b
        for b in BATCH_CANDIDATE_SIZES
        if batch_max is None or b <= int(batch_max)
    ]
    if not sizes:
        sizes = [1]
    return [{"label": f"fused/b{b}", "batch": int(b)} for b in sizes]


def local_candidates(platform: str, dtype=None, fuse=None) -> list:
    """Local-plan candidates: engine x sparse-y-knob x fusion variants.

    The MXU candidates differ only in env overrides applied for the trial
    (and for the chosen plan's engine construction) — the knobs are already
    single-sourced in ``ops/fft.py`` / ``spfft_tpu.ir``, so the tuner tries
    them rather than re-modeling them. Platform only orders the list (likely
    winner first: MXU on accelerators, XLA/pocketfft on CPU); every
    candidate is buildable everywhere, and the platform is part of the
    wisdom key.

    The fusion axis (spfft_tpu.ir): the bare engine labels run FUSED (one
    IR-compiled program per direction — the default); ``*/staged`` runs the
    per-node dispatch reference, so a regime where fusion somehow loses
    (enormous programs, compile-memory pressure) is measurable rather than
    assumed away; ``mxu/bf16-twiddle`` is the mixed-precision FUSED variant
    (bf16 DFT matrices, f32 activations — f32 plans only, see
    ``ops/fft.twiddle_bf16_enabled``; when ``dtype`` says the plan is f64
    the knob is a no-op, so the candidate is dropped rather than trialed as
    a duplicate of the bare ``mxu`` whose noise win would persist a
    misleading mixed-precision choice). The winning variant's env persists
    in wisdom with the choice, so a warm store reproduces the fusion
    decision with zero trials.

    ``fuse``: the caller's explicit ``fuse=`` kwarg, or None to let the
    tuner own the axis. An explicit kwarg beats every candidate's env in
    ``ir.resolve_fuse``, so under a pin the ``*/staged`` variants would
    silently measure the pinned state while their label (and the persisted
    wisdom env) claims otherwise — the same mislabeled-choice class as the
    f64 bf16-twiddle duplicate above. A pinned axis therefore drops every
    candidate that sets ``SPFFT_TPU_FUSE``: the remaining candidates carry
    no fusion env, the kwarg owns the state, and the wisdom key records the
    pin (see ``tuned_local``) so pinned and tuner-owned entries never mix."""
    bf16_applies = dtype is None or np.dtype(dtype) == np.dtype(np.float32)
    mxu = [
        {"label": "mxu", "engine": "mxu", "env": {}},
        {
            "label": "mxu/dense-y",
            "engine": "mxu",
            "env": {"SPFFT_TPU_SPARSE_Y": "0", "SPFFT_TPU_SPARSE_Y_BLOCKS": "0"},
        },
        {"label": "mxu/staged", "engine": "mxu", "env": {"SPFFT_TPU_FUSE": "0"}},
    ]
    if bf16_applies:
        mxu.append(
            {
                "label": "mxu/bf16-twiddle",
                "engine": "mxu",
                "env": {"SPFFT_TPU_TWIDDLE_BF16": "1"},
            }
        )
    xla = [
        {"label": "xla", "engine": "xla", "env": {}},
        {"label": "xla/staged", "engine": "xla", "env": {"SPFFT_TPU_FUSE": "0"}},
    ]
    cands = xla + mxu if platform == "cpu" else mxu + xla
    if fuse is not None:
        cands = [c for c in cands if "SPFFT_TPU_FUSE" not in c["env"]]
    return cands
