"""spfft_tpu — TPU-native sparse 3D FFT framework.

A from-scratch rebuild of the capabilities of SpFFT (reference mounted at
/root/reference; see SURVEY.md) on JAX/XLA: 3D FFTs of sparse frequency-domain data
(z-stick pencil decomposition in frequency space, slab decomposition in real space),
C2C and R2C transforms with hermitian-symmetry completion, centered indexing, single
and double precision, local and mesh-distributed execution with ICI all-to-all
exchanges, grids, batched multi-transforms, and a C/C++/Fortran shim.
"""
# Runtime lockdep arms FIRST, before any submodule import creates its
# threading primitives: the wrapper factories must be installed when the
# module-level locks (obs registry/trace, faults plane, tuning wisdom,
# verify breaker, ...) are constructed. knobs pulls only errors (stdlib),
# and analysis.lockdep is stdlib-only — nothing here touches jax.
from . import knobs as _knobs

if _knobs.get_bool("SPFFT_TPU_LOCKDEP"):
    from .analysis import lockdep as _lockdep

    _lockdep.install(report_path=_knobs.get_str("SPFFT_TPU_LOCKDEP_REPORT"))

from .errors import (  # noqa: F401
    AllocationError,
    DeadlineExceededError,
    DuplicateIndicesError,
    ErrorCode,
    FFTWError,
    GenericError,
    GPUAllocationError,
    GPUCopyError,
    GPUError,
    GPUFFTError,
    GPUInvalidDevicePointerError,
    GPUInvalidValueError,
    GPULaunchError,
    GPUNoDeviceError,
    GPUPrecedingError,
    GPUSupportError,
    HostExecutionError,
    InvalidIndicesError,
    InvalidParameterError,
    MPIError,
    MPIParameterMismatchError,
    MPISupportError,
    OverflowError_,
    ServiceOverloadError,
    VerificationError,
)
from . import faults  # noqa: F401
from . import hostmesh  # noqa: F401
from . import obs  # noqa: F401
from . import sched  # noqa: F401
from . import serve  # noqa: F401
from . import timing  # noqa: F401
from . import tuning  # noqa: F401
from . import verify  # noqa: F401
from .distributed import DistributedTransform  # noqa: F401
from .grid import Grid  # noqa: F401
from .indices import (  # noqa: F401
    create_spherical_cutoff_triplets,
    spherical_radius_for_fraction,
)
from .multi_transform import (  # noqa: F401
    multi_transform_backward,
    multi_transform_forward,
)
from .parallel import init_distributed, make_fft_mesh, make_fft_mesh2  # noqa: F401
from .parameters import distribute_triplets  # noqa: F401
from .transform import Transform, TransformFloat  # noqa: F401
from .types import (  # noqa: F401
    ExchangeType,
    ExecType,
    IndexFormat,
    ProcessingUnit,
    ScalingType,
    TransformType,
    SPFFT_EXCH_BUFFERED,
    SPFFT_EXCH_BUFFERED_FLOAT,
    SPFFT_EXCH_COMPACT_BUFFERED,
    SPFFT_EXCH_COMPACT_BUFFERED_FLOAT,
    SPFFT_EXCH_DEFAULT,
    SPFFT_EXCH_UNBUFFERED,
    SPFFT_EXEC_ASYNCHRONOUS,
    SPFFT_EXEC_SYNCHRONOUS,
    SPFFT_FULL_SCALING,
    SPFFT_INDEX_TRIPLETS,
    SPFFT_NO_SCALING,
    SPFFT_PU_GPU,
    SPFFT_PU_HOST,
    SPFFT_TRANS_C2C,
    SPFFT_TRANS_R2C,
)

__version__ = "0.3.0"  # keep in sync with native/CMakeLists.txt + spfft/version.h
# Reference API surface this build mirrors (reference: CMakeLists.txt:2).
__reference_api_version__ = "1.0.2"
