"""Public enum surface of spfft_tpu.

Mirrors the reference C enum surface (reference: include/spfft/types.h:33-117) so that
callers of the original library find the same vocabulary, while documenting how each
value maps onto the TPU execution model.
"""
from __future__ import annotations

import enum


class ExchangeType(enum.IntEnum):
    """Slab<->pencil exchange strategy.

    Reference: include/spfft/types.h:33-62 (SpfftExchangeType).

    BUFFERED (and DEFAULT) lower to one equal-split ``lax.all_to_all`` over the ICI
    mesh axis on padded-uniform blocks — the reference's BUFFERED wire discipline and
    the collective shape ICI fuses best; it wins when shards are balanced.
    COMPACT_BUFFERED sends exact ``sticks_i x planes_j`` blocks per shard pair via a
    ppermute rotation chain (parallel/ragged.py) — true Alltoallv semantics; it wins
    when stick or plane counts are imbalanced (wire bytes track the exact volume
    instead of ``P^2 S_max L_max``), at the cost of P-1 sequential collective rounds
    per exchange. UNBUFFERED sends the same exact counts in ONE collective via XLA's
    ragged-all-to-all HLO (parallel/ragged.py OneShotExchange) — the analogue of the
    reference's zero-copy ``MPI_Alltoallw`` exchange: exact bytes AND single-round
    latency on backends that compile the HLO (TPU); elsewhere the same one-shot
    buffers ride a chain transport (P-1 rounds, identical numerics). On a 2-D
    pencil mesh (``make_fft_mesh2``, beyond the reference) UNBUFFERED runs the
    same one-shot discipline per exchange (OneShotBlockExchange; block chains as
    the off-TPU fallback) — check ``exchange_rounds()``/``exchange_wire_bytes()``
    for any plan's actual costs under its active transport. The
    ``*_FLOAT`` variants halve wire bytes by converting the exchanged payload to
    single precision on the wire, exactly like the reference's float exchange
    (reference: src/gpu_util/complex_conversion.cuh:37-56).

    DIVERGENCE from the reference: the reference documents SPFFT_EXCH_DEFAULT as
    equivalent to COMPACT_BUFFERED (reference: include/spfft/types.h:34-39); here
    DEFAULT is a measured auto-policy (parallel/policy.py): the discipline is
    picked per plan by a cost model over the plan's exact wire volumes, round
    counts, and the backend's one-shot ragged-a2a support — BUFFERED for
    balanced layouts (the single fused all_to_all is the ICI-native shape),
    UNBUFFERED when padding waste exceeds the round cost and the one-shot
    transport compiles, COMPACT where its per-step maxima undercut both.
    Ported code that relied on DEFAULT's exact-counts wire volume should pass
    COMPACT_BUFFERED explicitly (see docs/MIGRATION.md).

    The ``*_BF16`` variants are a TPU-native extension beyond the reference enum
    (which ends at UNBUFFERED): the wire payload is cast to bfloat16 around the
    collective, halving ICI bytes again relative to an f32 wire (quartering them
    for f64 data). bf16 keeps f32's exponent range but only ~3 significant decimal
    digits, so results are NOT held to the 1e-6 parity bar — this is an explicit
    opt-in for bandwidth-bound distributed transforms that tolerate ~1e-2 relative
    error, never an implicit downgrade.
    """

    DEFAULT = 0
    BUFFERED = 1
    BUFFERED_FLOAT = 2
    COMPACT_BUFFERED = 3
    COMPACT_BUFFERED_FLOAT = 4
    UNBUFFERED = 5
    # TPU extensions (not in the reference enum).
    BUFFERED_BF16 = 6
    COMPACT_BUFFERED_BF16 = 7


# Wire-format groupings used by both mesh engines (execution.py, execution_mxu.py).
FLOAT_EXCHANGES = (ExchangeType.BUFFERED_FLOAT, ExchangeType.COMPACT_BUFFERED_FLOAT)
BF16_EXCHANGES = (ExchangeType.BUFFERED_BF16, ExchangeType.COMPACT_BUFFERED_BF16)
# Exact-counts disciplines (not the padded all_to_all): COMPACT_* mirrors the
# reference's Alltoallv as a ppermute rotation chain; UNBUFFERED mirrors its
# zero-copy Alltoallw as ONE ragged-all-to-all collective (chain-transport
# fallback on backends without the HLO). Both send exactly sticks_i x planes_j
# elements per shard pair; see parallel/ragged.py.
RAGGED_EXCHANGES = (
    ExchangeType.COMPACT_BUFFERED,
    ExchangeType.COMPACT_BUFFERED_FLOAT,
    ExchangeType.COMPACT_BUFFERED_BF16,
    ExchangeType.UNBUFFERED,
)


def wire_dtype(exchange_type: "ExchangeType", real_dtype):
    """THE wire-format rule, single-sourced: the real scalar dtype an exchange
    puts on the interconnect for a plan of ``real_dtype``. Engines cast with it
    and the wire-byte accounting derives from it, so the two cannot diverge."""
    import ml_dtypes
    import numpy as np

    if exchange_type in BF16_EXCHANGES:
        return np.dtype(ml_dtypes.bfloat16)
    if exchange_type in FLOAT_EXCHANGES and np.dtype(real_dtype) == np.float64:
        return np.dtype(np.float32)
    return np.dtype(real_dtype)


def wire_scalar_bytes(exchange_type: "ExchangeType", real_dtype) -> int:
    """Bytes per real scalar on the wire under ``exchange_type``."""
    return int(wire_dtype(exchange_type, real_dtype).itemsize)


class ProcessingUnit(enum.IntFlag):
    """Where a transform executes. Reference: include/spfft/types.h:67-76.

    HOST selects the CPU backend (JAX on CPU devices), GPU selects the accelerator
    backend (the TPU in this build — the enum name is kept for API parity).
    """

    HOST = 1
    GPU = 2
    # Alias making intent explicit in new code.
    TPU = 2


class IndexFormat(enum.IntEnum):
    """Sparse frequency index format. Reference: include/spfft/types.h:78-83."""

    TRIPLETS = 0


class TransformType(enum.IntEnum):
    """C2C or R2C. Reference: include/spfft/types.h:85-95."""

    C2C = 0
    R2C = 1


class ScalingType(enum.IntEnum):
    """Forward-transform scaling. Reference: include/spfft/types.h:97-106."""

    NONE = 0
    FULL = 1


class ExecType(enum.IntEnum):
    """Synchronous vs asynchronous execution. Reference: include/spfft/types.h:108-117.

    JAX dispatch is asynchronous by default; SYNCHRONOUS blocks on the result before
    returning (``block_until_ready``), ASYNCHRONOUS returns as soon as the computation
    is enqueued.
    """

    SYNCHRONOUS = 0
    ASYNCHRONOUS = 1


# C-compatible aliases (same spelling as the reference C enum constants).
SPFFT_EXCH_DEFAULT = ExchangeType.DEFAULT
SPFFT_EXCH_BUFFERED = ExchangeType.BUFFERED
SPFFT_EXCH_BUFFERED_FLOAT = ExchangeType.BUFFERED_FLOAT
SPFFT_EXCH_COMPACT_BUFFERED = ExchangeType.COMPACT_BUFFERED
SPFFT_EXCH_COMPACT_BUFFERED_FLOAT = ExchangeType.COMPACT_BUFFERED_FLOAT
SPFFT_EXCH_UNBUFFERED = ExchangeType.UNBUFFERED
SPFFT_EXCH_BUFFERED_BF16 = ExchangeType.BUFFERED_BF16
SPFFT_EXCH_COMPACT_BUFFERED_BF16 = ExchangeType.COMPACT_BUFFERED_BF16

SPFFT_PU_HOST = ProcessingUnit.HOST
SPFFT_PU_GPU = ProcessingUnit.GPU

SPFFT_INDEX_TRIPLETS = IndexFormat.TRIPLETS

SPFFT_TRANS_C2C = TransformType.C2C
SPFFT_TRANS_R2C = TransformType.R2C

SPFFT_NO_SCALING = ScalingType.NONE
SPFFT_FULL_SCALING = ScalingType.FULL

SPFFT_EXEC_SYNCHRONOUS = ExecType.SYNCHRONOUS
SPFFT_EXEC_ASYNCHRONOUS = ExecType.ASYNCHRONOUS
