"""Error codes and exception hierarchy.

Mirrors the reference's dual error surface: a C error enum
(reference: include/spfft/errors.h:33-126) and a C++ exception hierarchy whose
exceptions each carry their enum value (reference: include/spfft/exceptions.hpp:40-306).
The Python exceptions below carry ``error_code`` the same way so the C shim can
translate exceptions to C error codes mechanically.
"""
from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    """Reference: include/spfft/errors.h:33-126 (SpfftError), same ordering."""

    SUCCESS = 0
    UNKNOWN = 1
    INVALID_HANDLE = 2
    OVERFLOW = 3
    ALLOCATION = 4
    INVALID_PARAMETER = 5
    DUPLICATE_INDICES = 6
    INVALID_INDICES = 7
    MPI_SUPPORT = 8
    MPI = 9
    MPI_PARAMETER_MISMATCH = 10
    HOST_EXECUTION = 11
    FFTW = 12
    GPU = 13
    GPU_PRECEDING = 14
    GPU_SUPPORT = 15
    GPU_ALLOCATION = 16
    GPU_LAUNCH = 17
    GPU_NO_DEVICE = 18
    GPU_INVALID_VALUE = 19
    GPU_INVALID_DEVICE_PTR = 20
    GPU_COPY = 21
    GPU_FFT = 22
    # TPU-build extension beyond the reference enum (reference stops at 22):
    # algorithm-based self-verification failed and recovery was exhausted
    # (spfft_tpu.verify). Mirrored in native/include/spfft/errors.h.
    VERIFICATION = 23
    # Serving-layer extensions (spfft_tpu.serve), mirrored the same way:
    # admission refused under overload (bounded queue full, tenant quota,
    # or load shedding) ...
    SERVICE_OVERLOAD = 24
    # ... and a request deadline expired (at admission or pre-dispatch).
    DEADLINE_EXCEEDED = 25
    # Multi-host extension (spfft_tpu.serve.cluster): a worker host died or
    # became unreachable (missed heartbeats, dead RPC transport) while work
    # addressed to it was queued or in flight. Mirrored like the rest.
    HOST_LOST = 26


class GenericError(Exception):
    """Base exception. Reference: include/spfft/exceptions.hpp:40-61.

    Constructing any typed error notifies the flight recorder
    (:mod:`spfft_tpu.obs.trace`): with tracing armed the error lands as an
    event stamped with the active run ID, and with ``SPFFT_TPU_TRACE_DUMP``
    set the recorder is flushed to disk — the events leading up to a typed
    failure (guard verdicts included — guard raises these) survive it."""

    error_code: ErrorCode = ErrorCode.UNKNOWN

    def __init__(self, message: str | None = None):
        super().__init__(message or self.__class__.__doc__ or self.__class__.__name__)
        from .obs import trace

        if trace.enabled():
            trace.event(
                "error",
                type=type(self).__name__,
                error_code=int(self.error_code),
                message=str(self)[:200],
            )
            trace.dump(reason=type(self).__name__)


class OverflowError_(GenericError):
    """Integer overflow in index or size computation."""

    error_code = ErrorCode.OVERFLOW


class AllocationError(GenericError):
    """Failed buffer allocation."""

    error_code = ErrorCode.ALLOCATION


class InvalidParameterError(GenericError):
    """Invalid parameter passed to a transform or grid."""

    error_code = ErrorCode.INVALID_PARAMETER


class DuplicateIndicesError(GenericError):
    """Duplicate frequency indices (possibly a z-stick split across shards)."""

    error_code = ErrorCode.DUPLICATE_INDICES


class InvalidIndicesError(GenericError):
    """Frequency index triplet out of bounds for the transform dimensions."""

    error_code = ErrorCode.INVALID_INDICES


class MPISupportError(GenericError):
    """Distributed execution requested without a multi-device backend."""

    error_code = ErrorCode.MPI_SUPPORT


class MPIError(GenericError):
    """Failure in the distributed communication backend."""

    error_code = ErrorCode.MPI


class MPIParameterMismatchError(GenericError):
    """Constructor parameters disagree across shards."""

    error_code = ErrorCode.MPI_PARAMETER_MISMATCH


class HostExecutionError(GenericError):
    """Execution failure on the host backend."""

    error_code = ErrorCode.HOST_EXECUTION


class FFTWError(GenericError):
    """Failure in the underlying FFT implementation."""

    error_code = ErrorCode.FFTW


class GPUError(GenericError):
    """Generic accelerator error."""

    error_code = ErrorCode.GPU


class GPUPrecedingError(GenericError):
    """An earlier accelerator operation already failed."""

    error_code = ErrorCode.GPU_PRECEDING


class GPUSupportError(GenericError):
    """Accelerator execution requested but no accelerator backend available."""

    error_code = ErrorCode.GPU_SUPPORT


class GPUAllocationError(GenericError):
    """Failed allocation in accelerator memory."""

    error_code = ErrorCode.GPU_ALLOCATION


class GPULaunchError(GenericError):
    """Failed to launch an accelerator kernel."""

    error_code = ErrorCode.GPU_LAUNCH


class GPUNoDeviceError(GenericError):
    """No accelerator device detected."""

    error_code = ErrorCode.GPU_NO_DEVICE


class GPUInvalidValueError(GenericError):
    """Invalid value passed to the accelerator runtime."""

    error_code = ErrorCode.GPU_INVALID_VALUE


class GPUInvalidDevicePointerError(GenericError):
    """Invalid device buffer reference."""

    error_code = ErrorCode.GPU_INVALID_DEVICE_PTR


class GPUCopyError(GenericError):
    """Failed host<->device transfer."""

    error_code = ErrorCode.GPU_COPY


class GPUFFTError(GenericError):
    """Failure in the accelerator FFT path."""

    error_code = ErrorCode.GPU_FFT


class VerificationError(GenericError):
    """Self-verification (ABFT) failed and recovery was exhausted.

    Raised by the :mod:`spfft_tpu.verify` supervisor when a transform's
    result fails its algebraic checks on the primary engine, retries do not
    heal it, and the ``jnp.fft`` reference rung cannot produce a verified
    result either — the typed terminal of the detect -> retry -> demote
    ladder. A silently corrupted output is never returned in its place."""

    error_code = ErrorCode.VERIFICATION


class ServiceOverloadError(GenericError):
    """The serving layer refused admission under overload.

    Raised by :mod:`spfft_tpu.serve` when the bounded admission queue is
    full, a tenant exceeded its quota, or a queued request was shed
    (fair-share eviction, breaker-open shedding). The typed form of
    backpressure: a caller sees this error immediately instead of unbounded
    queueing latency, and can back off and retry."""

    error_code = ErrorCode.SERVICE_OVERLOAD


class DeadlineExceededError(GenericError):
    """A request's deadline expired before its result was produced.

    Raised by :mod:`spfft_tpu.serve` at admission (the deadline was already
    in the past) or pre-dispatch (the request expired while queued — shed
    before burning device time on an answer nobody is waiting for)."""

    error_code = ErrorCode.DEADLINE_EXCEEDED


class HostLostError(MPIError):
    """A worker host died or became unreachable mid-operation.

    Raised by the multi-host serving layer (:mod:`spfft_tpu.serve.cluster`)
    when a host misses its heartbeat budget or its RPC transport dies with
    work queued or in flight. Subclasses :class:`MPIError` deliberately:
    host death IS a communication-layer failure, so every retry ladder that
    already treats ``MPIError`` as transient (the serving retries, the
    scheduler's per-task ladder) handles it — the scheduler additionally
    requeues the in-flight work onto surviving hosts before giving up
    (the ``host_lost`` degradation rung, docs/details.md "Multi-host
    serving & host loss")."""

    error_code = ErrorCode.HOST_LOST
