"""Process-global fault-injection plane: named sites, armed kinds, rates.

Failure paths are first-class, testable code here, the way distributed FFT
stacks treat backend/scheduler failure (AccFFT's error plane, DaggerFFT's
scheduler faults) rather than accidents: every fallback claim the runtime
makes ("tuning degrades, never fails", "a corrupt wisdom store is bypassed",
"an MXU lowering failure falls back to jnp.fft") is provable by arming the
fault site that triggers it and asserting the ladder's response.

**Sites** (:data:`SITES`) are named checkpoints threaded through the runtime
— ``tuning.trial``, ``wisdom.load``, ``wisdom.save``, ``engine.compile``,
``engine.execute``, ``exchange.build``, ``hlo.stats``, ``sync.fence`` — each
a single :func:`site` call at the point where that operation can really
fail. ``programs/lint.py`` enforces that every ``faults.site(...)`` call
names a registered site and that every registered site is threaded through
the package and documented.

**Kinds** (:data:`KINDS`):

- ``raise`` — raise :class:`InjectedFault` at the site (the generic
  backend-blew-up case; the surrounding ladder must convert it to a typed
  :mod:`spfft_tpu.errors` exception or degrade),
- ``nan`` / ``corrupt`` — poison the site's data payload with NaN /
  Inf-or-mangled-text (guard mode and the wisdom quarantine must catch it),
- ``delay`` — sleep ``SPFFT_TPU_FAULTS_DELAY_S`` seconds (timeout/backoff
  paths; the result must stay correct).

**Arming**: the ``SPFFT_TPU_FAULTS`` env knob
(``"site=kind[:rate],site=kind[:rate],..."``, parsed at import) or the
:func:`inject` context manager / :func:`arm` programmatically. Sub-1.0 rates
draw from one process-global ``random.Random`` seeded by
``SPFFT_TPU_FAULTS_SEED`` (:func:`reseed`), so a chaos run replays
deterministically. Disarmed, :func:`site` is one falsy-dict check — the same
no-overhead-when-off discipline as ``SPFFT_TPU_METRICS=0``'s shared no-op
instruments.

Every fired injection counts into the run-metrics registry
(``faults_injected_total{site,kind}``), so a chaos run's metrics snapshot
shows exactly what was injected where.
"""
from __future__ import annotations

import contextlib
import random
import threading
import time

from .. import knobs, obs
from ..errors import InvalidParameterError

FAULTS_ENV = "SPFFT_TPU_FAULTS"
FAULTS_SEED_ENV = "SPFFT_TPU_FAULTS_SEED"
FAULTS_DELAY_ENV = "SPFFT_TPU_FAULTS_DELAY_S"

# Canonical injection-site vocabulary. Each name is one faults.site(...) call
# in the runtime; programs/lint.py enforces the list both ways (every call
# registered, every registration threaded through the package + documented in
# docs/details.md "Failure model & degradation ladder").
SITES = (
    "tuning.trial",
    "wisdom.load",
    "wisdom.save",
    "engine.compile",
    "engine.execute",
    "ir.lower",
    "ir.compile",
    "ir.batch",
    "exchange.build",
    "hlo.stats",
    "sync.fence",
    "verify.check",
    "serve.admit",
    "serve.batch",
    "serve.dispatch",
    "sched.place",
    "sched.run",
    "host.heartbeat",
    "rpc.submit",
)

KINDS = ("raise", "nan", "corrupt", "delay")


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` fault site.

    Deliberately a ``RuntimeError`` subclass: the degradation ladder treats an
    injected failure exactly like a real backend failure (XLA's runtime
    errors are ``RuntimeError`` subclasses too), so the same ``except`` arms
    that catch production faults catch injected ones — chaos tests exercise
    the real handlers, not injection-only shims."""


_lock = threading.Lock()
_armed: dict = {}  # site -> {"kind": str, "rate": float}
_rng = random.Random(knobs.get_int(FAULTS_SEED_ENV))


def parse_spec(spec: str) -> dict:
    """Parse a ``"site=kind[:rate],..."`` arming spec into
    ``{site: {"kind", "rate"}}``.

    Every malformed token raises a typed :class:`InvalidParameterError`
    *naming the offending token* — a chaos configuration must never be
    silently dropped or partially applied (a typo'd ``SPFFT_TPU_FAULTS``
    that went unnoticed would make a chaos run vacuously green). Duplicate
    site tokens in one spec raise too: last-wins would silently discard the
    earlier arming."""
    table: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, action = part.partition("=")
        name = name.strip()
        if not sep or not action.strip():
            raise InvalidParameterError(
                f"malformed fault spec token {part!r}: expected site=kind[:rate]"
            )
        kind, _, rate_s = action.strip().partition(":")
        if name not in SITES:
            raise InvalidParameterError(
                f"unknown fault site {name!r} in token {part!r}: expected one "
                f"of {SITES}"
            )
        if kind not in KINDS:
            raise InvalidParameterError(
                f"unknown fault kind {kind!r} in token {part!r}: expected one "
                f"of {KINDS}"
            )
        try:
            rate = float(rate_s) if rate_s else 1.0
        except ValueError as e:
            raise InvalidParameterError(
                f"malformed fault rate {rate_s!r} in token {part!r}"
            ) from e
        if not 0.0 <= rate <= 1.0:
            raise InvalidParameterError(
                f"fault rate must be in [0, 1] in token {part!r}, got {rate}"
            )
        if name in table:
            raise InvalidParameterError(
                f"duplicate fault site {name!r} in token {part!r}: an earlier "
                "token in the same spec already armed it"
            )
        table[name] = {"kind": kind, "rate": rate}
    return table


def arm(spec) -> None:
    """Arm fault sites from a spec string (``"site=kind[:rate],..."``) or a
    pre-parsed ``{site: {"kind", "rate"}}`` table (``rate`` optional,
    defaulting to 1.0), merging over what is already armed."""
    table = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    normalized = {}
    for name, fault in table.items():
        if name not in SITES:
            raise InvalidParameterError(
                f"unknown fault site {name!r}: expected one of {SITES}"
            )
        if fault.get("kind") not in KINDS:
            raise InvalidParameterError(
                f"unknown fault kind {fault.get('kind')!r}: expected one of {KINDS}"
            )
        rate = float(fault.get("rate", 1.0))
        if not 0.0 <= rate <= 1.0:
            raise InvalidParameterError(
                f"fault rate must be in [0, 1], got {rate}"
            )
        normalized[name] = {"kind": fault["kind"], "rate": rate}
    with _lock:
        _armed.update(normalized)


def disarm(site_name: str | None = None) -> None:
    """Disarm one site, or every site when ``site_name`` is None."""
    with _lock:
        if site_name is None:
            _armed.clear()
        else:
            _armed.pop(site_name, None)


def armed() -> dict:
    """Copy of the currently armed table (``{site: {"kind", "rate"}}``)."""
    with _lock:
        return {k: dict(v) for k, v in _armed.items()}


def reseed(seed: int | None = None) -> None:
    """Reseed the sub-1.0-rate draw stream (default: ``SPFFT_TPU_FAULTS_SEED``,
    else 0) — a chaos run with fractional rates replays exactly."""
    if seed is None:
        seed = knobs.get_int(FAULTS_SEED_ENV)
    with _lock:
        _rng.seed(int(seed))


@contextlib.contextmanager
def inject(spec):
    """Scoped arming: apply ``spec`` on top of the current table, restore the
    previous table on exit (exception-safe) — the programmatic counterpart of
    ``SPFFT_TPU_FAULTS`` for chaos tests."""
    with _lock:
        saved = {k: dict(v) for k, v in _armed.items()}
    arm(spec)
    try:
        yield
    finally:
        with _lock:
            _armed.clear()
            _armed.update(saved)


def _poison(payload, value: float):
    """NaN/Inf-poison every array leaf of ``payload`` (jax or numpy; works
    on device without a host roundtrip); non-array payloads pass through."""
    import jax

    def leaf(a):
        if hasattr(a, "dtype") and hasattr(a, "shape"):
            return a * value
        return a

    return jax.tree_util.tree_map(leaf, payload)


def _corrupt(payload):
    """Mangle a data payload: text/bytes get truncated + garbage appended
    (downstream parsers must reject it); arrays get Inf-poisoned (guard mode
    must catch it); anything else passes through unchanged."""
    if isinstance(payload, str):
        return payload[: len(payload) // 2] + "\x00<injected corruption>"
    if isinstance(payload, (bytes, bytearray)):
        return bytes(payload[: len(payload) // 2]) + b"\x00<injected corruption>"
    return _poison(payload, float("inf"))


def site(name: str, payload=None):
    """Fault checkpoint ``name``; returns ``payload`` (possibly poisoned).

    Disarmed (the common case) this is a single falsy-dict check. Armed, the
    site fires with its configured probability: ``raise`` raises
    :class:`InjectedFault`, ``delay`` sleeps, ``nan``/``corrupt`` return a
    poisoned copy of ``payload``. Callers pass the data flowing through the
    site as ``payload`` and use the return value in its place."""
    if not _armed:
        return payload
    fault = _armed.get(name)
    if fault is None:
        return payload
    rate = fault["rate"]
    if rate <= 0.0:
        return payload
    if rate < 1.0:
        with _lock:
            draw = _rng.random()
        if draw >= rate:
            return payload
    kind = fault["kind"]
    if payload is None and kind in ("nan", "corrupt"):
        # nothing flows through this site to poison: a genuine no-op, NOT
        # counted — faults_injected_total must never claim injections that
        # had zero effect
        return payload
    obs.counter("faults_injected_total", site=name, kind=kind).inc()
    # flight-recorder instant (spfft_tpu.obs.trace): the injection lands in
    # the active run's event stream, so a chaos trace shows what fired where
    obs.trace.event("fault.injected", site=name, kind=kind)
    if kind == "raise":
        raise InjectedFault(f"injected fault at site {name!r}")
    if kind == "delay":
        time.sleep(knobs.get_float(FAULTS_DELAY_ENV))
        return payload
    if kind == "nan":
        return _poison(payload, float("nan"))
    return _corrupt(payload)


# Env arming at import: the SPFFT_TPU_FAULTS knob makes whole test suites /
# CLIs runnable under injection without code changes (ci.sh chaos stage).
_env_spec = knobs.get_str(FAULTS_ENV)
if _env_spec:
    arm(_env_spec)
del _env_spec
