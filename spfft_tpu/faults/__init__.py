"""spfft_tpu.faults — fault-injection plane, guard mode, degradation ladder.

Three pieces that make failure a first-class, testable path (the robustness
counterpart of :mod:`spfft_tpu.obs` making behavior a first-class, observable
path):

1. **Injection plane** (:mod:`.plane`): a registry of named fault sites
   (:data:`SITES`) threaded through tuning, wisdom I/O, engine lowering,
   exchange construction, execution dispatch, compiled-program introspection
   and the completion fence; armed via ``SPFFT_TPU_FAULTS="site=kind[:rate]"``
   or the :func:`inject` context manager, deterministic under
   ``SPFFT_TPU_FAULTS_SEED``, one falsy-dict check when disarmed.
2. **Guard mode** (:mod:`.guard`): ``SPFFT_TPU_GUARD=1`` / ``guard=`` kwarg
   — NaN/Inf scans plus shape/dtype/device validation around every
   host-facing transform, raising typed :mod:`spfft_tpu.errors` exceptions
   with ``guard_checks_total``/``guard_failures_total`` metrics.
3. **Degradation ladder** (:mod:`.ladder`): engine-compile failures fall back
   to the ``jnp.fft`` engine, wisdom I/O retries/quarantines, execution
   failures convert to the typed error surface — every fallback recorded in
   the plan card's ``degradations`` section and the run-metrics registry.

The chaos suites (``tests/test_faults.py``, ``tests/test_degradation.py``,
``./ci.sh chaos``) arm each site at rate 1.0 and assert the invariant: every
transform either raises a typed exception or returns a parity-correct result
via a recorded fallback — never a silent wrong answer.
"""
from .plane import (  # noqa: F401
    FAULTS_DELAY_ENV,
    FAULTS_ENV,
    FAULTS_SEED_ENV,
    KINDS,
    SITES,
    InjectedFault,
    arm,
    armed,
    disarm,
    inject,
    parse_spec,
    reseed,
    site,
)
from .guard import (  # noqa: F401
    GUARD_ENV,
    check_array,
    check_device,
    execution_error,
    guard_enabled,
)
from .ladder import (  # noqa: F401
    ENGINE_BUILD_ERRORS,
    backoff_s,
    collecting,
    current_sink,
    engine_fallback,
    record_degradation,
    summarize,
    typed_execution,
)
