"""The graceful-degradation ladder: record fallbacks, type-convert failures.

The runtime's ladder, in order (docs/details.md "Failure model & degradation
ladder"):

1. **Engine fallback** — an MXU/pallas engine that fails to lower or compile
   (injectable via the ``engine.compile`` site) degrades to the ``jnp.fft``
   engine instead of failing plan construction
   (:func:`engine_fallback`, ``engine_fallbacks_total`` metric).
2. **Wisdom resilience** — store corruption is quarantined once
   (``*.corrupt``), transient write failures get bounded retry with backoff
   (``wisdom_retries_total``), and a dead store degrades to the model policy
   (:mod:`spfft_tpu.tuning.wisdom`).
3. **Trial isolation** — a tuning candidate that fails becomes an ``error``
   trial row; all candidates failing degrades to the model policy
   (:mod:`spfft_tpu.tuning.runner`).
4. **Typed execution errors** — dispatch/fence failures that cannot be
   degraded raise :class:`~spfft_tpu.errors.HostExecutionError` /
   :class:`~spfft_tpu.errors.GPUFFTError` (:func:`typed_execution`) instead
   of leaking raw backend exceptions.
5. **Optional introspection degrades silently-but-recorded** — compiled-stats
   failure (``hlo.stats`` site) drops the ``compiled`` card section and
   records the degradation instead of failing ``plan.report()``.

Every rung records what it did: an entry in the owning plan's
``degradations`` list (surfaced schema-pinned in the plan card) plus a
``degradations_total{event=...}`` counter — a degraded plan is always
diagnosable after the fact.
"""
from __future__ import annotations

import contextlib
import threading

from .. import obs
from ..errors import GenericError
from .guard import execution_error
from .plane import InjectedFault

# Failure classes the ladder treats as "the backend/engine blew up" and may
# degrade: injected faults, XLA runtime/compile errors (RuntimeError
# subclasses), and unimplemented-lowering holes. Deliberately excludes the
# typed spfft_tpu.errors hierarchy (user/parameter errors must surface) and
# Python programming errors (TypeError/AttributeError are bugs, not faults).
ENGINE_BUILD_ERRORS = (InjectedFault, RuntimeError, NotImplementedError)

_tls = threading.local()


def backoff_s(base: float, attempt: int, rng=None) -> float:
    """Jittered exponential backoff delay for re-attempt ``attempt``
    (1-based): ``base * 2**(attempt-1)``, scaled by a uniform draw in
    [0.5, 1.5) when ``rng`` (a ``random.Random``) is given.

    The jitter is the thundering-herd guard shared by every retry loop in
    the package (the verify supervisor's re-executions, the serving layer's
    transient-failure retries): N concurrent callers that failed on the same
    engine at the same moment must not all re-hit it on the same schedule —
    deterministic exponential backoff synchronizes the herd instead of
    spreading it. Pass ``rng=None`` for the legacy deterministic delay
    (tests that pin exact sleep values)."""
    delay = float(base) * (2.0 ** (max(1, int(attempt)) - 1))
    if rng is not None:
        delay *= 0.5 + rng.random()
    return delay


def summarize(exc: BaseException, limit: int = 200) -> str:
    """One-line ``"Type: first message line"`` summary of an exception — the
    single formatting rule for degradation reasons and trial error rows."""
    first = str(exc).splitlines()[0] if str(exc) else ""
    return f"{type(exc).__name__}: {first}"[:limit]


@contextlib.contextmanager
def collecting(sink: list):
    """Route :func:`record_degradation` entries into ``sink`` for the scope —
    plan constructors wrap their build so every fallback taken lands on the
    plan's own ``degradations`` list (nested plan builds, e.g. tuning trials,
    push their own sink and do not leak into the outer plan's)."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(sink)
    try:
        yield sink
    finally:
        stack.pop()


def current_sink():
    """The innermost :func:`collecting` sink, or ``None`` — lets a component
    constructed inside a plan's collecting scope keep recording onto that
    plan's live ``degradations`` list after the scope exits (runtime rungs:
    :class:`spfft_tpu.ir.compile.EngineIr`'s first-dispatch
    ``fuse_compile_failed``)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def record_degradation(event: str, reason: str, **extra) -> dict:
    """Record one degradation: count ``degradations_total{event=...}`` and
    append ``{"event", "reason", **extra}`` to the innermost
    :func:`collecting` sink (if any). Returns the entry so callers outside a
    collecting scope (plan-card assembly) can place it themselves."""
    entry = {"event": str(event), "reason": str(reason), **extra}
    obs.counter("degradations_total", event=str(event)).inc()
    # ladder rungs stamp the active run ID in the flight recorder, so a
    # degraded plan's trace shows the rung among the events around it
    obs.trace.event("degradation", event=str(event), reason=str(reason))
    stack = getattr(_tls, "stack", None)
    if stack:
        stack[-1].append(entry)
    return entry


def engine_fallback(from_engine: str, to_engine: str, reason: str) -> dict:
    """Record rung 1 of the ladder: an engine construction failure degraded
    ``from_engine`` -> ``to_engine`` (``engine_fallbacks_total`` metric plus
    a ``degradations`` entry on the plan being built)."""
    obs.counter(
        "engine_fallbacks_total",
        **{"from": str(from_engine), "to": str(to_engine)},
    ).inc()
    return record_degradation(
        "engine_fallback",
        reason,
        **{"from": str(from_engine), "to": str(to_engine)},
    )


@contextlib.contextmanager
def typed_execution(platform: str, op: str):
    """Convert backend execution failures inside the scope into the typed
    error surface: :class:`HostExecutionError` on CPU plans,
    :class:`GPUFFTError` on accelerator plans (rung 4). Typed
    :mod:`spfft_tpu.errors` exceptions pass through untouched; the original
    exception rides as ``__cause__``. Each conversion counts
    ``execution_failures_total{op=...}``."""
    try:
        yield
    except GenericError:
        raise
    except ENGINE_BUILD_ERRORS + (FloatingPointError,) as e:
        obs.counter("execution_failures_total", op=str(op)).inc()
        raise execution_error(platform)(f"{op} failed: {e}") from e
