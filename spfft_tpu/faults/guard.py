"""Guard mode: pre/post execution validation on the host-facing paths.

``SPFFT_TPU_GUARD=1`` (or ``guard=True`` on a Transform/DistributedTransform)
turns on defensive checks around every host-facing ``backward``/``forward``:

- **NaN/Inf scan** on inputs before staging and on outputs after fetch —
  poisoned data raises a typed :mod:`spfft_tpu.errors` exception
  (:class:`~spfft_tpu.errors.HostExecutionError` on CPU plans,
  :class:`~spfft_tpu.errors.GPUFFTError` on accelerator plans) instead of
  flowing silently into the caller's pipeline,
- **shape/dtype validation** of outputs against the plan's contract
  (the packed value count, the ``(dim_z, dim_y, dim_x)`` slab, the plan
  dtype),
- **device validation** of the device-resident result against the plan's
  bound device (a result that migrated off the plan device means the
  runtime broke the placement contract).

Every check counts ``guard_checks_total{check=...}``; every failure counts
``guard_failures_total{check=...}`` before raising, so a metrics snapshot
shows guard coverage and hit rate. Guard mode is pure host-side
instrumentation — it never changes what is compiled or dispatched, which is
what the guard-mode run of the engine-parity fuzzer (``./ci.sh chaos``)
proves.
"""
from __future__ import annotations

import numpy as np

from .. import knobs, obs
from ..errors import GPUFFTError, HostExecutionError

GUARD_ENV = "SPFFT_TPU_GUARD"


def guard_enabled(explicit: bool | None = None) -> bool:
    """Whether guard mode is active: an explicit ``guard=`` argument wins,
    else the ``SPFFT_TPU_GUARD`` env knob (default off)."""
    if explicit is not None:
        return bool(explicit)
    return knobs.get_bool(GUARD_ENV)


def execution_error(platform: str):
    """The typed exception class for an execution-level failure on
    ``platform``: host plans raise :class:`HostExecutionError`, accelerator
    plans :class:`GPUFFTError` (the reference's dual error surface)."""
    return HostExecutionError if str(platform) == "cpu" else GPUFFTError


def _fail(check: str, platform: str, message: str):
    obs.counter("guard_failures_total", check=check).inc()
    obs.trace.event("guard", check=check, verdict="fail", message=message)
    raise execution_error(platform)(f"guard [{check}]: {message}")


def check_array(arr, *, check: str, platform: str, shape=None, dtype=None):
    """Validate one array (or each array of a per-shard list): finite
    values, and optionally an exact shape/dtype contract. Raises the
    platform's typed execution error on the first violation; returns the
    input unchanged so calls can be threaded inline."""
    obs.counter("guard_checks_total", check=check).inc()
    arrays = arr if isinstance(arr, (list, tuple)) else (arr,)
    for i, a in enumerate(arrays):
        if a is None:  # multi-host: remote shards are None by contract
            continue
        a = np.asarray(a)
        tag = f"{check}[{i}]" if len(arrays) > 1 else check
        if shape is not None and tuple(a.shape) != tuple(shape):
            _fail(check, platform, f"{tag} shape {a.shape} != expected {tuple(shape)}")
        if dtype is not None and a.dtype != np.dtype(dtype):
            _fail(check, platform, f"{tag} dtype {a.dtype} != expected {np.dtype(dtype)}")
        if np.issubdtype(a.dtype, np.floating) or np.issubdtype(
            a.dtype, np.complexfloating
        ):
            finite = np.isfinite(a)
            if not finite.all():
                bad = int(a.size - int(np.count_nonzero(finite)))
                _fail(
                    check,
                    platform,
                    f"{tag}: {bad} non-finite value(s) of {a.size}",
                )
    # verdicts land in the flight recorder both ways: _fail records the
    # failing one before raising, a clean pass is recorded here
    obs.trace.event("guard", check=check, verdict="ok")
    return arr


def check_device(tree, device, *, check: str, platform: str):
    """Validate that every device-resident array in ``tree`` still lives on
    the plan's bound ``device`` — placement drift means a later dispatch
    would silently recompile or cross-copy."""
    import jax

    obs.counter("guard_checks_total", check=check).inc()
    for leaf in jax.tree_util.tree_leaves(tree):
        devices = getattr(leaf, "devices", None)
        if not callable(devices):
            continue
        try:
            devs = devices()
        except (RuntimeError, ValueError):  # deleted/donated buffers: skip
            continue
        if device not in devs:
            _fail(
                check,
                platform,
                f"result on {sorted(str(d) for d in devs)} but the plan is "
                f"bound to {device}",
            )
    obs.trace.event("guard", check=check, verdict="ok")
    return tree
