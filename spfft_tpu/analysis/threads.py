"""Checker 17: thread-lifecycle discipline (SA017).

The package starts worker threads in five modules (serve dispatch loop,
sched-adjacent runners, fence/trial deadline workers, the hang watchdog) —
and the multi-host arc will add more. Two failure shapes this checker
closes off before they ship:

* **A non-daemon thread nobody joins.** Process shutdown hangs on it, and
  a test suite that created one leaks it into every later test. Every
  ``threading.Thread(...)`` the package constructs must either be
  ``daemon=True`` at construction (or via a ``t.daemon = True`` assignment
  on the same binding) or be ``.join()``-ed somewhere in the same file.
* **An unbounded wait.** ``Condition.wait()`` / ``Event.wait()`` /
  ``Queue.get()`` / ``Thread.join()`` without a timeout parks a thread
  forever when the notify/put/exit it expects never comes — the
  no-deadlock serving contract requires every park to be bounded. Waits
  and gets are checked on bindings this file can resolve to a
  ``threading.Condition/Event`` / ``queue.Queue`` construction;
  ``.join()`` with zero arguments is flagged unconditionally (string and
  path joins always carry an argument).

Resolution is name-based within one file (module globals, ``self.<attr>``
assignments, locals), conservative like the lock checker: dynamically
stored primitives are not tracked. The runtime lockdep layer observes
what this checker cannot.
"""
from __future__ import annotations

import ast

from .core import PACKAGE_DIRS, Tree, checker

THREAD_CTOR = "Thread"
WAITABLE_CTORS = {"Condition": "Condition", "Event": "Event"}
QUEUE_CTORS = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")


def _binding_key(target):
    """A comparable key for a Name / ``self.<attr>`` assignment target."""
    if isinstance(target, ast.Name):
        return target.id
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return f"self.{target.attr}"
    return None


def _receiver_key(expr):
    """The binding key of a call receiver (``worker.join`` /
    ``self._worker.join``)."""
    return _binding_key(expr)


def _ctor_name(call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _has_timeout(call) -> bool:
    """Whether a wait/join call carries a timeout argument (the single
    positional IS the timeout for both)."""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def _get_unbounded(call) -> bool:
    """Whether a ``Queue.get`` call provably parks forever: no timeout
    (second positional or keyword) and blocking not literally False —
    ``get(block=True)`` / ``get(True)`` / bare ``get()`` all park; a
    non-literal ``block`` expression is skipped (conservative)."""
    if len(call.args) >= 2 or any(kw.arg == "timeout" for kw in call.keywords):
        return False
    block = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "block":
            block = kw.value
    if block is None:
        return True  # bare get(): blocking, unbounded
    if isinstance(block, ast.Constant):
        return block.value is not False  # get(False)/get_nowait shape is fine
    return False  # dynamic block= expression: cannot judge statically


def _daemon_true(call) -> bool:
    return any(
        kw.arg == "daemon"
        and isinstance(kw.value, ast.Constant)
        and kw.value.value is True
        for kw in call.keywords
    )


@checker(
    "thread-lifecycle",
    code="SA017",
    doc="Every threading.Thread the package constructs is daemon=True (or "
    "daemon-assigned on the same binding) or joined in the same file — a "
    "non-daemon thread nobody joins hangs shutdown; and every park is "
    "bounded: Condition.wait/Event.wait/Queue.get on resolvable bindings "
    "and every zero-argument .join() must carry a timeout. Name-based "
    "within one file, conservative; dynamically stored primitives are not "
    "tracked.",
)
def check_thread_lifecycle(tree: Tree):
    findings = []
    for rel in tree.py_files(PACKAGE_DIRS):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        threads: dict = {}     # binding key -> (lineno, daemon)
        waitables: dict = {}   # binding key -> ctor kind
        queues: set = set()
        joined: set = set()
        unbound_threads: list = []  # (lineno, call) never assigned
        # pass 1: collect every construction — ast.walk order is breadth-
        # first, so a `t.daemon = True` at outer level can precede a
        # nested construction; binding collection must complete first
        for node in ast.walk(mod):
            if not (
                isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
            ):
                continue
            ctor = _ctor_name(node.value)
            keys = [
                k for k in map(_binding_key, node.targets) if k is not None
            ]
            if ctor == THREAD_CTOR and keys:
                for k in keys:
                    threads[k] = (node.lineno, _daemon_true(node.value))
            elif ctor in WAITABLE_CTORS and keys:
                for k in keys:
                    waitables[k] = ctor
            elif ctor in QUEUE_CTORS and keys:
                queues.update(keys)
        # pass 2: daemon assignments, joins, waits, gets, unbound starts
        for node in ast.walk(mod):
            if isinstance(node, ast.Assign):
                v = node.value
                # t.daemon = True after construction
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and isinstance(v, ast.Constant)
                        and v.value is True
                    ):
                        key = _binding_key(t.value)
                        if key in threads:
                            threads[key] = (threads[key][0], True)
            elif isinstance(node, ast.Call):
                fn = node.func
                if not isinstance(fn, ast.Attribute):
                    continue
                recv = _receiver_key(fn.value)
                if fn.attr == "join":
                    if recv is not None:
                        joined.add(recv)
                    if not _has_timeout(node):
                        findings.append(
                            check_thread_lifecycle.finding(
                                rel, node.lineno,
                                ".join() without a timeout parks the caller "
                                "forever if the thread never exits — pass a "
                                "timeout and handle the survivor",
                            )
                        )
                elif fn.attr == "wait" and recv in waitables:
                    if not _has_timeout(node):
                        findings.append(
                            check_thread_lifecycle.finding(
                                rel, node.lineno,
                                f"{waitables[recv]}.wait() without a timeout "
                                "is an unbounded park — every wait must be "
                                "bounded (the no-deadlock contract)",
                            )
                        )
                elif fn.attr == "get" and recv in queues:
                    if _get_unbounded(node):
                        findings.append(
                            check_thread_lifecycle.finding(
                                rel, node.lineno,
                                "blocking Queue.get() without a timeout is "
                                "an unbounded park — pass timeout= (or use "
                                "get_nowait and back off)",
                            )
                        )
                elif (
                    fn.attr == "start"
                    and isinstance(fn.value, ast.Call)
                    and _ctor_name(fn.value) == THREAD_CTOR
                ):
                    # threading.Thread(...).start() — never bound, cannot be
                    # joined: daemon=True is the only acceptable shape
                    if not _daemon_true(fn.value):
                        unbound_threads.append(node.lineno)
        for key, (lineno, daemon) in sorted(threads.items()):
            if not daemon and key not in joined:
                findings.append(
                    check_thread_lifecycle.finding(
                        rel, lineno,
                        f"thread {key!r} is neither daemon=True nor joined "
                        "in this file — a leaked non-daemon thread hangs "
                        "process shutdown",
                    )
                )
        for lineno in unbound_threads:
            findings.append(
                check_thread_lifecycle.finding(
                    rel, lineno,
                    "unbound Thread(...).start() without daemon=True can "
                    "never be joined — mark it daemon or bind and join it",
                )
            )
    return findings
