"""``spfft_tpu.analysis`` — the pluggable static-analysis engine.

Fourteen AST-based checkers over the repository, on one framework
(:mod:`.core`): a registry with a stable code/severity/doc per checker,
``Finding`` records with ``file:line``, ``# noqa: <CODE>`` suppression, a
committed baseline (``analysis_baseline.json``) that lets accepted
pre-existing findings pass while NEW findings fail CI with exit 3, and the
``spfft_tpu.analysis/1`` JSON report schema.

Checkers 1–9 (SA001–SA009) are the nine lint checks ported from the old
monolithic ``programs/lint.py`` (which remains as a thin shim over them);
10–14 are the deep production-invariant checkers: typed-error discipline,
lock-order analysis, donation safety, jit purity, and the knob-registry
read path.

Import discipline: this package is loadable WITHOUT importing ``spfft_tpu``
itself (which pulls ``jax``) — ``programs/analyze.py`` loads it standalone
via ``importlib`` (see ``load_analysis`` there). Keep every module here
stdlib-only; sibling knowledge (vocabulary tuples, the knob registry) is
read via ``ast``, never imported.
"""
from .core import (  # noqa: F401
    BASELINE_SCHEMA,
    CHECKERS,
    SCHEMA,
    AnalysisError,
    Checker,
    Finding,
    Tree,
    apply_baseline,
    baseline_doc,
    list_noqa,
    load_baseline,
    report_doc,
    run,
    validate_report,
)

# Importing a checker module registers its checkers; the order here is the
# catalog order (SA001..SA019).
from . import hygiene  # noqa: F401  checkers 1-2: import hygiene
from . import vocab  # noqa: F401  checkers 3-9: both-ways vocabularies
from . import typed_errors  # noqa: F401  checker 10: typed-error discipline
from . import locks  # noqa: F401  checker 11: lock-order analysis
from . import donation  # noqa: F401  checker 12: donation safety
from . import purity  # noqa: F401  checker 13: jit purity
from . import knobreads  # noqa: F401  checker 14: knob-registry read path
from . import donation_dist  # noqa: F401  checker 15: batched/mesh donation
from . import metricsvocab  # noqa: F401  checker 16: metrics vocabulary
from . import threads  # noqa: F401  checker 17: thread lifecycle
from . import faultcov  # noqa: F401  checker 18: fault-site chaos coverage
from . import tracedblock  # noqa: F401  checker 19: blocking while traced

# The runtime half of the concurrency soundness layer: not a checker —
# armed via SPFFT_TPU_LOCKDEP, cross-checked against SA011's static graph
# (programs/analyze.py --lockdep-check).
from . import lockdep  # noqa: F401

PORTED_LINT_CODES = tuple(f"SA00{i}" for i in range(1, 10))
