"""Checkers 3–9: the both-ways vocabulary contracts (ported from
``programs/lint.py`` checks 3–9).

Each enforces one canonical vocabulary in both directions — everything used
is declared, everything declared is used — because a one-way check lets the
vocabulary silently rot into either an unchecked free-for-all or a pile of
dead names:

3. ``env-knob-docs`` (SA003) — the ``spfft_tpu.knobs`` registry is the knob
   surface: every ``SPFFT_TPU_*`` string in the package is a registered
   knob, every non-internal registered knob is documented in
   ``docs/details.md`` AND referenced by package code, and every knob the
   docs mention still exists (dead-doc detection). This check reads the
   REGISTRY (via ast), not regexes over scattered parsing code — the
   registry replaced that code.
4. ``stage-scope`` (SA004) — engine/tuning ``jax.named_scope`` labels vs
   ``obs.STAGES``.
5. ``fault-site`` (SA005) — ``faults.site(...)`` names vs ``faults.SITES``
   (+ docs).
6. ``trace-event`` (SA006) — ``trace.event/span/operation`` names vs
   ``trace.EVENTS``.
7. ``verify-check`` (SA007) — ``verify.CHECKS`` vs the ``CHECK_FNS``
   implementation registry (+ docs).
8. ``perf-stage`` (SA008) — ``perf.MODELED_STAGES`` vs the engine-pipeline
   subset of ``obs.STAGES``.
9. ``ir-node`` (SA009) — ``ir.NODES`` vs STAGES and MODELED_STAGES, plus
   the ``IR_KEYS``/``IR_SECTION_KEYS`` plan-card mirror.
"""
from __future__ import annotations

import ast
import re

from .core import PACKAGE_DIRS, DOCS_PATH, Tree, checker, missing_anchor

KNOBS_FILE = "spfft_tpu/knobs.py"
STAGES_FILE = "spfft_tpu/obs/stages.py"
FAULTS_PLANE_FILE = "spfft_tpu/faults/plane.py"
TRACE_FILE = "spfft_tpu/obs/trace.py"
VERIFY_CHECKS_FILE = "spfft_tpu/verify/checks.py"
PERF_FILE = "spfft_tpu/obs/perf.py"
IR_GRAPH_FILE = "spfft_tpu/ir/graph.py"
IR_COMPILE_FILE = "spfft_tpu/ir/compile.py"
PLANCARD_FILE = "spfft_tpu/obs/plancard.py"

# The engine pipeline modules: every named_scope label inside them must come
# from obs.STAGES, and every STAGES entry must appear in at least one.
ENGINE_FILES = (
    "spfft_tpu/execution.py",
    "spfft_tpu/execution_mxu.py",
    "spfft_tpu/parallel/execution.py",
    "spfft_tpu/parallel/execution_mxu.py",
    "spfft_tpu/parallel/pencil2.py",
    "spfft_tpu/parallel/pencil2_mxu.py",
)
# The autotuner's trial runner labels its phases from the same canonical
# vocabulary, under the same both-ways rule as the engines.
TUNING_FILES = ("spfft_tpu/tuning/runner.py",)

KNOB_RE = re.compile(r"SPFFT_TPU_[A-Z0-9_]+")


def package_files(tree: Tree) -> list:
    return tree.py_files(PACKAGE_DIRS)


# =============================================================================
# SA003 env-knob-docs
# =============================================================================


def registry_knobs(tree: Tree) -> dict:
    """``{name: {"internal": bool}}`` parsed from the literal ``register``
    calls in ``spfft_tpu/knobs.py`` (import-free)."""
    out: dict = {}
    for node in ast.walk(tree.parse(KNOBS_FILE)):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "register"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            continue
        internal = any(
            kw.arg == "internal"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        )
        out[node.args[0].value] = {"internal": internal, "line": node.lineno}
    return out


@checker(
    "env-knob-docs",
    code="SA003",
    doc="Three-way contract over the env-knob surface: every SPFFT_TPU_* "
    "string in the package is registered in spfft_tpu.knobs, every "
    "non-internal registered knob is documented in docs/details.md and "
    "referenced by package code, and every knob the docs mention is still "
    "registered. The registry (read via ast) is the single source; "
    "internal=True rows are the registry-level docs exemptions.",
)
def check_env_knob_docs(tree: Tree):
    skip, findings = missing_anchor(check_env_knob_docs, tree, KNOBS_FILE)
    if skip:
        return findings
    registered = registry_knobs(tree)
    in_package: dict = {}  # knob -> first (file, line)
    for rel in package_files(tree):
        if rel == KNOBS_FILE:
            continue
        for i, line in enumerate(tree.lines(rel), 1):
            for knob in KNOB_RE.findall(line):
                in_package.setdefault(knob, (rel, i))
    in_harness: dict = {}  # env reads in programs/tests (C macros excluded)
    for rel in tree.py_files(("programs", "tests")):
        for i, line in enumerate(tree.lines(rel), 1):
            if "environ" in line or "getenv" in line or "knobs." in line:
                for knob in KNOB_RE.findall(line):
                    in_harness.setdefault(knob, (rel, i))
    for knob, (rel, lineno) in sorted({**in_harness, **in_package}.items()):
        if knob not in registered:
            findings.append(
                check_env_knob_docs.finding(
                    rel, lineno,
                    f"env knob {knob} is not registered in spfft_tpu.knobs "
                    "(the registry is the single allowed knob surface)",
                )
            )
    doc_knobs: set = set()
    if tree.exists(DOCS_PATH):
        doc_knobs = set(KNOB_RE.findall(tree.source(DOCS_PATH)))
        for knob in sorted(doc_knobs):
            if knob not in registered:
                findings.append(
                    check_env_knob_docs.finding(
                        DOCS_PATH, 0,
                        f"env knob {knob} is documented but no longer "
                        "registered in spfft_tpu.knobs (dead doc)",
                    )
                )
    elif not tree.partial:
        findings.append(
            check_env_knob_docs.finding(
                DOCS_PATH, 0, "docs/details.md is missing"
            )
        )
        return findings
    for knob, info in sorted(registered.items()):
        if info["internal"]:
            continue
        if not tree.partial and knob not in doc_knobs:
            findings.append(
                check_env_knob_docs.finding(
                    KNOBS_FILE, info["line"],
                    f"env knob {knob} is registered but not documented in "
                    f"{DOCS_PATH} (regenerate the knob table: "
                    "python programs/gen_api_docs.py)",
                )
            )
        if knob not in in_package:
            findings.append(
                check_env_knob_docs.finding(
                    KNOBS_FILE, info["line"],
                    f"env knob {knob} is registered but referenced by no "
                    "package code (dead knob — delete the registration or "
                    "mark it internal)",
                )
            )
    return findings


# =============================================================================
# SA004 stage-scope
# =============================================================================


def _pipeline_strings(mod) -> set:
    """String constants of an engine/tuning file, EXCLUDING those inside the
    ``stage_accounting`` perf hooks: the hooks restate every stage name for
    the flop/byte model, so counting them would let the coverage directions
    satisfy themselves."""
    skip: set = set()
    for node in ast.walk(mod):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "stage_accounting"
        ):
            for sub in ast.walk(node):
                skip.add(id(sub))
    return {
        node.value
        for node in ast.walk(mod)
        if isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and id(node) not in skip
    }


@checker(
    "stage-scope",
    code="SA004",
    doc="Every jax.named_scope label in an engine or tuning pipeline comes "
    "from the canonical obs.STAGES list, and every listed stage appears in "
    "at least one pipeline — profiler traces stay attributable against one "
    "vocabulary.",
)
def check_stage_scopes(tree: Tree):
    skip, findings = missing_anchor(check_stage_scopes, tree, STAGES_FILE)
    if skip:
        return findings
    stages = tuple(tree.literal_assign(STAGES_FILE, "STAGES") or ())
    if len(set(stages)) != len(stages):
        findings.append(
            check_stage_scopes.finding(
                STAGES_FILE, 0, "duplicate entries in STAGES"
            )
        )
    strings: set = set()
    used: dict = {}
    for rel in ENGINE_FILES + TUNING_FILES:
        if not tree.exists(rel):
            if not tree.partial:
                findings.append(
                    check_stage_scopes.finding(
                        rel, 0, "engine/tuning pipeline file is missing"
                    )
                )
            continue
        mod = tree.parse(rel)
        strings |= _pipeline_strings(mod)
        for node in ast.walk(mod):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "named_scope"
            ):
                continue
            if node.args and isinstance(node.args[0], ast.Constant):
                label = node.args[0].value
                used.setdefault(label, (rel, node.args[0].lineno))
    for label, (rel, lineno) in sorted(used.items()):
        if label not in stages:
            findings.append(
                check_stage_scopes.finding(
                    rel, lineno,
                    f"named_scope {label!r} is not in the canonical stage "
                    f"list ({STAGES_FILE})",
                )
            )
    for stage in stages:
        if stage not in strings:
            findings.append(
                check_stage_scopes.finding(
                    STAGES_FILE, 0,
                    f"stage {stage!r} appears in no engine or tuning "
                    "pipeline",
                )
            )
    return findings


# =============================================================================
# SA005 fault-site
# =============================================================================


@checker(
    "fault-site",
    code="SA005",
    doc="Every faults.site(...) call names a site registered in the "
    "canonical faults.SITES vocabulary, every registered site is threaded "
    "through the package at least once, and every site is documented — the "
    "chaos suite's arm-every-site sweep is only exhaustive if the "
    "vocabulary is.",
)
def check_fault_sites(tree: Tree):
    skip, findings = missing_anchor(check_fault_sites, tree, FAULTS_PLANE_FILE)
    if skip:
        return findings
    sites = tuple(tree.literal_assign(FAULTS_PLANE_FILE, "SITES") or ())
    if len(set(sites)) != len(sites):
        findings.append(
            check_fault_sites.finding(
                FAULTS_PLANE_FILE, 0, "duplicate entries in SITES"
            )
        )
    used: dict = {}
    for rel in package_files(tree):
        if rel == FAULTS_PLANE_FILE:
            continue  # the registry itself is not a threading site
        mod = tree.parse(rel)
        for node in ast.walk(mod):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "site"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "faults"
            ):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)):
                findings.append(
                    check_fault_sites.finding(
                        rel, node.lineno,
                        "faults.site(...) must take a literal site name "
                        "(static analysis cannot check dynamic names)",
                    )
                )
                continue
            name = node.args[0].value
            if name not in sites:
                findings.append(
                    check_fault_sites.finding(
                        rel, node.lineno,
                        f"fault site {name!r} is not registered in the "
                        f"canonical vocabulary ({FAULTS_PLANE_FILE})",
                    )
                )
            used.setdefault(name, (rel, node.lineno))
    for name in sites:
        if name not in used:
            findings.append(
                check_fault_sites.finding(
                    FAULTS_PLANE_FILE, 0,
                    f"site {name!r} is registered but threaded through no "
                    "package code path",
                )
            )
    if tree.exists(DOCS_PATH):
        docs_text = tree.source(DOCS_PATH)
        for name in sites:
            if name not in docs_text:
                findings.append(
                    check_fault_sites.finding(
                        DOCS_PATH, 0,
                        f"fault site {name!r} is not documented",
                    )
                )
    return findings


# =============================================================================
# SA006 trace-event
# =============================================================================

TRACE_EMITTERS = ("event", "span", "operation")


def _is_trace_receiver(value) -> bool:
    """Whether a call receiver is the trace module (``trace.x`` after a
    ``from .obs import trace``, or a dotted ``obs.trace.x``)."""
    if isinstance(value, ast.Name):
        return value.id == "trace"
    return isinstance(value, ast.Attribute) and value.attr == "trace"


@checker(
    "trace-event",
    code="SA006",
    doc="Every trace.event/span/operation(...) call in the package names an "
    "event registered in the canonical trace.EVENTS vocabulary, and every "
    "registered event is emitted by at least one package call site — "
    "flight-recorder streams and their consumers stay on one vocabulary.",
)
def check_trace_events(tree: Tree):
    skip, findings = missing_anchor(check_trace_events, tree, TRACE_FILE)
    if skip:
        return findings
    events = tuple(tree.literal_assign(TRACE_FILE, "EVENTS") or ())
    if len(set(events)) != len(events):
        findings.append(
            check_trace_events.finding(
                TRACE_FILE, 0, "duplicate entries in EVENTS"
            )
        )
    used: dict = {}
    for rel in package_files(tree):
        if rel == TRACE_FILE:
            continue  # the recorder itself is not an emission site
        for node in ast.walk(tree.parse(rel)):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in TRACE_EMITTERS
                and _is_trace_receiver(node.func.value)
            ):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)):
                findings.append(
                    check_trace_events.finding(
                        rel, node.lineno,
                        f"trace.{node.func.attr}(...) must take a literal "
                        "event name (static analysis cannot check dynamic "
                        "names)",
                    )
                )
                continue
            name = node.args[0].value
            if name not in events:
                findings.append(
                    check_trace_events.finding(
                        rel, node.lineno,
                        f"trace event {name!r} is not registered in the "
                        f"canonical vocabulary ({TRACE_FILE})",
                    )
                )
            used.setdefault(name, (rel, node.lineno))
    for name in events:
        if name not in used:
            findings.append(
                check_trace_events.finding(
                    TRACE_FILE, 0,
                    f"event {name!r} is registered but emitted by no "
                    "package code path",
                )
            )
    return findings


# =============================================================================
# SA007 verify-check
# =============================================================================


@checker(
    "verify-check",
    code="SA007",
    doc="The canonical verify.CHECKS vocabulary matches the CHECK_FNS "
    "implementation registry exactly both ways, and every check is "
    "documented — the ABFT layer's instance of the both-ways contract.",
)
def check_verify_checks(tree: Tree):
    skip, findings = missing_anchor(
        check_verify_checks, tree, VERIFY_CHECKS_FILE
    )
    if skip:
        return findings
    checks = tuple(tree.literal_assign(VERIFY_CHECKS_FILE, "CHECKS") or ())
    fns: tuple = ()
    for node in tree.parse(VERIFY_CHECKS_FILE).body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "CHECK_FNS"
            for t in node.targets
        ):
            if not isinstance(node.value, ast.Dict):
                findings.append(
                    check_verify_checks.finding(
                        VERIFY_CHECKS_FILE, node.lineno,
                        "CHECK_FNS must be a dict literal",
                    )
                )
                return findings
            fns = tuple(
                k.value for k in node.value.keys if isinstance(k, ast.Constant)
            )
    if len(set(checks)) != len(checks):
        findings.append(
            check_verify_checks.finding(
                VERIFY_CHECKS_FILE, 0, "duplicate entries in CHECKS"
            )
        )
    for name in checks:
        if name not in fns:
            findings.append(
                check_verify_checks.finding(
                    VERIFY_CHECKS_FILE, 0,
                    f"check {name!r} is registered in CHECKS but has no "
                    "CHECK_FNS implementation",
                )
            )
    for name in fns:
        if name not in checks:
            findings.append(
                check_verify_checks.finding(
                    VERIFY_CHECKS_FILE, 0,
                    f"CHECK_FNS implements {name!r} but it is not "
                    "registered in CHECKS",
                )
            )
    if tree.exists(DOCS_PATH):
        docs_text = tree.source(DOCS_PATH)
        for name in checks:
            if name not in docs_text:
                findings.append(
                    check_verify_checks.finding(
                        DOCS_PATH, 0,
                        f"verify check {name!r} is not documented",
                    )
                )
    return findings


# =============================================================================
# SA008 perf-stage
# =============================================================================


@checker(
    "perf-stage",
    code="SA008",
    doc="perf.MODELED_STAGES equals the engine-pipeline subset of "
    "obs.STAGES exactly both ways: every modeled stage is canonical and "
    "appears in an engine pipeline, every engine-pipeline stage carries a "
    "flop/byte model (tuning-only trial phases are harness stages, exempt).",
)
def check_perf_stages(tree: Tree):
    for anchor in (PERF_FILE, STAGES_FILE):
        skip, findings = missing_anchor(check_perf_stages, tree, anchor)
        if skip:
            return findings
    stages = tuple(tree.literal_assign(STAGES_FILE, "STAGES") or ())
    modeled = tuple(tree.literal_assign(PERF_FILE, "MODELED_STAGES") or ())
    findings = []
    if len(set(modeled)) != len(modeled):
        findings.append(
            check_perf_stages.finding(
                PERF_FILE, 0, "duplicate entries in MODELED_STAGES"
            )
        )
    engine_strings: set = set()
    for rel in ENGINE_FILES:
        if tree.exists(rel):
            # accounting hooks excluded (_pipeline_strings): membership here
            # must mean "the compiled pipeline tags this stage", not "the
            # perf model mentions it"
            engine_strings |= _pipeline_strings(tree.parse(rel))
    engine_stages = [s for s in stages if s in engine_strings]
    for name in modeled:
        if name not in stages:
            findings.append(
                check_perf_stages.finding(
                    PERF_FILE, 0,
                    f"modeled stage {name!r} is not in the canonical stage "
                    f"list ({STAGES_FILE})",
                )
            )
        elif name not in engine_stages:
            findings.append(
                check_perf_stages.finding(
                    PERF_FILE, 0,
                    f"modeled stage {name!r} appears in no engine pipeline",
                )
            )
    for name in engine_stages:
        if name not in modeled:
            findings.append(
                check_perf_stages.finding(
                    STAGES_FILE, 0,
                    f"engine stage {name!r} carries no flop/byte model in "
                    f"{PERF_FILE} (MODELED_STAGES)",
                )
            )
    return findings


# =============================================================================
# SA009 ir-node (+ plan-card IR_KEYS mirror)
# =============================================================================


@checker(
    "ir-node",
    code="SA009",
    doc="The stage-graph IR's NODES vocabulary matches obs.STAGES and "
    "perf.MODELED_STAGES both ways (an IR stage can never escape profiler "
    "attribution or perf accounting), and the plan card's IR_SECTION_KEYS "
    "mirror of ir.compile.IR_KEYS is identical (cards missing a new ir key "
    "must not pass schema validation).",
)
def check_ir_nodes(tree: Tree):
    for anchor in (IR_GRAPH_FILE, STAGES_FILE, PERF_FILE):
        skip, findings = missing_anchor(check_ir_nodes, tree, anchor)
        if skip:
            return findings
    stages = tuple(tree.literal_assign(STAGES_FILE, "STAGES") or ())
    modeled = tuple(tree.literal_assign(PERF_FILE, "MODELED_STAGES") or ())
    nodes = tuple(tree.literal_assign(IR_GRAPH_FILE, "NODES") or ())
    findings = []
    if len(set(nodes)) != len(nodes):
        findings.append(
            check_ir_nodes.finding(
                IR_GRAPH_FILE, 0, "duplicate entries in NODES"
            )
        )
    for name in nodes:
        if name not in stages:
            findings.append(
                check_ir_nodes.finding(
                    IR_GRAPH_FILE, 0,
                    f"IR node {name!r} is not in the canonical stage list "
                    f"({STAGES_FILE})",
                )
            )
        if name not in modeled:
            findings.append(
                check_ir_nodes.finding(
                    IR_GRAPH_FILE, 0,
                    f"IR node {name!r} carries no flop/byte model in "
                    f"{PERF_FILE} (MODELED_STAGES)",
                )
            )
    for name in modeled:
        if name not in nodes:
            findings.append(
                check_ir_nodes.finding(
                    PERF_FILE, 0,
                    f"modeled stage {name!r} is not an IR node "
                    f"({IR_GRAPH_FILE} NODES) — the stage graph cannot "
                    "express it",
                )
            )
    # the plan-card mirror: IR_SECTION_KEYS (plancard stays import-free)
    # must equal the source-of-truth IR_KEYS literal in ir/compile.py
    if tree.exists(IR_COMPILE_FILE) and tree.exists(PLANCARD_FILE):
        ir_keys = tree.literal_assign(IR_COMPILE_FILE, "IR_KEYS")
        card_keys = tree.literal_assign(PLANCARD_FILE, "IR_SECTION_KEYS")
        if tuple(ir_keys or ()) != tuple(card_keys or ()):
            findings.append(
                check_ir_nodes.finding(
                    PLANCARD_FILE, 0,
                    f"IR_SECTION_KEYS {tuple(card_keys or ())!r} does not "
                    f"match {IR_COMPILE_FILE} IR_KEYS "
                    f"{tuple(ir_keys or ())!r} — the card validator would "
                    "accept cards missing (or carrying stale) ir keys",
                )
            )
        # the batch-section mirror follows the same contract
        batch_keys = tree.literal_assign(IR_COMPILE_FILE, "BATCH_KEYS")
        card_batch = tree.literal_assign(PLANCARD_FILE, "BATCH_SECTION_KEYS")
        if tuple(batch_keys or ()) != tuple(card_batch or ()):
            findings.append(
                check_ir_nodes.finding(
                    PLANCARD_FILE, 0,
                    f"BATCH_SECTION_KEYS {tuple(card_batch or ())!r} does "
                    f"not match {IR_COMPILE_FILE} BATCH_KEYS "
                    f"{tuple(batch_keys or ())!r} — the card validator "
                    "would accept cards missing (or carrying stale) batch "
                    "keys",
                )
            )
    elif not tree.partial:
        findings.append(
            check_ir_nodes.finding(
                IR_COMPILE_FILE, 0,
                "ir/compile.py or obs/plancard.py is missing — the IR_KEYS "
                "mirror check cannot run",
            )
        )
    return findings
