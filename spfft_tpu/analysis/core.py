"""Framework core of the pluggable static-analysis engine.

The pieces every checker builds on:

* :class:`Finding` — one defect record (``file:line``, checker code, a
  line-number-free message so baselines survive unrelated edits).
* :class:`Checker` + :func:`checker` — the registry: every checker declares
  a stable short code (``SA001``...), a slug name (``duplicate-import``),
  a severity, and a one-paragraph doc (the catalog in ``docs/details.md``
  renders from these).
* :class:`Tree` — the source under analysis: the real repository (rooted)
  or an in-memory ``{relpath: source}`` map (test fixtures). Parses are
  cached so fourteen checkers share one ``ast`` per file.
* :func:`run` — execute (a subset of) the registry over a tree, dropping
  findings suppressed by ``# noqa: <CODE>`` comments.
* Baseline machinery — :func:`load_baseline` / :func:`apply_baseline` /
  :func:`baseline_doc`: pre-existing accepted findings (keyed
  ``CODE:file:message``) don't block CI, NEW findings fail with exit 3,
  and a baseline entry whose finding was fixed is *stale* and fails too —
  a fixed finding must leave the baseline.
* :func:`report_doc` — the ``spfft_tpu.analysis/1`` JSON report schema.

Import discipline: this package must be loadable WITHOUT importing
``spfft_tpu`` itself (which pulls ``jax``) — ``programs/analyze.py`` loads
it standalone, the same import-free rule the old ``programs/lint.py``
followed. Stdlib only; sibling knowledge (the knob registry, vocabulary
tuples) is read via ``ast``, never imported.
"""
from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from pathlib import Path

SCHEMA = "spfft_tpu.analysis/1"
BASELINE_SCHEMA = "spfft_tpu.analysis.baseline/1"

PACKAGE_DIRS = ("spfft_tpu",)
SCAN_DIRS = ("spfft_tpu", "programs", "tests")
DOCS_PATH = "docs/details.md"

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Za-z0-9, ]+))?")


class AnalysisError(Exception):
    """Internal analysis failure (bad tree, malformed baseline) — distinct
    from findings, which are results, not errors."""


class Finding:
    """One defect record. ``message`` must not embed line numbers: the
    baseline key is ``CODE:file:message`` so accepted findings survive
    unrelated edits that shift lines."""

    __slots__ = ("code", "checker", "severity", "file", "line", "message")

    def __init__(self, code, checker, severity, file, line, message):
        self.code = code
        self.checker = checker
        self.severity = severity
        self.file = str(file)
        self.line = int(line)
        self.message = str(message)

    def key(self) -> str:
        return f"{self.code}:{self.file}:{self.message}"

    def render(self) -> str:
        where = f"{self.file}:{self.line}" if self.line else self.file
        return f"{where}: [{self.code}] {self.message}"

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "checker": self.checker,
            "severity": self.severity,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "key": self.key(),
        }


class Checker:
    """One registered checker: stable code, slug name, severity, doc, fn.

    ``fn(tree)`` returns a list of :class:`Finding`; it must emit findings
    with ``code=self.code`` (the :func:`checker` decorator binds a
    convenience constructor onto the wrapper for that)."""

    __slots__ = ("code", "name", "severity", "doc", "fn")

    def __init__(self, code, name, severity, doc, fn):
        self.code = code
        self.name = name
        self.severity = severity
        self.doc = doc
        self.fn = fn

    def describe(self) -> dict:
        return {
            "code": self.code,
            "name": self.name,
            "severity": self.severity,
            "doc": self.doc,
        }


CHECKERS: dict = {}  # name -> Checker, insertion-ordered (checker 1..14)
_BY_CODE: dict = {}


def checker(name: str, *, code: str, severity: str = "error", doc: str):
    """Register a checker function. The decorated function gains a
    ``finding(file, line, message)`` attribute pre-bound to its code."""

    def decorate(fn):
        if name in CHECKERS or code in _BY_CODE:
            raise AnalysisError(f"checker {name}/{code} registered twice")

        def finding(file, line, message):
            return Finding(code, name, severity, file, line, message)

        fn.finding = finding
        entry = Checker(code, name, severity, doc, fn)
        CHECKERS[name] = entry
        _BY_CODE[code] = entry
        return fn

    return decorate


class Tree:
    """The source under analysis.

    Rooted mode (``Tree(root=...)``) walks the real repository; in-memory
    mode (``Tree(files={relpath: source})``) serves test fixtures without a
    filesystem. ``partial`` is True for in-memory trees: checkers anchored
    on specific repo files (vocabulary registries, ``docs/details.md``)
    no-op when their anchor is absent from a *partial* tree, but report a
    missing anchor loudly on a rooted one — a renamed anchor file must
    never silently disable its checker in CI."""

    def __init__(self, root=None, files=None):
        if (root is None) == (files is None):
            raise AnalysisError("Tree needs exactly one of root=/files=")
        self.root = Path(root) if root is not None else None
        self.partial = files is not None
        self._files = dict(files) if files is not None else None
        self._sources: dict = {}
        self._trees: dict = {}

    def py_files(self, dirs=SCAN_DIRS) -> list:
        """Sorted relpaths of every ``.py`` file under ``dirs``."""
        out = []
        if self._files is not None:
            for rel in sorted(self._files):
                if rel.endswith(".py") and rel.split("/", 1)[0] in dirs:
                    out.append(rel)
            return out
        for d in dirs:
            base = self.root / d
            if not base.is_dir():
                continue
            for path in sorted(base.rglob("*.py")):
                if "__pycache__" in path.parts:
                    continue
                out.append(path.relative_to(self.root).as_posix())
        return out

    def exists(self, rel: str) -> bool:
        if self._files is not None:
            return rel in self._files
        return (self.root / rel).is_file()

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            if self._files is not None:
                try:
                    self._sources[rel] = self._files[rel]
                except KeyError:
                    raise AnalysisError(f"no such file in tree: {rel}") from None
            else:
                self._sources[rel] = (self.root / rel).read_text()
        return self._sources[rel]

    def lines(self, rel: str) -> list:
        return self.source(rel).splitlines()

    def parse(self, rel: str):
        """Cached ``ast`` parse; a syntax error is reported by the caller
        as a finding (raised here as :class:`SyntaxError`)."""
        if rel not in self._trees:
            self._trees[rel] = ast.parse(self.source(rel), filename=rel)
        return self._trees[rel]

    def literal_assign(self, rel: str, name: str):
        """A module-level literal assignment ``name = <literal>`` evaluated
        via ``ast.literal_eval`` (the import-free vocabulary-read idiom), or
        ``None`` when absent."""
        for node in self.parse(rel).body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name for t in node.targets
            ):
                return ast.literal_eval(node.value)
        return None


def missing_anchor(fn, tree: Tree, rel: str):
    """Anchored-checker preamble: ``(skip, findings)`` for an anchor file.

    Partial (fixture) trees skip silently; a rooted tree missing its anchor
    gets a loud finding — the checker must never evaporate with the file."""
    if tree.exists(rel):
        return False, []
    if tree.partial:
        return True, []
    return True, [
        fn.finding(
            rel, 0,
            "anchor file is missing: this checker's vocabulary source moved "
            "or was deleted — update spfft_tpu/analysis or restore the file",
        )
    ]


def suppressed(tree: Tree, finding: Finding) -> bool:
    """Whether the finding's source line carries a matching ``# noqa``.

    ``# noqa`` (bare) suppresses any checker on that line; ``# noqa: SA010``
    suppresses the named codes only. Foreign codes (``# noqa: F401`` /
    ``BLE001`` conventions used for editors) do not suppress analysis
    findings."""
    if not finding.line or not tree.exists(finding.file):
        return False
    lines = tree.lines(finding.file)
    if finding.line > len(lines):
        return False
    m = _NOQA_RE.search(lines[finding.line - 1])
    if not m:
        return False
    codes = m.group("codes")
    if codes is None:
        return True
    wanted = {c.strip().upper() for c in codes.split(",")}
    return finding.code.upper() in wanted


def run(tree: Tree, only=None, *, suppress=True, jobs=None) -> list:
    """Run (a subset of) the registered checkers; returns surviving
    findings, sorted (code, file, line, message).

    ``suppress=False`` returns the RAW findings including ``# noqa``-covered
    ones — the orphaned-suppression audit (:func:`list_noqa` consumers)
    needs to know what would fire without the comments.

    ``jobs`` > 1 runs the checkers on a thread pool after pre-parsing every
    scanned file concurrently (checkers are pure functions of the parsed
    tree; the per-file ``ast`` caches make parsing the dominant cost, and a
    racing double-parse is harmless last-write-wins). The final sort makes
    the result identical to a serial run — asserted in the test suite."""
    names = list(CHECKERS)
    if only:
        only = [only] if isinstance(only, str) else list(only)
        unknown = [n for n in only if n not in CHECKERS and n not in _BY_CODE]
        if unknown:
            raise AnalysisError(
                f"unknown checker(s) {unknown}: expected names "
                f"{sorted(CHECKERS)} or codes {sorted(_BY_CODE)}"
            )
        names = [
            n for n in names
            if n in only or CHECKERS[n].code in only
        ]
    if jobs is not None and jobs > 1 and len(names) > 1:
        from concurrent.futures import ThreadPoolExecutor

        def parse_quiet(rel):
            try:
                tree.parse(rel)
            except SyntaxError:
                pass  # each checker reports/skips syntax errors itself

        with ThreadPoolExecutor(max_workers=jobs) as pool:
            list(pool.map(parse_quiet, tree.py_files()))
            per_checker = list(
                pool.map(lambda name: CHECKERS[name].fn(tree), names)
            )
        raw = [f for batch in per_checker for f in batch]
    else:
        raw = []
        for name in names:
            raw.extend(CHECKERS[name].fn(tree))
    findings = [
        f for f in raw if not suppress or not suppressed(tree, f)
    ]
    findings.sort(key=lambda f: (f.code, f.file, f.line, f.message))
    return findings


def list_noqa(tree: Tree) -> list:
    """Every ``# noqa: SA*`` suppression comment in the scanned tree, as
    ``{"file", "line", "codes"}`` rows (real COMMENT tokens only — prose in
    docstrings that *mentions* a noqa is not a suppression). Bare
    ``# noqa`` and foreign codes (``F401``) are editor vocabulary, skipped.
    The ``--list-noqa`` audit joins these against a ``suppress=False`` run
    to flag ORPHANED suppressions — a noqa whose code no longer fires on
    that line hides the next real regression there."""
    out = []
    for rel in tree.py_files():
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(tree.source(rel)).readline
            )
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _NOQA_RE.search(tok.string)
                if not m or m.group("codes") is None:
                    continue
                sa_codes = [
                    c.strip().upper()
                    for c in m.group("codes").split(",")
                    if c.strip().upper().startswith("SA")
                ]
                if sa_codes:
                    out.append(
                        {"file": rel, "line": tok.start[0], "codes": sa_codes}
                    )
        except (tokenize.TokenError, SyntaxError, IndentationError):
            continue
    return out


# ---- baseline ----------------------------------------------------------------


def load_baseline(path) -> set:
    """Accepted finding keys from a committed baseline file; an absent file
    is an empty baseline."""
    path = Path(path)
    if not path.is_file():
        return set()
    doc = json.loads(path.read_text())
    if doc.get("schema") != BASELINE_SCHEMA:
        raise AnalysisError(
            f"{path}: unexpected baseline schema {doc.get('schema')!r} "
            f"(expected {BASELINE_SCHEMA})"
        )
    entries = doc.get("entries")
    if not isinstance(entries, list) or not all(
        isinstance(e, str) for e in entries
    ):
        raise AnalysisError(f"{path}: baseline entries must be a string list")
    return set(entries)


def apply_baseline(findings: list, accepted: set) -> dict:
    """Split findings against the accepted set.

    ``new`` fail CI (exit 3); ``baselined`` are reported but pass; ``stale``
    are accepted keys no current finding matches — the freshness rule: a
    fixed finding must leave the baseline (also exit 3, or the baseline
    would silently rot into a blanket waiver)."""
    current = {f.key() for f in findings}
    return {
        "new": [f for f in findings if f.key() not in accepted],
        "baselined": [f for f in findings if f.key() in accepted],
        "stale": sorted(accepted - current),
    }


def baseline_doc(findings: list) -> dict:
    """The committed-baseline document accepting every given finding."""
    return {
        "schema": BASELINE_SCHEMA,
        "generated_by": "programs/analyze.py --write-baseline",
        "entries": sorted({f.key() for f in findings}),
    }


# ---- report ------------------------------------------------------------------


def report_doc(findings: list, split: dict, *, root: str, baseline_path: str) -> dict:
    """The ``spfft_tpu.analysis/1`` JSON report."""
    baselined_keys = {f.key() for f in split["baselined"]}
    rows = []
    for f in findings:
        row = f.to_json()
        row["baselined"] = f.key() in baselined_keys
        rows.append(row)
    return {
        "schema": SCHEMA,
        "root": str(root),
        "checkers": [CHECKERS[n].describe() for n in CHECKERS],
        "findings": rows,
        "counts": {
            "total": len(findings),
            "new": len(split["new"]),
            "baselined": len(split["baselined"]),
            "stale_baseline": len(split["stale"]),
        },
        "baseline": {
            "path": str(baseline_path),
            "stale_entries": split["stale"],
        },
    }


REPORT_KEYS = ("schema", "root", "checkers", "findings", "counts", "baseline")
FINDING_KEYS = (
    "code", "checker", "severity", "file", "line", "message", "key",
    "baselined",
)


def validate_report(doc: dict) -> list:
    """Missing-key list for a report document (schema floor; empty = valid),
    the same shape as ``obs.validate_report``."""
    missing = [k for k in REPORT_KEYS if k not in doc]
    if doc.get("schema") != SCHEMA:
        missing.append(f"schema=={SCHEMA}")
    for i, row in enumerate(doc.get("findings", [])):
        for k in FINDING_KEYS:
            if k not in row:
                missing.append(f"findings[{i}].{k}")
    for k in ("total", "new", "baselined", "stale_baseline"):
        if k not in doc.get("counts", {}):
            missing.append(f"counts.{k}")
    return missing
