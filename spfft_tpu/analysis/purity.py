"""Checker 13: jit purity (SA013).

Side effects inside traced code don't happen per call — they happen ONCE at
trace time and silently freeze: a metric counter bumped inside a ``_st_*``
stage body increments exactly once per compilation (the dispatch counts lie
forever after), a ``time.*`` read becomes a constant, an ``os.environ`` /
``knobs`` read pins the knob's trace-time value into the compiled program
(the runtime knob appears to work until the cache hits), and a flight-
recorder event records compilations instead of executions.

Traced scopes are found statically, name-based and conservative:

* every function or method named ``_st_*`` — the extracted stage bodies the
  IR lowers into fused programs (``ir/lower.py``),
* every function literally passed to ``jax.jit(...)`` / ``jit(...)`` /
  ``shard_map(...)`` — a lambda/def argument, or a same-file
  function/method name resolved through one level.

Host-side orchestration around the traced call (``StagedProgram.__call__``,
``EngineIr._count``) stays free to count and trace — that is exactly where
those effects belong.
"""
from __future__ import annotations

import ast

from .core import PACKAGE_DIRS, Tree, checker

STAGE_PREFIX = "_st_"
TRACING_ENTRY_NAMES = ("jit", "shard_map")

# receivers whose method calls are impure inside a trace
TRACE_EMITTERS = ("event", "span", "operation")
METRIC_MUTATORS = ("inc", "observe")
INSTRUMENT_FACTORIES = ("counter", "gauge", "histogram")


def _call_name(call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _root_name(expr):
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _impurity(call) -> str | None:
    """A description when ``call`` is an effect that must not be traced."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "getenv":
            return "os.getenv(...) read (frozen at trace time)"
        return None
    if not isinstance(fn, ast.Attribute):
        # call-of-call (`jax.vmap(f)(*args)`) / subscripted callables: no
        # attribute chain to inspect — not one of the effect shapes above
        return None
    root = _root_name(fn)
    if root == "time":
        return f"time.{fn.attr}(...) (a trace-time constant)"
    if root == "os" and fn.attr in ("getenv",):
        return "os.getenv(...) read (frozen at trace time)"
    if root == "knobs" or (
        isinstance(fn.value, ast.Attribute) and fn.value.attr == "knobs"
    ):
        return f"knobs.{fn.attr}(...) read (frozen at trace time)"
    if fn.attr in TRACE_EMITTERS and root in ("trace", "obs"):
        return f"trace.{fn.attr}(...) emission (records compilations, " \
            "not executions)"
    if fn.attr in METRIC_MUTATORS:
        return f".{fn.attr}() metric mutation (bumps once per compilation)"
    if fn.attr in INSTRUMENT_FACTORIES and root == "obs":
        return f"obs.{fn.attr}(...) instrument creation"
    return None


def _has_environ(node) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


def _traced_functions(mod) -> list:
    """(fn_node, why) scopes of one module that are traced: ``_st_*``
    bodies, plus defs/lambdas/named functions passed to jit/shard_map."""
    by_name: dict = {}
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
    out = []
    seen: set = set()

    def note(fn_node, why):
        if id(fn_node) not in seen:
            seen.add(id(fn_node))
            out.append((fn_node, why))

    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith(STAGE_PREFIX):
                note(node, f"stage body {node.name}")
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in TRACING_ENTRY_NAMES or not node.args:
            continue
        target = node.args[0]
        if isinstance(target, (ast.Lambda, ast.FunctionDef)):
            note(target, f"function passed to {name}")
        elif isinstance(target, ast.Name) and target.id in by_name:
            note(by_name[target.id], f"{target.id} passed to {name}")
        elif (
            isinstance(target, ast.Attribute)
            and target.attr in by_name
        ):
            # self._backward_impl style: resolve by method name, same file
            note(by_name[target.attr], f"{target.attr} passed to {name}")
    return out


@checker(
    "jit-purity",
    code="SA013",
    doc="No metric increments, trace events, time.* reads, os.environ/"
    "knobs reads, or instrument creation inside a _st_* stage body or any "
    "function passed to jax.jit/shard_map — side effects in traced code "
    "run once at trace time and silently freeze (a counter that lies, a "
    "knob that stops responding). Traced scopes are resolved name-based "
    "within one file; host-side orchestration around the traced call is "
    "exempt by construction.",
)
def check_jit_purity(tree: Tree):
    findings = []
    for rel in tree.py_files(PACKAGE_DIRS):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        for fn_node, why in _traced_functions(mod):
            body = fn_node.body
            nodes = []
            for stmt in body if isinstance(body, list) else [body]:
                nodes.extend(ast.walk(stmt))
            for node in nodes:
                if isinstance(node, ast.Call):
                    desc = _impurity(node)
                    if desc:
                        findings.append(
                            check_jit_purity.finding(
                                rel, node.lineno,
                                f"impure {desc} inside traced code "
                                f"({why}) — hoist it to the host-side "
                                "caller",
                            )
                        )
                elif _has_environ(node):
                    findings.append(
                        check_jit_purity.finding(
                            rel, node.lineno,
                            f"os.environ read inside traced code ({why}) — "
                            "resolve the knob before tracing and close "
                            "over the value",
                        )
                    )
    return findings
