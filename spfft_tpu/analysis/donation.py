"""Checker 12: donation safety (SA012).

The fused IR path donates the packed value buffers of the consuming local
backward (``ir/compile.py``: ``jax.jit(fn, donate_argnums=spec["donate"])``)
— XLA may overwrite a donated buffer the moment its consuming node runs, so
a lowered graph that references a donated input edge *after* that node (or
leaks it as a graph output) computes with freed memory. Two rules:

* **No use after donate.** In every local-builder backward graph of
  ``ir/lower.py``, each donatable input edge (the positions local
  ``_ir_spec`` methods declare in their ``"donate"`` tuples) is consumed by
  at most one node and never escapes via ``set_outputs``.
* **The card tells the truth.** The plan card's donation map
  (``EngineIr.describe``) must derive from the same spec key the fusion
  pass actually passes to ``donate_argnums`` (``build_fused``) — a card
  claiming donation that the jit does not apply (or vice versa) makes the
  provenance section silently wrong.

Graphs are reconstructed statically from the literal ``add_input``/``add``/
``set_outputs`` calls (string-constant propagation over simple local
assignments like ``cur = "sticks"``); nodes whose edge tuples are not
statically resolvable are skipped — conservative, like the lock analysis.
Donation only applies to ``kind == "local"`` specs on the backward
direction (``build_fused``), so only ``_lower_local_*`` builders are held
to the use-after-donate rule.
"""
from __future__ import annotations

import ast

from .core import PACKAGE_DIRS, Tree, checker, missing_anchor

IR_LOWER_FILE = "spfft_tpu/ir/lower.py"
IR_COMPILE_FILE = "spfft_tpu/ir/compile.py"

LOCAL_BUILDER_PREFIX = "_lower_local"


def donated_positions(tree: Tree) -> set:
    """Input positions any ``kind == "local"`` ``_ir_spec`` declares
    donatable (the union of the literal ``"donate"`` tuples)."""
    out: set = set()
    for rel in tree.py_files(PACKAGE_DIRS):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod):
            if not (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "_ir_spec"
            ):
                continue
            for ret in ast.walk(node):
                if not (
                    isinstance(ret, ast.Return)
                    and isinstance(ret.value, ast.Dict)
                ):
                    continue
                keys = {
                    k.value: v
                    for k, v in zip(ret.value.keys, ret.value.values)
                    if isinstance(k, ast.Constant)
                }
                kind = keys.get("kind")
                if not (
                    isinstance(kind, ast.Constant) and kind.value == "local"
                ):
                    continue
                donate = keys.get("donate")
                if isinstance(donate, (ast.Tuple, ast.List)):
                    for el in donate.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, int
                        ):
                            out.add(el.value)
    return out


def _string_values(expr, consts: dict) -> set:
    """Possible string values of a tuple/list element: a literal, or every
    literal ever assigned to that local name (``cur = "sticks"``)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, ast.Name):
        return consts.get(expr.id, set())
    return set()


class _Graph:
    """One statically reconstructed StageGraph build."""

    def __init__(self, direction, lineno):
        self.direction = direction
        self.lineno = lineno
        self.inputs: list = []          # ordered add_input names
        self.consumers: list = []       # (possible input-edge names, lineno)
        self.outputs: set = set()
        self.batch: set = set()         # declared batch_inputs edge names


def _reconstruct(fn_node) -> list:
    """Graphs built inside one function body (nested defs included)."""
    graphs: dict = {}  # var name -> _Graph (latest binding wins)
    consts: dict = {}  # local str-constant propagation
    out: list = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id == "StageGraph"
                and v.args
                and isinstance(v.args[0], ast.Constant)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        g = _Graph(v.args[0].value, node.lineno)
                        graphs[t.id] = g
                        out.append(g)
            elif isinstance(v, ast.Constant) and isinstance(v.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts.setdefault(t.id, set()).add(v.value)
            # g.batch_inputs = ("values_re", ...) — the declared per-request
            # edges (an IfExp of literal tuples contributes the union)
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "batch_inputs"
                    and isinstance(t.value, ast.Name)
                    and t.value.id in graphs
                ):
                    graphs[t.value.id].batch |= {
                        sub.value
                        for sub in ast.walk(v)
                        if isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                    }
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in graphs
        ):
            continue
        g = graphs[node.func.value.id]
        meth = node.func.attr
        if meth == "add_input" and node.args and isinstance(
            node.args[0], ast.Constant
        ):
            g.inputs.append(node.args[0].value)
        elif meth == "add" and len(node.args) >= 3:
            ins = node.args[2]
            if isinstance(ins, (ast.Tuple, ast.List)):
                possible: set = set()
                for el in ins.elts:
                    possible |= _string_values(el, consts)
                g.consumers.append((possible, node.lineno))
            # non-literal edge tuples: skipped (conservative)
        elif meth == "set_outputs" and node.args and isinstance(
            node.args[0], (ast.Tuple, ast.List)
        ):
            for el in node.args[0].elts:
                g.outputs |= _string_values(el, consts)
    return out


def _spec_keys(scope, receiver_names=("spec",)) -> set:
    """String keys read off a spec receiver (``spec["k"]`` /
    ``spec.get("k")`` / ``self.spec[...]``) anywhere under ``scope``."""

    def is_spec(expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in receiver_names
        return isinstance(expr, ast.Attribute) and expr.attr in receiver_names
    keys: set = set()
    for node in ast.walk(scope):
        if (
            isinstance(node, ast.Subscript)
            and is_spec(node.value)
            and isinstance(node.slice, ast.Constant)
        ):
            keys.add(node.slice.value)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and is_spec(node.func.value)
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            keys.add(node.args[0].value)
    return keys


@checker(
    "donation-safety",
    code="SA012",
    doc="In the lowered local backward graphs (ir/lower.py), every "
    "donatable input edge (the positions local _ir_spec methods declare "
    "under \"donate\") is consumed by at most one node and never escapes "
    "via set_outputs — XLA may overwrite a donated buffer at its consuming "
    "node, so any later reference computes with freed memory. In "
    "ir/compile.py, the plan card's donation map (EngineIr.describe) must "
    "derive from the same spec key build_fused passes to donate_argnums. "
    "Graphs are reconstructed from literal add_input/add/set_outputs calls; "
    "non-literal nodes are skipped (conservative).",
)
def check_donation_safety(tree: Tree):
    findings = []
    for anchor in (IR_LOWER_FILE, IR_COMPILE_FILE):
        skip, f = missing_anchor(check_donation_safety, tree, anchor)
        if skip:
            return findings + f
        findings += f
    positions = donated_positions(tree)

    # ---- rule 1: no use after donate in local backward graphs ---------------
    lower_mod = tree.parse(IR_LOWER_FILE)
    for builder in lower_mod.body:
        if not (
            isinstance(builder, (ast.FunctionDef, ast.AsyncFunctionDef))
            and builder.name.startswith(LOCAL_BUILDER_PREFIX)
        ):
            continue
        for g in _reconstruct(builder):
            if g.direction != "backward":
                continue
            for i in sorted(positions):
                if i >= len(g.inputs):
                    continue
                edge = g.inputs[i]
                uses = [
                    (possible, lineno)
                    for possible, lineno in g.consumers
                    if edge in possible
                ]
                for _possible, lineno in uses[1:]:
                    findings.append(
                        check_donation_safety.finding(
                            IR_LOWER_FILE, lineno,
                            f"donated input edge {edge!r} (donate position "
                            f"{i}) referenced after its consuming node in a "
                            f"{builder.name} backward graph — the fused "
                            "consuming jit may have freed it",
                        )
                    )
                if edge in g.outputs:
                    findings.append(
                        check_donation_safety.finding(
                            IR_LOWER_FILE, g.lineno,
                            f"donated input edge {edge!r} escapes as a graph "
                            f"output of a {builder.name} backward graph",
                        )
                    )

    # ---- rule 2: donate_argnums and the card's donation map agree -----------
    compile_mod = tree.parse(IR_COMPILE_FILE)
    build_keys: set = set()
    applied = False
    describe_keys: set = set()
    for node in ast.walk(compile_mod):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == "build_fused":
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                for kw in call.keywords:
                    if kw.arg != "donate_argnums":
                        continue
                    applied = True
                    # keys feeding the donate expression: the names it
                    # references, resolved through their assignments
                    names = {
                        n.id for n in ast.walk(kw.value)
                        if isinstance(n, ast.Name)
                    }
                    for stmt in ast.walk(node):
                        if isinstance(stmt, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id in names
                            for t in stmt.targets
                        ):
                            build_keys |= _spec_keys(stmt.value)
                    build_keys |= _spec_keys(kw.value)
        elif node.name == "describe":
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id in ("donated", "donation")
                    for t in stmt.targets
                ):
                    describe_keys |= _spec_keys(stmt.value)
    if positions and not applied:
        findings.append(
            check_donation_safety.finding(
                IR_COMPILE_FILE, 0,
                "local _ir_spec declares donatable inputs but no jit in "
                f"{IR_COMPILE_FILE} passes donate_argnums — the declared "
                "donation is never applied",
            )
        )
    if applied and not describe_keys:
        findings.append(
            check_donation_safety.finding(
                IR_COMPILE_FILE, 0,
                "build_fused donates buffers but EngineIr.describe derives "
                "no donation map from the spec — the plan card cannot "
                "report what was donated",
            )
        )
    if build_keys and describe_keys and build_keys != describe_keys:
        findings.append(
            check_donation_safety.finding(
                IR_COMPILE_FILE, 0,
                f"the card's donation map reads spec key(s) "
                f"{sorted(describe_keys)} but build_fused donates from "
                f"{sorted(build_keys)} — the provenance section would lie "
                "about the applied donate_argnums",
            )
        )
    return findings
