"""Runtime lockdep validator: observe the REAL lock-acquisition graph.

The static lock-order checker (:mod:`.locks`, SA011) models acquisition
order from the AST — conservatively, name-based. What it cannot see:
dynamic dispatch (a callback acquiring a lock the caller never names),
dict-held latches (``serve.batcher``'s per-digest build locks), and
cross-thread handoffs. This module is the second layer: armed via the
``SPFFT_TPU_LOCKDEP`` knob (read in ``spfft_tpu/__init__`` *before* any
submodule creates its threading primitives), :func:`install` replaces the
``threading.Lock/RLock/Condition/Event`` factories with recording wrappers
for every primitive the PACKAGE creates — foreign creations (stdlib
internals, jax, tests) pass through untouched, so overhead and noise stay
confined to the locks under study.

What gets recorded, per process:

* **Locks** — every package-created primitive, identified by its creation
  site ``file::line`` (the join key against the static model; a
  per-instance ``self.<attr>`` lock yields many primitives sharing one
  site id, aggregated exactly like the static model's one-name-per-site
  view).
* **Edges** — ``A -> B`` whenever a thread acquires ``B`` while holding
  ``A`` (recorded at the *attempt*, so a real deadlock still leaves its
  edge in the report). Re-entry of the SAME primitive instance (RLock) is
  not an edge, but nesting two same-site instances IS — it appears as a
  site-level self-edge, the shape of an unordered two-instance (ABBA)
  hazard.
* **Blocking** — a ``Condition.wait`` / ``Event.wait`` entered while some
  *other* recorded lock is still held (``Condition.wait`` releases only its
  own lock; anything else stays held across the unbounded wait).
* **Cycles** — SCCs of the observed edge graph (:func:`.locks.find_cycles`,
  the same detector the static pass uses).

:func:`report` exports the ``spfft_tpu.analysis.lockdep/1`` JSON document
(``SPFFT_TPU_LOCKDEP_REPORT`` dumps it at process exit); :func:`crosscheck`
validates it against :func:`.locks.static_graph`: a runtime edge between
two statically-known locks that the static graph does not contain means
THE STATIC MODEL IS STALE — itself a finding, exactly like a runtime cycle
or a blocking wait. Edges touching a lock the static pass cannot track
(dynamic creation sites) are reported as ``dynamic`` — listed, explained,
not findings.

Import discipline: stdlib-only, loadable without ``spfft_tpu`` (the same
contract as every module in this package). The wrappers implement the full
public lock API (``acquire(blocking, timeout)``, context-manager protocol,
``locked``, ``notify``/``wait_for``) so armed suites run unchanged.
"""
from __future__ import annotations

import atexit
import json
import sys
import threading

from .locks import find_cycles

SCHEMA = "spfft_tpu.analysis.lockdep/1"

_REAL: dict = {}       # saved threading factories (install/uninstall)
_installed = False
_report_path = None
_dump_registered = False  # atexit hook registered once per process

# recorder state — guarded by a REAL (unwrapped) lock created at install;
# the recorder lock is leaf-only: nothing else is ever acquired under it
_reclock = None
_locks: dict = {}      # lock_id -> {"kind", "file", "line", "created"}
_edges: dict = {}      # (from, to) -> {"file", "line", "count"}
_blocking: dict = {}   # (lock_id, held_tuple) -> {"file", "line", "count"}

_tls = threading.local()

_SELF_FILE = __file__
_THREADING_FILE = threading.__file__

# creation sites under these path components are "package" locks (recorded);
# everything else passes through unwrapped
_PACKAGE_MARKER = "spfft_tpu"


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _rel_path(filename: str) -> str:
    """Repository-relative path when the marker is present (the static
    model's file keys are repo-relative), else the filename unchanged."""
    norm = filename.replace("\\", "/")
    marker = f"/{_PACKAGE_MARKER}/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return norm[idx + 1:]
    return norm


def _caller_site() -> tuple:
    """(file, line) of the nearest frame outside this module and the
    threading module — where the user code created/acquired the primitive."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF_FILE and fn != _THREADING_FILE:
            return _rel_path(fn), f.f_lineno
        f = f.f_back
    return "?", 0


def _in_package(rel: str) -> bool:
    return rel.startswith(f"{_PACKAGE_MARKER}/") and "/analysis/" not in rel


def _register_lock(kind: str, site: tuple) -> str:
    lock_id = f"{site[0]}::{site[1]}"
    with _reclock:
        info = _locks.get(lock_id)
        if info is None:
            _locks[lock_id] = {
                "kind": kind, "file": site[0], "line": site[1], "created": 1,
            }
        else:
            info["created"] += 1
    return lock_id


def _note_attempt(wrapper) -> None:
    """Record held -> wrapper edges at the acquisition ATTEMPT (before the
    real acquire blocks), so a genuine deadlock still leaves its edge.

    The held stack carries wrapper INSTANCES: re-entry of the same
    instance (RLock) is exempt by identity, while nesting two different
    instances created at the same site records a site-level self-edge —
    the unordered two-instance hazard a shared-id comparison would hide."""
    held = _held()
    if not held:
        return
    lock_id = wrapper.lock_id
    site = _caller_site()
    with _reclock:
        for h in held:
            if h is wrapper:
                continue  # same-instance re-entry (RLock): not an edge
            e = _edges.get((h.lock_id, lock_id))
            if e is None:
                _edges[(h.lock_id, lock_id)] = {
                    "file": site[0], "line": site[1], "count": 1,
                }
            else:
                e["count"] += 1


def _note_blocking(lock_id: str, others: list) -> None:
    site = _caller_site()
    key = (lock_id, tuple(sorted(set(others))))
    with _reclock:
        b = _blocking.get(key)
        if b is None:
            _blocking[key] = {"file": site[0], "line": site[1], "count": 1}
        else:
            b["count"] += 1


def _push(wrapper) -> None:
    _held().append(wrapper)


def _pop(wrapper) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is wrapper:
            del held[i]
            return


class _LockWrapper:
    """Recording proxy over a real ``threading.Lock``/``RLock``."""

    __slots__ = ("_real", "lock_id", "kind")

    def __init__(self, real, kind: str, lock_id: str):
        self._real = real
        self.kind = kind
        self.lock_id = lock_id

    def acquire(self, blocking=True, timeout=-1):
        _note_attempt(self)
        ok = self._real.acquire(blocking, timeout)
        if ok:
            _push(self)
        return ok

    def release(self):
        self._real.release()
        _pop(self)

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _ConditionWrapper:
    """Recording proxy over a real ``threading.Condition``.

    Constructed without a lock, the wrapper owns a real RLock (created from
    the saved original so the inner lock is never itself recorded) and does
    its own held bookkeeping. Constructed WITH a caller lock, that lock's
    own wrapper (if any) already does the bookkeeping — this wrapper then
    only adds the wait-while-holding detection."""

    __slots__ = ("_real", "lock_id", "kind", "_tracks", "_inner")

    def __init__(self, kind: str, lock_id: str, lock=None):
        self.kind = kind
        self.lock_id = lock_id
        if lock is None:
            self._real = _REAL["Condition"](_REAL["RLock"]())
            self._tracks = True
            self._inner = self
        else:
            self._real = _REAL["Condition"](lock)
            self._tracks = False
            self._inner = lock  # the caller's (possibly wrapped) lock

    def acquire(self, *args, **kwargs):
        if self._tracks:
            _note_attempt(self)
        ok = self._real.acquire(*args, **kwargs)
        if self._tracks and ok:
            _push(self)
        return ok

    def release(self):
        self._real.release()
        if self._tracks:
            _pop(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _waiting_blocked(self):
        others = [h.lock_id for h in _held() if h is not self._inner]
        if others:
            _note_blocking(
                getattr(self._inner, "lock_id", self.lock_id), others
            )

    def wait(self, timeout=None):
        self._waiting_blocked()
        if self._tracks:
            _pop(self)  # the wait releases the condition's own lock
        try:
            return self._real.wait(timeout)
        finally:
            if self._tracks:
                _push(self)  # implicit re-acquire on wakeup

    def wait_for(self, predicate, timeout=None):
        self._waiting_blocked()
        if self._tracks:
            _pop(self)
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            if self._tracks:
                _push(self)

    def notify(self, n=1):
        self._real.notify(n)

    def notify_all(self):
        self._real.notify_all()


class _EventWrapper:
    """Recording proxy over a real ``threading.Event`` — only ``wait`` is
    instrumented (an Event wait entered with a lock held blocks every other
    path through that lock, exactly like a foreign ``.wait()`` in SA011)."""

    __slots__ = ("_real", "lock_id")

    def __init__(self, lock_id: str):
        self._real = _REAL["Event"]()
        self.lock_id = lock_id

    def wait(self, timeout=None):
        others = [h.lock_id for h in _held()]
        if others:
            _note_blocking(self.lock_id, others)
        return self._real.wait(timeout)

    def set(self):
        self._real.set()

    def clear(self):
        self._real.clear()

    def is_set(self):
        return self._real.is_set()


def _lock_factory():
    site = _caller_site()
    if not _in_package(site[0]):
        return _REAL["Lock"]()
    return _LockWrapper(_REAL["Lock"](), "lock", _register_lock("lock", site))


def _rlock_factory():
    site = _caller_site()
    if not _in_package(site[0]):
        return _REAL["RLock"]()
    return _LockWrapper(
        _REAL["RLock"](), "rlock", _register_lock("rlock", site)
    )


def _condition_factory(lock=None):
    site = _caller_site()
    if not _in_package(site[0]):
        return _REAL["Condition"](lock)
    return _ConditionWrapper(
        "condition", _register_lock("condition", site), lock
    )


def _event_factory():
    site = _caller_site()
    if not _in_package(site[0]):
        return _REAL["Event"]()
    return _EventWrapper(_register_lock("event", site))


def install(report_path=None) -> None:
    """Arm the validator: replace the ``threading`` factories with the
    recording wrappers (package-created primitives only). Idempotent. With
    ``report_path``, the ``spfft_tpu.analysis.lockdep/1`` report is written
    there at process exit."""
    global _installed, _reclock, _report_path, _dump_registered
    if not _installed:
        _REAL.update(
            Lock=threading.Lock,
            RLock=threading.RLock,
            Condition=threading.Condition,
            Event=threading.Event,
        )
        _reclock = _REAL["Lock"]()
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory
        threading.Condition = _condition_factory
        threading.Event = _event_factory
        _installed = True
    if report_path:
        _report_path = str(report_path)
        if not _dump_registered:
            _dump_registered = True
            atexit.register(_dump)


def uninstall() -> None:
    """Restore the real ``threading`` factories (recorded data is kept —
    :func:`reset` clears it). Already-created wrappers keep working."""
    global _installed
    if not _installed:
        return
    threading.Lock = _REAL["Lock"]
    threading.RLock = _REAL["RLock"]
    threading.Condition = _REAL["Condition"]
    threading.Event = _REAL["Event"]
    _installed = False


def reset() -> None:
    """Drop every recorded lock/edge/blocking entry (tests)."""
    with (_reclock if _reclock is not None else threading.Lock()):
        _locks.clear()
        _edges.clear()
        _blocking.clear()


def installed() -> bool:
    return _installed


def report() -> dict:
    """The ``spfft_tpu.analysis.lockdep/1`` JSON document of everything
    observed so far (JSON-plain; cycles via the shared SCC detector)."""
    guard = _reclock if _reclock is not None else threading.Lock()
    with guard:
        locks = [
            {"id": lock_id, **info} for lock_id, info in sorted(_locks.items())
        ]
        edges = [
            {"from": a, "to": b, **info}
            for (a, b), info in sorted(_edges.items())
        ]
        blocking = [
            {"lock": lock_id, "held": list(held), **info}
            for (lock_id, held), info in sorted(_blocking.items())
        ]
    graph: dict = {}
    for e in edges:
        graph.setdefault(e["from"], set()).add(e["to"])
    return {
        "schema": SCHEMA,
        "installed": _installed,
        "locks": locks,
        "edges": edges,
        "blocking": blocking,
        "cycles": find_cycles(graph),
        "counts": {
            "locks": len(locks),
            "edges": len(edges),
            "blocking": len(blocking),
        },
    }


REPORT_KEYS = (
    "schema", "installed", "locks", "edges", "blocking", "cycles", "counts",
)


def merge_reports(docs: list) -> dict:
    """Union N per-process lockdep reports into one (the multi-host merge:
    every spawned worker writes its own ``SPFFT_TPU_LOCKDEP_REPORT`` and
    ``programs/analyze.py --lockdep-check`` cross-checks the fleet as one
    graph). Locks are keyed by creation site (``created`` summed), edges by
    (from, to) with ``count`` summed, blocking rows by (lock, held) with
    ``count`` summed; cycles are recomputed over the merged edge graph.
    Site-keyed edges from different processes compose meaningfully: a
    cycle assembled from one host's ``a -> b`` and another's ``b -> a`` is
    a real latent ABBA hazard — both orders exist in the code that ran,
    and nothing stops one process's threads from interleaving them."""
    locks: dict = {}
    edges: dict = {}
    blocking: dict = {}
    installed = False
    for doc in docs:
        installed = installed or bool(doc.get("installed"))
        for row in doc.get("locks", []):
            info = locks.get(row["id"])
            if info is None:
                locks[row["id"]] = {
                    k: v for k, v in row.items() if k != "id"
                }
            else:
                info["created"] = info.get("created", 0) + row.get(
                    "created", 0
                )
        for row in doc.get("edges", []):
            key = (row["from"], row["to"])
            info = edges.get(key)
            if info is None:
                edges[key] = {
                    k: v for k, v in row.items() if k not in ("from", "to")
                }
            else:
                info["count"] = info.get("count", 0) + row.get("count", 0)
        for row in doc.get("blocking", []):
            key = (row["lock"], tuple(row.get("held", ())))
            info = blocking.get(key)
            if info is None:
                blocking[key] = {
                    k: v for k, v in row.items() if k not in ("lock", "held")
                }
            else:
                info["count"] = info.get("count", 0) + row.get("count", 0)
    lock_rows = [{"id": i, **info} for i, info in sorted(locks.items())]
    edge_rows = [
        {"from": a, "to": b, **info} for (a, b), info in sorted(edges.items())
    ]
    blocking_rows = [
        {"lock": lock_id, "held": list(held), **info}
        for (lock_id, held), info in sorted(blocking.items())
    ]
    graph: dict = {}
    for e in edge_rows:
        graph.setdefault(e["from"], set()).add(e["to"])
    return {
        "schema": SCHEMA,
        "installed": installed,
        "locks": lock_rows,
        "edges": edge_rows,
        "blocking": blocking_rows,
        "cycles": find_cycles(graph),
        "counts": {
            "locks": len(lock_rows),
            "edges": len(edge_rows),
            "blocking": len(blocking_rows),
        },
    }


def validate_report(doc: dict) -> list:
    """Missing-key list for a lockdep report (schema floor; empty = valid),
    the same shape as the analysis report validator."""
    missing = [k for k in REPORT_KEYS if k not in doc]
    if doc.get("schema") != SCHEMA:
        missing.append(f"schema=={SCHEMA}")
    for i, row in enumerate(doc.get("locks", [])):
        for k in ("id", "kind", "file", "line"):
            if k not in row:
                missing.append(f"locks[{i}].{k}")
    for i, row in enumerate(doc.get("edges", [])):
        for k in ("from", "to", "file", "line", "count"):
            if k not in row:
                missing.append(f"edges[{i}].{k}")
    return missing


def _dump() -> None:
    if not _report_path:
        return
    try:
        with open(_report_path, "w") as fh:
            json.dump(report(), fh, indent=2)
            fh.write("\n")
    except OSError:  # a vanished tmpdir at exit must not mask the real exit
        pass


def crosscheck(doc: dict, static: dict) -> dict:
    """Validate a runtime report against the static model
    (:func:`.locks.static_graph`).

    Returns ``findings`` (each a dict with ``kind``/``message``/``where``)
    plus the explanation tables. Findings:

    * ``stale-static`` — a runtime edge between two statically-known locks
      that the static graph lacks: the SA011 model no longer matches the
      code that actually ran.
    * ``same-site-nesting`` — a site-level self-edge: two DISTINCT
      primitive instances created at one site nested inside each other
      (the per-instance ``self.<attr>`` pattern acquired pairwise). The
      static model cannot order instances, and pairwise acquisition
      without a documented instance order is the ABBA deadlock shape.
    * ``cycle`` — an observed acquisition-order cycle.
    * ``blocking`` — a wait entered while another recorded lock was held.

    Runtime locks with no static counterpart (creation sites the static
    pass cannot track) are ``dynamic``; their edges are explained, listed,
    and not findings."""
    by_site = {
        (info["file"], info["line"]): lock_id
        for lock_id, info in static.get("locks", {}).items()
    }
    static_edges = {tuple(e) for e in static.get("edges", [])}
    mapping = {}
    for row in doc.get("locks", []):
        mapping[row["id"]] = by_site.get((row["file"], row["line"]))
    findings: list = []
    explained = {"static": [], "dynamic": []}
    for e in doc.get("edges", []):
        if e["from"] == e["to"]:
            # wrapper identity already exempts same-instance re-entry, so a
            # surviving self-edge means two instances from one site nested
            findings.append(
                {
                    "kind": "same-site-nesting",
                    "where": f"{e['file']}:{e['line']}",
                    "message": (
                        f"two distinct instances of {e['from']} were "
                        "nested inside each other — pairwise acquisition "
                        "of same-site locks without a documented instance "
                        "order is the ABBA deadlock shape"
                    ),
                }
            )
            continue
        a = mapping.get(e["from"])
        b = mapping.get(e["to"])
        if a is None or b is None:
            explained["dynamic"].append(e)
            continue
        if (a, b) in static_edges:
            explained["static"].append(e)
            continue
        findings.append(
            {
                "kind": "stale-static",
                "where": f"{e['file']}:{e['line']}",
                "message": (
                    f"runtime acquisition edge {a} -> {b} is missing from "
                    "the SA011 static graph — the static model is stale "
                    "(dynamic dispatch or a callback the AST walk cannot "
                    "resolve); teach spfft_tpu/analysis/locks.py the path "
                    "or restructure the acquisition"
                ),
            }
        )
    for comp in doc.get("cycles", []):
        findings.append(
            {
                "kind": "cycle",
                "where": comp[0],
                "message": (
                    "observed lock-order cycle (potential deadlock): "
                    + " <-> ".join(comp)
                ),
            }
        )
    for b in doc.get("blocking", []):
        findings.append(
            {
                "kind": "blocking",
                "where": f"{b['file']}:{b['line']}",
                "message": (
                    f"wait on {b['lock']} entered while still holding "
                    f"{', '.join(b['held'])} — the held lock blocks every "
                    "other path for the whole wait"
                ),
            }
        )
    return {"findings": findings, "explained": explained, "mapping": mapping}
