"""Checker 19: blocking while traced (SA019).

``timing.scoped`` phases double as flight-recorder ``phase`` spans and as
the host timing tree's nodes; ``trace.span``/``trace.operation`` scopes
are the execution trace's duration slices. Their whole value is that a
span measures THE OPERATION IT NAMES — a ``dispatch`` span that also
waits on a lock, or a retry span that sleeps its backoff inside the
scope, reports contention and backoff as if they were dispatch time:
the perf attribution and every Chrome-trace reading of the span are
silently wrong, exactly the class of lie the observability layers exist
to prevent.

Rule: inside the body of a ``with timing.scoped(...)`` /
``trace.span(...)`` / ``trace.operation(...)`` statement, no

* ``time.sleep(...)`` call (backoffs belong OUTSIDE the span, the
  supervisor/wisdom retry rule),
* lock acquisition — a ``with <lock>`` item or a ``.acquire()`` call on a
  lock this file can resolve (module-level, ``self.<attr>``, or local
  ``threading.X()`` bindings, the SA011 resolution).

Direct statements only, conservatively: calls into other functions that
acquire locks are the lock checker's transitive territory (SA011 flags a
lock held across sleeps/waits from the other side), and nested function
bodies execute outside the span. The runtime lockdep layer observes the
dynamic cases at real acquisitions.
"""
from __future__ import annotations

import ast

from .core import Tree, checker
from .locks import LockIndex, _stmt_lists

SCOPE_RECEIVERS = ("timing", "trace")
TRACE_SPAN_ATTRS = ("span", "operation")


def _span_desc(item) -> str | None:
    """A description when a with-item opens a timing/trace span."""
    expr = item.context_expr
    if not (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)):
        return None
    fn = expr.func
    recv = fn.value
    recv_name = None
    if isinstance(recv, ast.Name):
        recv_name = recv.id
    elif isinstance(recv, ast.Attribute):
        recv_name = recv.attr
    if fn.attr == "scoped" and recv_name == "timing":
        label = ""
        if expr.args and isinstance(expr.args[0], ast.Constant):
            label = f" {expr.args[0].value!r}"
        return f"timing.scoped{label}"
    if fn.attr in TRACE_SPAN_ATTRS and recv_name == "trace":
        label = ""
        if expr.args and isinstance(expr.args[0], ast.Constant):
            label = f" {expr.args[0].value!r}"
        return f"trace.{fn.attr}{label}"
    return None


def _is_sleep(call) -> bool:
    fn = call.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "sleep"
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "time"
    )


@checker(
    "traced-blocking",
    code="SA019",
    doc="No time.sleep and no lock acquisition (a `with <lock>` item or a "
    "resolvable .acquire() call) directly inside the body of a "
    "timing.scoped / trace.span / trace.operation scope — a span that "
    "sleeps or waits on a lock attributes backoff and contention to the "
    "operation it names, so the timing tree, the perf attribution, and "
    "every trace reading lie. Direct statements only; transitive callees "
    "are SA011's territory and the runtime lockdep layer's.",
)
def check_traced_blocking(tree: Tree):
    findings = []
    index = LockIndex(tree)

    def scan_body(m, class_name, local_locks, stmts, span_desc):
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # executes outside the span
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    got = index.resolve_lock(
                        m, class_name, local_locks, item.context_expr
                    )
                    if got:
                        findings.append(
                            check_traced_blocking.finding(
                                m.rel, stmt.lineno,
                                f"lock {got[0]} acquired inside {span_desc} "
                                "— contention is attributed to the span; "
                                "acquire outside the scope",
                            )
                        )
                scan_body(m, class_name, local_locks, stmt.body, span_desc)
                continue
            # ast.walk cannot be pruned: pre-collect everything under a
            # nested def/lambda anywhere in the statement — those bodies
            # execute outside the span
            skip: set = set()
            for node in ast.walk(stmt):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    for sub in ast.walk(node):
                        if sub is not node:
                            skip.add(id(sub))
            for node in ast.walk(stmt):
                if id(node) in skip:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if _is_sleep(node):
                    findings.append(
                        check_traced_blocking.finding(
                            m.rel, node.lineno,
                            f"time.sleep(...) inside {span_desc} — the "
                            "backoff is billed to the span; sleep outside "
                            "the scope",
                        )
                    )
                elif isinstance(node.func, ast.Attribute) and (
                    node.func.attr == "acquire"
                ):
                    got = index.resolve_lock(
                        m, class_name, local_locks, node.func.value
                    )
                    if got:
                        findings.append(
                            check_traced_blocking.finding(
                                m.rel, node.lineno,
                                f"lock {got[0]} .acquire()d inside "
                                f"{span_desc} — contention is attributed "
                                "to the span; acquire outside the scope",
                            )
                        )

    def walk(m, class_name, qual, local_locks, stmts):
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                descs = [d for d in map(_span_desc, stmt.items) if d]
                if descs:
                    scan_body(
                        m, class_name, local_locks, stmt.body, descs[0]
                    )
                walk(m, class_name, qual, local_locks, stmt.body)
                continue
            for sub in _stmt_lists(stmt):
                walk(m, class_name, qual, local_locks, sub)

    for m in index.modules.values():
        for qual, fn_node in m.functions.items():
            class_name = qual.split(".")[0] if "." in qual else None
            local_locks = index._local_locks(m.rel, qual, fn_node)
            walk(m, class_name, qual, local_locks, fn_node.body)
    return findings
