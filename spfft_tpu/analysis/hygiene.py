"""Checkers 1–2: import hygiene (ported from ``programs/lint.py``).

1. ``duplicate-import`` (SA001) — the same module/name imported more than
   once in one scope (the round-3/4 nit class in capi.py),
2. ``unused-import`` (SA002) — an imported name never referenced in the
   file (``# noqa: F401`` on the import line exempts re-exports — the
   legacy lint exemption, preserved verbatim: any ``noqa`` substring on the
   import line exempts it from BOTH import checks).
"""
from __future__ import annotations

import ast

from .core import Tree, checker


def _import_forms(node):
    """Canonical (form, bound-name) pairs for an import statement."""
    out = []
    if isinstance(node, ast.Import):
        for a in node.names:
            form = f"import {a.name}" + (f" as {a.asname}" if a.asname else "")
            out.append((form, (a.asname or a.name).split(".")[0]))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        mod = "." * node.level + (node.module or "")
        for a in node.names:
            if a.name == "*":
                continue
            form = f"from {mod} import {a.name}" + (
                f" as {a.asname}" if a.asname else ""
            )
            out.append((form, a.asname or a.name))
    return out


def _walk_scope(body):
    """Statements of one scope, not descending into nested function/class
    bodies (lazy function-scope imports are a deliberate pattern here —
    duplicates only count within a single scope)."""
    for stmt in body:
        yield stmt
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, field, None)
            if not sub:
                continue
            for child in sub:
                if isinstance(child, ast.ExceptHandler):
                    yield from _walk_scope(child.body)
                else:
                    yield from _walk_scope([child])


def _parsed(fn, tree: Tree, rel: str):
    """(ast, findings) with a syntax error reported as a finding."""
    try:
        return tree.parse(rel), []
    except SyntaxError as e:
        return None, [
            fn.finding(rel, e.lineno or 0, f"syntax error: {e.msg}")
        ]


def _legacy_exempt(lines, node) -> bool:
    line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
    return "noqa" in line


@checker(
    "duplicate-import",
    code="SA001",
    doc="The same module or name imported more than once within a single "
    "scope (module body, one class body, or one function). Lazy "
    "function-scope imports are deliberate here, so duplicates only count "
    "within one scope; any `noqa` on the line exempts it (legacy lint "
    "contract).",
)
def check_duplicate_imports(tree: Tree):
    findings = []
    for rel in tree.py_files():
        mod, errs = _parsed(check_duplicate_imports, tree, rel)
        findings += errs
        if mod is None:
            continue
        lines = tree.lines(rel)
        scopes = [mod.body]
        for node in ast.walk(mod):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                scopes.append(node.body)
        for body in scopes:
            seen: dict = {}
            for stmt in _walk_scope(body):
                if not isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    continue
                for form, _name in _import_forms(stmt):
                    if form in seen and not _legacy_exempt(lines, stmt):
                        findings.append(
                            check_duplicate_imports.finding(
                                rel, stmt.lineno, f"duplicate {form!r}"
                            )
                        )
                    seen.setdefault(form, stmt.lineno)
    return findings


@checker(
    "unused-import",
    code="SA002",
    doc="A module-scope import whose bound name is never referenced in the "
    "file. `# noqa: F401` (or any `noqa`) on the import line exempts "
    "re-export surfaces; `__all__` strings count as uses.",
)
def check_unused_imports(tree: Tree):
    findings = []
    for rel in tree.py_files():
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue  # SA001 already reported it
        lines = tree.lines(rel)
        bound = []
        for stmt in _walk_scope(mod.body):
            if isinstance(stmt, (ast.Import, ast.ImportFrom)) and not (
                _legacy_exempt(lines, stmt)
            ):
                bound.extend(
                    (name, stmt.lineno) for _form, name in _import_forms(stmt)
                )
        used = set()
        for node in ast.walk(mod):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Assign):
                # __all__ strings count as uses (re-export surface)
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        for el in ast.walk(node.value):
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                used.add(el.value)
        for name, lineno in bound:
            if name not in used and name != "_":
                findings.append(
                    check_unused_imports.finding(
                        rel, lineno, f"unused import {name!r}"
                    )
                )
    return findings
