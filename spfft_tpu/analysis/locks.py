"""Checker 11: lock-order analysis (SA011).

The package holds ~a dozen ``threading.Lock/RLock/Condition`` objects across
serve, sched, verify, tuning, obs and faults — and the first multi-host
scheduling work (ROADMAP item 2) is exactly the kind that deadlocks where
lock discipline is informal. This checker builds the *static acquisition
graph* over the whole package and enforces two rules:

* **No cycles.** An edge ``A -> B`` is recorded wherever code that holds
  ``A`` may acquire ``B`` — directly (a nested ``with``), through a call to
  a function whose (transitively computed) lock effects include ``B``, or
  through a typed-error construction (``GenericError.__init__`` emits a
  flight-recorder event, i.e. takes the trace lock). A cycle in the graph
  is a potential deadlock; a self-edge on a non-reentrant lock is a
  guaranteed one.
* **Nothing slow under a lock.** A lock held across ``time.sleep``, a
  ``.join()``/``.result()``/foreign ``.wait()``, or a ``jax``/``jnp`` call
  (dispatch/compile can take seconds) serializes every other path through
  that lock behind an unbounded wait. ``Condition.wait`` on the *held*
  condition is exempt — it releases while waiting.

Resolution is intentionally conservative and name-based (documented
limitations): module-level locks, ``self.<attr>`` locks assigned in the
defining file, and local variables bound to a fresh ``threading.Lock()``
are tracked; dynamically stored locks (dict-held latches) and locks
reached through unresolvable receivers are not. Same-package calls resolve
through one level of ``__init__`` re-exports.

Modeled acquisition shapes beyond the nested ``with``:

* ``stack.enter_context(lock)`` — an ``ExitStack`` chain acquires in call
  order and holds until the stack unwinds, so each ``enter_context`` of a
  resolvable lock extends the held set for the remaining statements of the
  enclosing body (edges + self-deadlock checks identical to ``with``).
* ``Condition.wait`` — the wait *releases the condition's own lock* while
  blocked (exempt when the condition is the only thing held), but any
  OTHER held lock stays held across the unbounded wait and is flagged.
  The implicit re-acquire on wakeup re-establishes the edges the original
  acquisition already recorded, so no separate edge is emitted for it.

The same walk feeds two consumers: :func:`check_lock_order` (the SA011
findings) and :func:`static_graph` — the JSON-plain lock/edge export the
runtime lockdep validator (:mod:`.lockdep`) cross-checks its observed
acquisition graph against.
"""
from __future__ import annotations

import ast

from .core import PACKAGE_DIRS, Tree, checker

LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
REENTRANT = ("rlock",)


def _ctor_kind(node):
    """'lock'/'rlock'/'condition' when ``node`` is (or contains) a
    ``threading.X()`` constructor call."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = None
            if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                if fn.value.id == "threading":
                    name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            if name in LOCK_CTORS:
                return LOCK_CTORS[name]
    return None


def _root_name(node):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _module_rel(tree, parts):
    """Module path parts -> existing file relpath (module or package)."""
    rel = "/".join(parts) + ".py"
    if tree.exists(rel):
        return rel
    rel = "/".join(parts) + "/__init__.py"
    if tree.exists(rel):
        return rel
    return None


class _Module:
    """Per-file facts: locks, imports, functions, classes."""

    def __init__(self, rel, node):
        self.rel = rel
        self.node = node
        self.module_locks: dict = {}   # name -> (lock_id, kind)
        self.attr_locks: dict = {}     # attr -> (lock_id, kind)  (self.X)
        self.lock_lines: dict = {}     # lock_id -> definition lineno
        self.mod_alias: dict = {}      # alias -> module rel
        self.obj_alias: dict = {}      # alias -> (module rel, attr)
        self.functions: dict = {}      # qual -> ast node ("f" / "C.m")
        self.classes: dict = {}        # class name -> ClassDef
        self.instance_of: dict = {}    # module-global name -> [class names]


class LockIndex:
    """Whole-package lock/function/import index + transitive lock effects."""

    def __init__(self, tree: Tree):
        self.tree = tree
        self.modules: dict = {}
        for rel in tree.py_files(PACKAGE_DIRS):
            try:
                node = tree.parse(rel)
            except SyntaxError:
                continue
            self.modules[rel] = self._scan(rel, node)
        self._effects: dict = {}  # (rel, qual) -> frozenset(lock ids)
        self._busy: set = set()

    # ---- per-file scan -------------------------------------------------------

    def _scan(self, rel, node):
        m = _Module(rel, node)
        pkg_parts = rel.split("/")[:-1]
        if rel.endswith("/__init__.py"):
            own_parts = rel.split("/")[:-1]
        else:
            own_parts = rel.split("/")[:-1]
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                kind = _ctor_kind(stmt.value)
                classes = [
                    s.func.id
                    for s in ast.walk(stmt.value)
                    if isinstance(s, ast.Call) and isinstance(s.func, ast.Name)
                ]
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if kind:
                            m.module_locks[t.id] = (f"{rel}::{t.id}", kind)
                            m.lock_lines[f"{rel}::{t.id}"] = stmt.lineno
                        if classes:
                            m.instance_of[t.id] = classes
        # imports anywhere in the file (the lazy function-scope import is a
        # deliberate pattern here; alias collisions across scopes are rare
        # enough that a flat map stays honest)
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.ImportFrom):
                continue
            base = own_parts[: len(own_parts) - (stmt.level - 1)] if (
                stmt.level
            ) else []
            if stmt.level and stmt.module:
                base = base + stmt.module.split(".")
            elif not stmt.level and stmt.module:
                base = stmt.module.split(".")
            if base[:1] and base[0] != pkg_parts[0] and stmt.level == 0:
                continue  # external import
            for a in stmt.names:
                if a.name == "*":
                    continue
                alias = a.asname or a.name
                sub = _module_rel(self.tree, base + [a.name])
                if sub:
                    m.mod_alias.setdefault(alias, sub)
                else:
                    mod = _module_rel(self.tree, base)
                    if mod:
                        m.obj_alias.setdefault(alias, (mod, a.name))
        for cls in [s for s in node.body if isinstance(s, ast.ClassDef)]:
            m.classes[cls.name] = cls
            for sub in ast.walk(cls):
                if isinstance(sub, ast.Assign):
                    kind = _ctor_kind(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            m.attr_locks[t.attr] = (
                                f"{rel}::{cls.name}.{t.attr}", kind,
                            )
                            m.lock_lines[f"{rel}::{cls.name}.{t.attr}"] = (
                                sub.lineno
                            )
            for meth in cls.body:
                if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    m.functions[f"{cls.name}.{meth.name}"] = meth
        for fn in node.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m.functions[fn.name] = fn
        return m

    # ---- name resolution -----------------------------------------------------

    def resolve_export(self, rel, attr, depth=0):
        """(rel, qual) of ``attr`` looked up in module ``rel``, chasing
        one-level ``__init__`` re-exports and submodules."""
        if depth > 3 or rel not in self.modules:
            return None
        m = self.modules[rel]
        for qual in (attr, ):
            if qual in m.functions:
                return (rel, qual)
        if attr in m.classes:
            # constructor effects: the class's own __init__, else the
            # nearest same-file base's (errors.py's taxonomy pattern)
            seen = set()
            name = attr
            while name in m.classes and name not in seen:
                seen.add(name)
                if f"{name}.__init__" in m.functions:
                    return (rel, f"{name}.__init__")
                bases = [
                    b.id for b in m.classes[name].bases
                    if isinstance(b, ast.Name)
                ]
                name = bases[0] if bases else ""
            return None
        if attr in m.mod_alias:
            return ("__module__", m.mod_alias[attr])
        if attr in m.obj_alias:
            mod, a = m.obj_alias[attr]
            return self.resolve_export(mod, a, depth + 1)
        sub = _module_rel(self.tree, rel.rsplit("/", 1)[0].split("/") + [attr]) \
            if rel.endswith("/__init__.py") else None
        if sub:
            return ("__module__", sub)
        return None

    def resolve_call(self, m: _Module, class_name, call):
        """(rel, qual) of a call's callee, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in m.functions:
                return (m.rel, fn.id)
            if class_name and f"{class_name}.{fn.id}" in m.functions:
                return (m.rel, f"{class_name}.{fn.id}")
            if fn.id in m.obj_alias:
                mod, attr = m.obj_alias[fn.id]
                got = self.resolve_export(mod, attr)
                return got if got and got[0] != "__module__" else None
            if fn.id in m.classes:
                return self.resolve_export(m.rel, fn.id)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                if class_name and f"{class_name}.{fn.attr}" in m.functions:
                    return (m.rel, f"{class_name}.{fn.attr}")
                for qual in m.functions:
                    if qual.endswith(f".{fn.attr}"):
                        return (m.rel, qual)
                return None
            if recv.id in m.mod_alias:
                got = self.resolve_export(m.mod_alias[recv.id], fn.attr)
                return got if got and got[0] != "__module__" else None
            if recv.id in m.instance_of:
                for cls in m.instance_of[recv.id]:
                    if f"{cls}.{fn.attr}" in m.functions:
                        return (m.rel, f"{cls}.{fn.attr}")
            return None
        if isinstance(recv, ast.Attribute):
            # dotted module receiver, e.g. obs.trace.event
            root = _root_name(recv)
            if root and root in m.mod_alias:
                got = self.resolve_export(m.mod_alias[root], recv.attr)
                if got and got[0] == "__module__":
                    got = self.resolve_export(got[1], fn.attr)
                    return got if got and got[0] != "__module__" else None
        return None

    def resolve_lock(self, m: _Module, class_name, local_locks, expr):
        """(lock_id, kind) of a with-item/receiver expression, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in local_locks:
                return local_locks[expr.id]
            return m.module_locks.get(expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return m.attr_locks.get(expr.attr)
        return None

    # ---- transitive lock effects --------------------------------------------

    def effects(self, key) -> frozenset:
        """Locks ``key = (rel, qual)`` may acquire, transitively."""
        if key in self._effects:
            return self._effects[key]
        if key in self._busy:
            return frozenset()  # recursion cycle: partial is fine (fixpoint)
        rel, qual = key
        m = self.modules.get(rel)
        if m is None or qual not in m.functions:
            self._effects[key] = frozenset()
            return self._effects[key]
        self._busy.add(key)
        class_name = qual.split(".")[0] if "." in qual else None
        fn_node = m.functions[qual]
        local_locks = self._local_locks(m.rel, qual, fn_node)
        out = set()
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    got = self.resolve_lock(
                        m, class_name, local_locks, item.context_expr
                    )
                    if got:
                        out.add(got[0])
            elif isinstance(node, ast.Call):
                got = _enter_context_lock(
                    self, m, class_name, local_locks, node
                )
                if got:
                    out.add(got[0])
                callee = self.resolve_call(m, class_name, node)
                if callee:
                    out |= self.effects(callee)
        self._busy.discard(key)
        self._effects[key] = frozenset(out)
        return self._effects[key]

    @staticmethod
    def _local_locks(rel, qual, fn_node) -> dict:
        out = {}
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                if not kind:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = (f"{rel}::{qual}.{t.id}", kind)
        return out


BLOCKING_RECEIVER_ATTRS = ("join", "result")


def _enter_context_lock(index, m, class_name, local_locks, call):
    """``(lock_id, kind)`` when ``call`` is ``<stack>.enter_context(<lock>)``
    on a resolvable lock — the ExitStack acquisition shape."""
    fn = call.func
    if not (
        isinstance(fn, ast.Attribute)
        and fn.attr == "enter_context"
        and call.args
    ):
        return None
    return index.resolve_lock(m, class_name, local_locks, call.args[0])


def _blocking_desc(index, m, class_name, local_locks, held, call):
    """A human description when ``call`` blocks while locks are held."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        root = _root_name(fn)
        if fn.attr == "sleep" and root == "time":
            return "time.sleep(...)"
        if root in ("jax", "jnp"):
            return f"a {root}.* call (dispatch/compile)"
        if fn.attr == "block_until_ready":
            return ".block_until_ready()"
        if fn.attr in BLOCKING_RECEIVER_ATTRS:
            return f".{fn.attr}()"
        if fn.attr == "wait":
            got = index.resolve_lock(m, class_name, local_locks, fn.value)
            if got and got[0] in held:
                # Condition.wait releases the condition's OWN lock while
                # blocked — exempt only when that is the whole held set; any
                # other lock stays held across the unbounded wait
                if all(h == got[0] for h in held):
                    return None
                return (
                    ".wait() (Condition.wait releases only its own lock; "
                    "the other held lock stays held across the wait)"
                )
            return ".wait()"
    elif isinstance(fn, ast.Name) and fn.id == "fence":
        return "fence() (a completion wait)"
    return None


def _stmt_lists(stmt):
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if sub:
            yield sub
    for h in getattr(stmt, "handlers", []) or []:
        yield h.body


def _calls_here(stmt):
    """Calls in a statement, not descending into nested defs/lambdas or
    nested statement bodies (those are walked by the caller)."""
    skip: set = set()
    for sub_list in _stmt_lists(stmt):
        for s in sub_list:
            for n in ast.walk(s):
                skip.add(id(n))
    for node in ast.walk(stmt):
        if id(node) in skip:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for n in ast.walk(node):
                skip.add(id(n))
            continue
        if isinstance(node, ast.Call):
            yield node


def find_cycles(graph: dict) -> list:
    """Non-trivial strongly connected components of ``{node: {succ, ...}}``,
    each a sorted node list — iterative Tarjan (recursion-free; the graphs
    are tiny but deep recursion limits are not worth trusting). Shared by
    the static checker and the runtime lockdep report."""
    idx: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == idx[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in idx:
            strongconnect(v)
    return sorted(sccs)


def collect(tree: Tree, make_finding) -> dict:
    """The SA011 walk over the whole package: acquisition edges, lock kinds
    and definition sites, plus the non-cycle findings (``make_finding(file,
    line, message)`` constructs them). One walk, two consumers — the static
    checker and :func:`static_graph` (the lockdep cross-check's model)."""
    findings: list = []
    index = LockIndex(tree)
    kinds: dict = {}
    sites: dict = {}
    for m in index.modules.values():
        for lock_id, kind in list(m.module_locks.values()) + list(
            m.attr_locks.values()
        ):
            kinds[lock_id] = kind
            sites[lock_id] = (m.rel, m.lock_lines.get(lock_id, 0))
    edges: dict = {}  # (A, B) -> (rel, line)

    def note_edge(a, b, rel, line):
        edges.setdefault((a, b), (rel, line))

    def acquire(lock_id, kind, held, rel, line):
        for h in held:
            note_edge(h, lock_id, rel, line)
        if lock_id in held and kind not in REENTRANT:
            findings.append(
                make_finding(
                    rel, line,
                    f"non-reentrant lock {lock_id} re-acquired while "
                    "already held (guaranteed self-deadlock)",
                )
            )

    def walk(m, class_name, qual, local_locks, stmts, held):
        held = list(held)
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = []
                for item in stmt.items:
                    got = index.resolve_lock(
                        m, class_name, local_locks, item.context_expr
                    )
                    if got:
                        lock_id, kind = got
                        acquire(lock_id, kind, held + newly, m.rel, stmt.lineno)
                        newly.append(lock_id)
                    else:
                        # a with on a call (context manager): treat like a
                        # call for lock effects
                        if isinstance(item.context_expr, ast.Call):
                            _note_call_effects(
                                m, class_name, item.context_expr, held
                            )
                walk(m, class_name, qual, local_locks, stmt.body, held + newly)
                continue
            if held:
                for call in _calls_here(stmt):
                    desc = _blocking_desc(
                        index, m, class_name, local_locks, held, call
                    )
                    if desc:
                        findings.append(
                            make_finding(
                                m.rel, call.lineno,
                                f"lock {held[-1]} held across {desc} — "
                                "move the blocking call outside the lock",
                            )
                        )
                    _note_call_effects(m, class_name, call, held)
            for sub in _stmt_lists(stmt):
                walk(m, class_name, qual, local_locks, sub, held)
            # an ExitStack acquisition holds for the REST of this body:
            # record its edges and extend the held set for what follows
            for call in _calls_here(stmt):
                got = _enter_context_lock(
                    index, m, class_name, local_locks, call
                )
                if got:
                    acquire(got[0], got[1], held, m.rel, call.lineno)
                    held.append(got[0])

    def _note_call_effects(m, class_name, call, held):
        callee = index.resolve_call(m, class_name, call)
        if not callee:
            return
        for lock_id in index.effects(callee):
            for h in held:
                note_edge(h, lock_id, m.rel, call.lineno)
                if h == lock_id and kinds.get(lock_id) not in REENTRANT:
                    findings.append(
                        make_finding(
                            m.rel, call.lineno,
                            f"call may re-acquire held non-reentrant lock "
                            f"{lock_id} (self-deadlock through "
                            f"{callee[0]}::{callee[1]})",
                        )
                    )

    for m in index.modules.values():
        for qual, fn_node in m.functions.items():
            class_name = qual.split(".")[0] if "." in qual else None
            local_locks = index._local_locks(m.rel, qual, fn_node)
            walk(m, class_name, qual, local_locks, fn_node.body, [])

    return {
        "findings": findings,
        "edges": edges,
        "kinds": kinds,
        "sites": sites,
    }


def static_graph(tree: Tree) -> dict:
    """JSON-plain export of the static acquisition model — the baseline the
    runtime lockdep validator (:mod:`.lockdep`) cross-checks against:
    ``locks`` keyed by lock id with kind + definition site (the runtime
    wrapper joins on ``file:line``), ``edges`` as ``[from, to]`` pairs."""
    data = collect(tree, lambda file, line, message: None)
    return {
        "locks": {
            lock_id: {
                "kind": data["kinds"].get(lock_id, "lock"),
                "file": rel,
                "line": line,
            }
            for lock_id, (rel, line) in sorted(data["sites"].items())
        },
        "edges": sorted([a, b] for (a, b) in data["edges"]),
    }


@checker(
    "lock-order",
    code="SA011",
    doc="Builds the static lock-acquisition graph over every "
    "threading.Lock/RLock/Condition in the package (nested `with` blocks, "
    "ExitStack.enter_context chains, transitive call effects, typed-error "
    "constructions) and flags cycles, re-acquisition of a held "
    "non-reentrant lock, and locks held across blocking calls (time.sleep, "
    ".join/.result/foreign .wait, Condition.wait with another lock still "
    "held, jax/jnp dispatch). Name-based and conservative: dynamically "
    "stored locks are not tracked. The runtime lockdep layer "
    "(SPFFT_TPU_LOCKDEP) validates this model against observed "
    "acquisitions.",
)
def check_lock_order(tree: Tree):
    data = collect(tree, check_lock_order.finding)
    findings = list(data["findings"])
    edges = data["edges"]

    # ---- cycle detection over the acquisition graph -------------------------
    graph: dict = {}
    for (a, b), _loc in edges.items():
        if a != b:
            graph.setdefault(a, set()).add(b)

    for comp in find_cycles(graph):
        example = None
        for (a, b), loc in sorted(edges.items()):
            if a in comp and b in comp and a != b:
                example = loc
                break
        rel, line = example if example else (comp[0].split("::")[0], 0)
        findings.append(
            check_lock_order.finding(
                rel, line,
                "lock-order cycle (potential deadlock): "
                + " <-> ".join(comp),
            )
        )
    return findings
