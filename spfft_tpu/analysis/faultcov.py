"""Checker 18: fault-site chaos coverage (SA018).

``faults.SITES`` is the chaos plane's vocabulary: SA005 already pins every
``faults.site(...)`` call to a registered name and every registered name to
a real call site. What SA005 cannot see is whether anybody ever FIRES a
site: the arm-every-site sweeps iterate ``faults.SITES`` dynamically, so a
new site is swept — but the sweep only asserts the generic
typed-error-or-parity invariant. Every site also needs a TARGETED chaos
test pinning its specific ladder response (which rung, which degradation
event, which fallback), and that test necessarily names the site
literally. Two directions:

* every registered site is referenced by at least one literal arming in
  ``tests/`` — an ``inject("site=kind")`` / ``arm(...)`` spec string, an
  ``arm({...})`` table key, or a ``SPFFT_TPU_FAULTS``-style spec constant,
* every site-shaped token armed in a test spec is a registered site — a
  typo'd site would raise typed at runtime, but a site REMOVED from the
  vocabulary while its targeted test still arms it should fail the gate,
  not the suite.

Literal detection is string-based and anchored on the fault-kind grammar
(``<site>=<raise|nan|corrupt|delay>``), so env-knob spec strings count
exactly like ``inject`` arguments; f-string sweeps are dynamic and
deliberately do not count as targeted coverage.
"""
from __future__ import annotations

import ast
import re

from .core import Tree, checker, missing_anchor

FAULTS_PLANE_FILE = "spfft_tpu/faults/plane.py"
TESTS_DIRS = ("tests",)

# a literal arming token: site=kind with the canonical kind grammar — the
# anchor that keeps random "a.b=c" strings from matching
_SPEC_RE = re.compile(
    r"([a-z_][a-z0-9_]*\.[a-z_][a-z0-9_]*)=(?:raise|nan|corrupt|delay)\b"
)


def _armed_dict_keys(call) -> list:
    """Literal site keys of an ``arm({...})`` / ``inject({...})`` table."""
    out = []
    for arg in call.args:
        if isinstance(arg, ast.Dict):
            out.extend(
                (k.value, k.lineno)
                for k in arg.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            )
    return out


@checker(
    "fault-coverage",
    code="SA018",
    doc="Every faults.SITES entry is armed by at least one LITERAL chaos "
    "reference in tests/ (an inject/arm spec string or table key, or a "
    "SPFFT_TPU_FAULTS-style spec constant — the site=kind grammar), and "
    "every literal site token armed in tests is a registered site. The "
    "dynamic arm-every-site sweep proves the generic invariant; the "
    "targeted literal test pins each site's specific ladder response, and "
    "a site without one has an untested failure path.",
)
def check_fault_coverage(tree: Tree):
    skip, findings = missing_anchor(
        check_fault_coverage, tree, FAULTS_PLANE_FILE
    )
    if skip:
        return findings
    sites = tuple(tree.literal_assign(FAULTS_PLANE_FILE, "SITES") or ())
    referenced: dict = {}  # site -> first (file, line)
    for rel in tree.py_files(TESTS_DIRS):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for name in _SPEC_RE.findall(node.value):
                    referenced.setdefault(name, (rel, node.lineno))
            elif isinstance(node, ast.Call):
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if attr in ("inject", "arm"):
                    for name, lineno in _armed_dict_keys(node):
                        referenced.setdefault(name, (rel, lineno))
    for name in sites:
        if name not in referenced:
            findings.append(
                check_fault_coverage.finding(
                    FAULTS_PLANE_FILE, 0,
                    f"site {name!r} has no targeted chaos test: no literal "
                    "inject/arm reference in tests/ pins its ladder "
                    "response (the dynamic sweep alone is not coverage)",
                )
            )
    for name, (rel, lineno) in sorted(referenced.items()):
        if name not in sites:
            findings.append(
                check_fault_coverage.finding(
                    rel, lineno,
                    f"chaos test arms {name!r}, which is not a registered "
                    f"fault site ({FAULTS_PLANE_FILE} SITES) — the arming "
                    "would raise typed at runtime",
                )
            )
    return findings
