"""Checker 15: donation safety for distributed and batched graphs (SA015).

SA012 guards the per-request local backward donation. PR 12's batch axis
and the mesh lowerings widened the surface it cannot see:

* **Batched/mesh consume-once.** Every lowered backward graph declares its
  per-request edges (``g.batch_inputs`` — the packed value pair). On the
  batched path those edges are the STACKED buffers ``build_batched``
  donates on the consuming backward, and on mesh graphs they are the
  per-shard blocks a future mesh donation would free — so in EVERY
  ``_lower_*`` backward graph (local, slab, pencil) a declared batch edge
  must be consumed by at most one node and never escape via
  ``set_outputs``. A second reference computes with memory the batched
  consuming jit may already have overwritten.
* **Donate only per-request edges.** The local ``_ir_spec`` donation
  positions must name edges listed in the backward graph's
  ``batch_inputs``: donating a SHARED plan constant (an index table, a
  phase operand) would let one batch's execution free memory every later
  batch still reads.
* **The batched jit donates what the fused jit donates.** ``build_batched``
  must apply ``donate_argnums`` from the same spec key ``build_fused``
  does — a batched path that silently stopped donating doubles peak value
  memory per batch; one donating from a different key frees the wrong
  buffers.

Reconstruction is the SA012 machinery (literal ``add_input``/``add``/
``set_outputs``/``batch_inputs`` calls, string-constant propagation;
non-literal nodes skipped, conservative).
"""
from __future__ import annotations

import ast

from .core import Tree, checker, missing_anchor
from .donation import (
    IR_COMPILE_FILE,
    IR_LOWER_FILE,
    _reconstruct,
    _spec_keys,
    donated_positions,
)

BUILDER_PREFIX = "_lower_"


def _donate_keys_of(compile_mod, fn_name: str) -> tuple:
    """(applied, spec keys feeding donate_argnums) for one build function."""
    keys: set = set()
    applied = False
    for node in ast.walk(compile_mod):
        if not (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == fn_name
        ):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            for kw in call.keywords:
                if kw.arg != "donate_argnums":
                    continue
                applied = True
                names = {
                    n.id for n in ast.walk(kw.value)
                    if isinstance(n, ast.Name)
                }
                for stmt in ast.walk(node):
                    if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id in names
                        for t in stmt.targets
                    ):
                        keys |= _spec_keys(stmt.value)
                keys |= _spec_keys(kw.value)
    return applied, keys


@checker(
    "donation-batch",
    code="SA015",
    doc="Donation safety beyond the local backward (SA012): in EVERY "
    "lowered backward graph (local, slab, pencil) the declared "
    "batch_inputs edges — the per-request value pair build_batched donates "
    "stacked, and the per-shard blocks of mesh graphs — are consumed by at "
    "most one node and never escape via set_outputs; the local _ir_spec "
    "donation positions name only batch_inputs edges (donating a shared "
    "plan constant frees memory every later batch still reads); and "
    "build_batched applies donate_argnums from the same spec key "
    "build_fused does. Reconstructed from literal graph-build calls, "
    "conservative like SA012.",
)
def check_donation_batch(tree: Tree):
    findings = []
    for anchor in (IR_LOWER_FILE, IR_COMPILE_FILE):
        skip, f = missing_anchor(check_donation_batch, tree, anchor)
        if skip:
            return findings + f
        findings += f
    positions = donated_positions(tree)

    # ---- rule 1 + 2: batch edges consume-once / never-escape; donated
    # positions are batch edges --------------------------------------------
    lower_mod = tree.parse(IR_LOWER_FILE)
    for builder in lower_mod.body:
        if not (
            isinstance(builder, (ast.FunctionDef, ast.AsyncFunctionDef))
            and builder.name.startswith(BUILDER_PREFIX)
        ):
            continue
        local = builder.name.startswith("_lower_local")
        for g in _reconstruct(builder):
            if g.direction != "backward" or not g.batch:
                continue
            for edge in sorted(g.batch):
                uses = [
                    (possible, lineno)
                    for possible, lineno in g.consumers
                    if edge in possible
                ]
                for _possible, lineno in uses[1:]:
                    findings.append(
                        check_donation_batch.finding(
                            IR_LOWER_FILE, lineno,
                            f"batched input edge {edge!r} referenced after "
                            f"its consuming node in a {builder.name} "
                            "backward graph — the batched consuming jit "
                            "donates the stacked buffer at that node",
                        )
                    )
                if edge in g.outputs:
                    findings.append(
                        check_donation_batch.finding(
                            IR_LOWER_FILE, g.lineno,
                            f"batched input edge {edge!r} escapes as a "
                            f"graph output of a {builder.name} backward "
                            "graph",
                        )
                    )
            if local:
                for i in sorted(positions):
                    if i >= len(g.inputs):
                        continue
                    if g.inputs[i] not in g.batch:
                        findings.append(
                            check_donation_batch.finding(
                                IR_LOWER_FILE, g.lineno,
                                f"donate position {i} names input edge "
                                f"{g.inputs[i]!r}, which is not a declared "
                                f"batch_inputs edge of a {builder.name} "
                                "backward graph — donating a shared plan "
                                "constant frees memory every later batch "
                                "still reads",
                            )
                        )

    # ---- rule 3: build_batched donates from build_fused's spec key ---------
    compile_mod = tree.parse(IR_COMPILE_FILE)
    fused_applied, fused_keys = _donate_keys_of(compile_mod, "build_fused")
    batch_applied, batch_keys = _donate_keys_of(compile_mod, "build_batched")
    if fused_applied and not batch_applied:
        findings.append(
            check_donation_batch.finding(
                IR_COMPILE_FILE, 0,
                "build_fused donates the consuming backward's buffers but "
                "build_batched passes no donate_argnums — the batched path "
                "silently stopped donating (doubled peak value memory per "
                "batch)",
            )
        )
    if (
        fused_applied
        and batch_applied
        and fused_keys
        and batch_keys
        and fused_keys != batch_keys
    ):
        findings.append(
            check_donation_batch.finding(
                IR_COMPILE_FILE, 0,
                f"build_batched donates from spec key(s) "
                f"{sorted(batch_keys)} but build_fused donates from "
                f"{sorted(fused_keys)} — the stacked donation no longer "
                "mirrors the per-request rule",
            )
        )
    return findings
