"""Checker 10: typed-error discipline (SA010).

The reliability posture (PAPER.md): a plan must never be *silently* wrong —
every failure surfaces as a member of the ``spfft_tpu.errors`` taxonomy so
the C shim, the retry/demote ladder, and callers can react mechanically.
Two rules enforce it:

* **Every ``raise`` constructs taxonomy.** A raise in package code must
  construct a :class:`GenericError` subclass (or one of the documented
  fault-model ``RuntimeError`` subclasses below — they exist precisely so
  the ladder's production ``except`` arms catch injected/timeout failures),
  re-raise bare, or re-raise a stored exception object. ``raise
  ValueError(...)`` and friends leak untyped contracts.
* **``except Exception`` must convert and count.** A blanket handler is
  allowed when it re-raises bare (cleanup handlers swallow nothing), or
  when it (a) bumps a counter (``.inc()`` / ``self._count*``) and (b)
  converts to typed (``as_typed`` or a taxonomy construction) — the
  serving layer's no-silent-exit catch-alls. Anything else swallows
  failures invisibly.

Known-deliberate builtin raises (e.g. ``Ticket.result``'s documented
builtin ``TimeoutError`` contract) and the cross-thread re-raise pattern
(``except BaseException as e: err.append(e)`` with the caller re-raising)
carry ``# noqa: SA010`` at the site.
"""
from __future__ import annotations

import ast

from .core import Tree, checker

ERRORS_FILE = "spfft_tpu/errors.py"

# Deliberate RuntimeError subclasses of the failure model: each is
# documented ("a RuntimeError subclass on purpose") so the production
# ``except`` arms that catch real backend failures catch these too, and the
# surrounding typed_execution scopes convert them. A NEW RuntimeError
# subclass must either join this list (with the same documented rationale)
# or subclass the taxonomy.
DELIBERATE_RUNTIME_CLASSES = (
    "InjectedFault",        # faults.plane — chaos failures use real handlers
    "FenceTimeout",         # sync — converted by faults.typed_execution
    "TrialTimeout",         # tuning.runner — member of TRIAL_ERRORS
    "TrialDegradedError",   # tuning.runner — isolation-scope signal
)

# Factory functions returning a taxonomy class (``raise execution_error(
# platform)(...)`` is the dual-error-surface idiom).
TYPED_FACTORIES = ("execution_error",)

# The import-free tooling layer (spfft_tpu/analysis) cannot import the
# taxonomy without pulling jax; its AnalysisError marks internal tool
# failures (bad tree, malformed baseline) — distinct from findings, and
# never part of the production error surface.
TOOLING_CLASSES = ("AnalysisError",)

BUILTIN_EXCEPTIONS = {
    "ArithmeticError", "AssertionError", "AttributeError", "BaseException",
    "BufferError", "EOFError", "Exception", "FloatingPointError",
    "ImportError", "IndexError", "KeyError", "KeyboardInterrupt",
    "LookupError", "MemoryError", "ModuleNotFoundError", "NameError",
    "NotImplementedError", "OSError", "OverflowError", "RecursionError",
    "ReferenceError", "RuntimeError", "StopIteration", "SystemError",
    "SystemExit", "TimeoutError", "TypeError", "UnboundLocalError",
    "UnicodeError", "ValueError", "ZeroDivisionError",
}


def taxonomy_classes(tree: Tree) -> set:
    """Names of every package-defined GenericError subclass, computed
    transitively over all package class definitions (import-free)."""
    bases: dict = {}
    for rel in tree.py_files(("spfft_tpu",)):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod):
            if isinstance(node, ast.ClassDef):
                names = []
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        names.append(b.id)
                    elif isinstance(b, ast.Attribute):
                        names.append(b.attr)
                bases.setdefault(node.name, set()).update(names)
    typed = {"GenericError"}
    changed = True
    while changed:
        changed = False
        for name, parents in bases.items():
            if name not in typed and parents & typed:
                typed.add(name)
                changed = True
    return typed


def _constructor_name(exc) -> tuple:
    """(kind, name) of a raise's value expression.

    kind: "call" (Class(...)), "factory" (factory(...)(...)), "name"
    (bare class/object reference), "other" (stored exception, subscripts,
    attribute reads — re-raises of objects, always allowed)."""
    if isinstance(exc, ast.Call):
        fn = exc.func
        if isinstance(fn, ast.Name):
            return "call", fn.id
        if isinstance(fn, ast.Attribute):
            return "call", fn.attr
        if isinstance(fn, ast.Call):
            inner = fn.func
            if isinstance(inner, ast.Name):
                return "factory", inner.id
            if isinstance(inner, ast.Attribute):
                return "factory", inner.attr
        return "call", None
    if isinstance(exc, ast.Name):
        return "name", exc.id
    return "other", None


def _bumps_counter(handler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in (
                "inc", "_count", "_count_only", "observe",
            ):
                return True
            if isinstance(fn, ast.Name) and fn.id in ("_count", "_count_only"):
                return True
    return False


def _reraises_bare(handler) -> bool:
    """A bare ``raise`` anywhere in the handler: nothing is swallowed, so
    the handler is a cleanup scope, not a conversion site."""
    return any(
        isinstance(node, ast.Raise) and node.exc is None
        for node in ast.walk(handler)
    )


def _converts_or_reraises(handler, typed: set) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True  # bare re-raise
            kind, name = _constructor_name(node.exc)
            if kind == "call" and name in typed:
                return True
            if kind == "factory" and name in TYPED_FACTORIES:
                return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name == "as_typed":
                return True
    return False


def _caught_names(mod) -> set:
    """Names bound by ``except ... as e`` anywhere in the module (re-raising
    a caught name is a re-raise, not a construction)."""
    out = set()
    for node in ast.walk(mod):
        if isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


def _is_broad(handler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Attribute):
        names = [t.attr]
    elif isinstance(t, ast.Tuple):
        for el in t.elts:
            if isinstance(el, ast.Name):
                names.append(el.id)
            elif isinstance(el, ast.Attribute):
                names.append(el.attr)
    return any(n in ("Exception", "BaseException") for n in names)


@checker(
    "typed-error",
    code="SA010",
    doc="Every raise in spfft_tpu/ constructs a taxonomy GenericError "
    "subclass (or a documented fault-model RuntimeError subclass), "
    "re-raises bare, or re-raises a stored exception; every `except "
    "Exception` must re-raise bare (a cleanup scope) or bump a counter "
    "AND convert to typed (as_typed / taxonomy raise). Deliberate builtin "
    "contracts carry `# noqa: SA010` at the site.",
)
def check_typed_errors(tree: Tree):
    findings = []
    typed = taxonomy_classes(tree)
    typed |= set(DELIBERATE_RUNTIME_CLASSES)
    typed |= set(TOOLING_CLASSES)
    for rel in tree.py_files(("spfft_tpu",)):
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        caught = _caught_names(mod)
        for node in ast.walk(mod):
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    continue
                kind, name = _constructor_name(node.exc)
                if kind == "other":
                    continue  # re-raise of a stored exception object
                if kind == "name":
                    if name in BUILTIN_EXCEPTIONS and name not in typed:
                        findings.append(
                            check_typed_errors.finding(
                                rel, node.lineno,
                                f"raise of builtin {name} — construct a "
                                "spfft_tpu.errors taxonomy class instead",
                            )
                        )
                    continue  # re-raise of a caught/stored name
                if kind == "factory":
                    if name not in TYPED_FACTORIES:
                        findings.append(
                            check_typed_errors.finding(
                                rel, node.lineno,
                                f"raise through unknown factory {name}() — "
                                "only typed factories "
                                f"({', '.join(TYPED_FACTORIES)}) are "
                                "statically checkable",
                            )
                        )
                    continue
                # kind == "call"
                if name is None or name in typed or name in caught:
                    continue
                findings.append(
                    check_typed_errors.finding(
                        rel, node.lineno,
                        f"raise {name}(...) is not a spfft_tpu.errors "
                        "taxonomy class (typed-error discipline: every "
                        "failure surfaces as a GenericError subclass)",
                    )
                )
            elif isinstance(node, ast.ExceptHandler) and _is_broad(node):
                if _reraises_bare(node):
                    continue  # cleanup scope: nothing swallowed
                if _bumps_counter(node) and _converts_or_reraises(node, typed):
                    continue
                findings.append(
                    check_typed_errors.finding(
                        rel, node.lineno,
                        "broad `except Exception` without counter + typed "
                        "conversion — narrow it to a typed tuple, or count "
                        "and convert (as_typed / taxonomy raise / bare "
                        "re-raise)",
                    )
                )
    return findings
