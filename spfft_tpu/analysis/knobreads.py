"""Checker 14: knob-registry read path (SA014).

``spfft_tpu.knobs`` is the single allowed read path for the package's
``SPFFT_TPU_*`` env surface: the typed getters raise
:class:`~spfft_tpu.errors.InvalidParameterError` on malformed values, the
docs table regenerates from the registry, and the ``env-knob-docs`` checker
(SA003) holds the surface in sync — none of which works if a module keeps
its own ``os.environ`` parsing on the side. This checker flags every
``os.environ`` / ``os.getenv`` access in package code outside ``knobs.py``:

* an access whose key is a literal ``SPFFT_TPU_*`` string is a bypass of
  the registry — migrate it to a typed getter;
* an access whose key is *not statically resolvable* (a variable) might be
  one, so it is flagged too, conservative — a deliberate raw path (the
  tuning trial isolation scope saves/restores arbitrary ambient values
  verbatim) documents itself with ``# noqa: SA014`` at the site;
* an access with a non-``SPFFT_TPU_*`` literal key (``XLA_FLAGS``,
  ``JAX_PLATFORMS``) is someone else's vocabulary and allowed.

Harness code (``programs/``, ``tests/``) sets knobs from the outside and is
out of scope here; SA003 still checks that every knob it touches is
registered.
"""
from __future__ import annotations

import ast

from .core import PACKAGE_DIRS, Tree, checker

KNOBS_FILE = "spfft_tpu/knobs.py"
PREFIX = "SPFFT_TPU_"


def _is_environ(expr) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "environ"
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "os"
    )


def _key_expr(node):
    """The key expression of an ``os.environ``/``os.getenv`` access, or
    ``False`` when ``node`` is not one. ``None`` means keyless/dynamic
    (e.g. ``os.environ.update(...)``, iteration)."""
    if isinstance(node, ast.Subscript) and _is_environ(node.value):
        return node.slice
    if isinstance(node, ast.Call):
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and _is_environ(fn.value)
            and fn.attr in ("get", "pop", "setdefault")
        ):
            return node.args[0] if node.args else None
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "os"
            and fn.attr == "getenv"
        ) or (isinstance(fn, ast.Name) and fn.id == "getenv"):
            return node.args[0] if node.args else None
    return False


@checker(
    "knob-registry",
    code="SA014",
    doc="Every SPFFT_TPU_* env read in package code goes through the "
    "spfft_tpu.knobs typed registry — raw os.environ/os.getenv accesses "
    "outside knobs.py are flagged when their key is a SPFFT_TPU_* literal "
    "or not statically resolvable (conservative; deliberate raw paths "
    "carry `# noqa: SA014`). Non-SPFFT_TPU_* literal keys (XLA_FLAGS, "
    "JAX_PLATFORMS) are someone else's vocabulary and allowed.",
)
def check_knob_reads(tree: Tree):
    findings = []
    for rel in tree.py_files(PACKAGE_DIRS):
        if rel == KNOBS_FILE:
            continue
        try:
            mod = tree.parse(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod):
            key = _key_expr(node)
            if key is False:
                continue
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                if not key.value.startswith(PREFIX):
                    continue
                findings.append(
                    check_knob_reads.finding(
                        rel, node.lineno,
                        f"raw os.environ access of {key.value} bypasses "
                        "the spfft_tpu.knobs registry — use the typed "
                        "getter (knobs.get_*)",
                    )
                )
            else:
                findings.append(
                    check_knob_reads.finding(
                        rel, node.lineno,
                        "os.environ access with a non-literal key may "
                        "bypass the spfft_tpu.knobs registry — resolve "
                        "through knobs.get_*, or mark a deliberate raw "
                        "path with `# noqa: SA014`",
                    )
                )
    return findings
