"""spfft_tpu.sched — task-graph scheduling across transforms and devices.

The generalization of :mod:`spfft_tpu.multi_transform` from one homogeneous
batch to arbitrary graphs of transform executions (ROADMAP item 4; the
DaggerFFT task-scheduled-FFT shape, arxiv 2601.12209): independent
transforms overlap — one transform's host staging and fetch hide behind
another's FFTs — while dependent ones chain through explicit edges. Three
pieces:

1. **Graphs** (:mod:`.graph`): :class:`TaskGraph` nodes are single
   split-phase transform executions (the ``multi_transform``
   dispatch/finalize halves plus host staging); edges are data dependencies
   (``after=`` / ``input_from=``) and the per-plan retained-buffer
   constraint (tasks sharing a transform object serialize automatically —
   the rule that makes duplicate plans illegal in ``multi_transform_*``
   becomes an edge here). Cycles and dangling deps fail typed before
   anything dispatches.
2. **Placement** (:mod:`.placement`): spec'd tasks (geometry, no plan) are
   assigned an engine/device by a TUNED pass — round-robin width candidates
   (``tuning.candidates.sched_candidates``) measured on the real workload
   and persisted in the wisdom store (``kind: "sched"`` keys), with the
   model fallback (spread across every visible device) on cold CPU-only
   hosts — and resolved through a :class:`PlanPool` (one build per geometry
   per device). Every placed plan's card carries the decision provenance
   (``placement`` section: wisdom hit/miss, width, device).
3. **Execution** (:mod:`.executor`): :func:`run_graph` keeps up to
   ``SPFFT_TPU_SCHED_INFLIGHT`` tasks dispatched at once and finalizes them
   in **completion order** (``jax.Array.is_ready`` polling), not submission
   order; a failed task retries, demotes through the plan's ``jnp.fft``
   reference rung, then resolves typed — dependents resolve typed too
   (``upstream_failed``) — so a failure never stalls the graph. Fault sites
   ``sched.place`` / ``sched.run`` chaos-test both passes; the ``sched``
   trace event and ``sched_tasks_total{outcome}`` / ``sched_inflight`` /
   ``sched_graph_depth`` metrics land on the obs registries.

Surfaces: the serving layer dispatches its coalesced batches through
:func:`run_tasks` and (``sched=True``) whole mixed-geometry graphs through
:func:`run_graph` (:mod:`spfft_tpu.serve`); ``programs/gbench.py`` measures
scheduled-vs-serial graph throughput on the multichip mesh and ``./ci.sh
sched`` gates it.
"""
from .graph import Task, TaskGraph  # noqa: F401
from .placement import (  # noqa: F401
    PlanPool,
    build_plan,
    resolve_width,
    workload_key,
)
from .executor import (  # noqa: F401
    DEFAULT_INFLIGHT,
    LADDER_ERRORS,
    OUTCOMES,
    SCHED_INFLIGHT_ENV,
    GraphReport,
    resolve_inflight,
    run_graph,
    run_tasks,
)
