"""Placement pass: assign every spec'd task an engine/device, TUNED.

Placement is a plan decision like the exchange discipline, and it is resolved
the same way (:mod:`spfft_tpu.tuning`): the candidate set — round-robin
widths over the visible devices, ``tuning.candidates.sched_candidates`` — is
measured by running the *actual graph workload* once per candidate width,
the winner persists in the wisdom store under a ``kind: "sched"`` key (the
workload's geometry signature, device count, platform, jax version), and a
warm store answers with zero trials, so the same graph placed twice gets the
same placement (the reproducibility half of the provenance contract). Hosts
where trials are disallowed (CPU-only unless ``SPFFT_TPU_TUNE_CPU=1``) and
non-tuned policies fall back to the **model placement**: round-robin across
every visible device (independent transforms spread; DaggerFFT's default).

The ``sched.place`` fault site fires at the head of the pass: an injected
placement failure degrades to the model placement with a recorded
``sched_place_failed`` degradation — placement never fails a graph run.

Every pool-built plan carries its decision as ``plan._placement`` (surfaced
as the plan card's ``placement`` section): provenance (``wisdom`` /
``model`` / ``pinned``), wisdom hit/miss, key digest, the chosen width and
the assigned device.
"""
from __future__ import annotations

import time

import numpy as np

from .. import faults, obs
from ..errors import InvalidParameterError
from ..tuning import wisdom as _wisdom
from ..tuning.candidates import sched_candidates
from ..tuning.runner import TRIAL_ERRORS, trials_allowed

SPEC_KEYS = ("transform_type", "dims", "indices")


def _spec_digest(spec: dict) -> str:
    """Stable identity of one task spec (geometry + construction knobs)."""
    for k in SPEC_KEYS:
        if k not in spec:
            raise InvalidParameterError(
                f"task spec is missing {k!r} (required: {SPEC_KEYS})"
            )
    ttype = spec["transform_type"]
    ttype = ttype.name if hasattr(ttype, "name") else str(ttype)
    key = {
        "type": ttype,
        "dims": [int(d) for d in spec["dims"]],
        "dtype": str(np.dtype(spec["dtype"])) if spec.get("dtype") is not None
        else None,
        "engine": str(spec.get("engine", "auto")),
        "precision": str(spec.get("precision", "highest")),
        "sticks": _wisdom.sparsity_signature(np.asarray(spec["indices"])),
    }
    return _wisdom.key_digest(key)


def build_plan(spec: dict, device):
    """Default plan builder of the pool: a local :class:`Transform` of the
    spec's geometry bound to ``device`` (HOST plans on CPU devices, GPU
    plans elsewhere — the device IS the placement decision)."""
    from ..transform import Transform
    from ..types import ProcessingUnit, TransformType

    ttype = spec["transform_type"]
    if not hasattr(ttype, "name"):
        ttype = TransformType[str(ttype)]
    dx, dy, dz = (int(d) for d in spec["dims"])
    pu = (
        ProcessingUnit.HOST
        if getattr(device, "platform", "cpu") == "cpu"
        else ProcessingUnit.GPU
    )
    return Transform(
        pu, ttype, dx, dy, dz,
        indices=spec["indices"],
        dtype=spec.get("dtype"),
        engine=spec.get("engine", "auto"),
        precision=spec.get("precision", "highest"),
        device=device,
        policy=spec.get("policy"),
        guard=spec.get("guard"),
        verify=spec.get("verify"),
    )


class PlanPool:
    """Plans keyed by (spec digest, device): one build per geometry per
    placement target, reused across graphs — the scheduler's analogue of the
    serving layer's plan cache (unbounded here; the pool's owner scopes its
    lifetime)."""

    def __init__(self, build=None):
        self._build = build or build_plan
        self._plans: dict = {}

    def plan_for(self, spec: dict, device):
        key = (_spec_digest(spec), getattr(device, "id", str(device)))
        plan = self._plans.get(key)
        if plan is None:
            plan = self._plans[key] = self._build(spec, device)
        return plan

    def __len__(self) -> int:
        return len(self._plans)


def workload_key(graph, num_devices: int, platform: str) -> dict:
    """Wisdom key of one graph workload: the multiset of spec geometries
    (digest -> count), graph shape (size, depth), device count, platform and
    jax version — everything that changes which width wins."""
    import jax

    counts: dict = {}
    pinned = 0
    for task in graph:
        if task.spec is None:
            pinned += 1
            continue
        d = _spec_digest(task.spec)
        counts[d] = counts.get(d, 0) + 1
    return {
        "kind": "sched",
        "workload": sorted(counts.items()),
        "pinned_tasks": pinned,
        "tasks": len(graph),
        "depth": graph.depth(),
        "num_devices": int(num_devices),
        "platform": str(platform),
        "jax": jax.__version__,
        "env": _wisdom.env_signature(),
    }


def _record(provenance, *, hit, store, choice, trials, reason, key) -> dict:
    """The placement half of ``tuning._record`` — same JSON shape, so plan
    cards and tests read one format for both decision kinds."""
    return {
        "policy": "tuned" if provenance == "wisdom" else provenance,
        "provenance": provenance,
        "hit": hit,
        "wisdom_path": getattr(store, "path", None),
        "key_digest": _wisdom.key_digest(key),
        "reason": reason,
        "choice": choice,
        "trials": trials,
    }


def resolve_width(graph, devices, policy, measure) -> dict:
    """Resolve the placement width (how many devices the round-robin pass
    spreads spec'd tasks over) for one graph.

    ``measure(candidate)`` runs the graph at the candidate's width and
    returns wall seconds (the executor provides it — the trial IS the
    workload). Returns the placement record (:func:`_record` shape) with the
    chosen width in ``choice["width"]``. Ladder: wisdom hit -> zero trials;
    miss with trials allowed -> measure every candidate, persist the winner;
    otherwise the model placement (width = device count)."""
    num = len(devices)
    platform = str(getattr(devices[0], "platform", "cpu")) if num else "cpu"
    key = workload_key(graph, num, platform)
    store = _wisdom.active_store()
    model_choice = {"label": f"rr{num}", "width": num}

    def model(reason, trials=()):
        return _record(
            "model", hit=False, store=store, choice=dict(model_choice),
            trials=list(trials), reason=reason, key=key,
        )

    if policy != "tuned":
        return model(f"policy={policy!r}: model placement (round-robin)")
    entry = store.lookup(key)
    if entry is not None:
        return _record(
            "wisdom", hit=True, store=store, choice=dict(entry["choice"]),
            trials=entry.get("trials", []), reason="wisdom hit", key=key,
        )
    if not trials_allowed(platform):
        return model(
            store.fallback_reason
            or "trials skipped on CPU-only host "
            "(set SPFFT_TPU_TUNE_CPU=1 to allow)"
        )
    rows, failed = [], []
    for cand in sched_candidates(num):
        try:
            with obs.trace.operation("tune.trial", label=cand["label"]), \
                    obs.trace.suppressed_dumps():
                faults.site("tuning.trial")
                t0 = time.perf_counter()
                measure(cand)
                seconds = time.perf_counter() - t0
        except TRIAL_ERRORS as e:
            obs.counter(
                "tuning_trial_failures_total", candidate=cand["label"]
            ).inc()
            failed.append(dict(cand, error=faults.summarize(e)))
            continue
        obs.counter("tuning_trials_total", candidate=cand["label"]).inc()
        rows.append(dict(cand, ms=round(seconds * 1e3, 4)))
    rows.sort(key=lambda r: r["ms"])
    trials = rows + failed
    if not rows:
        return model("all placement trial candidates failed", trials)
    choice = {"label": rows[0]["label"], "width": int(rows[0]["width"])}
    store.record(key, _wisdom.make_entry(key, choice, trials))
    return _record(
        "wisdom", hit=False, store=store, choice=choice, trials=trials,
        reason=store.fallback_reason or "measured", key=key,
    )


def place(graph, devices, pool: PlanPool, policy, measure) -> dict:
    """The placement pass: resolve the width (:func:`resolve_width`), then
    assign each spec'd task a device round-robin in topological order and
    resolve its plan through the pool. Pinned tasks keep their transforms.

    Fault site ``sched.place`` fires first: an injected failure degrades to
    the model placement (recorded), never a failed run. Returns the
    placement record; every pool-built plan gets it (plus its own device) as
    ``plan._placement`` — the plan card's ``placement`` section."""
    specd = [t for t in graph if t.spec is not None]
    if not specd:
        return {"provenance": "pinned", "reason": "all tasks carry plans"}
    if not devices:
        raise InvalidParameterError("placement needs at least one device")
    try:
        faults.site("sched.place")
        record = resolve_width(graph, devices, policy, measure)
    except faults.InjectedFault as e:
        faults.record_degradation("sched_place_failed", faults.summarize(e))
        num = len(devices)
        record = _record(
            "model", hit=False, store=_wisdom.active_store(),
            choice={"label": f"rr{num}", "width": num}, trials=[],
            reason=f"placement fault: {faults.summarize(e)}",
            key=workload_key(graph, num, str(
                getattr(devices[0], "platform", "cpu"))),
        )
    width = max(1, min(int(record["choice"]["width"]), len(devices)))
    if width != int(record["choice"]["width"]):
        # a wisdom entry from a wider host clamps here: the record (and
        # every card it lands on) must state the spread that actually ran
        record = dict(
            record,
            choice={"label": f"rr{width}", "width": width},
            reason=record["reason"]
            + f" (clamped from rr{record['choice']['width']}: "
            f"{len(devices)} devices visible)",
        )
    obs.counter(
        "sched_place_total", provenance=record["provenance"]
    ).inc()
    obs.trace.event(
        "sched", what="place", width=width,
        provenance=record["provenance"], tasks=len(specd),
    )
    for i, task in enumerate(specd):
        device = devices[i % width]
        task.plan = pool.plan_for(task.spec, device)
        task.plan._placement = dict(
            record,
            device=str(device),
            device_index=int(i % width),
        )
    return record
