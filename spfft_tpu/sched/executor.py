"""Task-graph executor: keep transforms in flight, finalize on completion.

The execution half of the scheduler (module doc: :mod:`spfft_tpu.sched`).
One-at-a-time submission leaves the device idle during every host staging
and fetch; ``multi_transform`` pipelines one homogeneous batch; this
executor generalizes both to arbitrary graphs:

- **Windowed dispatch** — up to ``max_inflight`` tasks
  (``SPFFT_TPU_SCHED_INFLIGHT``) are dispatched and device-resident at once,
  in topological order, so device queues never drain between batches: while
  one task's FFTs run, the next task's host staging and dispatch proceed,
  and another's results are fetched.
- **Completion-order finalize** — in-flight results are polled for device
  completion (``jax.Array.is_ready``) and finalized as they finish, not in
  submission order: a small transform behind a large one is fetched the
  moment it completes instead of queueing behind the large one's fetch.
- **Per-task failure ladder** — a failed task (fault site ``sched.run``,
  real dispatch/fence failures, guard-caught poison) is retried, then
  demoted through the plan's ``jnp.fft`` reference rung (the verify
  supervisor's demotion path), then resolved with a typed error — and its
  dependents resolve typed (``upstream_failed``) — so a failed task never
  stalls the rest of the graph (the chaos contract: remaining tasks
  complete or resolve typed).

Observability: ``sched_tasks_total{outcome}`` / ``sched_inflight`` /
``sched_graph_depth`` on the run-metrics registry, ``sched`` flight-recorder
events for place/dispatch/finalize/demote/fail transitions, and placement
provenance on every pool-built plan's card.
"""
from __future__ import annotations

import time

import numpy as np

from .. import faults, knobs, obs
from ..errors import (
    DeadlineExceededError,
    FFTWError,
    GenericError,
    GPUFFTError,
    HostExecutionError,
    HostLostError,
    InvalidParameterError,
    MPIError,
)
from ..types import ScalingType
from .graph import TaskGraph
from .placement import PlanPool, place

SCHED_INFLIGHT_ENV = "SPFFT_TPU_SCHED_INFLIGHT"
DEFAULT_INFLIGHT = knobs.default(SCHED_INFLIGHT_ENV)

# Completion-poll cadence and patience: between polls the executor sleeps
# _POLL_S; after _POLL_PATIENCE_S without any task completing it stops
# polling and blocking-finalizes the oldest in-flight task (progress is
# guaranteed even where is_ready never flips — the fence-budget discipline
# still bounds a truly wedged dispatch).
_POLL_S = 0.0002
_POLL_PATIENCE_S = 0.05

# Task outcomes (the ``outcome`` label of ``sched_tasks_total``).
# ``host_lost`` is the multi-host rung: the task's worker host died, the
# requeue ladder found no surviving host (or exhausted its move budget),
# and the task resolved typed HostLostError — dependents cascade
# ``upstream_failed`` exactly as for ``failed``.
OUTCOMES = ("completed", "demoted", "failed", "upstream_failed", "host_lost")

# Outcomes that fail a task's dependents (the upstream_failed cascade).
_FAILED_OUTCOMES = ("failed", "upstream_failed", "host_lost")

HOST_RETRIES_ENV = "SPFFT_TPU_HOSTS_RETRIES"
HOST_BACKOFF_ENV = "SPFFT_TPU_HOSTS_BACKOFF_S"

# Typed execution failures the per-task ladder may retry/demote: the same
# classes the serving layer retries (dispatch/fence conversions + the
# collective layer) — parameter errors fail fast.
LADDER_ERRORS = (HostExecutionError, GPUFFTError, MPIError, FFTWError)


def resolve_inflight(value=None) -> int:
    """The in-flight window (``SPFFT_TPU_SCHED_INFLIGHT``, floor 1)."""
    if value is not None:
        return max(1, int(value))
    return knobs.get_int(SCHED_INFLIGHT_ENV)


class GraphReport:
    """Outcome of one :func:`run_graph` call."""

    __slots__ = (
        "results", "outcomes", "errors", "depth", "tasks", "placement",
        "wall_seconds",
    )

    def __init__(self, graph: TaskGraph, placement, wall_seconds, depth=None):
        self.results = {
            t.id: t.result for t in graph if t.outcome in ("completed", "demoted")
        }
        self.outcomes = {t.id: t.outcome for t in graph}
        self.errors = {t.id: t.error for t in graph if t.error is not None}
        self.depth = graph.depth() if depth is None else int(depth)
        self.tasks = len(graph)
        self.placement = placement
        self.wall_seconds = wall_seconds

    def result(self, task_id: str):
        """The task's result; raises its typed error if it did not complete."""
        tid = str(task_id)
        if tid in self.errors:
            raise self.errors[tid]
        if tid not in self.results:
            raise InvalidParameterError(f"unknown task id {task_id!r}")
        return self.results[tid]

    def describe(self) -> dict:
        from collections import Counter

        return {
            "tasks": self.tasks,
            "depth": self.depth,
            "outcomes": dict(Counter(self.outcomes.values())),
            "wall_seconds": self.wall_seconds,
            "placement": self.placement,
        }


def _pending_ready(pending) -> bool:
    """Whether every device leaf of a dispatched result has completed
    (host-side leaves and backends without ``is_ready`` count as ready)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(pending):
        probe = getattr(leaf, "is_ready", None)
        if probe is not None and not probe():
            return False
    return True


class _Run:
    """One graph execution (state shared by the dispatch/finalize loop)."""

    def __init__(self, graph, *, retries, demote, on_error, poll_patience_s,
                 backoff_s=0.0, backoff_rng=None, host_retries=None,
                 host_backoff_s=None):
        self.graph = graph
        self.retries = max(0, int(retries))
        self.demote = bool(demote)
        # host-loss requeue budget (the multi-host rung): how many times one
        # task may move to a surviving host via its plan's rehost() hook
        # before resolving typed with the host_lost outcome
        self.host_retries = knobs.get_int(HOST_RETRIES_ENV, host_retries)
        self.host_backoff_s = knobs.get_float(HOST_BACKOFF_ENV, host_backoff_s)
        if on_error not in ("resolve", "raise"):
            raise InvalidParameterError(
                f"on_error must be 'resolve' or 'raise', got {on_error!r}"
            )
        self.on_error = on_error
        self.poll_patience_s = float(poll_patience_s)
        # jittered exponential backoff between a task's retry attempts (the
        # serving layer's thundering-herd rule; 0 = retry immediately)
        self.backoff_s = max(0.0, float(backoff_s))
        self.backoff_rng = backoff_rng

    # ---- per-task execution -------------------------------------------------

    def _platform(self, task) -> str:
        dev = getattr(task.plan, "device", None)
        return str(getattr(dev, "platform", "cpu"))

    def _payload(self, task):
        if task.input_from is not None:
            return self.graph.task(task.input_from).result
        return task.payload

    def _dispatch(self, task) -> None:
        """Stage + enqueue one task (no waiting). Supervised plans execute
        whole under their recovery supervisor (the supervisor owns the
        retry/demote ladder for them) and complete immediately."""
        plan = task.plan
        task.attempts += 1
        task.dispatched_at = time.monotonic()
        payload = self._payload(task)
        obs.trace.event(
            "sched", what="dispatch", task=task.id,
            direction=task.direction, attempt=task.attempts,
        )
        with faults.typed_execution(self._platform(task), "sched dispatch"):
            if plan._verifier is not None:
                if task.batch:
                    # supervised plans run per-request under their recovery
                    # supervisor even inside a batch task (the ABFT ladder
                    # owns each request's attempt — the serving rule)
                    if task.direction == "backward":
                        task.result = [plan.backward(v) for v in payload]
                    else:
                        task.result = [
                            plan.forward(v, task.scaling) for v in payload
                        ]
                elif task.direction == "backward":
                    task.result = plan.backward(payload)
                else:
                    task.result = plan.forward(payload, task.scaling)
                task.pending = ()
                return
            if task.batch:
                # one batched program dispatch for the whole request list
                # (spfft_tpu.ir batch fusion; the split-phase per-request
                # loop is the in-dispatch rung when the batched build fails)
                if task.direction == "backward":
                    pending = plan._dispatch_backward_batch(payload)
                else:
                    pending = plan._dispatch_forward_batch(
                        payload, task.scaling
                    )
            elif task.direction == "backward":
                pending = plan._dispatch_backward(payload)
            else:
                pending = plan._dispatch_forward(payload, task.scaling)
            # the scheduler's execution fault site, engine.execute-style:
            # `raise` surfaces here (typed via the scope), nan/corrupt
            # poison the in-flight payload so the guard check at finalize
            # must catch it — chaos runs prove the whole ladder
            task.pending = faults.site("sched.run", payload=pending)

    def _finalize(self, task) -> None:
        """Fetch + complete one dispatched task; guard-scan the result when
        the plan runs in guard mode (a poisoned in-flight payload surfaces
        typed here, feeding the retry/demote ladder)."""
        plan = task.plan
        if task.result is not None or plan._verifier is not None:
            return  # supervised: completed at dispatch
        import jax

        with faults.typed_execution(self._platform(task), "sched finalize"):
            if task.batch:
                if task.direction == "backward":
                    result = plan._finalize_backward_batch(task.pending)
                else:
                    result = plan._finalize_forward_batch(task.pending)
            elif task.direction == "backward":
                result = plan._finalize_backward(task.pending)
            else:
                result = plan._finalize_forward(task.pending)
            if plan._guard:
                for leaf in jax.tree_util.tree_leaves(result):
                    faults.check_array(
                        np.asarray(leaf),
                        check="sched output",
                        platform=self._platform(task),
                    )
        task.result = result

    def _reference(self, task):
        """The demotion rung: re-execute through the plan's ``jnp.fft``
        reference pipeline — a code path disjoint from the primary engine's
        dispatch (no ``sched.run`` site, no shared compiled programs)."""
        plan = task.plan
        payload = self._payload(task)
        with faults.typed_execution(self._platform(task), "sched demote"):
            if task.batch:
                # per-request reference rung: correctness over batching on
                # the degraded path (the serving demote rule)
                if task.direction == "backward":
                    return [plan._reference_backward(v) for v in payload]
                return [
                    plan._reference_forward(v, task.scaling) for v in payload
                ]
            if task.direction == "backward":
                return plan._reference_backward(payload)
            if payload is None:
                payload = plan.space_domain_data()
            return plan._reference_forward(payload, task.scaling)

    def _expired(self, task) -> bool:
        """Deadline gate, applied before EVERY dispatch — first attempts and
        retries alike (the serving layer's between-retries shedding rule):
        an expired task resolves typed without burning device time."""
        if task.deadline is None or time.monotonic() < task.deadline:
            return False
        self._fail(
            task,
            DeadlineExceededError(
                f"sched task {task.id!r} deadline expired before "
                f"{'retry' if task.attempts else 'dispatch'}"
            ),
        )
        return True

    def _retry_pause(self, task) -> None:
        obs.counter("sched_retries_total").inc()
        if self.backoff_s > 0.0:
            time.sleep(
                faults.backoff_s(self.backoff_s, task.attempts, self.backoff_rng)
            )

    def _attempt(self, task) -> bool:
        """One dispatch of ``task`` with the failure ladder applied; returns
        True when the task is in flight (or already resolved)."""
        while True:
            if self._expired(task):
                return False
            try:
                self._dispatch(task)
                return True
            except HostLostError as e:
                # the multi-host rung, BEFORE the generic ladder (HostLost
                # subclasses MPIError): requeue onto a surviving host
                # instead of retrying the dead one
                if not self._rehost(task, e):
                    return False
                continue
            except LADDER_ERRORS as e:
                if task.attempts <= self.retries:
                    self._retry_pause(task)
                    continue
                self._demote_or_fail(task, e)
                return False
            except GenericError as e:
                # non-retryable typed failures (parameter errors, an
                # exhausted supervisor's VerificationError): they would fail
                # identically on retry or the reference rung — resolve the
                # TASK typed; the graph keeps running (on_error governs)
                self._fail(task, e)
                return False

    def _finalize_ladder(self, task) -> None:
        """Finalize with the same ladder: a finalize/guard failure re-runs
        the whole attempt (dispatch included — the in-flight payload is
        spent), then demotes, then resolves typed."""
        while True:
            try:
                self._finalize(task)
            except HostLostError as e:
                # host died with the task in flight: the work was never
                # acked, so requeueing it onto a survivor is idempotent
                task.pending = None
                if not self._rehost(task, e):
                    return
                if self._attempt(task):
                    continue  # re-dispatched on the new host
                return  # ladder already resolved the task
            except LADDER_ERRORS as e:
                task.pending = None
                if task.attempts <= self.retries:
                    self._retry_pause(task)
                    if self._attempt(task):
                        continue  # re-dispatched: finalize the new attempt
                    return  # ladder already resolved the task
                self._demote_or_fail(task, e)
                return
            except GenericError as e:
                task.pending = None
                self._fail(task, e)  # non-retryable typed: see _attempt
                return
            self._resolve(task, "completed")
            return

    def _rehost(self, task, error) -> bool:
        """The host-loss requeue rung: move the task to a surviving host
        via its plan's ``rehost()`` hook, bounded by ``host_retries`` moves
        with jittered backoff; False when the task was resolved instead
        (no hook — a local plan cannot move — budget exhausted, or no
        surviving host)."""
        rehost = getattr(task.plan, "rehost", None)
        if rehost is None or task.host_moves >= self.host_retries:
            self._host_lost(task, error)
            return False
        task.host_moves += 1
        obs.counter("host_requeues_total").inc()
        obs.trace.event(
            "sched", what="rehost", task=task.id, move=task.host_moves,
        )
        if self.host_backoff_s > 0.0:
            time.sleep(
                faults.backoff_s(
                    self.host_backoff_s, task.host_moves, self.backoff_rng
                )
            )
        try:
            rehost(error)
        except GenericError as e:
            self._host_lost(task, e)
            return False
        return True

    def _host_lost(self, task, error) -> None:
        """Resolve a task whose host died beyond recovery: typed error,
        distinct ``host_lost`` outcome (dependents cascade
        ``upstream_failed``), the rung recorded."""
        faults.record_degradation(
            "host_lost", faults.summarize(error), task=task.id
        )
        task.error = error
        obs.trace.event(
            "sched", what="fail", task=task.id, error=type(error).__name__,
            outcome="host_lost",
        )
        self._resolve(task, "host_lost")
        if self.on_error == "raise":
            raise error

    def _demote_or_fail(self, task, error) -> None:
        if self.demote:
            obs.trace.event("sched", what="demote", task=task.id)
            try:
                task.result = self._reference(task)
            except GenericError as demote_err:
                self._fail(task, demote_err)
                return
            task.error = None
            self._resolve(task, "demoted")
            return
        self._fail(task, error)

    def _fail(self, task, error) -> None:
        task.error = error
        obs.trace.event(
            "sched", what="fail", task=task.id,
            error=type(error).__name__,
        )
        self._resolve(task, "failed")
        if self.on_error == "raise":
            raise error

    def _resolve(self, task, outcome: str) -> None:
        task.outcome = outcome
        task.finished_at = time.monotonic()
        obs.counter("sched_tasks_total", outcome=outcome).inc()
        if outcome in ("completed", "demoted"):
            obs.trace.event("sched", what="finalize", task=task.id)

    def _cascade(self, task) -> None:
        """Resolve a task whose dependency failed: typed, never stalled."""
        causes = [
            d for d in task.deps
            if self.graph.task(d).outcome in _FAILED_OUTCOMES
        ]
        cause = self.graph.task(causes[0]).error if causes else None
        err = HostExecutionError(
            f"sched task {task.id!r} not run: upstream task "
            f"{causes[0] if causes else '?'!r} failed"
        )
        err.__cause__ = cause
        task.error = err
        self._resolve(task, "upstream_failed")

    # ---- the loop -----------------------------------------------------------

    def execute(self, order: list, max_inflight: int) -> None:
        def gauge(n):
            obs.gauge("sched_inflight").set(n)

        try:
            self._execute(order, max_inflight, gauge)
        finally:
            gauge(0)  # drained OR aborted (on_error="raise"): never stuck

    def _execute(self, order: list, max_inflight: int, gauge) -> None:
        waiting = list(order)
        inflight: list = []
        last_progress = time.monotonic()

        while waiting or inflight:
            progressed = False
            # dispatch while the window has room and a task is ready
            while waiting and len(inflight) < max_inflight:
                task = self._next_ready(waiting)
                if task is None:
                    break
                waiting.remove(task)
                if any(
                    self.graph.task(d).outcome in _FAILED_OUTCOMES
                    for d in task.deps
                ):
                    self._cascade(task)
                    progressed = True
                    continue
                if self._attempt(task):
                    if task.result is not None:  # supervised: done already
                        self._resolve(task, "completed")
                    else:
                        inflight.append(task)
                        gauge(len(inflight))
                progressed = True
            # finalize in completion order: poll the window, take whichever
            # finished; after the patience window, fall back to the oldest
            if inflight:
                ready = next(
                    (t for t in inflight if _pending_ready(t.pending)), None
                )
                if ready is None and (
                    time.monotonic() - last_progress > self.poll_patience_s
                    or (not waiting and len(inflight) == 1)
                ):
                    ready = inflight[0]
                if ready is not None:
                    inflight.remove(ready)
                    gauge(len(inflight))
                    self._finalize_ladder(ready)
                    progressed = True
                elif not progressed:
                    time.sleep(_POLL_S)
            if progressed:
                last_progress = time.monotonic()

    def _next_ready(self, waiting: list):
        """First task (topological order) whose deps are all resolved."""
        for task in waiting:
            states = [self.graph.task(d).outcome for d in task.deps]
            if all(s is not None for s in states):
                return task
        return None


def run_graph(
    graph: TaskGraph,
    *,
    devices=None,
    pool: PlanPool | None = None,
    policy: str | None = None,
    width: int | None = None,
    max_inflight=None,
    retries: int = 1,
    demote: bool = True,
    on_error: str = "resolve",
    backoff_s: float = 0.0,
    backoff_rng=None,
    host_retries: int | None = None,
    host_backoff_s: float | None = None,
    _poll_patience_s: float = _POLL_PATIENCE_S,
) -> GraphReport:
    """Execute a :class:`TaskGraph`; returns a :class:`GraphReport`.

    ``devices`` (default: all visible jax devices) and ``policy`` feed the
    placement pass for spec'd tasks (``policy="tuned"`` resolves the
    round-robin width through wisdom/trials — :mod:`.placement`; ``width=``
    pins it outright). ``pool`` reuses plan builds across calls. ``retries``
    / ``demote`` configure the per-task failure ladder; ``on_error="raise"``
    aborts on the first task failure instead of resolving it (the serving
    layer's batch semantics — its own retry loop owns recovery there).
    ``host_retries`` / ``host_backoff_s`` bound the host-loss requeue rung:
    a task whose plan carries a ``rehost()`` hook (remote plans,
    :mod:`spfft_tpu.serve.cluster`) moves to a surviving host on typed
    :class:`~spfft_tpu.errors.HostLostError` before resolving with the
    ``host_lost`` outcome (defaults: ``SPFFT_TPU_HOSTS_RETRIES`` /
    ``SPFFT_TPU_HOSTS_BACKOFF_S``).
    """
    from ..parallel.policy import resolve_policy

    order = graph.order()  # validates (cycles) before anything dispatches
    if not order:
        return GraphReport(graph, None, 0.0)
    if devices is None:
        import jax

        devices = jax.devices()
    pool = pool if pool is not None else PlanPool()
    policy = resolve_policy(policy)
    t0 = time.monotonic()
    depth = graph.depth()
    obs.gauge("sched_graph_depth").set(depth)
    obs.trace.event(
        "sched", what="graph", tasks=len(order), depth=depth,
        policy=str(policy),
    )
    if width is not None:
        # record the EFFECTIVE width: a pin wider than the device list is
        # clamped for assignment, and provenance must state what actually
        # happened, not what was asked for
        w = max(1, min(int(width), len(devices)))
        placement = {
            "provenance": "pinned",
            "hit": None,
            "wisdom_path": None,
            "key_digest": None,
            "choice": {"label": f"rr{w}", "width": w},
            "trials": [],
            "reason": "explicit width"
            + (f" (clamped from {int(width)})" if w != int(width) else ""),
        }
        specd = [t for t in order if t.spec is not None]
        for i, task in enumerate(specd):
            task.plan = pool.plan_for(task.spec, devices[i % w])
            task.plan._placement = dict(
                placement, device=str(devices[i % w]), device_index=i % w
            )
    else:
        placement = place(
            graph, devices, pool, policy,
            measure=lambda cand: _measure_width(
                graph, devices, pool, cand["width"], max_inflight,
            ),
        )
    run = _Run(
        graph, retries=retries, demote=demote, on_error=on_error,
        poll_patience_s=_poll_patience_s, backoff_s=backoff_s,
        backoff_rng=backoff_rng, host_retries=host_retries,
        host_backoff_s=host_backoff_s,
    )
    run.execute(order, resolve_inflight(max_inflight))
    return GraphReport(graph, placement, time.monotonic() - t0, depth=depth)


def _measure_width(graph, devices, pool, width, max_inflight):
    """One placement trial: execute a fresh copy of the workload with the
    candidate width pinned. Trial runs are idempotent re-executions of the
    graph (same payloads, same deps); their results are discarded — only the
    wall clock is kept (the caller times this call). The trial runs WITHOUT
    the retry/demote ladder (``on_error="raise"``): a width whose tasks fail
    or demote must become an ``error`` trial row, never a fast-looking
    winner timing the failure path (the ``TrialDegradedError`` rule)."""
    run_graph(
        _copy_graph(graph), devices=devices, pool=pool, width=int(width),
        max_inflight=max_inflight, retries=0, demote=False, on_error="raise",
    )


def _copy_graph(graph: TaskGraph) -> TaskGraph:
    """Fresh execution state over the same tasks (payloads shared read-only;
    pinned transforms shared — a trial re-executes them idempotently)."""
    copy = TaskGraph()
    for task in graph:
        copy.add(
            task.direction, id=task.id, payload=task.payload,
            scaling=task.scaling, after=task.deps, input_from=task.input_from,
            transform=task.transform, spec=task.spec, deadline=task.deadline,
            batch=task.batch,
        )
    return copy


def run_tasks(
    plans: list,
    directions,
    payloads: list,
    scalings=None,
    *,
    max_inflight=None,
    retries: int = 0,
    demote: bool = False,
    on_error: str = "raise",
) -> list:
    """Flat-batch convenience: execute ``plans[i]`` on ``payloads[i]`` as one
    dependency-free graph (completion-order finalize, windowed dispatch) and
    return results in batch order — the scheduler-backed replacement for a
    dispatch-all/finalize-all loop (the serving layer's batch path).

    ``directions`` is one direction or a per-task list; defaults mirror the
    serving batch contract: no internal retries or demotion (the caller owns
    recovery), first failure raises typed."""
    plans = list(plans)
    payloads = list(payloads)
    if len(plans) != len(payloads):
        raise InvalidParameterError(
            f"run_tasks: got {len(plans)} plans but {len(payloads)} payloads"
        )
    if isinstance(directions, str):
        directions = [directions] * len(plans)
    directions = list(directions)
    if len(directions) != len(plans):
        raise InvalidParameterError(
            f"run_tasks: got {len(plans)} plans but {len(directions)} directions"
        )
    if scalings is None:
        scalings = [ScalingType.NONE] * len(plans)
    scalings = list(scalings)
    if len(scalings) != len(plans):
        raise InvalidParameterError(
            f"run_tasks: got {len(plans)} plans but {len(scalings)} scalings"
        )
    graph = TaskGraph()
    ids = [
        graph.add(d, payload=v, scaling=s, transform=p)
        for p, d, v, s in zip(plans, directions, payloads, scalings)
    ]
    report = run_graph(
        graph, max_inflight=max_inflight, retries=retries, demote=demote,
        on_error=on_error,
    )
    return [report.result(tid) for tid in ids]
