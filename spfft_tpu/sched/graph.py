"""Task graphs over split-phase transform executions.

A :class:`TaskGraph` is the unit the scheduler executes: nodes are single
transform executions — the same split-phase halves ``multi_transform``
pipelines (``_dispatch_backward`` / ``_finalize_backward`` and the forward
pair) plus their host staging — and edges are the two dependency kinds the
runtime actually has:

- **data dependencies** — an explicit ``after=[...]`` list, optionally with
  ``input_from=<task id>`` so a task's payload IS an upstream result (a
  forward chained on a backward, a backward consuming a produced spectrum);
- **retained-buffer constraints** — two tasks naming the same transform
  *object* are implicitly serialized in submission order, because a plan's
  retained space-domain buffer is per-object state (the same rule that makes
  ``multi_transform_*`` reject duplicate transform objects; here the graph
  encodes the constraint as an edge instead of refusing the batch).

Nodes carry either a pre-built ``transform`` (the plan is pinned — the
placement pass leaves it where it is) or a ``spec`` dict (geometry only —
the placement pass assigns a device and resolves the plan through the
scheduler's plan pool). Validation is eager and typed: unknown ids,
duplicate ids, dangling dependencies and cycles raise
:class:`~spfft_tpu.errors.InvalidParameterError` before anything dispatches.
"""
from __future__ import annotations

from ..errors import InvalidParameterError
from ..types import ScalingType

DIRECTIONS = ("backward", "forward")

_obj_id = id  # the builtin; shadowed by the public ``id=`` task-id kwarg


class Task:
    """One transform execution in a :class:`TaskGraph` (see module doc)."""

    __slots__ = (
        "id", "direction", "payload", "scaling", "deps", "input_from",
        "transform", "spec", "deadline", "batch",
        # execution state (owned by sched.executor)
        "plan", "pending", "result", "error", "outcome", "attempts",
        "host_moves", "dispatched_at", "finished_at",
    )

    def __init__(
        self, id, direction, *, payload=None, scaling=ScalingType.NONE,
        deps=(), input_from=None, transform=None, spec=None, deadline=None,
        batch=False,
    ):
        if direction not in DIRECTIONS:
            raise InvalidParameterError(
                f"task {id!r}: unknown direction {direction!r} "
                f"(expected one of {DIRECTIONS})"
            )
        if (transform is None) == (spec is None):
            raise InvalidParameterError(
                f"task {id!r}: exactly one of transform= (pinned plan) or "
                "spec= (placed through the plan pool) is required"
            )
        if (
            spec is not None and direction == "forward"
            and payload is None and input_from is None
        ):
            raise InvalidParameterError(
                f"task {id!r}: a spec'd forward task needs an explicit "
                "payload or input_from= — pool-resolved plans are shared "
                "per (geometry, device), so their retained space buffers "
                "are not task-addressable"
            )
        # batch task (spfft_tpu.ir batch fusion): payload is a LIST of
        # per-request payloads executed as one batched program dispatch —
        # the scheduler treats the whole batch as one task (one dispatch,
        # one finalize, one ladder). Requires a pinned plan.
        self.batch = bool(batch)
        if self.batch:
            if transform is None:
                raise InvalidParameterError(
                    f"task {id!r}: a batch task needs a pinned transform="
                )
            if not isinstance(payload, (list, tuple)) or not payload:
                raise InvalidParameterError(
                    f"task {id!r}: a batch task needs a non-empty list "
                    "payload (one entry per request)"
                )
            payload = list(payload)
        self.id = str(id)
        self.direction = direction
        self.payload = payload
        self.scaling = ScalingType(scaling)
        self.deps = tuple(str(d) for d in deps)
        self.input_from = None if input_from is None else str(input_from)
        self.transform = transform
        self.spec = dict(spec) if spec is not None else None
        # absolute monotonic deadline, or None: the executor refuses to
        # dispatch (or re-dispatch) an expired task — typed
        # DeadlineExceededError, device time never burned on it
        self.deadline = None if deadline is None else float(deadline)
        self.plan = transform
        self.pending = None
        self.result = None
        self.error = None
        self.outcome = None  # one of executor.OUTCOMES once resolved
        self.attempts = 0
        self.host_moves = 0  # host-loss requeues taken (executor ladder)
        self.dispatched_at = None
        self.finished_at = None

    def describe(self) -> dict:
        """JSON-plain record of this task's identity and outcome."""
        return {
            "id": self.id,
            "direction": self.direction,
            "batch": len(self.payload) if self.batch else None,
            "deps": list(self.deps),
            "outcome": self.outcome,
            "attempts": self.attempts,
            "error": None if self.error is None else type(self.error).__name__,
        }


class TaskGraph:
    """Ordered collection of :class:`Task` nodes with dependency edges."""

    def __init__(self):
        self._tasks: dict = {}
        self._last_user: dict = {}  # id(transform) -> last task id (buffer edge)
        self._auto_id = 0

    def add(
        self, direction, *, id=None, payload=None, scaling=ScalingType.NONE,
        after=(), input_from=None, transform=None, spec=None, deadline=None,
        batch=False,
    ) -> str:
        """Add one task; returns its id (generated when not given).

        ``after`` lists upstream task ids; ``input_from`` names one of them
        whose result becomes this task's payload (it is added to the
        dependency set automatically). Tasks sharing a ``transform`` object
        are additionally serialized in submission order (the retained-buffer
        constraint — see module doc)."""
        if id is not None:
            tid = str(id)
        else:
            # skip over caller-supplied ids of the same shape: an auto id
            # must never collide with a name the caller chose
            while f"t{self._auto_id}" in self._tasks:
                self._auto_id += 1
            tid = f"t{self._auto_id}"
            self._auto_id += 1
        if tid in self._tasks:
            raise InvalidParameterError(f"duplicate task id {tid!r}")
        deps = [str(a) for a in after]
        if input_from is not None and str(input_from) not in deps:
            deps.append(str(input_from))
        if transform is not None:
            prev = self._last_user.get(_obj_id(transform))
            if prev is not None and prev not in deps:
                # per-object retained-buffer state: serialize, don't reject
                deps.append(prev)
            self._last_user[_obj_id(transform)] = tid
        for d in deps:
            if d not in self._tasks:
                raise InvalidParameterError(
                    f"task {tid!r} depends on unknown task {d!r} "
                    "(dependencies must be added first)"
                )
        task = Task(
            tid, direction, payload=payload, scaling=scaling, deps=deps,
            input_from=input_from, transform=transform, spec=spec,
            deadline=deadline, batch=batch,
        )
        self._tasks[tid] = task
        return tid

    def __len__(self) -> int:
        return len(self._tasks)

    def __iter__(self):
        return iter(self._tasks.values())

    def task(self, tid: str) -> Task:
        try:
            return self._tasks[str(tid)]
        except KeyError:
            raise InvalidParameterError(f"unknown task id {tid!r}") from None

    def order(self) -> list:
        """Topological order (submission order among ready peers) — Kahn's
        algorithm; a cycle raises typed (the graph would deadlock)."""
        indeg = {t.id: len(t.deps) for t in self._tasks.values()}
        children: dict = {t.id: [] for t in self._tasks.values()}
        for t in self._tasks.values():
            for d in t.deps:
                children[d].append(t.id)
        ready = [tid for tid, n in indeg.items() if n == 0]
        out = []
        while ready:
            tid = ready.pop(0)
            out.append(tid)
            for c in children[tid]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(out) != len(self._tasks):
            stuck = sorted(tid for tid, n in indeg.items() if n > 0)
            raise InvalidParameterError(
                f"task graph has a dependency cycle through {stuck}"
            )
        return [self._tasks[tid] for tid in out]

    def depth(self) -> int:
        """Longest dependency chain (1 for a flat batch, 0 when empty) —
        the ``sched_graph_depth`` gauge the executor reports."""
        depth: dict = {}
        for task in self.order():
            depth[task.id] = 1 + max(
                (depth[d] for d in task.deps), default=0
            )
        return max(depth.values(), default=0)

    def describe(self) -> dict:
        """JSON-plain graph summary (size, depth, per-task outcomes)."""
        return {
            "tasks": len(self._tasks),
            "depth": self.depth(),
            "nodes": [t.describe() for t in self._tasks.values()],
        }
