"""Marshalling bridge for the native C/C++ API layer.

The native library (``native/``) embeds a CPython interpreter and drives the
XLA core through the functions in this module. Everything crossing the
boundary is either a plain scalar or a writable/readonly buffer created by the
C++ side with ``PyMemoryView_FromMemory`` — no numpy C API, no pybind11.

Layout contracts (all row-major, matching the public Python API):
- frequency values: ``num_local_elements`` complex numbers, interleaved
  (re, im) pairs of the transform's real dtype.
- space domain: ``(dim_z, dim_y, dim_x)`` slab; complex-interleaved for C2C,
  real for R2C (reference semantics: docs/source/details.rst:21-27 — the
  space-domain array of the reference is real for R2C and complex for C2C).
- index triplets: ``3 * num_local_elements`` int32.

Reference parity: this module plays the role of the reference's C-API
implementation layer (reference: src/spfft/transform.cpp:178+ wraps the C++
class in ``spfft_transform_*`` handle functions); here the handle lives in
C++ and the compute core is the JAX/XLA plan object.
"""
from __future__ import annotations

import numpy as np

from . import errors, knobs
from .grid import Grid
from .multi_transform import multi_transform_backward, multi_transform_forward
from .transform import Transform
from .types import ExchangeType, ExecType, ProcessingUnit, ScalingType, TransformType

__all__ = [
    "error_code",
    "grid_create",
    "grid_create_distributed",
    "grid_create_distributed2",
    "grid_get",
    "transform_create",
    "transform_create_from_grid",
    "transform_clone",
    "transform_get",
    "transform_set_execution_mode",
    "transform_backward",
    "transform_forward",
    "multi_backward",
    "multi_forward",
    "dist_transform_create",
    "dist_transform_get",
    "dist_transform_get_shard",
    "dist_backward",
    "dist_forward",
]

# Virtual CPU mesh size for native callers (the C analogue of the tests'
# 8-device conftest): must be applied before JAX initializes its backends,
# i.e. before the first Grid/Transform creation in the embedded interpreter.
_num_cpu = knobs.get_int("SPFFT_TPU_NUM_CPU_DEVICES")
if _num_cpu:
    from .parallel.mesh import configure_virtual_devices

    configure_virtual_devices(_num_cpu, warn=True)

_SP_SUCCESS = 0
_SP_UNKNOWN = int(errors.ErrorCode.UNKNOWN)


def error_code(exc: BaseException) -> int:
    """Translate a Python exception into an ``SpfftError`` C enum value.

    Mirrors the reference's catch-GenericError-return-error_code pattern
    (reference: src/spfft/transform.cpp:184-195)."""
    if isinstance(exc, errors.GenericError):
        return int(exc.error_code)
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return int(errors.ErrorCode.INVALID_PARAMETER)
    if isinstance(exc, MemoryError):
        return int(errors.ErrorCode.ALLOCATION)
    return _SP_UNKNOWN


def _ensure_x64(double_precision: bool) -> None:
    """Native callers requesting double precision must actually get f64: the
    embedded interpreter does not run the test conftest, and without x64 JAX
    silently truncates f64 arrays to f32 (a ~4e-7 roundtrip instead of ~1e-15).
    jax_enable_x64 is runtime-updatable, so flip it on first f64 plan.

    GLOBAL SIDE EFFECT: jax_enable_x64 is process-wide — an embedding
    application that also uses JAX sees default dtypes widen from this point
    on. Documented in the C header (native/include/spfft/transform.h); callers
    who must not perturb the host process use the float entry points."""
    if double_precision:
        import jax

        if not jax.config.read("jax_enable_x64"):
            jax.config.update("jax_enable_x64", True)


def _real_dtype(t: Transform) -> np.dtype:
    return np.dtype(t.dtype)


def _complex_dtype(t: Transform) -> np.dtype:
    return np.dtype(np.complex128 if _real_dtype(t) == np.float64 else np.complex64)


# ---- creation ---------------------------------------------------------------


def grid_create(
    max_dim_x: int,
    max_dim_y: int,
    max_dim_z: int,
    max_num_local_z_columns: int,
    processing_unit: int,
    max_num_threads: int,
) -> Grid:
    return Grid(
        max_dim_x,
        max_dim_y,
        max_dim_z,
        max_num_local_z_columns,
        ProcessingUnit(processing_unit),
        max_num_threads,
    )


def transform_create(
    processing_unit: int,
    transform_type: int,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    num_local_elements: int,
    indices,
    double_precision: bool,
) -> Transform:
    _ensure_x64(double_precision)
    idx = np.frombuffer(indices, dtype=np.int32).copy()
    return Transform(
        ProcessingUnit(processing_unit),
        TransformType(transform_type),
        dim_x,
        dim_y,
        dim_z,
        num_local_elements,
        idx,
        dtype=np.float64 if double_precision else np.float32,
    )


def transform_create_from_grid(
    grid: Grid,
    processing_unit: int,
    transform_type: int,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    local_z_length: int,
    num_local_elements: int,
    indices,
    double_precision: bool,
) -> Transform:
    _ensure_x64(double_precision)
    idx = np.frombuffer(indices, dtype=np.int32).copy()
    return grid.create_transform(
        ProcessingUnit(processing_unit),
        TransformType(transform_type),
        dim_x,
        dim_y,
        dim_z,
        num_local_elements,
        idx,
        local_z_length=local_z_length if local_z_length > 0 else None,
        dtype=np.float64 if double_precision else np.float32,
    )


def transform_clone(t: Transform) -> Transform:
    return t.clone()


# ---- distributed (single-controller) ----------------------------------------
# The reference's MPI Grid ctor takes a communicator and each rank supplies its
# local part (reference: include/spfft/grid.hpp:89-91). The native TPU analogue
# is single-controller: ONE process drives every shard of a device mesh, so the
# C caller passes per-shard counts and shard-major concatenated data.


def _make_dist_grid(
    mesh_factory,
    num_devices: int,
    max_dim_x: int,
    max_dim_y: int,
    max_dim_z: int,
    max_num_local_z_columns: int,
    max_local_z_length: int,
    processing_unit: int,
    exchange_type: int,
    max_num_threads: int,
) -> Grid:
    """Shared distributed-grid construction; ``mesh_factory(devices)`` builds
    the mesh (1-D or 2-D pencil)."""
    pu = ProcessingUnit(processing_unit)
    if pu == ProcessingUnit.HOST:
        # Resolved without initializing non-CPU backends: the embedded
        # interpreter's HOST paths must work (or fail fast) even when the
        # host's accelerator runtime is unreachable (see _platform.py).
        from ._platform import cpu_devices

        devices = cpu_devices(num_devices)
    else:
        devices = None
    return Grid(
        max_dim_x,
        max_dim_y,
        max_dim_z,
        max_num_local_z_columns,
        pu,
        max_num_threads,
        max_local_z_length=max_local_z_length if max_local_z_length > 0 else None,
        mesh=mesh_factory(devices),
        exchange_type=ExchangeType(exchange_type),
    )


def grid_create_distributed(
    max_dim_x: int,
    max_dim_y: int,
    max_dim_z: int,
    max_num_local_z_columns: int,
    max_local_z_length: int,
    num_shards: int,
    processing_unit: int,
    exchange_type: int,
    max_num_threads: int,
) -> Grid:
    from .parallel.mesh import make_fft_mesh

    return _make_dist_grid(
        lambda devices: make_fft_mesh(num_shards, devices=devices),
        num_shards,
        max_dim_x,
        max_dim_y,
        max_dim_z,
        max_num_local_z_columns,
        max_local_z_length,
        processing_unit,
        exchange_type,
        max_num_threads,
    )


def grid_create_distributed2(
    max_dim_x: int,
    max_dim_y: int,
    max_dim_z: int,
    max_num_local_z_columns: int,
    max_local_z_length: int,
    p1: int,
    p2: int,
    processing_unit: int,
    exchange_type: int,
    max_num_threads: int,
) -> Grid:
    """2-D pencil mesh grid (parallel/pencil2.py): transforms created from it
    use the z-slabs x y-slabs decomposition; same dist_* execution surface."""
    from .parallel.mesh import make_fft_mesh2

    return _make_dist_grid(
        lambda devices: make_fft_mesh2(p1, p2, devices=devices),
        p1 * p2,
        max_dim_x,
        max_dim_y,
        max_dim_z,
        max_num_local_z_columns,
        max_local_z_length,
        processing_unit,
        exchange_type,
        max_num_threads,
    )


def dist_transform_create(
    grid: Grid,
    processing_unit: int,
    transform_type: int,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    num_shards: int,
    shard_num_elements,
    indices,
    double_precision: bool,
):
    _ensure_x64(double_precision)
    counts = np.frombuffer(shard_num_elements, dtype=np.int32, count=num_shards)
    flat = np.frombuffer(indices, dtype=np.int32).copy().reshape(-1, 3)
    if flat.shape[0] != int(counts.sum()):
        raise errors.InvalidParameterError(
            "indices length does not match the sum of shard_num_elements"
        )
    per_shard, off = [], 0
    for n in counts:
        per_shard.append(flat[off : off + int(n)])
        off += int(n)
    return grid.create_transform(
        ProcessingUnit(processing_unit),
        TransformType(transform_type),
        dim_x,
        dim_y,
        dim_z,
        indices=per_shard,
        dtype=np.float64 if double_precision else np.float32,
    )


# ---- accessors --------------------------------------------------------------

_TRANSFORM_GETTERS = {
    "dim_x": lambda t: t.dim_x,
    "dim_y": lambda t: t.dim_y,
    "dim_z": lambda t: t.dim_z,
    "local_z_length": lambda t: t.local_z_length,
    "local_z_offset": lambda t: t.local_z_offset,
    "local_slice_size": lambda t: t.local_slice_size,
    "num_local_elements": lambda t: t.num_local_elements,
    "num_global_elements": lambda t: t.num_global_elements,
    "global_size": lambda t: t.global_size,
    "transform_type": lambda t: int(t.transform_type),
    "processing_unit": lambda t: int(t.processing_unit),
    "device_id": lambda t: t.device_id,
    "num_threads": lambda t: t.num_threads,
    "execution_mode": lambda t: int(t.execution_mode()),
}

_GRID_GETTERS = {
    "max_dim_x": lambda g: g.max_dim_x,
    "max_dim_y": lambda g: g.max_dim_y,
    "max_dim_z": lambda g: g.max_dim_z,
    "max_num_local_z_columns": lambda g: g.max_num_local_z_columns,
    "max_local_z_length": lambda g: g.max_local_z_length,
    "processing_unit": lambda g: int(g.processing_unit),
    "max_num_threads": lambda g: g.max_num_threads,
    "device_id": lambda g: 0,
    "num_shards": lambda g: g.num_shards,
    "has_mesh": lambda g: int(g.mesh is not None),
    # p1 of a 2-D pencil mesh, 0 for local/1-D grids (drives copy fidelity)
    "mesh_p1": lambda g: (
        int(g.mesh.shape["fft"])
        if g.mesh is not None and "fft2" in g.mesh.axis_names
        else 0
    ),
    "exchange_type": lambda g: int(g.exchange_type),
}


def transform_get(t: Transform, name: str) -> int:
    return int(_TRANSFORM_GETTERS[name](t))


def grid_get(g: Grid, name: str) -> int:
    return int(_GRID_GETTERS[name](g))


def transform_set_execution_mode(t: Transform, mode: int) -> None:
    t.set_execution_mode(ExecType(mode))


# ---- execution --------------------------------------------------------------


def _freq_from_buffer(t: Transform, buf) -> np.ndarray:
    n = t.num_local_elements
    vals = np.frombuffer(buf, dtype=_real_dtype(t), count=2 * n)
    return vals.view(_complex_dtype(t))


def _space_size_reals(t: Transform) -> int:
    n = t.local_slice_size
    return n if int(t.transform_type) == int(TransformType.R2C) else 2 * n



def _write_space(t: Transform, out, buf) -> None:
    """Copy a space-domain result into a caller buffer (R2C: real, C2C:
    complex-interleaved)."""
    dst = np.frombuffer(buf, dtype=_real_dtype(t), count=_space_size_reals(t))
    if int(t.transform_type) == int(TransformType.R2C):
        dst[:] = np.asarray(out, dtype=_real_dtype(t)).ravel()
    else:
        dst.view(_complex_dtype(t))[:] = np.asarray(out).ravel()


def _read_space(t: Transform, buf) -> np.ndarray:
    """View a caller space-domain buffer as the (Z, Y, X) slab."""
    flat = np.frombuffer(buf, dtype=_real_dtype(t), count=_space_size_reals(t))
    if int(t.transform_type) == int(TransformType.R2C):
        return flat.reshape(t.dim_z, t.dim_y, t.dim_x)
    return flat.view(_complex_dtype(t)).reshape(t.dim_z, t.dim_y, t.dim_x)


def _write_freq(t: Transform, vals, buf) -> None:
    """Copy packed frequency values into a caller buffer."""
    n = t.num_local_elements
    dst = np.frombuffer(buf, dtype=_real_dtype(t), count=2 * n)
    dst.view(_complex_dtype(t))[:] = np.asarray(vals).ravel()


def transform_backward(t: Transform, values_buf, space_out_buf) -> None:
    """Freq -> space; writes the (Z, Y, X) slab into ``space_out_buf``."""
    _write_space(t, t.backward(_freq_from_buffer(t, values_buf)), space_out_buf)


def transform_forward(t: Transform, space_buf, values_out_buf, scaling: int) -> None:
    """Space -> freq; ``space_buf`` of None reads the retained space buffer
    (the reference's pointer-free forward overload)."""
    space = None if space_buf is None else _read_space(t, space_buf)
    _write_freq(t, t.forward(space, ScalingType(scaling)), values_out_buf)


# ---- multi-transform --------------------------------------------------------


def multi_backward(transforms, values_bufs, space_out_bufs) -> None:
    """Pipelined batched backward (reference: include/spfft/multi_transform.hpp:48)."""
    values = [_freq_from_buffer(t, b) for t, b in zip(transforms, values_bufs)]
    outs = multi_transform_backward(list(transforms), values)
    for t, out, buf in zip(transforms, outs, space_out_bufs):
        _write_space(t, out, buf)


def multi_forward(transforms, space_bufs, values_out_bufs, scalings) -> None:
    spaces = [None if b is None else _read_space(t, b) for t, b in zip(transforms, space_bufs)]
    results = multi_transform_forward(
        list(transforms), spaces, [ScalingType(s) for s in scalings]
    )
    for t, vals, buf in zip(transforms, results, values_out_bufs):
        _write_freq(t, vals, buf)


# ---- distributed execution --------------------------------------------------

_DIST_GETTERS = {
    "dim_x": lambda t: t.dim_x,
    "dim_y": lambda t: t.dim_y,
    "dim_z": lambda t: t.dim_z,
    "num_shards": lambda t: t.num_shards,
    "num_global_elements": lambda t: t.num_global_elements,
    "global_size": lambda t: t.global_size,
    "transform_type": lambda t: int(t.transform_type),
    "processing_unit": lambda t: int(t.processing_unit),
    "exchange_type": lambda t: int(t.exchange_type),
    "exchange_wire_bytes": lambda t: t.exchange_wire_bytes(),
    "exchange_rounds": lambda t: t.exchange_rounds(),
    "execution_mode": lambda t: int(t.execution_mode()),
}

_DIST_SHARD_GETTERS = {
    "local_z_length": lambda t, r: t.local_z_length(r),
    "local_z_offset": lambda t, r: t.local_z_offset(r),
    "local_y_length": lambda t, r: t.local_y_length(r),
    "local_y_offset": lambda t, r: t.local_y_offset(r),
    "local_slice_size": lambda t, r: t.local_slice_size(r),
    "num_local_elements": lambda t, r: t.num_local_elements(r),
}


def dist_transform_get(t, name: str) -> int:
    return int(_DIST_GETTERS[name](t))


def dist_transform_get_shard(t, name: str, shard: int) -> int:
    return int(_DIST_SHARD_GETTERS[name](t, shard))


def _dist_dtypes(t):
    rt = np.dtype(t.dtype)
    return rt, np.dtype(np.complex128 if rt == np.float64 else np.complex64)


def _dist_values_view(t, buf):
    rt, ct = _dist_dtypes(t)
    total = t.num_global_elements
    return np.frombuffer(buf, dtype=rt, count=2 * total).view(ct)


def _dist_space_reals(t) -> int:
    n = t.global_size
    return n if int(t.transform_type) == int(TransformType.R2C) else 2 * n


def dist_backward(t, values_buf, space_out_buf) -> None:
    """Shard-major concatenated freq values -> global (Z, Y, X) space array."""
    rt, ct = _dist_dtypes(t)
    vals = _dist_values_view(t, values_buf)
    vps, off = [], 0
    for r in range(t.num_shards):
        n = t.num_local_elements(r)
        vps.append(vals[off : off + n])
        off += n
    out = t.backward(vps)
    dst = np.frombuffer(space_out_buf, dtype=rt, count=_dist_space_reals(t))
    if int(t.transform_type) == int(TransformType.R2C):
        dst[:] = np.asarray(out, dtype=rt).ravel()
    else:
        dst.view(ct)[:] = np.asarray(out).ravel()


def dist_forward(t, space_buf, values_out_buf, scaling: int) -> None:
    """Global (Z, Y, X) space array (or None for the retained buffer) ->
    shard-major concatenated freq values."""
    rt, ct = _dist_dtypes(t)
    if space_buf is None:
        space = None
    else:
        flat = np.frombuffer(space_buf, dtype=rt, count=_dist_space_reals(t))
        if int(t.transform_type) != int(TransformType.R2C):
            flat = flat.view(ct)
        space = flat.reshape(t.dim_z, t.dim_y, t.dim_x)
    res = t.forward(space, ScalingType(scaling))
    dst = _dist_values_view(t, values_out_buf)
    # frombuffer of a readonly memoryview is readonly; the C side passes a
    # writable view for outputs, so this is writable
    off = 0
    for r, vals in enumerate(res):
        n = t.num_local_elements(r)
        dst[off : off + n] = np.asarray(vals)
        off += n
