"""Distributed ``Transform`` public API.

Parity with the reference's distributed transforms (Grid MPI ctor + create_transform,
reference: include/spfft/grid.hpp:89-141, include/spfft/transform.hpp:102-131), under
a single-controller JAX model: one process drives all shards of a
``jax.sharding.Mesh``. Per-shard quantities (the reference's per-rank values) are
lists indexed by shard.
"""
from __future__ import annotations

import numpy as np

import jax

from . import faults, obs, timing
from .errors import InvalidParameterError, MPIError
from .sync import fence
from .grid import Grid
from .parallel.execution import DistributedExecution
from .parameters import distribute_triplets, make_distributed_parameters
from .types import ExchangeType, ExecType, IndexFormat, ProcessingUnit, ScalingType, TransformType


class DistributedTransform:
    """A sparse 3D FFT plan sharded over a mesh axis.

    ``indices`` is either a list of per-shard triplet arrays (the reference's
    per-rank local indices) or one global triplet array, which is then distributed
    by whole z-sticks with balanced value counts (:func:`distribute_triplets`).
    """

    def __init__(
        self,
        processing_unit,
        transform_type,
        dim_x,
        dim_y,
        dim_z,
        indices,
        *,
        mesh=None,
        local_z_lengths=None,
        exchange_type: ExchangeType = ExchangeType.DEFAULT,
        index_format: IndexFormat = IndexFormat.TRIPLETS,
        grid: Grid | None = None,
        dtype=None,
        engine: str = "auto",
        precision="highest",
        policy: str | None = None,
        guard: bool | None = None,
        verify=None,
        overlap: int | None = None,
        fuse=None,
    ):
        if IndexFormat(index_format) != IndexFormat.TRIPLETS:
            raise InvalidParameterError("only SPFFT_INDEX_TRIPLETS is supported")
        if mesh is None and grid is not None:
            mesh = grid.mesh
        if mesh is None:
            raise InvalidParameterError("distributed transform requires a mesh")
        from .parallel.mesh import fft_mesh_size, is_pencil2_mesh

        pencil2 = is_pencil2_mesh(mesh)
        num_shards = fft_mesh_size(mesh)

        if isinstance(indices, (list, tuple)):
            indices_per_shard = [np.asarray(t).reshape(-1, 3) for t in indices]
        elif pencil2:
            # Column-local stick placement (x-groups whole per shard-column)
            # makes the pencil engines' exchange A column-diagonal — see
            # distribute_triplets(layout=...).
            ax = dict(zip(mesh.axis_names, mesh.devices.shape))
            indices_per_shard = distribute_triplets(
                np.asarray(indices), num_shards, int(dim_y),
                layout=(int(ax["fft"]), int(ax["fft2"])), dim_x=int(dim_x),
            )
        else:
            indices_per_shard = distribute_triplets(
                np.asarray(indices), num_shards, int(dim_y)
            )

        self._processing_unit = ProcessingUnit(processing_unit)
        self._grid = grid
        self._mesh = mesh
        self._platform = str(mesh.devices.flat[0].platform)
        self._exec_mode = ExecType.SYNCHRONOUS
        self._params = make_distributed_parameters(
            TransformType(transform_type),
            dim_x,
            dim_y,
            dim_z,
            indices_per_shard,
            local_z_lengths,
        )

        if grid is not None:
            p = self._params
            if (
                p.dim_x > grid.max_dim_x
                or p.dim_y > grid.max_dim_y
                or p.dim_z > grid.max_dim_z
            ):
                raise InvalidParameterError("transform dimensions exceed grid maxima")
            if p.max_num_sticks > grid.max_num_local_z_columns:
                raise InvalidParameterError("more z-columns than grid maximum")
            if p.max_local_z_length > grid.max_local_z_length:
                raise InvalidParameterError("local z length exceeds grid maximum")
            if exchange_type == ExchangeType.DEFAULT:
                exchange_type = grid.exchange_type

        if dtype is None:
            dtype = np.float64 if jax.config.read("jax_enable_x64") else np.float32
        self._real_dtype = np.dtype(dtype)

        from .parallel.policy import resolve_overlap_chunks, resolve_policy

        self._policy = resolve_policy(policy)
        # Exchange-overlap chunk count (the OVERLAPPED discipline): explicit
        # argument or SPFFT_TPU_OVERLAP_CHUNKS; under the TUNED policy an
        # unset knob is owned by the autotuner below (overlap candidates are
        # trialed with the disciplines and the measured pick lands in
        # wisdom). Engines clamp the request to what their geometry supports.
        self._overlap_requested = overlap
        overlap_chunks = resolve_overlap_chunks(overlap)
        # Guard mode + degradation record, mirroring the local Transform
        # (spfft_tpu.faults): fallbacks taken during construction land on
        # _degradations and surface in the plan card.
        self._guard = faults.guard_enabled(guard)
        self._degradations: list = []
        self._tuning = None
        # Fusion request (spfft_tpu.ir): engines resolve kwarg-else-env
        self._fuse = fuse
        # Run ID (spfft_tpu.obs.trace): the correlation key joining this
        # plan's card, metrics and flight-recorder events; the "plan"
        # operation span keeps it active so tuning trials, ladder rungs and
        # fault injections during construction stamp it.
        self._run_id = obs.trace.new_run_id()
        with obs.trace.operation(
            "plan", run_id=self._run_id, kind="distributed"
        ):
            if (
                ExchangeType(exchange_type) == ExchangeType.DEFAULT
                and self._policy == "tuned"
            ):
                # TUNED policy (spfft_tpu.tuning): resolve DEFAULT empirically —
                # wisdom-store hit, else on-device trials of the candidate
                # disciplines on THIS geometry/mesh/dtype, else the model policy
                # (CPU-only hosts / corrupt store). Trial plans are this same
                # constructor with explicit disciplines and the model policy, so
                # tuning cannot recurse. The record lands on the plan card.
                from . import tuning

                p = self._params

                def build(cand):
                    return DistributedTransform(
                        self._processing_unit,
                        p.transform_type,
                        p.dim_x,
                        p.dim_y,
                        p.dim_z,
                        [t.copy() for t in indices_per_shard],
                        mesh=mesh,
                        local_z_lengths=np.asarray(p.local_z_lengths).copy(),
                        exchange_type=ExchangeType[cand["exchange_type"]],
                        dtype=self._real_dtype,
                        engine=engine,
                        precision=precision,
                        policy="default",
                        overlap=cand.get("overlap", 1),
                    )

                with faults.collecting(self._degradations):
                    exchange_type, overlap_chunks, self._tuning = (
                        tuning.tuned_exchange(
                            p, mesh, self._real_dtype, engine, precision,
                            pencil2, build, overlap=overlap,
                        )
                    )
            elif ExchangeType(exchange_type) == ExchangeType.DEFAULT and not pencil2:
                # Measured auto-policy (parallel/policy.py): pick the discipline
                # from the plan's exact wire volumes + round counts + the
                # backend's one-shot ragged-a2a support (probed compile-only,
                # cached, and only when the answer depends on it). The reference
                # instead hardwires DEFAULT = COMPACT_BUFFERED
                # (grid_internal.cpp:176-179); ported callers who want that exact
                # behavior pass COMPACT_BUFFERED explicitly. 2-D pencil plans
                # resolve DEFAULT inside the engine (pencil2.py
                # _resolve_pencil2_default — the x-group strategy and the
                # discipline are chosen together there).
                from .parallel.policy import resolve_default_for_plan

                exchange_type = resolve_default_for_plan(
                    self._params, mesh, self._real_dtype
                )

            from .ops.fft import resolve_precision

            resolve_precision(precision)  # validate up front on every engine path
            self._precision = precision

            # Engine selection mirrors the local Transform: the MXU engine (matmul
            # DFT stages + lane-copy value plans) wins on accelerator meshes; the
            # XLA engine (jnp.fft + scatter) wins on CPU meshes where pocketfft is
            # the fast path. Selected by the platform the MESH lives on, not the
            # process default backend. The decomposition (1-D slab vs 2-D pencil)
            # comes from the mesh shape; the engine knob picks the compute path.
            if engine == "auto":
                engine = "xla" if mesh.devices.flat[0].platform == "cpu" else "mxu"
            if engine not in ("xla", "mxu"):
                raise InvalidParameterError(f"unknown engine {engine!r}")

            def _build(which: str):
                """Construct the execution engine for ``which`` (fault site
                ``engine.compile`` guards the MXU lowerings — ladder rung 1)."""
                if pencil2:
                    if which == "mxu":
                        from .parallel.pencil2_mxu import MxuPencil2Execution

                        faults.site("engine.compile")
                        return (
                            MxuPencil2Execution(
                                self._params, self._real_dtype, mesh,
                                exchange_type, precision,
                                overlap=overlap_chunks, fuse=fuse,
                            ),
                            "pencil2-mxu",
                        )
                    from .parallel.pencil2 import Pencil2Execution

                    return (
                        Pencil2Execution(
                            self._params, self._real_dtype, mesh, exchange_type,
                            overlap=overlap_chunks, fuse=fuse,
                        ),
                        "pencil2",
                    )
                if which == "mxu":
                    from .parallel.execution_mxu import MxuDistributedExecution

                    faults.site("engine.compile")
                    return (
                        MxuDistributedExecution(
                            self._params, self._real_dtype, mesh, exchange_type,
                            precision, overlap=overlap_chunks, fuse=fuse,
                        ),
                        "mxu",
                    )
                return (
                    DistributedExecution(
                        self._params, self._real_dtype, mesh, exchange_type,
                        overlap=overlap_chunks, fuse=fuse,
                    ),
                    "xla",
                )

            # Degradation ladder rung 1 (distributed): an MXU engine that fails
            # to lower/compile falls back to the jnp.fft engine over the same
            # mesh and discipline; a failure with no rung below it (the jnp.fft
            # engine or the exchange machinery itself — fault site
            # exchange.build) raises typed MPIError.
            with faults.collecting(self._degradations):
                try:
                    self._exec, self._engine = _build(engine)
                except faults.ENGINE_BUILD_ERRORS as e:
                    if engine != "mxu":
                        raise MPIError(
                            f"distributed engine construction failed: {e}"
                        ) from e
                    faults.engine_fallback(
                        "pencil2-mxu" if pencil2 else "mxu",
                        "pencil2" if pencil2 else "xla",
                        faults.summarize(e),
                    )
                    try:
                        self._exec, self._engine = _build("xla")
                    except faults.ENGINE_BUILD_ERRORS as e2:
                        raise MPIError(
                            f"distributed engine construction failed: {e2}"
                        ) from e2
            obs.trace.event(
                "decision",
                what="engine",
                choice=self._engine,
                policy=self._policy,
            )
            obs.trace.event(
                "decision",
                what="exchange",
                choice=self.exchange_type.name,
                overlap=self.overlap_chunks,
            )
        self._space_data = None
        # Plan-constant; cached lazily so the metrics-off path never pays the
        # per-step numpy accounting in exchange_wire_bytes().
        self._wire_bytes_cache = None
        # Self-verification (spfft_tpu.verify), mirroring the local Transform.
        # Single-controller meshes only: the reference rung and the checks
        # need every shard's data host-side, which a multi-process mesh
        # cannot provide (remote shards are None by contract).
        from .verify import resolve_mode

        self._verify_mode = resolve_mode(verify)
        self._verifier = None
        self._reference_exec = None
        if self._verify_mode != "off":
            from .parallel.execution import mesh_process_span

            span = mesh_process_span(mesh)
            if span > 1:
                raise InvalidParameterError(
                    f"verify={verify!r} requires a single-controller mesh, "
                    f"but this {'x'.join(str(s) for s in mesh.devices.shape)} "
                    f"mesh (axes {tuple(mesh.axis_names)}) spans {span} "
                    "processes: the ABFT checks and the reference recovery "
                    "rung need every shard's data host-side, and remote "
                    "shards are None by the per-rank contract. Run "
                    "verification on each host's local plans instead (see "
                    'docs/details.md "Multi-host serving & host loss").'
                )
            from .verify import Supervisor

            self._verifier = Supervisor(self, self._verify_mode)

    # ---- transforms -----------------------------------------------------------

    def backward(self, values, output_location: ProcessingUnit | None = None):
        """Per-shard packed freq values -> global (dim_z, dim_y, dim_x) space array.

        ``values``: list of per-shard complex arrays (lengths must match
        ``num_local_elements_per_shard``).
        """
        obs.counter("transforms_total", direction="backward", engine=self._engine).inc()
        plat = self._platform
        # "execute" operation span (spfft_tpu.obs.trace): runs under the
        # plan's run ID, so the trace of this call joins the plan card.
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="backward"
        ), timing.scoped("backward"):
            if self._guard:
                faults.check_array(
                    list(values), check="backward input", platform=plat
                )
            if self._verifier is not None:
                # supervised path (spfft_tpu.verify): check -> retry ->
                # jnp.fft reference -> typed VerificationError
                return self._verifier.backward(values)
            return self._backward_attempt(values)

    def _backward_attempt(self, values):
        """One full backward execution (stage, exchange dispatch, fence,
        finalize, guard post-checks) — the unit the verify supervisor
        re-executes; identical to the whole unsupervised path."""
        plat = self._platform
        out = self._dispatch_backward(values)
        if self._exec_mode == ExecType.SYNCHRONOUS:
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="backward"
            ), faults.typed_execution(plat, "backward wait"):
                fence(out)
        with timing.scoped("output staging"):
            result = self._finalize_backward(out)
        if self._guard:
            # single-controller meshes return the global slab; multi-
            # process meshes return per-shard local z-slabs (unpad_space
            # contract) whose shapes differ per shard — finite-scan only
            faults.check_array(
                result,
                check="backward output",
                platform=plat,
                shape=None
                if isinstance(result, (list, tuple))
                else (self.dim_z, self.dim_y, self.dim_x),
            )
        return result

    def _record_wire_bytes(self):
        """Count the exchange's per-dispatch wire bytes (plan-constant) into
        the run registry; a no-op when metrics are disabled."""
        if not obs.is_enabled():
            return
        if self._wire_bytes_cache is None:
            self._wire_bytes_cache = self.exchange_wire_bytes()
        obs.counter("exchange_wire_bytes_total", engine=self._engine).inc(
            self._wire_bytes_cache
        )

    def _dispatch_backward(self, values):
        """Stage per-shard inputs and enqueue the backward pipeline without
        waiting (split-phase hook used by multi-transform pipelining)."""
        with timing.scoped("input staging"):
            pair = self._exec.pad_values(values)
        self._record_wire_bytes()
        with timing.scoped("dispatch"), obs.phase_timer(
            "dispatch_seconds", direction="backward"
        ), faults.typed_execution(self._platform, "backward dispatch"):
            out = self._exec.backward_pair(*pair)
            out = faults.site("engine.execute", payload=out)
        self._space_data = out
        return out

    def backward_pair(self, values_re, values_im):
        """Device-side backward on sharded (P, V_max) pairs; no host transfers."""
        out = self._exec.backward_pair(values_re, values_im)
        self._space_data = out
        return out

    def forward(
        self,
        space=None,
        scaling: ScalingType = ScalingType.NONE,
        input_location: ProcessingUnit | None = None,
    ):
        """Space -> per-shard packed freq values (list of complex arrays)."""
        obs.counter("transforms_total", direction="forward", engine=self._engine).inc()
        plat = self._platform
        with obs.trace.operation(
            "execute", run_id=self._run_id, direction="forward"
        ), timing.scoped("forward"):
            if self._guard and space is not None:
                faults.check_array(
                    np.asarray(space), check="forward input", platform=plat
                )
            if self._verifier is not None:
                return self._verifier.forward(space, scaling)
            return self._forward_attempt(space, scaling)

    def _forward_attempt(self, space, scaling):
        """One full forward execution — the re-executable unit of the verify
        supervisor (mirrors :meth:`_backward_attempt`)."""
        plat = self._platform
        pair = self._dispatch_forward(space, scaling)
        if self._exec_mode == ExecType.SYNCHRONOUS:
            with timing.scoped("wait"), obs.phase_timer(
                "wait_seconds", direction="forward"
            ), faults.typed_execution(plat, "forward wait"):
                fence(pair)
        with timing.scoped("output staging"):
            result = self._finalize_forward(pair)
        if self._guard:
            faults.check_array(
                result, check="forward output", platform=plat
            )
        return result

    def _dispatch_forward(self, space, scaling):
        """Stage the space-domain input (or reuse the retained slabs) and enqueue
        the forward pipeline without waiting."""
        if space is None:
            if self._space_data is None:
                raise InvalidParameterError(
                    "no space domain data: run backward first or pass an array"
                )
        else:
            with timing.scoped("input staging"):
                self._retain_space(space)
        if self._exec.is_r2c:
            re, im = self._space_data, None
        else:
            re, im = self._space_data
        self._record_wire_bytes()
        with timing.scoped("dispatch"), obs.phase_timer(
            "dispatch_seconds", direction="forward"
        ), faults.typed_execution(self._platform, "forward dispatch"):
            pair = self._exec.forward_pair(re, im, ScalingType(scaling))
            return faults.site("engine.execute", payload=pair)

    def _retain_space(self, space) -> None:
        """Stage a host global space array as the retained sharded buffer —
        the staging half of :meth:`_dispatch_forward`, also used by the
        verify supervisor to replace a failed primary result with the
        verified recovery."""
        re, im = self._exec.pad_space(np.asarray(space))
        self._space_data = re if self._exec.is_r2c else (re, im)

    def forward_pair(self, scaling: ScalingType = ScalingType.NONE):
        """Device-side forward over the retained sharded space buffer."""
        if self._space_data is None:
            raise InvalidParameterError("no space domain data: run backward first")
        if self._exec.is_r2c:
            return self._exec.forward_pair(self._space_data, None, ScalingType(scaling))
        re, im = self._space_data
        return self._exec.forward_pair(re, im, ScalingType(scaling))

    # ---- batch-fused execution (SPFFT_TPU_BATCH_FUSE, spfft_tpu.ir) -----------

    def backward_batch(self, values_batch, *, fallback: bool = True):
        """Execute B same-plan backward transforms as ONE batched shard_map
        program (the local :meth:`Transform.backward_batch` contract on a
        mesh): per-request padded value pairs stack along a batch axis after
        the mesh block dim — ``(P, B, V_max)`` — and the whole batch pays one
        dispatch per direction. Same degradation rung (``batch_fuse_failed``
        → per-request loop; ``fallback=False`` returns ``None``); verified
        plans run per-request under their supervisor. Single-controller
        meshes only (the batched staging assembles global stacks)."""
        values_batch = list(values_batch)
        if not values_batch:
            return []
        if self._verifier is not None:
            return [self.backward(v) for v in values_batch]
        plat = self._platform
        out = None
        if self._exec._ir.batch_available():
            with obs.trace.operation(
                "execute", run_id=self._run_id, direction="backward"
            ), timing.scoped("backward"):
                if self._guard:
                    for values in values_batch:
                        faults.check_array(
                            list(values), check="backward input",
                            platform=plat,
                        )
                with timing.scoped("input staging"):
                    staged = [self._exec.pad_values(v) for v in values_batch]
                    re = self._exec.stack_staged(
                        [p[0] for p in staged], self._exec.value_sharding
                    )
                    im = self._exec.stack_staged(
                        [p[1] for p in staged], self._exec.value_sharding
                    )
                with timing.scoped("dispatch"), faults.typed_execution(
                    plat, "backward dispatch"
                ):
                    out = self._exec.backward_pair_batch(re, im)
                if out is not None:
                    # count ONLY on the batched arm: the fallback loop below
                    # re-enters backward(), which counts (and traces) itself
                    obs.counter(
                        "transforms_total", direction="backward",
                        engine=self._engine,
                    ).inc(len(values_batch))
                    with timing.scoped("wait"), faults.typed_execution(
                        plat, "backward wait"
                    ):
                        fence(out)
                    with timing.scoped("output staging"):
                        results = [
                            self._exec.unpad_space(_batch_slice(out, b))
                            for b in range(len(values_batch))
                        ]
                    if self._guard:
                        for result in results:
                            # single-controller meshes return global slabs;
                            # finite-scan plus shape, the per-request
                            # backward contract
                            faults.check_array(
                                result, check="backward output",
                                platform=plat,
                                shape=(self.dim_z, self.dim_y, self.dim_x),
                            )
                    return results
        if not fallback:
            return None
        return [self.backward(v) for v in values_batch]

    def forward_batch(
        self,
        spaces,
        scaling: ScalingType = ScalingType.NONE,
        *,
        fallback: bool = True,
    ):
        """Batched forward over explicit global space arrays: B ``(Z, Y,
        X)`` slabs -> B per-shard packed value lists through one batched
        shard_map program (one ``scaling`` for the whole batch)."""
        spaces = list(spaces)
        if not spaces:
            return []
        if self._verifier is not None:
            return [self.forward(s, scaling) for s in spaces]
        plat = self._platform
        out = None
        if self._exec._ir.batch_available():
            with obs.trace.operation(
                "execute", run_id=self._run_id, direction="forward"
            ), timing.scoped("forward"):
                if self._guard:
                    for s in spaces:
                        faults.check_array(
                            np.asarray(s), check="forward input",
                            platform=plat,
                        )
                with timing.scoped("input staging"):
                    staged = [
                        self._exec.pad_space(np.asarray(s)) for s in spaces
                    ]
                    re = self._exec.stack_staged(
                        [p[0] for p in staged], self._exec.space_sharding
                    )
                    im = (
                        None
                        if self._exec.is_r2c
                        else self._exec.stack_staged(
                            [p[1] for p in staged], self._exec.space_sharding
                        )
                    )
                with timing.scoped("dispatch"), faults.typed_execution(
                    plat, "forward dispatch"
                ):
                    out = self._exec.forward_pair_batch(
                        re, im, ScalingType(scaling)
                    )
                if out is not None:
                    # count ONLY on the batched arm (see backward_batch)
                    obs.counter(
                        "transforms_total", direction="forward",
                        engine=self._engine,
                    ).inc(len(spaces))
                    with timing.scoped("wait"), faults.typed_execution(
                        plat, "forward wait"
                    ):
                        fence(out)
                    with timing.scoped("output staging"):
                        results = [
                            self._exec.unpad_values(_batch_slice(out, b))
                            for b in range(len(spaces))
                        ]
                    if self._guard:
                        for result in results:
                            faults.check_array(
                                result, check="forward output", platform=plat
                            )
                    return results
        if not fallback:
            return None
        return [self.forward(s, scaling) for s in spaces]

    def _finalize_backward(self, out):
        """Host-side completion of a dispatched backward (fetch + unpad)."""
        return self._exec.unpad_space(out)

    def _finalize_forward(self, pair):
        """Host-side completion of a dispatched forward (fetch + unpad)."""
        return self._exec.unpad_values(pair)

    # ---- verification hooks (spfft_tpu.verify) --------------------------------

    def _per_shard_triplets(self) -> list:
        """Storage-order triplet rows per shard, aligned with each shard's
        packed value order (the clone()/verify decode)."""
        from .transform import storage_triplets_from

        p = self._params
        return [
            storage_triplets_from(
                p.value_indices[r, : int(p.num_values_per_shard[r])],
                p.stick_x_all[r],
                p.stick_y_all[r],
                p.dim_z,
            )
            for r in range(p.num_shards)
        ]

    def _verify_triplets(self) -> np.ndarray:
        """Concatenated storage-order triplets in shard order — aligned with
        the concatenation of the per-shard packed value vectors."""
        return np.concatenate(self._per_shard_triplets(), axis=0)

    def _reference_engine(self):
        """Lazily built LOCAL ``jnp.fft`` reference plan over the same global
        geometry (every stick of every shard): the verify supervisor's
        demotion rung. Single-device, exchange-free — a wedged collective or
        a corrupting accelerator path cannot touch it — and single-controller
        meshes hand backward the same global ``(Z, Y, X)`` slab this plan's
        own ``unpad_space`` returns, so results are directly comparable."""
        if self._reference_exec is None:
            from .execution import LocalExecution
            from .parameters import make_local_parameters

            p = self._params
            params = make_local_parameters(
                p.transform_type,
                p.dim_x,
                p.dim_y,
                p.dim_z,
                self._verify_triplets(),
            )
            self._reference_exec = LocalExecution(
                params, self._real_dtype, device=self._mesh.devices.flat[0]
            )
        return self._reference_exec

    def _reference_backward(self, values):
        """Reference backward: per-shard value lists concatenate in shard
        order and run through the local jnp.fft plan -> global slab."""
        ref = self._reference_engine()
        flat = np.concatenate([np.asarray(v).reshape(-1) for v in values])
        out = ref.backward(flat)
        fence(out)
        return ref.fetch(out) if self._exec.is_r2c else ref.fetch_space_complex(out)

    def _reference_forward(self, space, scaling):
        """Reference forward: global space slab -> packed values, split back
        into the per-shard list contract."""
        from .execution import from_pair

        ref = self._reference_engine()
        pair = ref.forward(
            np.asarray(space).reshape(self.dim_z, self.dim_y, self.dim_x),
            ScalingType(scaling),
        )
        fence(pair)
        flat = from_pair(pair)
        splits = np.cumsum(
            [int(n) for n in self._params.num_values_per_shard]
        )[:-1]
        return [np.asarray(part) for part in np.split(flat, splits)]

    def clone(self) -> "DistributedTransform":
        """Create an independent distributed transform with identical layout.

        Reference: include/spfft/transform.hpp:133 — clone deep-copies the
        grid so the clone never shares buffers; here the compiled pipelines
        and retained space buffers are per-object already, so a clone is a
        fresh plan over the same mesh/shard geometry and engine."""
        p = self._params
        per_shard = self._per_shard_triplets()
        engine = "xla" if self._engine in ("xla", "pencil2") else "mxu"
        return DistributedTransform(
            self._processing_unit,
            p.transform_type,
            p.dim_x,
            p.dim_y,
            p.dim_z,
            per_shard,
            mesh=self._mesh,
            local_z_lengths=np.asarray(p.local_z_lengths).copy(),
            exchange_type=self.exchange_type,
            grid=self._grid,
            dtype=self._real_dtype,
            engine=engine,
            precision=self._precision,
            guard=self._guard,
            verify=self._verify_mode,
            overlap=self.overlap_chunks,
            fuse=self._fuse,
        )

    @property
    def fused(self) -> bool:
        """Whether this plan executes through the IR-fused single shard_map
        program per direction (see :attr:`Transform.fused`)."""
        return bool(self._exec._ir.fused)

    def space_domain_data(self, processing_unit: ProcessingUnit | None = None):
        """Global trimmed space-domain array of the most recent result.

        Same location semantics as :meth:`Transform.space_domain_data`:
        ``ProcessingUnit.GPU`` returns the device-resident sharded
        (P, L, Y, X) buffer (pair for C2C) without host transfers."""
        if self._space_data is None:
            raise InvalidParameterError("no space domain data available yet")
        if processing_unit is not None:
            from .transform import _validate_data_location

            if _validate_data_location(processing_unit) == ProcessingUnit.GPU:
                return self._space_data
        return self._exec.unpad_space(self._space_data)

    def space_domain_data_local(self, shard: int):
        """Shard-local space block — the reference's per-rank
        ``space_domain_data`` pointer. 1-D meshes: a z-slab
        (local_z_length(shard), dim_y, dim_x); 2-D pencil meshes: a z×y block
        (local_z_length(shard), local_y_length(shard), dim_x). Fetches only
        that shard's block."""
        if self._space_data is None:
            raise InvalidParameterError("no space domain data available yet")
        l = self.local_z_length(shard)
        ly = self.local_y_length(shard)
        if self._exec.is_r2c:
            return np.asarray(self._space_data[shard])[:l, :ly]
        re, im = self._space_data
        return (
            np.asarray(re[shard])[:l, :ly] + 1j * np.asarray(im[shard])[:l, :ly]
        )

    # ---- introspection --------------------------------------------------------

    def report(self, *, include_compiled: bool = False) -> dict:
        """Plan card: the machine-readable record of this plan's decisions —
        grid geometry, sparsity, engine, decomposition, and the exchange
        discipline's wire bytes / rounds / transport PLUS the cost-model table
        of the alternatives the DEFAULT policy weighed (chosen and rejected,
        ``parallel/policy.py`` accounting). ``include_compiled=True``
        additionally compiles the backward pipeline and adds compile wall
        time, memory analysis and HLO op-class counts. See
        :mod:`spfft_tpu.obs`."""
        return obs.plan_card(self, include_compiled=include_compiled)

    # ---- accessors ------------------------------------------------------------

    @property
    def transform_type(self) -> TransformType:
        return self._params.transform_type

    @property
    def dim_x(self) -> int:
        return self._params.dim_x

    @property
    def dim_y(self) -> int:
        return self._params.dim_y

    @property
    def dim_z(self) -> int:
        return self._params.dim_z

    @property
    def num_shards(self) -> int:
        return self._params.num_shards

    @property
    def mesh(self):
        return self._mesh

    # Per-shard space layout. The 2-D pencil engine carries its own z×y split
    # (the 1-D slab metadata in params does not describe it), so the engine is
    # consulted when it defines the accessor.

    def local_z_length(self, shard: int) -> int:
        if hasattr(self._exec, "local_z_length"):
            return self._exec.local_z_length(shard)
        return int(self._params.local_z_lengths[shard])

    def local_z_offset(self, shard: int) -> int:
        if hasattr(self._exec, "local_z_offset"):
            return self._exec.local_z_offset(shard)
        return int(self._params.z_offsets[shard])

    def local_y_length(self, shard: int) -> int:
        """dim_y on 1-D meshes; the shard's y-slab length on 2-D pencil meshes."""
        if hasattr(self._exec, "local_y_length"):
            return self._exec.local_y_length(shard)
        return self.dim_y

    def local_y_offset(self, shard: int) -> int:
        if hasattr(self._exec, "local_y_offset"):
            return self._exec.local_y_offset(shard)
        return 0

    def local_slice_size(self, shard: int) -> int:
        return self.dim_x * self.local_y_length(shard) * self.local_z_length(shard)

    def num_local_elements(self, shard: int) -> int:
        return int(self._params.num_values_per_shard[shard])

    @property
    def num_global_elements(self) -> int:
        return int(self._params.num_values_per_shard.sum())

    @property
    def global_size(self) -> int:
        return self._params.total_size

    @property
    def processing_unit(self) -> ProcessingUnit:
        return self._processing_unit

    @property
    def exchange_type(self) -> ExchangeType:
        return self._exec.exchange_type

    @property
    def overlap_chunks(self) -> int:
        """Effective exchange-overlap chunk count of the compiled pipelines
        (the OVERLAPPED discipline): 1 means bulk-synchronous. May be lower
        than requested — engines clamp to the chunkable extent and the
        ragged disciplines (whose chains already round-pipeline) ignore it."""
        return int(getattr(self._exec, "_overlap", 1))

    def exchange_wire_bytes(self) -> int:
        """Off-shard interconnect bytes per slab<->pencil repartition under the
        plan's exchange discipline (see PaddingHelpers.exchange_wire_bytes).
        Bytes only — pair with :meth:`exchange_rounds` for the latency side."""
        return self._exec.exchange_wire_bytes()

    def exchange_rounds(self) -> int:
        """Sequential collective rounds per repartition under the plan's
        exchange discipline and active transport: 1 for the padded all_to_all
        and the one-shot UNBUFFERED ragged exchange, P-1 for the COMPACT
        ppermute chain (and UNBUFFERED's chain-transport fallback on backends
        without the ragged-all-to-all HLO). Together with
        :meth:`exchange_wire_bytes` this is the bytes-vs-latency picture a
        discipline choice trades off (see BASELINE.md's measured comparison)."""
        return self._exec.exchange_rounds()

    @property
    def dtype(self) -> np.dtype:
        return self._real_dtype

    @property
    def grid(self) -> Grid | None:
        return self._grid

    def execution_mode(self) -> ExecType:
        return self._exec_mode

    def set_execution_mode(self, mode: ExecType) -> None:
        self._exec_mode = ExecType(mode)

    def synchronize(self) -> None:
        # typed conversion mirrors the in-transform waits (see
        # Transform.synchronize)
        if self._space_data is not None:
            with faults.typed_execution(self._platform, "synchronize"):
                fence(self._space_data)


def _batch_slice(out, b: int):
    """Per-request view of a stacked batched result: index the batch axis
    (axis 1, after the mesh block dim) on every leaf, preserving the
    pair/single structure the unpad helpers expect."""
    if isinstance(out, tuple):
        return tuple(a[:, b] for a in out)
    return out[:, b]
