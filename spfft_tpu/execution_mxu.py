"""MXU execution engine: the TPU-fast single-device pipeline.

Same role as :class:`spfft_tpu.execution.LocalExecution` (the analogue of the
reference's ExecutionGPU, reference: src/execution/execution_gpu.cpp:47-410), but
engineered around what profiles fast on TPU hardware:

* every DFT stage is a batched matmul on the MXU (see ops/fft.py) — the fused-2D-FFT
  idea of the reference's GPU path (reference: src/fft/transform_2d_gpu.hpp:47-149)
  taken further: x/y/z stages contract *in place* over a fixed (Y, X, Z) native
  layout, so the pipeline has NO transposes at all,
* sparse value pack/unpack run as lane-aligned row-gather copy plans
  (see ops/lanecopy.py) instead of element scatters (40x measured difference),
* the stick -> plane expansion is one whole-row gather from the stick table
  (the reference's local transpose, src/transpose/transpose_gpu.hpp:54-124,
  reduced to a single XLA gather of 128-lane rows),
* z is the minor (lane) dimension throughout, so z-sticks are rows — the same
  "z-columns contiguous" layout insight as the reference
  (reference: docs/source/details.rst:53).

Native space-domain layout is ``(Y, X, Z)``; the host-facing Transform converts
to the public ``(Z, Y, X)`` contract at the boundary (the reference's GPU backend
likewise uses an internal layout that differs from the host one,
reference: docs/source/details.rst:55-59).

Falls back to scatter/gather for caller value orders too fragmented for copy
planning (CopyPlan.build -> None).
"""
from __future__ import annotations

import functools
import numpy as np

import jax
import jax.numpy as jnp

from .errors import InvalidParameterError
from .execution import ExecutionBase, as_pair
from .ops import fft as offt
from .ops import lanecopy, symmetry
from .parameters import LocalParameters
from .types import ScalingType, TransformType



class MxuLocalExecution(ExecutionBase):
    """Single-device MXU pipeline for one plan. Boundary-compatible with
    LocalExecution (pair I/O), except space-domain arrays are (Y, X, Z) native."""

    NATIVE_LAYOUT = "yxz"

    def __init__(
        self, params: LocalParameters, real_dtype=np.float32, device=None,
        precision="highest", fuse=None,
    ):
        super().__init__(params, real_dtype, device)
        p = params
        r2c = p.transform_type == TransformType.R2C
        rt = self.real_dtype
        self._precision = offt.resolve_precision(precision)

        # ---- unique-x compaction -------------------------------------------------
        # The y/x stages only touch x-rows that carry at least one stick — the
        # reference's "uniqueXIndices" optimization (reference:
        # src/execution/execution_host.cpp:138-144, src/fft/transform_1d_host.hpp:155-235)
        # becomes *rectangular* DFT matrices here: the intermediate grid is
        # (Y, A, Z) with A = #active x rows, and the x-stage contracts A <-> dim_x
        # directly via the permutation-folding hook of ops/fft.c2c_matrix. At 15%
        # spherical cutoff this cuts the xy-stage matmul flops ~6.7x. Extent
        # padding / full-extent fallback policy: ops/fft.compact_x_extent.
        if p.num_sticks:
            ux = np.unique(np.asarray(p.stick_x, dtype=np.int64))
            xslot = np.searchsorted(ux, np.asarray(p.stick_x, dtype=np.int64))
        else:
            ux = np.zeros(1, dtype=np.int64)
            xslot = np.zeros(0, dtype=np.int64)
        A = offt.compact_x_extent(ux.size, p.dim_x_freq)
        self._num_x_active = A

        # ---- DFT matrices (static constants; scale folded into forward z) ----
        self._wz_b, self._wy_b, self._wy_f, self._wz_f = offt.zy_stage_matrices(
            p.dim_z, p.dim_y, p.total_size, rt
        )

        # ---- sparse copy plans + expansion map ----
        S, Z = p.num_sticks, p.dim_z

        # Sparse-y stage (C2C only): contract the y-DFT only over each active-x
        # slot's sticks via an (A, Sy_max, Z) table — the y-occupancy analogue
        # of the uniqueXIndices compaction (stick table rows relabel
        # s -> a*Sy + j; the expand gather and the forward pack disappear).
        # Engagement policy, crossover measurements, and the per-slot matrix
        # build live in ops/fft.plan_sparse_y (shared with the distributed
        # engine). ABOVE its Sy/Y crossover the blocked variant
        # (ops/fft.plan_sparse_y_blocked) takes over: exact stick table,
        # per-bucket padding, bucket gathers in place of expand/pack.
        self._sparse_y = False
        self._sparse_y_blocked = None
        self._sy_x0_bucket = None
        value_indices = np.asarray(p.value_indices, dtype=np.int64)
        if p.num_sticks:
            sy_plan = (
                offt.plan_sparse_y(xslot, p.stick_y, A, p.dim_y, rt)
                if not r2c
                else None  # per-slot variant stays C2C-only
            )
            if sy_plan is not None:
                self._sparse_y = True
                self._sy, row_of_stick, self._wy_b_sp, self._wy_f_sp = sy_plan
                stick_of_value = value_indices // Z
                value_indices = row_of_stick[stick_of_value] * Z + value_indices % Z
            else:
                # R2C rides the blocked variant too: the x == 0 plane (the
                # hermitian-fill site) becomes a dense trailing bucket, all
                # other slots keep exact per-bucket tables (VERDICT r4 item 3)
                dense_slots = (0,) if r2c and int(ux[0]) == 0 else ()
                blk = offt.plan_sparse_y_blocked(
                    xslot, p.stick_y, p.dim_y, rt, S, A * p.dim_y,
                    dense_slots=dense_slots,
                )
                if blk is not None:
                    self._sparse_y_blocked = blk["buckets"]
                    self._sy_row_of_stick = blk["row_of_stick"]
                    if dense_slots:
                        # the x0 plane is the LAST bucket (trailing dense)
                        self._sy_x0_bucket = len(blk["buckets"]) - 1
                    # bucket-major slot order: permute the active-x list (the
                    # x-stage matrices fold the permutation) and remap slots
                    perm = blk["slot_perm"]
                    ux = ux[perm]
                    pos = np.empty(perm.size, dtype=np.int64)
                    pos[perm] = np.arange(perm.size)
                    xslot = pos[xslot]

        self._wx_b, self._wx_f = offt.x_stage_matrices(p.dim_x, ux, A, r2c, rt)
        self._x_active = ux

        # R2C backward plane symmetry acts on the x == 0 plane; locate its slot
        # in the CURRENT (possibly bucket-major-permuted) active-x order. The
        # dense-path fill below uses it; when blocked sparse-y engages for R2C
        # the fill instead runs inside the dense x0 bucket (_sy_x0_bucket).
        x0_pos = np.flatnonzero(ux == 0) if p.num_sticks else np.empty(0)
        self._x0_slot = int(x0_pos[0]) if x0_pos.size else None

        rows = A * self._sy if self._sparse_y else S
        self._table_rows = rows

        # Lane-alignment stick rotations: rotate each stick's frequency-z axis
        # so every copy-plan run is shift-0 (CopyPlan.apply fast path), at the
        # cost of one fused per-(stick, k) phase multiply on the space side of
        # each z matmul (the DFT rotation theorem). Measured 5.7 -> ~1 ms
        # pack/unpack at the 256^3/15% headline (BASELINE.md). The hermitian
        # (0, 0) stick stays unrotated — its in-place freq-domain fill assumes
        # the standard layout. Composes with sparse-y (rotations act on the
        # relabeled rows).
        rot = lanecopy.plan_alignment_rotations(
            value_indices, rows, Z,
            keep_zero=(self._zero_stick_id,) if r2c else (),
        )
        if rot is not None:
            delta, self._vi = rot
            self._phase = lanecopy.alignment_phase_rep(delta, Z, rt)
            # device-resident operand form — threaded through the jit
            # boundaries instead of embedded (critical at 512^3-class sizes)
            phase_ops = lanecopy.phase_rep_operands(self._phase, rt, self.put)
        else:
            self._vi = value_indices
            self._phase = None
            phase_ops = ()
        # Plan operands = phase tables + blocked-y bucket matrices, one flat
        # tuple threaded through every jit boundary. The bucket matrices MUST
        # be operands at large sizes: at 512^3 they are ~800 MB, which
        # overflowed the tunnel compile transport as embedded HLO constants
        # (measured round 4 — the same failure class as the phase tables).
        # Below the budget they stay embedded: measured ~2% faster at 256^3
        # (101 MB of matrices; bench_results/round4_onchip2.json
        # c2c_256_s15_r4b_default vs round4_onchip.json r4_default).
        self._n_phase_ops = len(phase_ops)
        mat_ops = ()
        if self._sparse_y_blocked is not None:
            mat_bytes = sum(
                2 * (wyb[0].nbytes + wyf[0].nbytes)
                for _, wyb, wyf in self._sparse_y_blocked
            )
            if mat_bytes > offt.sparse_y_matrix_budget_bytes():
                for row_idx, wyb, wyf in self._sparse_y_blocked:
                    mat_ops += (
                        self.put(wyb[0]), self.put(wyb[1]),
                        self.put(wyf[0]), self.put(wyf[1]),
                    )
                # the host copies' only consumer is the embedded fallback,
                # unreachable once operands thread — free ~800 MB at 512^3
                self._sparse_y_blocked = [
                    (row_idx, None, None)
                    for row_idx, _, _ in self._sparse_y_blocked
                ]
        self.phase_operands = phase_ops + mat_ops
        self._decompress_plan = lanecopy.build_decompress_plan(
            self._vi, rows * Z, p.num_values
        )
        self._compress_plan = lanecopy.build_compress_plan(self._vi, rows * Z)
        yx_map = np.full(p.dim_y * A, S, dtype=np.int32)  # S -> zero row
        keys = p.stick_y.astype(np.int64) * A + xslot
        yx_map[keys] = np.arange(S)
        self._yx_map = yx_map
        self._stick_keys = keys.astype(np.int32)

        # f64 stage chunking (accelerators only): XLA:TPU's f64 emulation holds
        # several ~8-component f32 temps per matmul — at 512^3 the x-stage alone
        # needed 12 GB and OOM'd the chip (BASELINE.md). Chunking the batch (Y)
        # axis of the x-stages bounds the temps; f32 and CPU paths are untouched.
        platform = device.platform if device is not None else jax.default_backend()
        self._x_stage_chunks = 1
        if rt == np.dtype(np.float64) and platform != "cpu":
            self._x_stage_chunks = offt.f64_stage_chunks(
                p.dim_y,
                p.dim_y * p.dim_x * p.dim_z,
                p.dim_y * A * p.dim_z,
            )

        self._backward = jax.jit(self._backward_impl)
        self._forward = {
            s: jax.jit(functools.partial(self._forward_impl, scaling=s))
            for s in (ScalingType.NONE, ScalingType.FULL)
        }
        # Donating variant for the host-facing flow (staged input copies are
        # dead after the call); see ExecutionBase.backward_pair_consuming for
        # when the alias can actually engage.
        self._backward_consume = jax.jit(self._backward_impl, donate_argnums=(0, 1))
        # Stage-graph IR (spfft_tpu.ir): see LocalExecution.__init__.
        from .ir.compile import init_engine_ir

        self._ir = init_engine_ir(self, fuse)

    # ---- introspection (spfft_tpu.obs plan cards) -----------------------------

    def _y_stage_scope(self) -> str:
        """The canonical named-scope label of the engaged y-DFT variant
        (obs.STAGES) — the perf layer's ``stage_accounting`` keys the dense
        relayout rows and the y-pass label off it (same rule as the
        distributed MXU engine's helper)."""
        if self._sparse_y:
            return "y transform sparse"
        if self._sparse_y_blocked is not None:
            return "y transform blocked"
        return "y transform"

    def describe(self) -> dict:
        """Engine fragment of the plan card (obs.plancard): the MXU engine's
        measured decisions — active-x compaction, the engaged sparse-y variant
        with its thresholds, alignment rotations, copy-plan engagement, and
        f64 stage chunking."""
        p = self.params
        sparse_y = offt.describe_sparse_y(
            self._sparse_y,
            self._sparse_y_blocked,
            self._sy if self._sparse_y else 0,
        )
        return {
            "pipeline": "matmul DFT stages + lane-copy value plans",
            "matmul_precision": str(self._precision).rsplit(".", 1)[-1],
            "num_x_active": int(self._num_x_active),
            "dim_x_freq": int(p.dim_x_freq),
            "sparse_y": sparse_y,
            "alignment_rotations": self._phase is not None,
            "copy_plans": {
                "decompress": self._decompress_plan is not None,
                "compress": self._compress_plan is not None,
            },
            "x_stage_chunks": int(self._x_stage_chunks),
        }

    def lowered_backward(self):
        """Lower (without compiling) the backward pipeline — the obs layer's
        hook for compiled-program stats (obs.hlo.compiled_stats). Threaded
        plan operands ride as their concrete device arrays."""
        v = jax.ShapeDtypeStruct((self.params.num_values,), self.real_dtype)
        return self._backward.lower(v, v, *self.phase_operands)

    # ---- stages ---------------------------------------------------------------

    def _decompress(self, values_re, values_im):
        p = self.params
        R, Z = self._table_rows, p.dim_z
        if self._decompress_plan is not None:
            # two independent applies by default; SPFFT_TPU_PAIR_COPY=1 inside
            # apply_pair stacks them into one gather per pipe (measured slower)
            pre, pim = self._decompress_plan.apply_pair(values_re, values_im)
            return (
                pre.reshape(-1)[: R * Z].reshape(R, Z),
                pim.reshape(-1)[: R * Z].reshape(R, Z),
            )
        vi = jnp.asarray(np.asarray(self._vi, dtype=np.int32))
        out = []
        for v in (values_re, values_im):
            flat = jnp.zeros(R * Z, dtype=v.dtype).at[vi].set(
                v, mode="drop", unique_indices=True
            )
            out.append(flat.reshape(R, Z))
        return tuple(out)

    def _compress(self, sre, sim):
        p = self.params
        if self._compress_plan is not None:
            pre, pim = self._compress_plan.apply_pair(
                sre.reshape(-1), sim.reshape(-1)
            )
            return (
                pre.reshape(-1)[: p.num_values],
                pim.reshape(-1)[: p.num_values],
            )
        vi = jnp.asarray(np.asarray(self._vi, dtype=np.int32))
        return sre.reshape(-1)[vi], sim.reshape(-1)[vi]

    def _expand(self, sre, sim):
        """(S, Z) sticks -> (Y, A, Z) active-x planes via one row-gather per part."""
        p = self.params
        zero = jnp.zeros((1, p.dim_z), dtype=sre.dtype)
        m = jnp.asarray(self._yx_map)
        gre = jnp.take(jnp.concatenate([sre, zero]), m, axis=0)
        gim = jnp.take(jnp.concatenate([sim, zero]), m, axis=0)
        shape = (p.dim_y, self._num_x_active, p.dim_z)
        return gre.reshape(shape), gim.reshape(shape)

    # ---- pipelines ------------------------------------------------------------

    # Stage names match the reference's rt_graph tags (reference:
    # src/execution/execution_host.cpp:249-293) so jax.profiler traces read
    # like the reference's timing tree.

    def _split_operands(self, ops):
        """Threaded plan operands -> (phase pair or (), bucket matrices or ())."""
        if not ops:
            return (), ()
        return ops[: self._n_phase_ops], ops[self._n_phase_ops :]

    def _phase_tables(self, phase_ops):
        """(cos, sin) from threaded operands, or the rep's fallback form."""
        if phase_ops:
            return phase_ops
        return lanecopy.phase_rep_tables(self._phase, self.real_dtype)

    def _bucket_mats(self, mats, b, forward):
        """Bucket ``b``'s (pair) y matrix from threaded operands, or the
        embedded-constant fallback (trace paths that do not thread operands —
        fine at the sizes those paths run)."""
        if mats:
            base = 4 * b + (2 if forward else 0)
            return (mats[base], mats[base + 1])
        row_idx, wyb, wyf = self._sparse_y_blocked[b]
        mat = wyf if forward else wyb
        if mat is None:
            # typed-error discipline (analysis SA010): caller misuse, so the
            # contract violation surfaces as taxonomy, not a builtin
            raise InvalidParameterError(
                "this plan's blocked-y matrices ride as jit operands "
                "(above SPFFT_TPU_SPARSE_Y_MATRIX_MB); thread "
                "phase=engine.phase_operands through the enclosing jit "
                "when composing via trace_backward/trace_forward"
            )
        return mat

    def _blocked_y_backward(self, sre, sim, mat_ops):
        """Blocked sparse-y backward stage: per-bucket row gathers off the
        EXACT stick table (replacing the expand gather), per-bucket batched y
        contractions, bucket-major slot concatenation. Shared by
        _backward_impl and the ablation harness (programs/ablate_blocked.py)
        so stage timings always bracket the shipped pipeline."""
        p = self.params
        prec = self._precision
        Z, A = p.dim_z, self._num_x_active
        zero = jnp.zeros((1, Z), dtype=sre.dtype)
        spad_re = jnp.concatenate([sre, zero])
        spad_im = jnp.concatenate([sim, zero])
        outs_re, outs_im = [], []
        for b, (row_idx, _, _) in enumerate(self._sparse_y_blocked):
            idx = jnp.asarray(row_idx)
            gre_b, gim_b = spad_re[idx], spad_im[idx]
            if b == self._sy_x0_bucket:
                # R2C: the x == 0 plane rides as this (1, Y, Z) dense bucket;
                # hermitian-complete it along y before its y-DFT (space-z
                # domain, same site as the dense path's plane symmetry)
                with jax.named_scope("plane symmetry"):
                    fre, fim = symmetry.hermitian_fill_1d_pair(
                        gre_b[0], gim_b[0], axis=0
                    )
                    gre_b, gim_b = fre[None], fim[None]
            wyb = self._bucket_mats(mat_ops, b, forward=False)
            ore, oim = offt.complex_matmul(
                gre_b, gim_b, *wyb, "ajz,ajk->kaz", prec
            )
            outs_re.append(ore)
            outs_im.append(oim)
        gre = jnp.concatenate(outs_re, axis=1)
        gim = jnp.concatenate(outs_im, axis=1)
        if gre.shape[1] < A:  # compact_x_extent padding slots
            padw = A - gre.shape[1]
            gre = jnp.pad(gre, ((0, 0), (0, padw), (0, 0)))
            gim = jnp.pad(gim, ((0, 0), (0, padw), (0, 0)))
        return gre, gim

    # ---- pipeline stage bodies -------------------------------------------------
    # One implementation per stage, shared by the hand-ordered monolithic
    # impls below and the IR node fns lowered from this engine
    # (spfft_tpu.ir.lower). The threaded plan operands ride through as the
    # opaque ``phase`` tuple; each stage splits off what it needs.

    def _st_decompress(self, values_re, values_im):
        rt = self.real_dtype
        return self._decompress(values_re.astype(rt), values_im.astype(rt))

    def _st_stick_symmetry(self, sre, sim):
        i = self._zero_stick_id
        fre, fim = symmetry.hermitian_fill_1d_pair(sre[i], sim[i], axis=0)
        return sre.at[i].set(fre), sim.at[i].set(fim)

    def _st_z_backward(self, sre, sim, phase):
        phase_ops, _ = self._split_operands(phase)
        sre, sim = offt.complex_matmul(
            sre, sim, *self._wz_b, "sz,zk->sk", self._precision
        )
        if self._phase is not None:
            # undo the alignment rotations (fused multiply)
            cos_t, sin_t = self._phase_tables(phase_ops)
            sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, -1)
        return sre, sim

    def _st_y_sparse_backward(self, sre, sim):
        # per-slot y contraction straight off the stick table: no expand,
        # y-DFT rows gathered per slot into the matrix constants
        A, Sy, Z = self._num_x_active, self._sy, self.params.dim_z
        return offt.complex_matmul(
            sre.reshape(A, Sy, Z), sim.reshape(A, Sy, Z),
            *self._wy_b_sp, "ajz,ajk->kaz", self._precision,
        )

    def _st_y_blocked_backward(self, sre, sim, phase):
        _, mat_ops = self._split_operands(phase)
        return self._blocked_y_backward(sre, sim, mat_ops)

    def _st_plane_symmetry(self, gre, gim):
        s = self._x0_slot
        pre, pim = symmetry.hermitian_fill_1d_pair(
            gre[:, s, :], gim[:, s, :], axis=0
        )
        return gre.at[:, s, :].set(pre), gim.at[:, s, :].set(pim)

    def _st_y_dense_backward(self, gre, gim):
        return offt.complex_matmul(
            gre, gim, *self._wy_b, "yxz,yk->kxz", self._precision
        )

    def _st_x_backward(self, gre, gim):
        prec = self._precision
        if self.is_r2c:
            fn = lambda r, i: offt.real_out_matmul(
                r, i, *self._wx_b, "kxz,xl->klz", prec
            )
        else:
            fn = lambda r, i: offt.complex_matmul(
                r, i, *self._wx_b, "kxz,xl->klz", prec
            )
        return offt.map_chunked(fn, (gre, gim), self._x_stage_chunks)

    def _st_x_forward(self, space_re, space_im):
        rt = self.real_dtype
        prec = self._precision
        if self.is_r2c:
            return offt.map_chunked(
                lambda s: offt.real_in_matmul(s, *self._wx_f, "yxz,xk->ykz", prec),
                (space_re.astype(rt),),
                self._x_stage_chunks,
            )
        return offt.map_chunked(
            lambda r, i: offt.complex_matmul(
                r, i, *self._wx_f, "yxz,xk->ykz", prec
            ),
            (space_re.astype(rt), space_im.astype(rt)),
            self._x_stage_chunks,
        )

    def _st_y_sparse_forward(self, gre, gim):
        # per-slot y contraction straight into the stick table: the pack
        # gather disappears (output rows ARE the table rows)
        p = self.params
        sre, sim = offt.complex_matmul(
            gre, gim, *self._wy_f_sp, "yaz,ajy->ajz", self._precision
        )
        R = self._table_rows
        return sre.reshape(R, p.dim_z), sim.reshape(R, p.dim_z)

    def _blocked_y_forward(self, gre, gim, mat_ops):
        """Blocked sparse-y forward stage: per-bucket contractions into
        bucket flats, one regather to exact stick rows (replacing the pack
        gather) — the forward mirror of :meth:`_blocked_y_backward`."""
        p = self.params
        prec = self._precision
        Z = p.dim_z
        flats_re, flats_im = [], []
        col = 0
        for b, (row_idx, _, _) in enumerate(self._sparse_y_blocked):
            Ag, Syg = row_idx.shape
            wyf = self._bucket_mats(mat_ops, b, forward=True)
            fre, fim = offt.complex_matmul(
                gre[:, col : col + Ag, :], gim[:, col : col + Ag, :],
                *wyf, "yaz,ajy->ajz", prec,
            )
            flats_re.append(fre.reshape(Ag * Syg, Z))
            flats_im.append(fim.reshape(Ag * Syg, Z))
            col += Ag
        rs = jnp.asarray(self._sy_row_of_stick)
        return (
            jnp.concatenate(flats_re, axis=0)[rs],
            jnp.concatenate(flats_im, axis=0)[rs],
        )

    def _st_y_blocked_forward(self, gre, gim, phase):
        _, mat_ops = self._split_operands(phase)
        return self._blocked_y_forward(gre, gim, mat_ops)

    def _st_y_dense_forward(self, gre, gim):
        return offt.complex_matmul(
            gre, gim, *self._wy_f, "ykz,yl->lkz", self._precision
        )

    def _st_pack(self, gre, gim):
        p = self.params
        flat_re = gre.reshape(p.dim_y * self._num_x_active, p.dim_z)
        flat_im = gim.reshape(p.dim_y * self._num_x_active, p.dim_z)
        keys = jnp.asarray(self._stick_keys)
        return jnp.take(flat_re, keys, axis=0), jnp.take(flat_im, keys, axis=0)

    def _st_z_forward(self, sre, sim, phase, scaling):
        phase_ops, _ = self._split_operands(phase)
        if self._phase is not None:
            # enter the rotated layout on the space side (fused multiply)
            cos_t, sin_t = self._phase_tables(phase_ops)
            sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, +1)
        return offt.complex_matmul(
            sre, sim, *self._wz_f[scaling], "sz,zk->sk", self._precision
        )

    def _backward_impl(self, values_re, values_im, *phase):
        with jax.named_scope("compression"):
            sre, sim = self._st_decompress(values_re, values_im)
        if self.is_r2c and self._zero_stick_id is not None:
            with jax.named_scope("stick symmetry"):
                sre, sim = self._st_stick_symmetry(sre, sim)

        with jax.named_scope("z transform"):
            sre, sim = self._st_z_backward(sre, sim, phase)
        if self._sparse_y:
            with jax.named_scope("y transform sparse"):
                gre, gim = self._st_y_sparse_backward(sre, sim)
        elif self._sparse_y_blocked is not None:
            with jax.named_scope("y transform blocked"):
                gre, gim = self._st_y_blocked_backward(sre, sim, phase)
        else:
            with jax.named_scope("expand"):
                gre, gim = self._expand(sre, sim)

            if self.is_r2c and self._x0_slot is not None:
                with jax.named_scope("plane symmetry"):
                    gre, gim = self._st_plane_symmetry(gre, gim)

            with jax.named_scope("y transform"):
                gre, gim = self._st_y_dense_backward(gre, gim)
        with jax.named_scope("x transform"):
            return self._st_x_backward(gre, gim)

    def _forward_impl(self, space_re, space_im, *phase, scaling):
        with jax.named_scope("x transform"):
            gre, gim = self._st_x_forward(space_re, space_im)
        if self._sparse_y:
            with jax.named_scope("y transform sparse"):
                sre, sim = self._st_y_sparse_forward(gre, gim)
        elif self._sparse_y_blocked is not None:
            with jax.named_scope("y transform blocked"):
                sre, sim = self._st_y_blocked_forward(gre, gim, phase)
        else:
            with jax.named_scope("y transform"):
                gre, gim = self._st_y_dense_forward(gre, gim)
            with jax.named_scope("pack"):
                sre, sim = self._st_pack(gre, gim)

        with jax.named_scope("z transform"):
            sre, sim = self._st_z_forward(sre, sim, phase, scaling)
        with jax.named_scope("compression"):
            return self._compress(sre, sim)

    # ---- boundary API (pair-form, native layout) ------------------------------

    def backward_pair(self, values_re, values_im):
        return self._ir.run_backward(values_re, values_im, *self.phase_operands)

    def forward_pair(self, space_re, space_im, scaling: ScalingType = ScalingType.NONE):
        if space_im is None:
            space_im = jnp.zeros((0,), dtype=self.real_dtype)
        return self._ir.run_forward(
            ScalingType(scaling), space_re, space_im, *self.phase_operands
        )

    # Un-jitted traceables for composition into larger jitted programs (see
    # LocalExecution.trace_backward for rationale). Callers owning the outer
    # jit thread ``phase=self.phase_operands`` through their own argument list
    # so the rotation tables stay jit OPERANDS (embedding them as closure
    # constants costs compile transport and, at 512^3, per-apply in-trace
    # regeneration — see ops/lanecopy.phase_rep_operands).

    def trace_backward(self, values_re, values_im, phase=()):
        return self._backward_impl(values_re, values_im, *phase)

    def trace_forward(
        self, space_re, space_im, scaling: ScalingType = ScalingType.NONE, phase=()
    ):
        if space_im is None:
            space_im = jnp.zeros((0,), dtype=self.real_dtype)
        return self._forward_impl(
            space_re, space_im, *phase, scaling=ScalingType(scaling)
        )

    # host-facing helpers translate between public (Z, Y, X) and native (Y, X, Z)

    def backward(self, values):
        re, im = as_pair(values, self.real_dtype)
        out = self.backward_pair(self.put(re), self.put(im))
        if self.is_r2c:
            return self.fetch(out).transpose(2, 0, 1)
        return self.fetch_space_complex(out).transpose(2, 0, 1)

    def forward(self, space, scaling: ScalingType = ScalingType.NONE):
        space = np.asarray(space).transpose(1, 2, 0)  # (Z,Y,X) -> (Y,X,Z)
        if self.is_r2c:
            sre = self.put(np.ascontiguousarray(space.real, dtype=self.real_dtype))
            sim = None
        else:
            re, im = as_pair(space, self.real_dtype)
            sre, sim = self.put(re), self.put(im)
        return self.forward_pair(sre, sim, scaling)
