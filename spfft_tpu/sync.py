"""Completion fences for SYNCHRONOUS execution mode.

``jax.block_until_ready`` is the canonical fence, but on some experimental
backends (the tunneled ``axon`` TPU platform in this environment) it returns
before device execution finishes — there it is advisory, not a fence. The
reference's ``SPFFT_EXEC_SYNCHRONOUS`` contract is that ``forward``/``backward``
return only after the transform completed (reference: include/spfft/types.h
SpfftExecType, src/spfft/transform.cpp forward/backward). :func:`fence`
restores that contract: after ``block_until_ready`` it additionally fetches one
scalar per device array on advisory platforms — a host read of an element
cannot complete before the computation producing it does.

On conforming platforms (CPU, standard TPU/GPU runtimes) the scalar fetch is
skipped entirely, so ``fence`` costs one tree traversal beyond
``block_until_ready``.
"""
from __future__ import annotations

import re
import threading

import numpy as np

import jax

from . import knobs, obs

# Wall-clock budget for one completion fence (seconds; 0/unset = no deadline).
# With a budget set, the wait runs in a worker thread and a wedged fence —
# a dead collective, a hung tunnel — surfaces as a typed execution error in
# FENCE_BUDGET_ENV seconds (the transform paths wrap fence() in
# faults.typed_execution, which converts FenceTimeout and counts
# execution_failures_total) instead of wedging until the driver timeout. A
# hang_watchdog (_platform) at 2x the budget backstops the truly unkillable
# case where even the deadline machinery cannot run.
FENCE_BUDGET_ENV = "SPFFT_TPU_FENCE_BUDGET_S"


class FenceTimeout(RuntimeError):
    """A completion fence exceeded its ``SPFFT_TPU_FENCE_BUDGET_S`` deadline.

    A ``RuntimeError`` subclass on purpose: the transform paths' surrounding
    :func:`spfft_tpu.faults.typed_execution` scopes convert it to the typed
    error surface (``HostExecutionError``/``GPUFFTError``) and count it in
    ``execution_failures_total`` — the same arm that catches real backend
    failures catches the deadline."""

# Runtimes whose block_until_ready is known not to wait for execution. The
# tunneled TPU identifies as platform "tpu" with "axon" only in the client's
# platform_version string, so both the platform name and the version string are
# consulted. Version matching is anchored on whole tokens (split at
# non-alphanumerics) — a version string that merely *contains* the marker
# inside another word must not trigger the per-array host fetches.
# ``SPFFT_TPU_ADVISORY_FENCE=1`` forces the scalar-probe fence on any platform;
# ``=0`` disables it everywhere (callers who know their runtime conforms).
ADVISORY_PLATFORMS = frozenset({"axon"})
ADVISORY_VERSION_MARKERS = frozenset({"axon"})


def _advisory_override():
    v = knobs.get_str("SPFFT_TPU_ADVISORY_FENCE")
    if v in ("0", "1"):
        return v == "1"
    return None


def _client_is_advisory(client) -> bool:
    if client.platform in ADVISORY_PLATFORMS:
        return True
    version = str(getattr(client, "platform_version", "") or "")
    tokens = set(re.split(r"[^A-Za-z0-9]+", version.lower()))
    return not tokens.isdisjoint(ADVISORY_VERSION_MARKERS)


def _on_advisory_platform(leaf) -> bool:
    devices = getattr(leaf, "devices", None)
    if not callable(devices):
        return False
    try:
        devs = devices()
    except (RuntimeError, ValueError, AttributeError) as e:
        # a leaf whose devices() dies (deleted buffer, torn-down backend) is
        # treated as non-advisory — but counted, never silently dropped
        obs.counter("sync_probe_failures_total", error=type(e).__name__).inc()
        return False
    return any(
        d.platform in ADVISORY_PLATFORMS or _client_is_advisory(d.client)
        for d in devs
    )


def _probe_scalar(arr):
    """One-element probe of a single-device array; fetching it host-side forces
    the array's producer to complete. ``.real`` so complex arrays fence too on
    platforms whose host transport rejects complex payloads (the axon tunnel
    does)."""
    probe = arr.ravel()[0] if arr.ndim else arr
    if np.issubdtype(probe.dtype, np.complexfloating):
        probe = probe.real
    return probe


def fence(tree):
    """Block until every array in ``tree`` has finished computing; returns ``tree``.

    Sharded arrays are fenced per addressable shard — a single global
    ``ravel()[0]`` would depend only on the device holding element 0, letting
    the other shards' computations keep running past the "fence". All probes
    across every leaf and shard are fetched in ONE batched ``jax.device_get``:
    on the tunneled platform each host fetch carries a fixed ~110 ms transport
    cost, so a per-shard loop would bill that cost P times per fence.

    Fault site ``sync.fence`` fires before the wait: an injected failure here
    models a runtime whose completion machinery died mid-transform — the
    transform paths convert it to a typed execution error
    (:func:`spfft_tpu.faults.typed_execution`).

    The whole fence is a ``fence`` trace span (:mod:`spfft_tpu.obs.trace`),
    stamped with the run ID of the operation it completes.

    With ``SPFFT_TPU_FENCE_BUDGET_S`` set (> 0), the wait carries a deadline:
    a wedged fence raises :class:`FenceTimeout` after the budget — converted
    to the typed error surface and counted in ``execution_failures_total`` by
    the callers' ``faults.typed_execution`` scopes — with a
    ``_platform.hang_watchdog`` at twice the budget as the unkillable-native
    backstop. Unset (the default), the wait is inline and unbudgeted.
    """
    with obs.trace.span("fence"):
        budget = _fence_budget_s()
        if budget <= 0:
            _wait_tree(tree)
            return tree
        # Deadline path: the blocking wait runs in a worker thread so a
        # wedged runtime becomes a typed failure after `budget` seconds (the
        # worker stays parked on the dead wait — daemon, reclaimed at exit).
        # The hang_watchdog at 2x budget is the unkillable-native backstop:
        # if even this thread machinery cannot make progress, the process
        # exits fast and capturably instead of hitting the driver timeout.
        from ._platform import hang_watchdog

        disarm = hang_watchdog(
            "sync.fence", FENCE_BUDGET_ENV, budget, exit_code=3,
            budget_s=2.0 * budget,
        )
        try:
            done = threading.Event()
            err: list = []
            # the run-ID stack is thread-local: capture the caller's active
            # run and re-enter it in the worker, so the fault site's trace
            # events keep the card <-> trace join even on the budgeted path
            run = obs.trace.current_run_id()

            def _wait():
                try:
                    with obs.trace.with_run(run):
                        _wait_tree(tree)
                except BaseException as e:  # noqa: SA010 — re-raised in the
                    # caller thread (cross-thread re-raise, nothing swallowed)
                    err.append(e)
                finally:
                    done.set()

            worker = threading.Thread(target=_wait, daemon=True)
            worker.start()
            if not done.wait(budget):
                raise FenceTimeout(
                    f"completion fence exceeded its {budget:.3g}s deadline "
                    f"({FENCE_BUDGET_ENV}); runtime completion machinery "
                    "wedged or collective dead"
                )
            if err:
                raise err[0]
        finally:
            disarm()
        return tree


def _fence_budget_s() -> float:
    # loud-config rule (same as faults.parse_spec / verify.resolve_mode):
    # a typo'd deadline must never silently disable the deadline — the
    # registry resolver raises typed on a malformed value
    return knobs.get_float(FENCE_BUDGET_ENV)


def _wait_tree(tree) -> None:
    """The actual blocking wait (fault site, block_until_ready, advisory
    scalar probes) — shared by the inline path and the deadline worker."""
    from . import faults

    faults.site("sync.fence")
    jax.block_until_ready(tree)
    force = _advisory_override()
    if force is False:
        return
    probes = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if (
            isinstance(leaf, jax.Array)
            and leaf.size
            and (force or _on_advisory_platform(leaf))
        ):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                for shard in shards:
                    if shard.data is not None and shard.data.size:
                        probes.append(_probe_scalar(shard.data))
            else:
                probes.append(_probe_scalar(leaf))
    if probes:
        jax.device_get(probes)
