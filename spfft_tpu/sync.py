"""Completion fences for SYNCHRONOUS execution mode.

``jax.block_until_ready`` is the canonical fence, but on some experimental
backends (the tunneled ``axon`` TPU platform in this environment) it returns
before device execution finishes — there it is advisory, not a fence. The
reference's ``SPFFT_EXEC_SYNCHRONOUS`` contract is that ``forward``/``backward``
return only after the transform completed (reference: include/spfft/types.h
SpfftExecType, src/spfft/transform.cpp forward/backward). :func:`fence`
restores that contract: after ``block_until_ready`` it additionally fetches one
scalar per device array on advisory platforms — a host read of an element
cannot complete before the computation producing it does.

On conforming platforms (CPU, standard TPU/GPU runtimes) the scalar fetch is
skipped entirely, so ``fence`` costs one tree traversal beyond
``block_until_ready``.
"""
from __future__ import annotations

import os
import re

import numpy as np

import jax

from . import obs

# Runtimes whose block_until_ready is known not to wait for execution. The
# tunneled TPU identifies as platform "tpu" with "axon" only in the client's
# platform_version string, so both the platform name and the version string are
# consulted. Version matching is anchored on whole tokens (split at
# non-alphanumerics) — a version string that merely *contains* the marker
# inside another word must not trigger the per-array host fetches.
# ``SPFFT_TPU_ADVISORY_FENCE=1`` forces the scalar-probe fence on any platform;
# ``=0`` disables it everywhere (callers who know their runtime conforms).
ADVISORY_PLATFORMS = frozenset({"axon"})
ADVISORY_VERSION_MARKERS = frozenset({"axon"})


def _advisory_override():
    v = os.environ.get("SPFFT_TPU_ADVISORY_FENCE")
    if v in ("0", "1"):
        return v == "1"
    return None


def _client_is_advisory(client) -> bool:
    if client.platform in ADVISORY_PLATFORMS:
        return True
    version = str(getattr(client, "platform_version", "") or "")
    tokens = set(re.split(r"[^A-Za-z0-9]+", version.lower()))
    return not tokens.isdisjoint(ADVISORY_VERSION_MARKERS)


def _on_advisory_platform(leaf) -> bool:
    devices = getattr(leaf, "devices", None)
    if not callable(devices):
        return False
    try:
        devs = devices()
    except (RuntimeError, ValueError, AttributeError) as e:
        # a leaf whose devices() dies (deleted buffer, torn-down backend) is
        # treated as non-advisory — but counted, never silently dropped
        obs.counter("sync_probe_failures_total", error=type(e).__name__).inc()
        return False
    return any(
        d.platform in ADVISORY_PLATFORMS or _client_is_advisory(d.client)
        for d in devs
    )


def _probe_scalar(arr):
    """One-element probe of a single-device array; fetching it host-side forces
    the array's producer to complete. ``.real`` so complex arrays fence too on
    platforms whose host transport rejects complex payloads (the axon tunnel
    does)."""
    probe = arr.ravel()[0] if arr.ndim else arr
    if np.issubdtype(probe.dtype, np.complexfloating):
        probe = probe.real
    return probe


def fence(tree):
    """Block until every array in ``tree`` has finished computing; returns ``tree``.

    Sharded arrays are fenced per addressable shard — a single global
    ``ravel()[0]`` would depend only on the device holding element 0, letting
    the other shards' computations keep running past the "fence". All probes
    across every leaf and shard are fetched in ONE batched ``jax.device_get``:
    on the tunneled platform each host fetch carries a fixed ~110 ms transport
    cost, so a per-shard loop would bill that cost P times per fence.

    Fault site ``sync.fence`` fires before the wait: an injected failure here
    models a runtime whose completion machinery died mid-transform — the
    transform paths convert it to a typed execution error
    (:func:`spfft_tpu.faults.typed_execution`).

    The whole fence is a ``fence`` trace span (:mod:`spfft_tpu.obs.trace`),
    stamped with the run ID of the operation it completes.
    """
    from . import faults

    with obs.trace.span("fence"):
        faults.site("sync.fence")
        jax.block_until_ready(tree)
        force = _advisory_override()
        if force is False:
            return tree
        probes = []
        for leaf in jax.tree_util.tree_leaves(tree):
            if (
                isinstance(leaf, jax.Array)
                and leaf.size
                and (force or _on_advisory_platform(leaf))
            ):
                shards = getattr(leaf, "addressable_shards", None)
                if shards:
                    for shard in shards:
                        if shard.data is not None and shard.data.size:
                            probes.append(_probe_scalar(shard.data))
                else:
                    probes.append(_probe_scalar(leaf))
        if probes:
            jax.device_get(probes)
        return tree
