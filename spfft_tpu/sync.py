"""Completion fences for SYNCHRONOUS execution mode.

``jax.block_until_ready`` is the canonical fence, but on some experimental
backends (the tunneled ``axon`` TPU platform in this environment) it returns
before device execution finishes — there it is advisory, not a fence. The
reference's ``SPFFT_EXEC_SYNCHRONOUS`` contract is that ``forward``/``backward``
return only after the transform completed (reference: include/spfft/types.h
SpfftExecType, src/spfft/transform.cpp forward/backward). :func:`fence`
restores that contract: after ``block_until_ready`` it additionally fetches one
scalar per device array on advisory platforms — a host read of an element
cannot complete before the computation producing it does.

On conforming platforms (CPU, standard TPU/GPU runtimes) the scalar fetch is
skipped entirely, so ``fence`` costs one tree traversal beyond
``block_until_ready``.
"""
from __future__ import annotations

import numpy as np

import jax

# Runtimes whose block_until_ready is known not to wait for execution. The
# tunneled TPU identifies as platform "tpu" with "axon" only in the client's
# platform_version string, so both the platform name and the version string are
# consulted.
ADVISORY_PLATFORMS = frozenset({"axon"})
ADVISORY_VERSION_MARKERS = ("axon",)


def _client_is_advisory(client) -> bool:
    version = str(getattr(client, "platform_version", "") or "")
    return client.platform in ADVISORY_PLATFORMS or any(
        marker in version for marker in ADVISORY_VERSION_MARKERS
    )


def _on_advisory_platform(leaf) -> bool:
    devices = getattr(leaf, "devices", None)
    if not callable(devices):
        return False
    try:
        devs = devices()
    except Exception:
        return False
    return any(
        d.platform in ADVISORY_PLATFORMS or _client_is_advisory(d.client)
        for d in devs
    )


def _probe_scalar(arr) -> None:
    """Host-fetch one element of a single-device array, forcing its producer to
    complete. ``.real`` so complex arrays fence too on platforms whose host
    transport rejects complex payloads (the axon tunnel does)."""
    probe = arr.ravel()[0] if arr.ndim else arr
    if np.issubdtype(probe.dtype, np.complexfloating):
        probe = probe.real
    jax.device_get(probe)


def fence(tree):
    """Block until every array in ``tree`` has finished computing; returns ``tree``.

    Sharded arrays are fenced per addressable shard — a single global
    ``ravel()[0]`` would depend only on the device holding element 0, letting
    the other shards' computations keep running past the "fence".
    """
    jax.block_until_ready(tree)
    for leaf in jax.tree_util.tree_leaves(tree):
        if (
            isinstance(leaf, jax.Array)
            and leaf.size
            and _on_advisory_platform(leaf)
        ):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                for shard in shards:
                    if shard.data is not None and shard.data.size:
                        _probe_scalar(shard.data)
            else:
                _probe_scalar(leaf)
    return tree
