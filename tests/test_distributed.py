"""Distributed transform tests on the virtual 8-device CPU mesh.

Parity with reference tests/mpi_tests/test_transform.cpp: exchange-type sweep,
distribution edge cases (uniform, all-sticks-on-one-shard, sticks on one shard with
planes on another), centered indexing, R2C, run-twice zeroing, and the float-wire
exchange for f64.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    Grid,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import (
    split_values,
    assert_close,
    oracle_backward_c2c,
    oracle_forward_c2c,
    random_sparse_triplets,
)


def make_mesh(n):
    return sp.make_fft_mesh(n)


@pytest.mark.parametrize("num_shards", [2, 4, 8])
@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED],
)
def test_distributed_c2c_backward_forward(num_shards, exchange):
    rng = np.random.default_rng(42)
    dims = (12, 11, 13)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    per_shard = distribute_triplets(triplets, num_shards, dy)
    values_per_shard = split_values(per_shard, triplets, values)

    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_mesh(num_shards),
        exchange_type=exchange,
    )
    out = t.backward(values_per_shard)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    assert_close(out, expected)

    # run twice (zeroing check, reference: tests/test_util/test_transform.hpp:129-131)
    assert_close(t.backward(values_per_shard), expected)

    # forward roundtrip with scaling
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(values_per_shard):
        assert_close(back[r], vals)


def test_all_sticks_on_one_shard():
    """Edge case from reference tests/mpi_tests/test_transform.cpp:38-127."""
    rng = np.random.default_rng(1)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.4)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    per_shard = [triplets] + [np.zeros((0, 3), dtype=np.int64)] * 3
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, per_shard, mesh=make_mesh(4)
    )
    out = t.backward([values] + [np.zeros(0)] * 3)
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))


def test_sticks_on_one_planes_on_other():
    """Sticks on shard 0, all xy-planes on shard 1 (zero-length slabs elsewhere)."""
    rng = np.random.default_rng(2)
    dims = (6, 6, 6)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    per_shard = [triplets, np.zeros((0, 3), dtype=np.int64)]
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_mesh(2),
        local_z_lengths=[0, dz],
    )
    out = t.backward([values, np.zeros(0)])
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))
    assert t.local_z_length(0) == 0 and t.local_z_length(1) == dz
    assert t.local_z_offset(1) == 0 + 0  # offset after zero-length slab


def test_uneven_plane_distribution():
    rng = np.random.default_rng(3)
    dims = (8, 8, 13)  # 13 planes over 4 shards -> 4,3,3,3
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(triplets, 4, dy)
    values_per_shard = split_values(per_shard, triplets, values)

    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, per_shard, mesh=make_mesh(4)
    )
    assert [t.local_z_length(r) for r in range(4)] == [4, 3, 3, 3]
    out = t.backward(values_per_shard)
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))

    space = rng.standard_normal((dz, dy, dx)) + 1j * rng.standard_normal((dz, dy, dx))
    got = t.forward(space)
    for r, trip in enumerate(per_shard):
        assert_close(got[r], oracle_forward_c2c(trip, space))


def test_distributed_centered_indices():
    rng = np.random.default_rng(4)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5, centered=True)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(triplets, 4, dy)
    values_per_shard = split_values(per_shard, triplets, values)

    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, per_shard, mesh=make_mesh(4)
    )
    out = t.backward(values_per_shard)
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))


def test_distributed_r2c():
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)

    # full half-spectrum split over shards
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, 4, dy)
    values_per_shard = [
        freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard
    ]

    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, per_shard, mesh=make_mesh(4)
    )
    out = t.backward(values_per_shard)
    assert out.dtype == np.float64
    assert_close(out, r)

    back = t.forward(scaling=ScalingType.FULL)
    for r_, vals in enumerate(values_per_shard):
        assert_close(back[r_], vals)


def test_distributed_r2c_redundant_omitted():
    """Non-redundant input only; stick+plane symmetry must complete across shards,
    including when the (0,0) stick sits on a nonzero shard."""
    rng = np.random.default_rng(6)
    dims = (6, 6, 6)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)

    out_triplets = []
    for x in range(dx // 2 + 1):
        for y in range(dy):
            if x == 0 and y > dy // 2:
                continue
            for z in range(dz):
                if x == 0 and y == 0 and z > dz // 2:
                    continue
                out_triplets.append((x, y, z))
    trip = np.asarray(out_triplets)

    # put the (0,0) stick deliberately on shard 1
    zero_stick = trip[(trip[:, 0] == 0) & (trip[:, 1] == 0)]
    rest = trip[~((trip[:, 0] == 0) & (trip[:, 1] == 0))]
    rest_split = distribute_triplets(rest, 2, dy)
    per_shard = [rest_split[0], np.concatenate([rest_split[1], zero_stick])]
    values_per_shard = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]

    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, per_shard, mesh=make_mesh(2)
    )
    out = t.backward(values_per_shard)
    assert_close(out, r)


def test_float_wire_exchange():
    """BUFFERED_FLOAT: f64 transform with complex64 wire payload — slight accuracy
    loss allowed (reference: include/spfft/types.h:42-47)."""
    rng = np.random.default_rng(7)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(triplets, 4, dy)
    values_per_shard = split_values(per_shard, triplets, values)

    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_mesh(4),
        exchange_type=ExchangeType.BUFFERED_FLOAT,
    )
    out = t.backward(values_per_shard)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=1e-4 * scale)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED_BF16, ExchangeType.COMPACT_BUFFERED_BF16],
)
def test_bf16_wire_exchange(exchange):
    """*_BF16 (TPU extension): bfloat16 wire payload — explicit opt-in with a
    documented ~1e-2 relative accuracy bar (spfft_tpu/types.py ExchangeType)."""
    rng = np.random.default_rng(11)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(triplets, 4, dy)
    values_per_shard = split_values(per_shard, triplets, values)

    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_mesh(4),
        exchange_type=exchange,
        dtype=np.float32,
    )
    out = t.backward(values_per_shard)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=3e-2 * scale)
    # forward roundtrip through the bf16 wire back to the packed values
    back = t.forward(scaling=ScalingType.FULL)
    vscale = max(np.abs(values).max(), 1.0)
    for r, vals in enumerate(values_per_shard):
        np.testing.assert_allclose(back[r], vals, rtol=0, atol=3e-2 * vscale)


@pytest.mark.parametrize("exchange", list(ExchangeType))
def test_every_exchange_type_routes(exchange):
    """Exhaustive enum sweep: every ExchangeType value produces a working
    2-shard transform at its documented accuracy bar (insurance that a new
    enum value cannot ship unrouted)."""
    from spfft_tpu.types import BF16_EXCHANGES

    rng = np.random.default_rng(17)
    dx, dy, dz = 8, 8, 8
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 2, dy)
    values_per_shard = split_values(per_shard, triplets, values)
    bf16 = exchange in BF16_EXCHANGES
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_mesh(2),
        exchange_type=exchange,
        dtype=np.float32 if bf16 else None,
    )
    out = t.backward(values_per_shard)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=(3e-2 if bf16 else 1e-6) * scale)
    back = t.forward(scaling=ScalingType.FULL)
    vtol = 3e-2 * max(1.0, np.abs(values).max()) if bf16 else 1e-6
    for r, vals in enumerate(values_per_shard):
        np.testing.assert_allclose(back[r], vals, rtol=0, atol=vtol)


def test_grid_with_mesh_creates_distributed():
    rng = np.random.default_rng(8)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.3)
    mesh = make_mesh(4)
    grid = Grid(dx, dy, dz, 64, ProcessingUnit.HOST, mesh=mesh)
    t = grid.create_transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=triplets
    )
    assert isinstance(t, DistributedTransform)
    assert t.num_shards == 4
    assert t.num_global_elements == len(triplets)


def test_duplicate_stick_across_shards_rejected():
    from spfft_tpu import DuplicateIndicesError

    per_shard = [np.asarray([(1, 1, 0)]), np.asarray([(1, 1, 1)])]
    with pytest.raises(DuplicateIndicesError):
        DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            4,
            4,
            4,
            per_shard,
            mesh=make_mesh(2),
        )


def test_mesh_size_mismatch_rejected():
    from spfft_tpu import MPIParameterMismatchError

    per_shard = [np.asarray([(0, 0, 0)])]
    with pytest.raises(MPIParameterMismatchError):
        DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            4,
            4,
            4,
            per_shard,
            mesh=make_mesh(2),
        )


def test_distributed_clone():
    """clone() yields an independent plan with identical layout on the same
    mesh (reference: include/spfft/transform.hpp:133), on both the slab and
    pencil decompositions."""
    rng = np.random.default_rng(91)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    from spfft_tpu import make_fft_mesh2

    for mesh in (make_mesh(4), make_fft_mesh2(2, 2)):
        t = DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
            [p.copy() for p in per_shard], mesh=mesh,
        )
        expected = t.backward([v.copy() for v in vps])
        c = t.clone()
        assert c is not t and c.num_shards == t.num_shards
        assert c.exchange_type == t.exchange_type
        out = c.backward([v.copy() for v in vps])
        assert_close(out, expected)
        back = c.forward(scaling=ScalingType.FULL)
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)
        # independence: the clone's retained space buffer is its own
        assert c._space_data is not t._space_data

    # R2C: the hermitian half-set must round-trip through clone's triplet decode
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    r2c_trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    r2c_shards = distribute_triplets(r2c_trip, 4, dy)
    r2c_vps = [freq[p[:, 2], p[:, 1], p[:, 0]] for p in r2c_shards]
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, r2c_shards,
        mesh=make_mesh(4), exchange_type=ExchangeType.COMPACT_BUFFERED,
    )
    c = t.clone()
    assert c.exchange_type == ExchangeType.COMPACT_BUFFERED
    assert_close(c.backward([v.copy() for v in r2c_vps]), r)
    back = c.forward(scaling=ScalingType.FULL)
    for i, vals in enumerate(r2c_vps):
        assert_close(back[i], vals)
