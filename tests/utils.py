"""Shared test utilities.

Mirrors the reference test strategy (SURVEY.md §4): a dense FFT oracle
(np.fft here, FFTW there — reference: tests/test_util/test_transform.hpp:41-46),
seeded random sparse stick sets every process can derive identically
(reference: tests/test_util/generate_indices.hpp:39-100), and element-wise
comparison at 1e-6 for double precision
(reference: tests/test_util/test_check_values.hpp:46-78).
"""
from __future__ import annotations

import numpy as np


def storage(idx, dim):
    idx = np.asarray(idx)
    return np.where(idx < 0, idx + dim, idx)


def random_sparse_triplets(
    rng: np.random.Generator,
    dim_x: int,
    dim_y: int,
    dim_z: int,
    stick_fraction: float = 0.5,
    z_fill: float = 1.0,
    centered: bool = False,
    hermitian: bool = False,
) -> np.ndarray:
    """Random z-stick set: a random subset of xy columns, each with a random subset of
    z entries (whole sticks by default, like the reference's generator)."""
    xs = np.arange(dim_x // 2 + 1) if hermitian else np.arange(dim_x)
    ys = np.arange(dim_y)
    keys = np.stack(np.meshgrid(xs, ys, indexing="ij"), axis=-1).reshape(-1, 2)
    n_sticks = max(1, int(len(keys) * stick_fraction))
    chosen = keys[rng.choice(len(keys), size=n_sticks, replace=False)]
    triplets = []
    for x, y in chosen:
        zs = np.arange(dim_z)
        if z_fill < 1.0:
            zs = np.sort(rng.choice(dim_z, size=max(1, int(dim_z * z_fill)), replace=False))
        for z in zs:
            triplets.append((x, y, z))
    triplets = np.asarray(triplets, dtype=np.int64)
    if centered:
        triplets = center_triplets(triplets, dim_x, dim_y, dim_z, hermitian)
    return triplets


def center_triplets(triplets, dim_x, dim_y, dim_z, hermitian=False):
    """Shift storage indices into the centered (negative-frequency) convention
    (reference: tests/test_util/generate_indices.hpp:87)."""
    t = np.array(triplets, dtype=np.int64)
    if not hermitian:
        t[:, 0] = np.where(t[:, 0] > dim_x // 2, t[:, 0] - dim_x, t[:, 0])
    t[:, 1] = np.where(t[:, 1] > dim_y // 2, t[:, 1] - dim_y, t[:, 1])
    t[:, 2] = np.where(t[:, 2] > dim_z // 2, t[:, 2] - dim_z, t[:, 2])
    return t


def dense_from_values(triplets, values, dim_x, dim_y, dim_z, dim_x_freq=None):
    """Scatter packed values into a dense (Z, Y, Xf) frequency grid at storage coords."""
    t = np.asarray(triplets).reshape(-1, 3)
    xs = storage(t[:, 0], dim_x)
    ys = storage(t[:, 1], dim_y)
    zs = storage(t[:, 2], dim_z)
    dense = np.zeros((dim_z, dim_y, dim_x_freq or dim_x), dtype=np.complex128)
    dense[zs, ys, xs] = values
    return dense


def oracle_backward_c2c(triplets, values, dim_x, dim_y, dim_z):
    """Unnormalized inverse DFT of the sparse data (the reference's dense FFTW oracle,
    backward direction)."""
    dense = dense_from_values(triplets, values, dim_x, dim_y, dim_z)
    return np.fft.ifftn(dense) * (dim_x * dim_y * dim_z)


def oracle_forward_c2c(triplets, space, scale=1.0):
    """Forward DFT sampled at the sparse storage coords."""
    dim_z, dim_y, dim_x = space.shape
    freq = np.fft.fftn(space)
    t = np.asarray(triplets).reshape(-1, 3)
    xs = storage(t[:, 0], dim_x)
    ys = storage(t[:, 1], dim_y)
    zs = storage(t[:, 2], dim_z)
    return freq[zs, ys, xs] * scale


def assert_close(actual, expected, dtype=np.float64):
    actual = np.asarray(actual)
    expected = np.asarray(expected)
    scale = max(1.0, float(np.abs(expected).max()) if expected.size else 1.0)
    # Reference bar: ASSERT_NEAR(..., 1e-6) element-wise in double precision
    # (tests/test_util/test_check_values.hpp:46-78); f32 gets a proportionally
    # looser bar.
    atol = 1e-6 * scale if np.dtype(dtype) == np.float64 else 1e-3 * scale
    np.testing.assert_allclose(actual, expected, rtol=0, atol=atol)


def split_values(triplets_per_shard, full_triplets, full_values):
    """Look up each shard's values from a global (triplet -> value) map."""
    lut = {tuple(t): v for t, v in zip(map(tuple, full_triplets), full_values)}
    return [np.asarray([lut[tuple(t)] for t in trip]) for trip in triplets_per_shard]


def contiguous_stick_triplets(rng, dx, dy, dz, drop=0.3, r2c=False):
    """Meshgrid-style stick-contiguous caller order with a contiguous wrapped-z
    run per stick — the plane-wave layout the lane-alignment rotations target.
    For R2C: non-negative x excluding the even-dx Nyquist plane (its internal
    conjugate redundancy is the caller's responsibility, as in the reference),
    and only the non-redundant half of the x == 0 plane."""
    trips = []
    xs = range((dx + 1) // 2) if r2c else range(-((dx - 1) // 2), dx // 2 + 1)
    for x in xs:
        for y in range(-((dy - 1) // 2), dy // 2 + 1):
            if rng.random() < drop:
                continue
            h = int(rng.integers(3, dz // 2))
            if r2c and x == 0 and y < 0:
                continue
            lo = 0 if (r2c and x == 0 and y == 0) else -h
            trips.extend((x, y, z) for z in range(lo, h + 1))
    return np.asarray(trips)
