"""Index conversion unit tests (reference parity: src/compression/indices.hpp:49-186)."""
import numpy as np
import pytest

from spfft_tpu import DuplicateIndicesError, InvalidIndicesError, InvalidParameterError
from spfft_tpu.indices import convert_index_triplets, stick_xy_to_xy, to_storage_index


def test_storage_index_wraps_negative():
    assert to_storage_index(10, np.asarray(-1)) == 9
    assert to_storage_index(10, np.asarray(3)) == 3


def test_value_indices_stick_layout():
    # two sticks: (0,0) and (1,2) in a 4x4x4 grid; sticks sorted by x*dimY+y
    triplets = [(1, 2, 0), (0, 0, 1), (1, 2, 3), (0, 0, 0)]
    vi, sticks = convert_index_triplets(False, 4, 4, 4, np.asarray(triplets))
    assert list(sticks) == [0, 1 * 4 + 2]
    # values: stick_id * dimZ + z
    assert list(vi) == [1 * 4 + 0, 0 * 4 + 1, 1 * 4 + 3, 0 * 4 + 0]


def test_centered_autodetect_and_wrap():
    vi, sticks = convert_index_triplets(False, 4, 4, 4, np.asarray([(-1, 2, -1)]))
    assert list(sticks) == [3 * 4 + 2]
    assert list(vi) == [3]


def test_bounds_noncentered():
    convert_index_triplets(False, 4, 4, 4, np.asarray([(3, 3, 3)]))
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(False, 4, 4, 4, np.asarray([(4, 0, 0)]))


def test_bounds_centered():
    # centered: allowed x in [-1, 2] for dim 4
    convert_index_triplets(False, 4, 4, 4, np.asarray([(2, -1, 0)]))
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(False, 4, 4, 4, np.asarray([(3, -1, 0)]))


def test_hermitian_x_bounds():
    convert_index_triplets(True, 4, 4, 4, np.asarray([(2, 0, 0)]))
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(True, 4, 4, 4, np.asarray([(3, 0, 0)]))
    with pytest.raises(InvalidIndicesError):
        convert_index_triplets(True, 4, 4, 4, np.asarray([(-1, 0, 0)]))


def test_duplicate_triplets_rejected():
    with pytest.raises(DuplicateIndicesError):
        convert_index_triplets(False, 4, 4, 4, np.asarray([(1, 1, 1), (1, 1, 1)]))


def test_too_many_values_rejected():
    trip = np.zeros((9, 3), dtype=np.int64)
    with pytest.raises(InvalidParameterError):
        convert_index_triplets(False, 2, 2, 2, trip)


def test_stick_xy_split():
    x, y = stick_xy_to_xy(np.asarray([0, 6]), 4)
    assert list(x) == [0, 1]
    assert list(y) == [0, 2]
