"""Chaos suite for the fault-injection plane and guard mode (spfft_tpu.faults).

The central invariant (ISSUE acceptance / faults module docstring): with any
registered fault site armed at rate 1.0, every forward/backward transform
either raises a *typed* ``spfft_tpu.errors`` exception or returns output
matching the fault-free run with the fallback recorded (plan-card
``degradations`` + obs metrics) — never a silent wrong answer.
``test_chaos_invariant_every_site`` sweeps every site in
``faults.SITES`` one-at-a-time; the targeted tests pin each site's exact
ladder response. Guard-mode tests prove the NaN/shape/device checks raise the
right typed errors, and the capi tests prove the whole errors taxonomy
round-trips to C error codes (including guard/degradation failures).
"""
import inspect

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    capi,
    errors,
    faults,
    obs,
    tuning,
)
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close

DIM = 8


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    """Disarm everything, fresh metrics, isolated tuning state, default
    rate-draw seed — chaos must never leak between tests."""
    faults.disarm()
    faults.reseed(0)
    sp.verify.breaker.reset()  # the engine breaker is process-global
    obs.enable()
    obs.clear()
    tuning.clear_memory()
    monkeypatch.delenv(tuning.WISDOM_ENV, raising=False)
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    monkeypatch.delenv(faults.GUARD_ENV, raising=False)
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    yield
    faults.disarm()
    tuning.clear_memory()


def _triplets():
    return sp.create_spherical_cutoff_triplets(DIM, DIM, DIM, 0.8)


def _values(trip, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))


def _local(trip, **kwargs):
    return Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip, **kwargs
    )


def _dist(per_shard, **kwargs):
    return DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        [p.copy() for p in per_shard],
        mesh=sp.make_fft_mesh(2),
        **kwargs,
    )


def _counter_sum(prefix: str) -> int:
    snap = obs.snapshot()
    return sum(v for k, v in snap["counters"].items() if k.startswith(prefix))


# ---- plane mechanics ---------------------------------------------------------


def test_spec_parsing_and_validation():
    table = faults.parse_spec("engine.compile=raise, wisdom.load=corrupt:0.5")
    assert table == {
        "engine.compile": {"kind": "raise", "rate": 1.0},
        "wisdom.load": {"kind": "corrupt", "rate": 0.5},
    }
    for bad in (
        "nonsense",
        "unknown.site=raise",  # noqa: SA018 — the typed-refusal case under test
        "engine.compile=explode",
        "engine.compile=raise:2.0",
        "engine.compile=raise:x",
    ):
        with pytest.raises(errors.InvalidParameterError):
            faults.parse_spec(bad)


def test_malformed_spec_errors_name_the_offending_token():
    """A typo'd SPFFT_TPU_FAULTS must fail loudly, naming the exact token —
    a silently dropped arming would make a chaos run vacuously green."""
    cases = {
        "sync.fence=raise,unknown.site=nan": "unknown.site=nan",
        "sync.fence=raise,engine.compile=explode:0.5": "engine.compile=explode:0.5",
        "sync.fence=raise,engine.compile=raise:lots": "engine.compile=raise:lots",
        "sync.fence=raise,engine.compile=raise:7": "engine.compile=raise:7",
        "sync.fence=raise,engine.compile=": "engine.compile=",
    }
    for spec, token in cases.items():
        with pytest.raises(errors.InvalidParameterError) as ei:
            faults.parse_spec(spec)
        assert token in str(ei.value), (spec, str(ei.value))


def test_duplicate_site_token_raises():
    """Two tokens arming the same site would silently drop the first under
    last-wins parsing — reject the spec instead, naming the duplicate."""
    with pytest.raises(errors.InvalidParameterError) as ei:
        faults.parse_spec("sync.fence=raise,sync.fence=delay")
    assert "duplicate" in str(ei.value) and "sync.fence=delay" in str(ei.value)


def test_dict_arm_defaults_rate_and_validates():
    faults.arm({"sync.fence": {"kind": "delay"}})  # rate omitted -> 1.0
    assert faults.armed()["sync.fence"] == {"kind": "delay", "rate": 1.0}
    faults.disarm()
    with pytest.raises(errors.InvalidParameterError):
        faults.arm({"sync.fence": {"kind": "delay", "rate": 7}})


def test_poison_kind_on_payloadless_site_is_uncounted_noop():
    with faults.inject("engine.compile=nan"):
        trip = _triplets()
        t = _local(trip, engine="mxu")  # site passes no payload: no-op
    assert t._engine == "mxu"
    assert t.report()["degradations"] == []
    assert _counter_sum("faults_injected_total") == 0


def test_inject_scoping_restores():
    assert faults.armed() == {}
    with faults.inject("sync.fence=delay"):
        assert "sync.fence" in faults.armed()
        with faults.inject("hlo.stats=raise"):
            assert set(faults.armed()) == {"sync.fence", "hlo.stats"}
        assert set(faults.armed()) == {"sync.fence"}
    assert faults.armed() == {}


def test_disarmed_site_is_noop():
    payload = object()
    assert faults.site("sync.fence", payload=payload) is payload
    assert _counter_sum("faults_injected_total") == 0


def test_fractional_rate_is_deterministic_under_seed():
    def fire_pattern():
        faults.reseed(1234)
        pattern = []
        with faults.inject("sync.fence=raise:0.5"):
            for _ in range(32):
                try:
                    faults.site("sync.fence")
                    pattern.append(False)
                except faults.InjectedFault:
                    pattern.append(True)
        return pattern

    a, b = fire_pattern(), fire_pattern()
    assert a == b
    assert any(a) and not all(a)  # ~half fire at rate 0.5


def test_env_arming():
    """SPFFT_TPU_FAULTS arms at import — proven in a fresh interpreter (the
    in-process plane was imported long ago)."""
    import subprocess
    import sys

    code = (
        "import os; os.environ['SPFFT_TPU_FAULTS'] = 'engine.execute=raise:0.25';"
        "from spfft_tpu import faults;"
        "assert faults.armed() == {'engine.execute': {'kind': 'raise', 'rate': 0.25}},"
        " faults.armed(); print('armed ok')"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        check=True,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert "armed ok" in out.stdout


def test_delay_kind_keeps_results_correct(monkeypatch):
    monkeypatch.setenv(faults.FAULTS_DELAY_ENV, "0.001")
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    with faults.inject("engine.execute=delay,sync.fence=delay"):
        t = _local(trip)
        assert_close(t.backward(values), expect)
    assert _counter_sum("faults_injected_total") >= 2


# ---- the chaos invariant, every site ----------------------------------------


@pytest.mark.parametrize("site_name", faults.SITES)
def test_chaos_invariant_every_site(site_name, tmp_path, monkeypatch):
    """Arm each registered site at rate 1.0 (kind=raise): the transform pair
    either raises typed spfft_tpu.errors or matches the fault-free run, with
    any fallback recorded in the plan card's degradations section."""
    if site_name.startswith("serve."):
        # serve.* sites only fire on the serving path, never inside a plain
        # Transform — their arm-every-site sweep (admission/coalesce/
        # dispatch under overload) lives in tests/test_serve.py
        pytest.skip("serve.* sites are swept in tests/test_serve.py")
    if site_name == "ir.batch":
        # ir.batch fires only on the batched dispatch path (backward_batch/
        # forward_batch / the serving batcher) — its arm-the-site sweep
        # (degrade to the split-phase loop, rung recorded, parity) lives in
        # tests/test_batch.py
        pytest.skip("ir.batch is swept in tests/test_batch.py")
    if site_name in ("host.heartbeat", "rpc.submit"):
        # the multi-host sites only fire on the cluster front's liveness/
        # dispatch paths, never inside a plain Transform — their armed
        # sweeps (missed-probe ladder, typed dispatch degradation, plus the
        # real SIGKILLed-worker scenario) live in tests/test_cluster.py
        pytest.skip("host.*/rpc.* sites are swept in tests/test_cluster.py")
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    trip = _triplets()
    values = _values(trip)
    # tuned policy + explicit mxu preference: construction exercises the
    # tuning sites AND the engine.compile ladder in one sweep
    baseline = _local(trip)
    expect_b = baseline.backward(values)
    expect_f = baseline.forward(scaling=ScalingType.FULL)
    tuning.clear_memory()
    (tmp_path / "wisdom.json").unlink(missing_ok=True)

    kwargs = dict(policy="tuned") if site_name.startswith(("tuning", "wisdom")) else {}
    if site_name == "engine.compile":
        kwargs = dict(engine="mxu")
    if site_name == "verify.check":
        # the detector's own fault site only fires on verified plans; with
        # the checker raising on every call the supervisor must fail closed
        # (typed VerificationError) — the typed arm of the invariant
        kwargs = dict(verify="on")
    if site_name == "wisdom.load":
        # populate the wisdom file first so the load site really fires
        _local(trip, **kwargs)
        tuning.clear_memory()
    with faults.inject(f"{site_name}=raise"):
        try:
            t = _local(trip, **kwargs)
            out = t.backward(values)
            back = t.forward(scaling=ScalingType.FULL)
        except errors.GenericError as e:
            # typed failure arm of the invariant: the C shim can translate it
            assert capi.error_code(e) == int(e.error_code) != int(
                errors.ErrorCode.SUCCESS
            )
            return
    # fallback arm: parity with the fault-free run, card schema-complete
    assert_close(out, expect_b)
    assert_close(back, expect_f)
    card = t.report()
    assert obs.validate_plan_card(card) == []
    if site_name == "engine.compile":
        assert card["degradations"], "engine fallback must be recorded"
    if site_name == "ir.lower":
        # IR degradation rung: a failed lowering runs the legacy monolithic
        # jits, recorded — never a failed plan (spfft_tpu.ir)
        assert card["ir"]["path"] == "legacy" and not card["ir"]["fused"]
        assert any(d["event"] == "ir_lower_failed" for d in card["degradations"])
    if site_name == "ir.compile":
        # a failed fusion compile falls back to the staged per-node path
        assert card["ir"]["path"] == "staged" and not card["ir"]["fused"]
        assert any(
            d["event"] == "fuse_compile_failed" for d in card["degradations"]
        )


@pytest.mark.parametrize("overlap", [1, 2])
@pytest.mark.parametrize(
    "site_name",
    [
        "exchange.build", "engine.compile", "engine.execute", "sync.fence",
        "ir.lower", "ir.compile",
    ],
)
def test_chaos_invariant_distributed(site_name, overlap):
    """The distributed chaos invariant, for the bulk-synchronous AND the
    OVERLAPPED (chunked double-buffered) exchange pipelines: a mid-pipeline
    injection must land a typed error or a recorded degradation rung — the
    chunk loop adds collectives, never a new silent-failure surface."""
    trip = _triplets()
    values = _values(trip)
    per_shard = distribute_triplets(trip, 2, DIM)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    expect = _local(trip).backward(values)

    kwargs = dict(engine="mxu") if site_name == "engine.compile" else {}
    with faults.inject(f"{site_name}=raise"):
        try:
            t = _dist(per_shard, overlap=overlap, **kwargs)
            out = t.backward([v.copy() for v in vps])
        except errors.GenericError as e:
            assert capi.error_code(e) == int(e.error_code) != int(
                errors.ErrorCode.SUCCESS
            )
            return
    assert t.overlap_chunks == overlap
    assert_close(out, expect)
    assert obs.validate_plan_card(t.report()) == []
    if site_name == "engine.compile":
        assert t.report()["degradations"][0]["event"] == "engine_fallback"


@pytest.mark.parametrize("site_name", ["exchange.build", "sync.fence"])
def test_chaos_invariant_pencil_overlapped(site_name):
    """The same invariant for the chunked pencil pipelines (exchange A and
    B both overlapped) on a 2-D mesh."""
    trip = _triplets()
    values = _values(trip)
    per_shard = distribute_triplets(trip, 4, DIM)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    expect = _local(trip).backward(values)

    with faults.inject(f"{site_name}=raise"):
        try:
            t = DistributedTransform(
                ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM,
                [p.copy() for p in per_shard], mesh=sp.make_fft_mesh2(2, 2),
                overlap=2,
            )
            out = t.backward([v.copy() for v in vps])
        except errors.GenericError as e:
            assert capi.error_code(e) == int(e.error_code) != int(
                errors.ErrorCode.SUCCESS
            )
            return
    assert t.overlap_chunks == 2
    assert_close(out, expect)
    assert obs.validate_plan_card(t.report()) == []


# ---- targeted site behavior --------------------------------------------------


def test_engine_execute_raises_typed_error():
    trip = _triplets()
    t = _local(trip)
    with faults.inject("engine.execute=raise"):
        with pytest.raises(errors.HostExecutionError):
            t.backward(_values(trip))
    assert _counter_sum("execution_failures_total") == 1


def test_sync_fence_raises_typed_error():
    trip = _triplets()
    t = _local(trip)
    with faults.inject("sync.fence=raise"):
        with pytest.raises(errors.HostExecutionError):
            t.backward(_values(trip))


def test_fence_deadline_turns_wedge_into_typed_error(monkeypatch):
    """SPFFT_TPU_FENCE_BUDGET_S: a wedged fence (modeled by the delay kind
    sleeping far past the budget inside the waited section) surfaces as a
    fast typed HostExecutionError counted in execution_failures_total,
    instead of blocking until a driver timeout."""
    from spfft_tpu.sync import FENCE_BUDGET_ENV

    monkeypatch.setenv(faults.FAULTS_DELAY_ENV, "3")
    monkeypatch.setenv(FENCE_BUDGET_ENV, "0.2")
    trip = _triplets()
    t = _local(trip)
    import time

    t0 = time.monotonic()
    with faults.inject("sync.fence=delay"):
        with pytest.raises(errors.HostExecutionError) as ei:
            t.backward(_values(trip))
    assert time.monotonic() - t0 < 2.5, "deadline did not cut the wedge short"
    assert "deadline" in str(ei.value)
    assert _counter_sum("execution_failures_total") == 1


def test_fence_budget_typo_raises_typed(monkeypatch):
    """The loud-config rule applies to the fence deadline too: a typo'd
    budget must raise, never silently disable the deadline it configures."""
    from spfft_tpu.sync import FENCE_BUDGET_ENV

    monkeypatch.setenv(FENCE_BUDGET_ENV, "30s")
    t = _local(_triplets())
    with pytest.raises(errors.InvalidParameterError) as ei:
        t.backward(_values(_triplets()))
    assert "30s" in str(ei.value)


def test_fence_budget_preserves_trace_run_id(monkeypatch):
    """The budgeted fence runs its wait in a worker thread; events emitted
    inside (the sync.fence fault site) must still carry the caller's run ID
    — the card <-> trace join must survive the thread hop (review finding)."""
    from spfft_tpu.obs import trace
    from spfft_tpu.sync import FENCE_BUDGET_ENV

    monkeypatch.setenv(faults.FAULTS_DELAY_ENV, "0.001")
    monkeypatch.setenv(FENCE_BUDGET_ENV, "30")
    trace.enable(capacity=256)
    try:
        trip = _triplets()
        t = _local(trip)
        with faults.inject("sync.fence=delay"):
            t.backward(_values(trip))
        injected = [
            e
            for e in trace.snapshot()["events"]
            if e["name"] == "fault.injected" and e["args"].get("site") == "sync.fence"
        ]
        assert injected, "the armed fence site did not record"
        assert all(e["run"] == t._run_id for e in injected), injected
    finally:
        trace.disable()


def test_fence_deadline_passthrough_when_healthy(monkeypatch):
    """With a budget armed and a healthy runtime, fence results are
    unchanged (the worker-thread wait is behavior-transparent)."""
    from spfft_tpu.sync import FENCE_BUDGET_ENV

    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    monkeypatch.setenv(FENCE_BUDGET_ENV, "30")
    assert_close(_local(trip).backward(values), expect)
    assert _counter_sum("execution_failures_total") == 0


def test_exchange_build_raises_mpi_error():
    per_shard = distribute_triplets(_triplets(), 2, DIM)
    with faults.inject("exchange.build=raise"):
        with pytest.raises(errors.MPIError):
            _dist(per_shard)


def test_hlo_stats_degrades_report():
    trip = _triplets()
    t = _local(trip)
    with faults.inject("hlo.stats=raise"):
        card = t.report(include_compiled=True)
    assert "compiled" not in card
    assert card["degradations"][0]["event"] == "hlo_stats_unavailable"
    assert obs.validate_plan_card(card) == []
    # fault-free report still carries the compiled section
    assert "compiled" in t.report(include_compiled=True)


def test_tuning_trial_chaos_degrades_to_model(monkeypatch):
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    per_shard = distribute_triplets(_triplets(), 2, DIM)
    with faults.inject("tuning.trial=raise"):
        t = _dist(per_shard, policy="tuned")
    rec = t._tuning
    assert rec["provenance"] == "model"
    assert rec["reason"] == "all trial candidates failed"
    assert rec["trials"] and all(
        row["error"].startswith("InjectedFault") for row in rec["trials"]
    )
    assert t.exchange_type == _dist(per_shard, policy="default").exchange_type


# ---- guard mode --------------------------------------------------------------


def test_guard_rejects_nonfinite_input():
    trip = _triplets()
    t = _local(trip, guard=True)
    values = _values(trip)
    values[3] = np.nan
    with pytest.raises(errors.HostExecutionError) as ei:
        t.backward(values)
    assert "non-finite" in str(ei.value)
    assert _counter_sum("guard_failures_total") == 1


def test_guard_catches_nan_poisoned_output():
    trip = _triplets()
    t = _local(trip, guard=True)
    with faults.inject("engine.execute=nan"):
        with pytest.raises(errors.HostExecutionError) as ei:
            t.backward(_values(trip))
    assert "non-finite" in str(ei.value)


def test_guard_catches_inf_corrupted_output():
    trip = _triplets()
    t = _local(trip, guard=True)
    with faults.inject("engine.execute=corrupt"):
        with pytest.raises(errors.HostExecutionError):
            t.backward(_values(trip))


def test_guard_env_knob(monkeypatch):
    trip = _triplets()
    monkeypatch.setenv(faults.GUARD_ENV, "1")
    t = _local(trip)
    assert t._guard is True
    # explicit kwarg beats the env knob
    assert _local(trip, guard=False)._guard is False
    with faults.inject("engine.execute=nan"):
        with pytest.raises(errors.HostExecutionError):
            t.backward(_values(trip))


def test_guard_off_lets_nan_flow():
    """Without guard mode the NaN payload flows (documented: the chaos
    invariant for data-poisoning kinds requires the guard) — this pins the
    contract boundary rather than an accident."""
    trip = _triplets()
    t = _local(trip, guard=False)
    with faults.inject("engine.execute=nan"):
        out = t.backward(_values(trip))
    assert np.isnan(np.asarray(out)).any()
    assert _counter_sum("guard_checks_total") == 0


def test_guard_counts_checks_and_preserves_numerics():
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    t = _local(trip, guard=True)
    assert_close(t.backward(values), expect)
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, values)
    assert _counter_sum("guard_checks_total") >= 4  # in+out, both directions
    assert _counter_sum("guard_failures_total") == 0


def test_guard_distributed_rejects_poisoned_shard():
    trip = _triplets()
    values = _values(trip)
    per_shard = distribute_triplets(trip, 2, DIM)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    t = _dist(per_shard, guard=True)
    vps[1] = vps[1].copy()
    vps[1][0] = np.inf
    with pytest.raises(errors.HostExecutionError):
        t.backward(vps)


# ---- errors taxonomy through capi -------------------------------------------


def _error_classes():
    return sorted(
        (
            cls
            for cls in vars(errors).values()
            if inspect.isclass(cls) and issubclass(cls, errors.GenericError)
        ),
        key=lambda c: c.__name__,
    )


def test_error_taxonomy_roundtrips_to_c_codes():
    """Every exception class in the taxonomy carries a distinct enum value
    and capi.error_code translates an instance back to exactly that value —
    the C shim's catch-and-translate contract, machine-checked."""
    classes = _error_classes()
    assert len(classes) == 25  # GenericError + 24 typed subclasses
    seen = {}
    for cls in classes:
        code = capi.error_code(cls("chaos"))
        assert code == int(cls.error_code)
        assert code not in seen, (cls, seen[code])
        seen[code] = cls
    # full enum coverage minus SUCCESS and the C-side-only INVALID_HANDLE
    expected = set(int(c) for c in errors.ErrorCode) - {
        int(errors.ErrorCode.SUCCESS),
        int(errors.ErrorCode.INVALID_HANDLE),
    }
    assert set(seen) == expected


def test_untyped_exceptions_map_to_fallback_codes():
    assert capi.error_code(faults.InjectedFault("x")) == int(errors.ErrorCode.UNKNOWN)
    assert capi.error_code(ValueError("x")) == int(
        errors.ErrorCode.INVALID_PARAMETER
    )
    assert capi.error_code(MemoryError()) == int(errors.ErrorCode.ALLOCATION)


def test_guard_and_ladder_failures_map_to_right_enums():
    trip = _triplets()
    t = _local(trip, guard=True)
    with faults.inject("engine.execute=nan"):
        with pytest.raises(errors.HostExecutionError) as ei:
            t.backward(_values(trip))
    assert capi.error_code(ei.value) == int(errors.ErrorCode.HOST_EXECUTION)

    per_shard = distribute_triplets(trip, 2, DIM)
    with faults.inject("exchange.build=raise"):
        with pytest.raises(errors.MPIError) as ei:
            _dist(per_shard)
    assert capi.error_code(ei.value) == int(errors.ErrorCode.MPI)

    # accelerator plans surface the GPU side of the dual error surface
    assert faults.execution_error("tpu") is errors.GPUFFTError
    assert capi.error_code(errors.GPUFFTError("x")) == int(
        errors.ErrorCode.GPU_FFT
    )
