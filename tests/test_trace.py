"""spfft_tpu.obs.trace: flight recorder, run IDs, Chrome export, dump-on-error.

Contract layers (ISSUE 4 acceptance):

* recorder — ring-buffer capacity/eviction honesty (``dropped``), the
  disarmed no-op fast path (shared falsy singletons, zero allocation),
  schema-pinned snapshots (``validate_trace`` + JSON round-trip);
* correlation — one run ID joins the plan card, the metrics window and the
  trace events of a plan's construction and executions, and event order is
  deterministic under ``delay`` fault injection;
* export — ``chrome_trace()`` loads as Chrome trace-event JSON with
  balanced begin/end pairs for every host phase;
* dump-on-error — a typed error (guard failure) flushes the recorder to
  ``SPFFT_TPU_TRACE_DUMP`` with the failing plan's run ID in the events.
"""
import glob
import json
import os
import subprocess
import sys
import warnings
from pathlib import Path

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    HostExecutionError,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    faults,
    obs,
)
from spfft_tpu.obs import trace
from utils import random_sparse_triplets


@pytest.fixture(autouse=True)
def fresh_trace():
    """Each test sees an armed, empty recorder and leaves tracing disarmed
    (the process default) with clean metrics."""
    obs.clear()
    trace.enable(capacity=4096)
    yield
    trace.disable()
    obs.clear()


def _roundtrip(dim=8, guard=None, seed=0):
    trip = random_sparse_triplets(np.random.default_rng(seed), dim, dim, dim, 0.5)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dim, dim, dim,
        indices=trip, guard=guard,
    )
    rng = np.random.default_rng(seed + 1)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t.backward(values)
    t.forward(scaling=ScalingType.FULL)
    return t


# ---- recorder ----------------------------------------------------------------


def test_ring_buffer_capacity_and_eviction():
    trace.enable(capacity=8)
    for i in range(20):
        trace.event("guard", check=f"c{i}", verdict="ok")
    snap = trace.snapshot()
    assert snap["capacity"] == 8
    assert len(snap["events"]) == 8
    # honesty about truncation: 12 evictions counted, the LAST 8 retained
    assert snap["dropped"] == 12
    assert [ev["seq"] for ev in snap["events"]] == list(range(13, 21))
    assert [ev["args"]["check"] for ev in snap["events"]] == [
        f"c{i}" for i in range(12, 20)
    ]


def test_disarmed_recorder_is_shared_noop():
    trace.disable()
    assert not trace.enabled()
    # zero-allocation contract: every disarmed scope is THE shared
    # singleton, and emitting records nothing
    s1 = trace.span("phase", label="x")
    s2 = trace.span("fence")
    op = trace.operation("plan")
    assert s1 is s2 is op
    with s1:
        trace.event("guard", check="noop", verdict="ok")
    snap = trace.snapshot()
    assert snap["enabled"] is False
    assert snap["events"] == [] and snap["capacity"] == 0
    # disarmed transform path records no trace events either
    _roundtrip()
    assert trace.snapshot()["events"] == []


def test_snapshot_schema_and_json_roundtrip():
    with trace.operation("plan", kind="local"):
        trace.event("decision", what="engine", choice="xla")
    snap = trace.snapshot()
    assert snap["schema"] == trace.TRACE_SCHEMA == "spfft_tpu.obs.trace/1"
    assert trace.validate_trace(snap) == []
    assert json.loads(json.dumps(snap)) == snap
    # the validator flags drift
    assert trace.validate_trace({"schema": "bogus/9"})
    bad = dict(snap, events=[{"seq": 1, "ts": 0.0, "run": None,
                              "name": "nope", "ph": "Z"}])
    findings = trace.validate_trace(bad)
    assert any("ph" in f for f in findings)
    assert any("name" in f for f in findings)
    assert any("args" in f for f in findings)


def test_operation_nesting_records_parent_run():
    with trace.operation("plan", run_id="rP") as _:
        assert trace.current_run_id() == "rP"
        with trace.operation("tune.trial", label="cand"):
            inner = trace.current_run_id()
            assert inner != "rP"
            trace.event("fault.injected", site="tuning.trial", kind="raise")
        assert trace.current_run_id() == "rP"
    assert trace.current_run_id() is None
    events = trace.snapshot()["events"]
    trial_b = [e for e in events if e["name"] == "tune.trial" and e["ph"] == "B"]
    assert trial_b and trial_b[0]["args"]["parent"] == "rP"
    assert trial_b[0]["run"] == inner
    instant = [e for e in events if e["name"] == "fault.injected"]
    assert instant[0]["run"] == inner


def test_trace_env_knobs_arm_at_import():
    """SPFFT_TPU_TRACE=1 arms the recorder at import with the
    SPFFT_TPU_TRACE_CAP capacity, before any user code runs."""
    r = subprocess.run(
        [
            sys.executable, "-c",
            "from spfft_tpu.obs import trace\n"
            "assert trace.enabled()\n"
            "snap = trace.snapshot()\n"
            "assert snap['capacity'] == 4, snap['capacity']\n"
            "print('ok')\n",
        ],
        env={
            **os.environ,
            "SPFFT_TPU_TRACE": "1",
            "SPFFT_TPU_TRACE_CAP": "4",
            "JAX_PLATFORMS": "cpu",
        },
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr[-1000:]
    assert "ok" in r.stdout


# ---- run-ID correlation ------------------------------------------------------


def test_run_id_joins_card_metrics_and_trace():
    t = _roundtrip()
    card = t.report()
    assert obs.validate_plan_card(card) == []
    rid = card["run_id"]
    assert rid == t._run_id and rid
    # every event of this plan's construction AND executions carries the
    # card's run ID — the one join key across the three artifacts
    events = trace.snapshot()["events"]
    assert events and all(ev["run"] == rid for ev in events)
    names = {ev["name"] for ev in events}
    assert {"plan", "execute", "phase", "fence", "decision"} <= names
    # the metrics window of the same process shows what ran
    snap = obs.snapshot()
    assert any(k.startswith("transforms_total") for k in snap["counters"])
    doc = {"plan": card, "metrics": snap, "trace": trace.snapshot()}
    assert json.loads(json.dumps(doc)) == doc
    # a second plan gets a distinct run ID — runs do not alias
    t2 = _roundtrip(seed=7)
    assert t2.report()["run_id"] != rid


def test_decision_event_matches_card():
    t = _roundtrip()
    card = t.report()
    decisions = [
        ev for ev in trace.snapshot()["events"] if ev["name"] == "decision"
    ]
    (engine_decision,) = [d for d in decisions if d["args"]["what"] == "engine"]
    assert engine_decision["args"]["choice"] == card["engine"]
    assert engine_decision["run"] == card["run_id"]


def test_deterministic_ordering_under_delay_injection():
    """With a delay fault armed at the fence, two identical runs record the
    identical event sequence — injected latency shifts timestamps, never
    order (the flight recorder's total order is seq, not ts)."""

    def shape():
        trace.clear()
        _roundtrip()
        return [
            (ev["name"], ev["ph"], ev["args"].get("label"))
            for ev in trace.snapshot()["events"]
        ]

    with faults.inject("sync.fence=delay"):
        first = shape()
        second = shape()
    assert first == second
    assert ("fault.injected", "i", None) in first
    seqs = [ev["seq"] for ev in trace.snapshot()["events"]]
    assert seqs == sorted(seqs)


# ---- Chrome export -----------------------------------------------------------


def test_chrome_trace_loads_with_balanced_host_phases():
    """ISSUE 4 acceptance: the Chrome export of a traced forward+backward
    loads as valid trace-event JSON and carries begin/end pairs for every
    host phase, one named track per phase."""
    _roundtrip()
    chrome = json.loads(json.dumps(trace.chrome_trace()))
    events = chrome["traceEvents"]
    assert chrome["displayTimeUnit"] == "ms"
    track_names = {
        e["args"]["name"] for e in events if e["name"] == "thread_name"
    }
    for phase in (
        "backward", "forward", "dispatch", "wait",
        "input staging", "output staging", "Execution init",
    ):
        assert phase in track_names
        begins = [e for e in events if e["name"] == phase and e["ph"] == "B"]
        ends = [e for e in events if e["name"] == phase and e["ph"] == "E"]
        assert begins, f"no begin event for host phase {phase!r}"
        assert len(begins) == len(ends), f"unbalanced phase {phase!r}"
    # spans carry their run ID into the viewer's args pane
    assert all(
        "run" in e["args"] for e in events if e["ph"] in ("B", "E", "i")
    )
    # timestamps are microseconds, non-decreasing per the seq order
    ts = [e["ts"] for e in events if e["ph"] in ("B", "E", "i")]
    assert ts == sorted(ts)


def test_timing_tree_and_trace_share_scopes():
    """Satellite: timing.scoped feeds BOTH the timing tree and the flight
    recorder when both are armed — the nested timing nodes ARE the trace's
    phase slices, not a separate report-only tree."""
    from spfft_tpu import timing

    timing.enable()
    try:
        timing.clear()
        _roundtrip()
        tree = timing.process()
        labels = {
            ev["args"]["label"]
            for ev in trace.snapshot()["events"]
            if ev["name"] == "phase"
        }
        for node in tree.sub:
            assert node.label in labels
    finally:
        timing.disable()
        timing.clear()


# ---- dump-on-error -----------------------------------------------------------


def test_guard_failure_dumps_flight_recorder(tmp_path, monkeypatch):
    monkeypatch.setenv(trace.TRACE_DUMP_ENV, str(tmp_path))
    trip = random_sparse_triplets(np.random.default_rng(3), 8, 8, 8, 0.5)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=trip, guard=True,
    )
    rid = t.report()["run_id"]
    poisoned = np.full(len(trip), np.nan, dtype=np.complex128)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(HostExecutionError):
            t.backward(poisoned)
    dumps = sorted(glob.glob(str(tmp_path / "trace-*.json")))
    assert dumps, "typed guard failure did not flush the flight recorder"
    doc = json.loads(open(dumps[-1]).read())
    assert doc["reason"] == "HostExecutionError"
    assert trace.validate_trace(doc) == []
    # the dump's events carry the failing plan's run ID (card join key)
    assert rid in {ev["run"] for ev in doc["events"]}
    names = {ev["name"] for ev in doc["events"]}
    assert "error" in names and "guard" in names
    (fail,) = [
        ev for ev in doc["events"]
        if ev["name"] == "guard" and ev["args"]["verdict"] == "fail"
    ]
    assert fail["run"] == rid


def test_suppressed_dumps_and_rotation(tmp_path, monkeypatch):
    """Expected-and-recovered typed errors must not flood the dump dir:
    suppression scopes silence dump() (events still record), and dump files
    rotate within DUMP_KEEP so disk stays bounded."""
    monkeypatch.setenv(trace.TRACE_DUMP_ENV, str(tmp_path))
    with trace.suppressed_dumps():
        assert trace.dump("handled") is None
        with pytest.raises(sp.InvalidParameterError):
            Transform(
                ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=None
            )
    assert not list(tmp_path.iterdir())
    # the error event itself still recorded — suppression only gates files
    assert any(
        ev["name"] == "error" for ev in trace.snapshot()["events"]
    )
    # outside the scope dumps write, and the filename index wraps < DUMP_KEEP
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        path = trace.dump("manual")
    assert path is not None and os.path.exists(path)
    idx = int(Path(path).stem.rsplit("-", 1)[1])
    assert 0 <= idx < trace.DUMP_KEEP


def test_dump_disabled_without_env(tmp_path):
    # no SPFFT_TPU_TRACE_DUMP: typed errors record the event but write nothing
    assert os.environ.get(trace.TRACE_DUMP_ENV) is None
    assert trace.dump("manual") is None
    with pytest.raises(sp.InvalidParameterError):
        Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=None
        )
    errors = [
        ev for ev in trace.snapshot()["events"] if ev["name"] == "error"
    ]
    assert errors and errors[-1]["args"]["type"] == "InvalidParameterError"


# ---- cross-host segments (ISSUE 16) ------------------------------------------


def test_segment_filters_by_run_and_bounds():
    with trace.with_run("r_a"):
        for i in range(10):
            trace.event("serve", what="admit", i=i)
    with trace.with_run("r_b"):
        trace.event("serve", what="admit")
    seg = trace.segment("r_a")
    assert seg["schema"] == trace.SEGMENT_SCHEMA
    assert seg["run"] == "r_a"
    assert len(seg["events"]) == 10
    assert trace.validate_segment(seg) == []
    # wire keys only: seq is recorder-local, run hoisted to the envelope
    assert set(seg["events"][0]) == {"ts", "name", "ph", "args"}
    # limit keeps the NEWEST events
    bounded = trace.segment("r_a", limit=3)
    assert [e["args"]["i"] for e in bounded["events"]] == [7, 8, 9]
    # other runs never leak into a segment
    assert len(trace.segment("r_b")["events"]) == 1
    assert trace.segment("r_nope")["events"] == []


def test_segment_empty_while_disarmed():
    trace.disable()
    seg = trace.segment("r_x")
    assert seg["events"] == [] and trace.validate_segment(seg) == []


def test_validate_segment_rejects_malformed():
    assert trace.validate_segment("nope") == ["segment (not a dict)"]
    findings = trace.validate_segment({"schema": "bogus/0", "events": [42]})
    assert any("run" in f for f in findings)
    assert any("schema" in f for f in findings)
    assert any("events[0]" in f for f in findings)
    bad_ev = {
        "schema": trace.SEGMENT_SCHEMA, "run": "r",
        "events": [{"ts": 0.0, "name": "not_a_name", "ph": "Z", "args": {}}],
    }
    findings = trace.validate_segment(bad_ev)
    assert any(".ph" in f for f in findings)
    assert any(".name" in f for f in findings)


def test_splice_tags_host_and_skips_invalid_events():
    with trace.with_run("r_remote"):
        trace.event("rpc", what="remote_execute")
        trace.event("serve", what="admit")
    seg = trace.segment("r_remote")
    # one malformed rider: skipped, never spliced, never a failure
    seg["events"].append({"ts": 0.0, "name": "bogus", "ph": "i", "args": {}})
    trace.clear()
    assert trace.splice(seg, host="host7") == 2
    evs = [e for e in trace.snapshot()["events"] if e["run"] == "r_remote"]
    assert len(evs) == 2
    for ev in evs:
        assert ev["args"]["host"] == "host7"
        assert "remote_ts" in ev["args"]
    # counted 0 on a malformed envelope or while disarmed
    assert trace.splice({"schema": "bogus/0"}, host="h") == 0
    assert trace.splice("nope", host="h") == 0
    trace.disable()
    assert trace.splice(seg, host="h") == 0
