"""Task-graph scheduler suite (spfft_tpu.sched).

The acceptance invariants (ISSUE 9): graph semantics (dependency kinds,
cycle/dangling rejection, the retained-buffer serialization edge),
completion-order execution parity with the one-shot paths, TUNED placement
with full card provenance and warm-store reproducibility (same placement
twice, trials run once), the serve integration, and the chaos contract —
with ``sched.place`` / ``sched.run`` armed at every site and kind, every
task either completes with parity via a recorded rung or resolves with a
typed error, and the rest of the graph never stalls.
"""
import os

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    errors,
    faults,
    obs,
    sched,
    verify,
)
from utils import assert_close

DIM = 8
FUZZ_SEED = int(os.environ.get("SPFFT_TPU_FUZZ_SEED", "0"))


@pytest.fixture(autouse=True)
def clean_sched(monkeypatch, tmp_path):
    """Scheduler tests touch every process-global registry: disarm faults,
    reset breaker + metrics, point wisdom at a per-test tmp store, scrub the
    sched env knobs."""
    faults.disarm()
    faults.reseed(0)
    verify.breaker.reset()
    obs.enable()
    obs.clear()
    monkeypatch.setenv("SPFFT_TPU_WISDOM", str(tmp_path / "wisdom.json"))
    for knob in (sched.SCHED_INFLIGHT_ENV, "SPFFT_TPU_TUNE_CPU",
                 "SPFFT_TPU_TUNE_REPEATS", "SPFFT_TPU_TUNE_WARMUP"):
        monkeypatch.delenv(knob, raising=False)
    yield
    faults.disarm()
    verify.breaker.reset()


def _triplets(dim=DIM, sparsity=0.9):
    return sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)


def _plan(dim=DIM, trip=None, **kw):
    trip = _triplets(dim) if trip is None else trip
    return Transform(
        ProcessingUnit.HOST, TransformType.C2C, dim, dim, dim,
        indices=trip, **kw,
    )


def _values(n, seed=0):
    rng = np.random.default_rng(FUZZ_SEED + seed)
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


# ---- graph semantics --------------------------------------------------------


def test_graph_rejects_cycles_and_dangling_deps():
    g = sched.TaskGraph()
    with pytest.raises(errors.InvalidParameterError):
        g.add("backward", after=["nope"], transform=_plan())
    t = _plan()
    a = g.add("backward", payload=_values(t.num_local_elements), transform=t)
    assert g.task(a).deps == ()
    with pytest.raises(errors.InvalidParameterError):
        g.add("sideways", transform=t)  # unknown direction
    with pytest.raises(errors.InvalidParameterError):
        g.add("backward", id=a, transform=t)  # duplicate id
    # a cycle introduced behind the API's back is caught by order()
    g2 = sched.TaskGraph()
    t2 = _plan()
    x = g2.add("backward", payload=_values(t2.num_local_elements), transform=t2)
    y = g2.add("forward", transform=t2)
    g2.task(x).deps = (y,)  # force x -> y -> x
    with pytest.raises(errors.InvalidParameterError, match="cycle"):
        g2.order()


def test_graph_requires_exactly_one_plan_source():
    g = sched.TaskGraph()
    with pytest.raises(errors.InvalidParameterError):
        g.add("backward")  # neither transform nor spec
    with pytest.raises(errors.InvalidParameterError):
        g.add("backward", transform=_plan(),
              spec={"transform_type": "C2C", "dims": (8, 8, 8),
                    "indices": _triplets()})
    # spec'd forward without payload/input_from is not addressable
    with pytest.raises(errors.InvalidParameterError, match="forward"):
        g.add("forward", spec={"transform_type": "C2C", "dims": (8, 8, 8),
                               "indices": _triplets()})


def test_retained_buffer_constraint_serializes_shared_plans():
    """Two tasks naming one transform object get an implicit edge in
    submission order — the multi_transform duplicate-plan rule as an edge."""
    g = sched.TaskGraph()
    t = _plan()
    vals = _values(t.num_local_elements)
    b = g.add("backward", payload=vals, transform=t)
    f = g.add("forward", scaling=ScalingType.FULL, transform=t)
    assert b in g.task(f).deps
    assert g.depth() == 2
    report = sched.run_graph(g)
    assert_close(report.result(f), vals)


def test_flat_batch_matches_solo_results():
    trip = _triplets()
    plans = [_plan(trip=trip) for _ in range(5)]
    vals = [_values(p.num_local_elements, seed=i) for i, p in enumerate(plans)]
    outs = sched.run_tasks(plans, "backward", vals)
    for v, out in zip(vals, outs):
        solo = _plan(trip=trip)
        assert_close(out, solo.backward(v))
    depth = obs.snapshot()["gauges"]
    assert any(k.startswith("sched_graph_depth") for k in depth)


def test_cross_plan_dependency_chain():
    """input_from threads one task's result into another plan's payload."""
    trip = _triplets()
    t1, t2 = _plan(trip=trip), _plan(trip=trip)
    vals = _values(t1.num_local_elements)
    g = sched.TaskGraph()
    b = g.add("backward", payload=vals, transform=t1)
    f = g.add("forward", scaling=ScalingType.FULL, transform=t2, input_from=b)
    report = sched.run_graph(g)
    assert report.outcomes == {b: "completed", f: "completed"}
    assert_close(report.result(f), vals)


def test_run_tasks_validates_lengths():
    plans = [_plan()]
    with pytest.raises(errors.InvalidParameterError):
        sched.run_tasks(plans, "backward", [])
    with pytest.raises(errors.InvalidParameterError):
        sched.run_tasks(plans, ["backward", "forward"], [None])
    with pytest.raises(errors.InvalidParameterError):
        sched.run_tasks(plans, "backward", [None], scalings=[])


def test_inflight_env_knob_validation(monkeypatch):
    monkeypatch.setenv(sched.SCHED_INFLIGHT_ENV, "not-a-number")
    with pytest.raises(errors.InvalidParameterError):
        sched.resolve_inflight()
    monkeypatch.setenv(sched.SCHED_INFLIGHT_ENV, "3")
    assert sched.resolve_inflight() == 3
    assert sched.resolve_inflight(1) == 1


# ---- interleaving / windows -------------------------------------------------


@pytest.mark.parametrize("inflight", [1, 2, 7])
def test_window_sizes_preserve_results(inflight):
    """Any window produces identical results — the window is a throughput
    knob, never a semantics knob."""
    trip = _triplets()
    plans = [_plan(trip=trip) for _ in range(5)]
    vals = [_values(p.num_local_elements, seed=i) for i, p in enumerate(plans)]
    expect = [_plan(trip=trip).backward(v) for v in vals]
    outs = sched.run_tasks(plans, "backward", vals, max_inflight=inflight)
    for got, want in zip(outs, expect):
        assert_close(got, want)


def test_mixed_direction_mixed_geometry_graph():
    rng = np.random.default_rng(FUZZ_SEED + 11)
    g = sched.TaskGraph()
    expects = {}
    for i, dim in enumerate((4, 8, 6)):
        trip = _triplets(dim)
        t = _plan(dim, trip=trip)
        vals = _values(t.num_local_elements, seed=20 + i)
        b = g.add("backward", payload=vals, transform=t, id=f"b{dim}")
        f = g.add("forward", scaling=ScalingType.FULL, transform=t,
                  id=f"f{dim}")
        expects[f] = vals
        expects[b] = _plan(dim, trip=trip).backward(vals)
    space = rng.standard_normal((4, 4, 4)) + 1j * rng.standard_normal((4, 4, 4))
    tf = _plan(4)
    fid = g.add("forward", payload=space, transform=tf, id="solo-fwd")
    expects[fid] = _plan(4).forward(space.copy())
    report = sched.run_graph(g, max_inflight=3)
    assert set(report.outcomes.values()) == {"completed"}
    for tid, want in expects.items():
        assert_close(report.result(tid), want)


# ---- placement --------------------------------------------------------------


def test_model_placement_round_robins_and_stamps_cards():
    import jax

    trip = _triplets()
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip}
    vals = _values(len(trip))
    g = sched.TaskGraph()
    ids = [g.add("backward", payload=vals, spec=spec, id=f"s{i}")
           for i in range(4)]
    pool = sched.PlanPool()
    report = sched.run_graph(g, pool=pool)
    assert report.placement["provenance"] == "model"
    width = min(report.placement["choice"]["width"], len(jax.devices()))
    devices = {str(g.task(tid).plan.device) for tid in ids}
    assert len(devices) == min(width, len(ids))
    assert len(pool) == len(devices)  # one plan per (geometry, device)
    card = g.task(ids[0]).plan.report()
    assert not obs.validate_plan_card(card), obs.validate_plan_card(card)
    placement = card["placement"]
    assert placement["provenance"] == "model"
    assert placement["hit"] is False
    assert placement["device"] == str(g.task(ids[0]).plan.device)
    expect = _plan(trip=trip).backward(vals)
    for tid in ids:
        assert_close(report.result(tid), expect)


def test_tuned_placement_is_reproducible_from_warm_store(monkeypatch):
    """The provenance acceptance bar: first tuned placement measures trial
    widths and persists; the second resolves from wisdom with ZERO new
    trials and the SAME width."""
    monkeypatch.setenv("SPFFT_TPU_TUNE_CPU", "1")
    monkeypatch.setenv("SPFFT_TPU_TUNE_REPEATS", "1")
    trip = _triplets()
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip}
    vals = _values(len(trip))

    def make_graph():
        g = sched.TaskGraph()
        for i in range(4):
            g.add("backward", payload=vals, spec=spec, id=f"s{i}")
        return g

    pool = sched.PlanPool()
    r1 = sched.run_graph(make_graph(), pool=pool, policy="tuned")
    assert r1.placement["provenance"] == "wisdom"
    assert r1.placement["hit"] is False
    measured = [row for row in r1.placement["trials"] if "ms" in row]
    assert measured, r1.placement["trials"]
    before = obs.snapshot()["counters"]
    trials_before = sum(
        v for k, v in before.items() if k.startswith("tuning_trials_total")
    )
    g2 = make_graph()
    r2 = sched.run_graph(g2, pool=pool, policy="tuned")
    assert r2.placement["hit"] is True
    assert r2.placement["choice"] == r1.placement["choice"]
    after = obs.snapshot()["counters"]
    trials_after = sum(
        v for k, v in after.items() if k.startswith("tuning_trials_total")
    )
    assert trials_after == trials_before, "warm placement re-ran trials"
    # the decision provenance rides every placed plan's card
    card = g2.task("s0").plan.report()
    assert not obs.validate_plan_card(card)
    assert card["placement"]["provenance"] == "wisdom"
    assert card["placement"]["hit"] is True


def test_cpu_only_tuned_placement_falls_back_to_model():
    """Without SPFFT_TPU_TUNE_CPU the tuned policy must not trial on a
    CPU-only host: model placement, reason recorded."""
    trip = _triplets()
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip}
    g = sched.TaskGraph()
    g.add("backward", payload=_values(len(trip)), spec=spec)
    report = sched.run_graph(g, policy="tuned")
    assert report.placement["provenance"] == "model"
    assert "trials skipped" in report.placement["reason"]


def test_pinned_width_wins_outright():
    trip = _triplets()
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip}
    vals = _values(len(trip))
    g = sched.TaskGraph()
    ids = [g.add("backward", payload=vals, spec=spec, id=f"s{i}")
           for i in range(3)]
    report = sched.run_graph(g, width=1)
    assert report.placement["provenance"] == "pinned"
    assert {str(g.task(t).plan.device) for t in ids} == {
        str(g.task(ids[0]).plan.device)
    }


def test_sched_candidates_shape():
    from spfft_tpu.tuning import sched_candidates

    assert [c["width"] for c in sched_candidates(8)] == [1, 2, 4, 8]
    assert [c["width"] for c in sched_candidates(6)] == [1, 2, 4, 6]
    assert [c["width"] for c in sched_candidates(1)] == [1]
    assert all(c["label"] == f"rr{c['width']}" for c in sched_candidates(8))


# ---- failure ladder / chaos -------------------------------------------------


def test_failed_task_demotes_without_stalling_graph():
    """sched.run armed raise at rate 1.0: the primary path always fails, the
    ladder demotes through the reference rung, the result holds parity and
    the graph completes."""
    trip = _triplets()
    t = _plan(trip=trip)
    vals = _values(t.num_local_elements)
    expect = _plan(trip=trip).backward(vals)
    with faults.inject("sched.run=raise:1.0"):
        g = sched.TaskGraph()
        tid = g.add("backward", payload=vals, transform=t)
        report = sched.run_graph(g)
    assert report.outcomes[tid] == "demoted"
    assert_close(report.result(tid), expect)
    counters = obs.snapshot()["counters"]
    assert counters.get('sched_tasks_total{outcome="demoted"}', 0) == 1


def test_failed_task_without_demotion_resolves_typed_and_cascades():
    trip = _triplets()
    t1, t2 = _plan(trip=trip), _plan(trip=trip)
    t3 = _plan(trip=trip)
    vals = _values(t1.num_local_elements)
    with faults.inject("sched.run=raise:1.0"):
        g = sched.TaskGraph()
        b = g.add("backward", payload=vals, transform=t1)
        f = g.add("forward", scaling=ScalingType.FULL, transform=t2,
                  input_from=b)
        report = sched.run_graph(g, demote=False, retries=0)
    assert report.outcomes[b] == "failed"
    assert isinstance(report.errors[b], errors.HostExecutionError)
    assert report.outcomes[f] == "upstream_failed"
    with pytest.raises(errors.HostExecutionError, match="upstream"):
        report.result(f)
    # an unrelated graph still runs clean afterwards — no stall, no leak
    outs = sched.run_tasks([t3], "backward", [vals])
    assert_close(outs[0], _plan(trip=trip).backward(vals))


def test_retry_rung_heals_transient_faults():
    """At rate 0.5 with retries, tasks heal by re-dispatch (or demote) —
    never an untyped escape, never a wrong answer."""
    faults.reseed(FUZZ_SEED)
    trip = _triplets()
    plans = [_plan(trip=trip) for _ in range(6)]
    vals = [_values(p.num_local_elements, seed=i) for i, p in enumerate(plans)]
    expect = [_plan(trip=trip).backward(v) for v in vals]
    with faults.inject("sched.run=raise:0.5"):
        g = sched.TaskGraph()
        ids = [g.add("backward", payload=v, transform=p)
               for p, v in zip(plans, vals)]
        report = sched.run_graph(g, retries=2)
    for tid, want in zip(ids, expect):
        assert report.outcomes[tid] in ("completed", "demoted")
        assert_close(report.result(tid), want)


@pytest.mark.parametrize("site", ["sched.place", "sched.run"])
@pytest.mark.parametrize("kind", ["raise", "nan", "corrupt", "delay"])
def test_chaos_every_site_every_kind(site, kind):
    """The arm-every-site invariant for the scheduler's sites: under every
    kind at rate 1.0, every task completes with parity via a recorded rung
    or resolves typed — and the graph always terminates. nan/corrupt kinds
    poison the in-flight payload, so plans run in guard mode (the scan that
    catches poisoned outputs is the guard's job, exactly as engine.execute
    chaos runs do)."""
    guard = kind in ("nan", "corrupt")
    trip = _triplets()
    plans = [_plan(trip=trip, guard=guard) for _ in range(3)]
    vals = [_values(p.num_local_elements, seed=i) for i, p in enumerate(plans)]
    expect = [_plan(trip=trip).backward(v) for v in vals]
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip,
            "guard": guard}
    with faults.inject(f"{site}={kind}:1.0"):
        g = sched.TaskGraph()
        ids = [g.add("backward", payload=v, transform=p)
               for p, v in zip(plans, vals)]
        ids.append(g.add("backward", payload=vals[0], spec=spec, id="placed"))
        report = sched.run_graph(g, retries=1)
    for tid, want in zip(ids, expect + [expect[0]]):
        outcome = report.outcomes[tid]
        if outcome in ("completed", "demoted"):
            assert_close(report.result(tid), want)
            if outcome == "demoted":
                # the rung is recorded, not silent
                counters = obs.snapshot()["counters"]
                assert counters.get(
                    'sched_tasks_total{outcome="demoted"}', 0
                ) > 0
        else:
            assert isinstance(report.errors[tid], errors.GenericError)
    # the injections actually fired (vacuous-green guard); delay alone
    # fires without counting only when nothing flows through the payload
    if kind == "raise":
        assert any(
            k.startswith("faults_injected_total")
            for k in obs.snapshot()["counters"]
        )


def test_auto_ids_never_collide_with_caller_ids():
    g = sched.TaskGraph()
    t = _plan()
    vals = _values(t.num_local_elements)
    a = g.add("backward", payload=vals, transform=t)  # auto "t0"
    g.add("backward", id="t2", payload=vals, transform=t)
    b = g.add("backward", payload=vals, transform=t)  # must skip "t2"
    c = g.add("backward", payload=vals, transform=t)
    assert len({a, "t2", b, c}) == 4


def test_expired_task_resolves_typed_without_dispatch():
    """A task whose deadline passed resolves DeadlineExceededError before
    any device work — first attempts and retries alike (the serving
    layer's between-retries shedding rule, enforced in the executor)."""
    import time as _time

    trip = _triplets()
    live, dead = _plan(trip=trip), _plan(trip=trip)
    vals = _values(live.num_local_elements)
    g = sched.TaskGraph()
    ok = g.add("backward", payload=vals, transform=live)
    late = g.add("backward", payload=vals, transform=dead,
                 deadline=_time.monotonic() - 0.001)
    report = sched.run_graph(g)
    assert report.outcomes[ok] == "completed"
    assert report.outcomes[late] == "failed"
    assert isinstance(report.errors[late], errors.DeadlineExceededError)
    assert g.task(late).attempts == 0  # never dispatched, never demoted


def test_non_retryable_typed_failure_resolves_task_not_graph():
    """A parameter-class typed error (wrong payload size) would fail
    identically on retry or the reference rung: the TASK resolves failed
    with that error, untouched by the ladder, and the rest of the graph
    still completes."""
    trip = _triplets()
    good, bad = _plan(trip=trip), _plan(trip=trip)
    vals = _values(good.num_local_elements)
    g = sched.TaskGraph()
    okid = g.add("backward", payload=vals, transform=good)
    badid = g.add("backward", payload=vals[:3], transform=bad)  # wrong size
    report = sched.run_graph(g, retries=2)
    assert report.outcomes[okid] == "completed"
    assert report.outcomes[badid] == "failed"
    assert isinstance(report.errors[badid], errors.InvalidParameterError)
    assert g.task(badid).attempts == 1  # no retries: not a ladder error
    assert_close(report.result(okid), _plan(trip=trip).backward(vals))


def test_place_fault_degrades_to_model_placement():
    trip = _triplets()
    spec = {"transform_type": "C2C", "dims": (DIM,) * 3, "indices": trip}
    vals = _values(len(trip))
    expect = _plan(trip=trip).backward(vals)
    with faults.inject("sched.place=raise:1.0"):
        g = sched.TaskGraph()
        tid = g.add("backward", payload=vals, spec=spec)
        report = sched.run_graph(g)
    assert report.placement["provenance"] == "model"
    assert "placement fault" in report.placement["reason"]
    assert_close(report.result(tid), expect)
    counters = obs.snapshot()["counters"]
    assert any(
        "sched_place_failed" in k for k in counters
        if k.startswith("degradations_total")
    ), counters


def test_supervised_plans_execute_under_their_supervisor():
    """verify= plans in a graph run whole under the recovery supervisor (it
    owns the ladder); with the engine corrupted the supervisor recovers and
    the scheduler sees a completed task."""
    trip = _triplets()
    t = _plan(trip=trip, verify="on")
    vals = _values(t.num_local_elements)
    expect = _plan(trip=trip).backward(vals)
    with faults.inject("engine.execute=corrupt:1.0"):
        outs = sched.run_tasks([t], "backward", [vals])
    assert_close(outs[0], expect)
    counters = obs.snapshot()["counters"]
    recoveries = sum(
        v for k, v in counters.items()
        if k.startswith("verify_recoveries_total")
    )
    assert recoveries > 0, counters


# ---- obs exposure -----------------------------------------------------------


def test_metrics_and_trace_exposure():
    obs.trace.enable()
    try:
        trip = _triplets()
        plans = [_plan(trip=trip) for _ in range(3)]
        vals = [_values(p.num_local_elements, seed=i)
                for i, p in enumerate(plans)]
        sched.run_tasks(plans, "backward", vals)
        snap = obs.snapshot()
        assert snap["counters"].get(
            'sched_tasks_total{outcome="completed"}', 0
        ) == 3
        assert "sched_inflight" in snap["gauges"]
        assert snap["gauges"]["sched_inflight"] == 0  # drained
        assert snap["gauges"].get("sched_graph_depth") == 1
        events = [
            e for e in obs.trace.snapshot()["events"] if e["name"] == "sched"
        ]
        whats = {e["args"].get("what") for e in events}
        assert {"graph", "dispatch", "finalize"} <= whats, whats
    finally:
        obs.trace.disable()
        obs.trace.clear()


def test_graph_report_describe_is_json_plain():
    import json

    trip = _triplets()
    t = _plan(trip=trip)
    g = sched.TaskGraph()
    g.add("backward", payload=_values(t.num_local_elements), transform=t)
    report = sched.run_graph(g)
    doc = report.describe()
    json.dumps(doc)
    assert doc["tasks"] == 1 and doc["depth"] == 1
    assert doc["outcomes"] == {"completed": 1}
    json.dumps(g.describe())


# ---- serve integration ------------------------------------------------------


def test_serve_sched_mode_mixed_geometries_one_cycle():
    from spfft_tpu.serve import TransformService

    trip_a = _triplets(DIM, 0.9)
    trip_b = _triplets(DIM, 0.5)
    vals_a = _values(len(trip_a), seed=1)
    vals_b = _values(len(trip_b), seed=2)
    expect_a = _plan(trip=trip_a).backward(vals_a)
    expect_b = _plan(trip=trip_b).backward(vals_b)
    with TransformService(start=False, queue_capacity=32, sched=True) as svc:
        assert svc.stats()["sched"] is True
        ta = [svc.submit(TransformType.C2C, (DIM,) * 3, trip_a, vals_a)
              for _ in range(3)]
        tb = [svc.submit(TransformType.C2C, (DIM,) * 3, trip_b, vals_b)
              for _ in range(3)]
        processed = svc.pump()
        assert processed == 2  # both geometry groups in ONE cycle
        for tk in ta:
            assert_close(tk.result(timeout=30), expect_a)
        for tk in tb:
            assert_close(tk.result(timeout=30), expect_b)


def test_serve_sched_chaos_tickets_always_resolve():
    from spfft_tpu.serve import TransformService

    trip = _triplets()
    vals = _values(len(trip))
    expect = _plan(trip=trip).backward(vals)
    with faults.inject("sched.run=raise:1.0"):
        with TransformService(
            start=False, queue_capacity=32, sched=True
        ) as svc:
            tickets = [
                svc.submit(TransformType.C2C, (DIM,) * 3, trip, vals)
                for _ in range(3)
            ]
            svc.pump()
            for tk in tickets:
                # demoted through the scheduler's reference rung: parity
                assert_close(tk.result(timeout=30), expect)
    counters = obs.snapshot()["counters"]
    assert sum(
        v for k, v in counters.items()
        if k.startswith("serve_demotions_total")
    ) > 0, counters


def test_serve_sched_pump_respects_max_batches():
    from spfft_tpu.serve import TransformService

    trip = _triplets()
    vals = _values(len(trip))
    with TransformService(
        start=False, queue_capacity=32, sched=True, sched_batches=8,
        batch_max=1,
    ) as svc:
        for _ in range(3):
            svc.submit(TransformType.C2C, (DIM,) * 3, trip, vals)
        assert svc.pump(max_batches=2) == 2
        assert svc.queue.depth() == 1
