"""ExecType semantics: SPFFT_EXEC_SYNCHRONOUS / ASYNCHRONOUS + synchronize().

Reference: include/spfft/types.h:108-117 (SpfftExecType),
include/spfft/transform.hpp:225 (set_execution_mode). The host-facing calls
materialize numpy results either way (docs/details.md "Asynchronous
execution"); these tests pin the mode plumbing and that results are identical
in both modes.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import ExecType, ProcessingUnit, ScalingType, Transform, TransformType
from spfft_tpu.errors import InvalidParameterError
from utils import assert_close, random_sparse_triplets


def _make(engine="xla"):
    rng = np.random.default_rng(8)
    trip = random_sparse_triplets(rng, 8, 9, 10, 0.5)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 9, 10, indices=trip, engine=engine
    )
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    return t, v


def test_default_mode_is_synchronous():
    t, _ = _make()
    assert t.execution_mode() == ExecType.SYNCHRONOUS


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_async_mode_same_results(engine):
    t, v = _make(engine)
    sync_space = t.backward(v)
    sync_round = t.forward(scaling=ScalingType.FULL)

    t.set_execution_mode(ExecType.ASYNCHRONOUS)
    assert t.execution_mode() == ExecType.ASYNCHRONOUS
    async_space = t.backward(v)
    t.synchronize()  # reference contract: wait on the retained space buffer
    async_round = t.forward(scaling=ScalingType.FULL)

    assert_close(async_space, sync_space)
    assert_close(async_round, sync_round)
    assert_close(async_round, v)


def test_invalid_mode_rejected():
    t, _ = _make()
    with pytest.raises((InvalidParameterError, ValueError)):
        t.set_execution_mode(99)


def test_synchronize_before_any_transform_is_noop():
    t, _ = _make()
    t.synchronize()  # no retained buffer yet; must not raise
