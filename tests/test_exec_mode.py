"""ExecType semantics: SPFFT_EXEC_SYNCHRONOUS / ASYNCHRONOUS + synchronize().

Reference: include/spfft/types.h:108-117 (SpfftExecType),
include/spfft/transform.hpp:225 (set_execution_mode). The host-facing calls
materialize numpy results either way (docs/details.md "Asynchronous
execution"); these tests pin the mode plumbing and that results are identical
in both modes.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import ExecType, ProcessingUnit, ScalingType, Transform, TransformType
from spfft_tpu.errors import InvalidParameterError
from utils import assert_close, random_sparse_triplets


def _make(engine="xla"):
    rng = np.random.default_rng(8)
    trip = random_sparse_triplets(rng, 8, 9, 10, 0.5)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 9, 10, indices=trip, engine=engine
    )
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    return t, v


def test_default_mode_is_synchronous():
    t, _ = _make()
    assert t.execution_mode() == ExecType.SYNCHRONOUS


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_async_mode_same_results(engine):
    t, v = _make(engine)
    sync_space = t.backward(v)
    sync_round = t.forward(scaling=ScalingType.FULL)

    t.set_execution_mode(ExecType.ASYNCHRONOUS)
    assert t.execution_mode() == ExecType.ASYNCHRONOUS
    async_space = t.backward(v)
    t.synchronize()  # reference contract: wait on the retained space buffer
    async_round = t.forward(scaling=ScalingType.FULL)

    assert_close(async_space, sync_space)
    assert_close(async_round, sync_round)
    assert_close(async_round, v)


def test_invalid_mode_rejected():
    t, _ = _make()
    with pytest.raises((InvalidParameterError, ValueError)):
        t.set_execution_mode(99)


def test_synchronize_before_any_transform_is_noop():
    t, _ = _make()
    t.synchronize()  # no retained buffer yet; must not raise


def test_synchronous_fence_scalar_path(monkeypatch):
    """SYNCHRONOUS must observe completion even where block_until_ready is
    advisory (the tunneled TPU platform, docs/details.md): fence() then takes a
    scalar-fetch path. Exercised here by declaring cpu advisory."""
    import jax
    import jax.numpy as jnp

    from spfft_tpu import sync

    monkeypatch.setattr(sync, "ADVISORY_PLATFORMS", frozenset({"cpu", "axon"}))

    # pairs, nested trees, complex (fetched via .real), scalars: all must fence
    tree = (
        jnp.arange(8.0),
        [jnp.ones((2, 3)), (jnp.asarray(1.5), jnp.arange(4) + 2j * jnp.arange(4))],
        np.arange(3),  # non-jax leaves pass through untouched
    )
    out = sync.fence(tree)
    assert out is tree

    # sharded leaves are fenced per addressable shard, not just element 0
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = sp.make_fft_mesh(8)
    sharded = jax.device_put(
        jnp.arange(16.0).reshape(8, 2), NamedSharding(mesh, PartitionSpec("fft"))
    )
    assert len(sharded.addressable_shards) == 8
    sync.fence((sharded,))

    # and the Transform SYNCHRONOUS path still returns correct results
    t, v = _make()
    space = t.backward(v)
    roundtrip = t.forward(scaling=ScalingType.FULL)
    assert_close(roundtrip, v)
    assert space.shape == (10, 9, 8)
