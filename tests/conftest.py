"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so distributed (mesh) paths are
exercised without TPU pod hardware — the analogue of the reference testing its MPI
paths under plain ``mpirun -n 2`` on a single CI VM
(reference: .github/workflows/ci.yml:80-84).

jax is already imported at interpreter startup in this environment (a site .pth
hook), so the platform is selected via jax.config (valid until first backend use)
rather than env vars.
"""
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.4.38: same knob spelled as an XLA flag
    import os

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
# Double precision is the reference's default precision; tests compare against the
# dense oracle at the reference's 1e-6 bar (tests/test_util/test_check_values.hpp:46-78).
jax.config.update("jax_enable_x64", True)
