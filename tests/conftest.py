"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so distributed (mesh) paths are
exercised without TPU pod hardware — the analogue of the reference testing its MPI
paths under plain ``mpirun -n 2`` on a single CI VM
(reference: .github/workflows/ci.yml:80-84). Must run before jax is imported.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Double precision is the reference's default precision; tests compare against the
# dense oracle at the reference's 1e-6 bar (tests/test_util/test_check_values.hpp:46-78).
jax.config.update("jax_enable_x64", True)
