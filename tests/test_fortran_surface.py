"""Fortran interface surface verification.

No Fortran compiler exists in this environment (the reference compiles its
module in CI, reference: include/spfft/spfft.f90 + .github workflows), so the
next-best check runs here: every ``bind(C)`` interface in
``native/include/spfft/spfft.f90`` must name a real C API function with the
same arity, and every C API function must carry a Fortran binding — a typo in
the interface blocks fails this test instead of a downstream user's link
step. When a Fortran compiler is present, the module itself is syntax-checked
too. Parsers are shared with the API-reference generator
(programs/api_surface.py), so docs and verification see the same surface.
"""
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "programs"))

from api_surface import (  # noqa: E402
    F90_PATH,
    REFERENCE_INCLUDE,
    c_enum_constants,
    c_functions,
    fortran_constants,
    fortran_functions,
    reference_only_names,
)


def test_every_fortran_binding_names_a_real_c_function_with_same_arity():
    fortran = fortran_functions()
    c = c_functions()
    assert fortran, "no bind(C) interfaces parsed from spfft.f90"
    missing = sorted(set(fortran) - set(c))
    assert not missing, f"Fortran bindings without a C function: {missing}"
    mismatched = sorted(name for name in fortran if fortran[name] != c[name])
    assert not mismatched, {
        name: (fortran[name], c[name]) for name in mismatched
    }


def test_every_c_function_has_a_fortran_binding():
    fortran = fortran_functions()
    c = c_functions()
    # pointer-returning accessors are unbindable as plain integer functions in
    # this scheme; the reference likewise exposes them through dedicated
    # Fortran-specific wrappers or not at all
    exempt = {
        name
        for name in c
        if name.endswith("_space_domain_data") or name.endswith("_ptr")
    }
    unbound = sorted(set(c) - set(fortran) - exempt)
    assert not unbound, f"C API functions with no Fortran binding: {unbound}"


def test_no_reference_only_c_api_names():
    """Every reference C prototype exists here with matching arity.

    The reference tree (read-only, /root/reference) defines the parity bar:
    a SIRIUS-style caller must find every name it links against, including the
    float Grid tier and the MPI stubs (reference: include/spfft/grid_float.h,
    transform.h:122,341, multi_transform.h:60-95)."""
    if not REFERENCE_INCLUDE.is_dir():
        pytest.skip("reference tree not present")
    assert reference_only_names() == []


def test_constants_match_between_fortran_and_c_both_directions():
    """Every C enum constant has a Fortran twin with the same value, and vice
    versa.

    Round-4 drift class: the module carried all 80 functions but stopped its
    error constants at SPFFT_GPU_ERROR=13 while errors.h defined the full GPU
    tier 14-22 (reference: include/spfft/spfft.f90:59-77 defines all 23), and
    no checker noticed because only names/arity of *functions* were machine-
    checked. This test closes that hole for constants in both directions."""
    fortran = fortran_constants()
    c = c_enum_constants()
    assert fortran and c, "constant parsers returned nothing"
    missing_in_fortran = sorted(set(c) - set(fortran))
    assert not missing_in_fortran, (
        f"C constants with no Fortran twin: {missing_in_fortran}"
    )
    missing_in_c = sorted(set(fortran) - set(c))
    assert not missing_in_c, (
        f"Fortran constants with no C definition: {missing_in_c}"
    )
    mismatched = {
        name: (fortran[name], c[name]) for name in c if fortran[name] != c[name]
    }
    assert not mismatched, f"value mismatches (fortran, c): {mismatched}"


def test_reference_fortran_constants_all_present():
    """Every constant the reference Fortran module defines exists here with
    the same value (reference: include/spfft/spfft.f90:28-110); extensions
    beyond the reference (e.g. the BF16 exchange values) are allowed."""
    ref_f90 = REFERENCE_INCLUDE / "spfft.f90"
    if not ref_f90.is_file():
        pytest.skip("reference tree not present")
    ref = fortran_constants(ref_f90)
    ours = fortran_constants()
    assert ref, "reference constant parser returned nothing"
    holes = {
        name: value
        for name, value in ref.items()
        if ours.get(name) != value
    }
    assert not holes, f"reference constants missing or mismatched here: {holes}"


def test_fortran_module_compiles_when_compiler_available():
    fc = shutil.which("gfortran") or shutil.which("flang") or shutil.which("f95")
    if fc is None:
        pytest.skip("no Fortran compiler in this environment")
    result = subprocess.run(
        [fc, "-fsyntax-only", str(F90_PATH)], capture_output=True, text=True
    )
    assert result.returncode == 0, result.stderr
