"""Chaos suite for self-verification (spfft_tpu.verify): ABFT checks, the
retry/demote recovery supervisor, and the engine circuit breaker.

The central invariant (ISSUE 5 acceptance): with verification armed and
``engine.execute`` corrupting every dispatch, a transform either returns a
result matching the jnp.fft reference (recovered, with the recovery counted
and a degradation rung recorded) or raises typed ``VerificationError`` — a
silently corrupted output is impossible. The suite pins each rung of the
detect -> retry -> demote -> break ladder, the check math itself, the strict
mode, the new ``verify.check`` fault site, and the plan-card/metrics/trace
exposure.
"""
import warnings

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    capi,
    errors,
    faults,
    obs,
    verify,
)
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close

DIM = 8

VERIFY_ENV_KNOBS = (
    verify.VERIFY_ENV,
    verify.VERIFY_RTOL_ENV,
    verify.VERIFY_SEED_ENV,
    verify.VERIFY_RETRIES_ENV,
    verify.VERIFY_BACKOFF_ENV,
    verify.VERIFY_JITTER_SEED_ENV,
    verify.breaker.BREAKER_K_ENV,
    verify.breaker.BREAKER_COOLDOWN_ENV,
)


@pytest.fixture(autouse=True)
def clean_verify(monkeypatch):
    """Disarm faults, reset the process-global breaker, fresh metrics, and
    scrub every verify env knob — verification state must never leak between
    tests (the breaker especially: it is process-global by design)."""
    faults.disarm()
    faults.reseed(0)
    verify.breaker.reset()
    obs.enable()
    obs.clear()
    for knob in VERIFY_ENV_KNOBS:
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setenv(verify.VERIFY_BACKOFF_ENV, "0.001")
    with warnings.catch_warnings():
        # corrupted attempts legitimately emit invalid-value RuntimeWarnings
        # while the poisoned result is fetched for checking
        warnings.simplefilter("ignore", RuntimeWarning)
        yield
    faults.disarm()
    verify.breaker.reset()


def _triplets(dim=DIM):
    return sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.8)


def _values(trip, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))


def _local(trip, **kwargs):
    return Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip, **kwargs
    )


def _dist(per_shard, **kwargs):
    return DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        [p.copy() for p in per_shard],
        mesh=sp.make_fft_mesh(2),
        **kwargs,
    )


def _counter_sum(prefix: str) -> int:
    snap = obs.snapshot()
    return sum(v for k, v in snap["counters"].items() if k.startswith(prefix))


# ---- mode resolution ---------------------------------------------------------


def test_mode_resolution(monkeypatch):
    assert verify.resolve_mode(None) == "off"
    assert verify.resolve_mode(True) == "on"
    assert verify.resolve_mode(False) == "off"
    assert verify.resolve_mode("strict") == "strict"
    monkeypatch.setenv(verify.VERIFY_ENV, "1")
    assert verify.resolve_mode(None) == "on"
    assert verify.resolve_mode(False) == "off"  # explicit kwarg beats the env
    monkeypatch.setenv(verify.VERIFY_ENV, "strict")
    assert verify.resolve_mode(None) == "strict"
    with pytest.raises(errors.InvalidParameterError):
        verify.resolve_mode("sometimes")
    monkeypatch.setenv(verify.VERIFY_ENV, "banana")
    with pytest.raises(errors.InvalidParameterError):
        verify.resolve_mode(None)


def test_off_mode_is_one_falsy_check():
    t = _local(_triplets())
    assert t._verifier is None  # the entire off-mode overhead per call
    t.backward(_values(_triplets()))
    assert _counter_sum("verify_checks_total") == 0


# ---- the checks themselves ---------------------------------------------------


def _dense_reference(trip, values, dim=DIM):
    grid = np.zeros((dim, dim, dim), dtype=np.complex128)
    for (x, y, z), v in zip(trip, values):
        grid[z, y, x] = v
    return np.fft.ifftn(grid) * grid.size  # unnormalized inverse DFT


def test_checks_pass_on_true_transform_pair():
    trip = _triplets()
    values = _values(trip)
    space = _dense_reference(trip, values)
    verdicts = verify.run_checks(
        direction="backward",
        freq=values,
        space=space,
        triplets=trip,
        transform_type=TransformType.C2C,
        rtol=1e-9,
    )
    assert [v["check"] for v in verdicts] == ["parseval", "dc", "probe"]
    assert all(v["verdict"] == "pass" for v in verdicts)


def test_checks_flag_corrupted_space():
    trip = _triplets()
    values = _values(trip)
    space = _dense_reference(trip, values)
    space[1, 2, 3] += 100.0  # finite-but-wrong: the case guard mode misses
    verdicts = verify.run_checks(
        direction="backward",
        freq=values,
        space=space,
        triplets=trip,
        transform_type=TransformType.C2C,
        rtol=1e-6,
    )
    failed = {v["check"] for v in verdicts if v["verdict"] == "fail"}
    assert "parseval" in failed or "dc" in failed, verdicts


def test_forward_checks_and_scaling():
    trip = _triplets()
    values = _values(trip)
    space = _dense_reference(trip, values)
    n = float(space.size)
    # a perfect FULL-scaled forward of `space` returns `values` at the
    # sparse sites (the spectrum of `space` IS the sparse set)
    verdicts = verify.run_checks(
        direction="forward",
        freq=values,
        space=space,
        triplets=trip,
        transform_type=TransformType.C2C,
        scale=1.0 / n,
        rtol=1e-9,
    )
    assert [v["check"] for v in verdicts] == ["dc", "probe"]
    assert all(v["verdict"] == "pass" for v in verdicts)
    # corrupt one output value: the probe must be able to see it, so sweep
    # the deterministic probe index onto the corrupted element via the seed
    bad = values.copy()
    bad[7] *= 3.0
    failed_any = False
    for seed in range(8):
        with pytest.MonkeyPatch.context() as mp:
            mp.setenv(verify.VERIFY_SEED_ENV, str(seed))
            verdicts = verify.run_checks(
                direction="forward",
                freq=bad,
                space=space,
                triplets=trip,
                transform_type=TransformType.C2C,
                scale=1.0 / n,
                rtol=1e-6,
            )
        failed_any = failed_any or any(v["verdict"] == "fail" for v in verdicts)
    assert failed_any, "no probe seed caught a 3x-corrupted output value"


def test_r2c_applicability():
    assert verify.applicable_checks("backward", TransformType.R2C) == ()
    assert verify.applicable_checks("forward", TransformType.R2C) == ("dc", "probe")
    assert verify.applicable_checks("backward", TransformType.C2C) == (
        "parseval",
        "dc",
        "probe",
    )


def test_checks_vocabulary_is_registry():
    assert set(verify.CHECKS) == set(verify.CHECK_FNS)


# ---- supervised transforms: detect -> retry -> demote -> recover -------------


def test_clean_verified_roundtrip_matches_unverified():
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    t = _local(trip, verify="on")
    assert_close(t.backward(values), expect)
    assert_close(t.forward(scaling=ScalingType.FULL), values)
    assert _counter_sum("verify_checks_total") > 0
    assert _counter_sum("verify_recoveries_total") == 0
    assert t.report()["degradations"] == []


def test_corrupt_dispatch_recovers_via_reference():
    """The acceptance invariant: every dispatch corrupted, result still
    matches the fault-free run, recovery counted and recorded."""
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    with faults.inject("engine.execute=corrupt:1.0"):
        t = _local(trip, verify="on")
        out = t.backward(values)
        back = t.forward(scaling=ScalingType.FULL)
    assert_close(out, expect)
    assert_close(back, values)
    assert _counter_sum("verify_recoveries_total") >= 2  # both directions
    assert _counter_sum("verify_retries_total") > 0
    card = t.report()
    assert any(d["event"] == "verify_demoted" for d in card["degradations"])
    assert obs.validate_plan_card(card) == []


def test_nan_dispatch_recovers_without_guard():
    """NaN poisoning is caught by the checks alone (guard off): rel=nan
    compares false against any rtol, which lands on the fail side."""
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    with faults.inject("engine.execute=nan:1.0"):
        t = _local(trip, verify="on", guard=False)
        out = t.backward(values)
    assert_close(out, expect)
    assert not np.isnan(np.asarray(out)).any()
    assert _counter_sum("verify_recoveries_total") == 1


def test_transient_fault_heals_within_retry_budget(monkeypatch):
    """A fractional-rate fault heals by re-execution (rung 2) on some calls;
    whatever path each call takes, the result is always parity-correct."""
    monkeypatch.setenv(verify.VERIFY_RETRIES_ENV, "4")
    faults.reseed(7)
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    t = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:0.5"):
        for _ in range(4):
            assert_close(t.backward(values), expect)
    assert _counter_sum("verify_retries_total") > 0


def test_forward_retained_buffer_safe_after_recovery():
    """After a recovered backward, forward(space=None) must read the
    *verified* space, not the failed primary's buffer."""
    trip = _triplets()
    values = _values(trip)
    t = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:1.0"):
        t.backward(values)
    # faults disarmed now: the forward runs clean off the retained buffer
    assert_close(t.forward(scaling=ScalingType.FULL), values)


def test_strict_mode_bypasses_open_breaker(monkeypatch):
    """Strict's contract is attempt-primary-then-fail-fast: an open breaker
    must not silently demote a strict plan to the reference (end-to-end
    drive regression — earlier 'on'-mode failures in the process had tripped
    the breaker and strict returned a recovered result instead of raising)."""
    monkeypatch.setenv(verify.breaker.BREAKER_K_ENV, "1")
    trip = _triplets()
    values = _values(trip)
    t_on = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:1.0"):
        t_on.backward(values)  # trips the engine breaker at K=1
        assert verify.breaker.describe(t_on._engine)["state"] == "open"
        t_strict = _local(trip, verify="strict")
        with pytest.raises(errors.VerificationError):
            t_strict.backward(values)


def test_rtol_tracks_effective_precision(monkeypatch):
    """A float64 plan with jax_enable_x64 off actually executes in f32
    (silent truncation): the default tolerance must follow the effective
    precision, or clean f32-accuracy results get condemned as corruption
    (end-to-end drive regression)."""
    import jax

    assert verify.resolve_rtol(np.float32) == 1e-4
    prev = jax.config.read("jax_enable_x64")
    try:
        jax.config.update("jax_enable_x64", True)
        assert verify.resolve_rtol(np.float64) == 1e-9
        jax.config.update("jax_enable_x64", False)
        assert verify.resolve_rtol(np.float64) == 1e-4
    finally:
        jax.config.update("jax_enable_x64", prev)


def test_strict_mode_raises_immediately_and_roundtrips_capi():
    trip = _triplets()
    t = _local(trip, verify="strict")
    with faults.inject("engine.execute=corrupt:1.0"):
        with pytest.raises(errors.VerificationError) as ei:
            t.backward(_values(trip))
    assert _counter_sum("verify_retries_total") == 0
    assert _counter_sum("verify_failures_total") == 1
    # the new taxonomy member round-trips through the C error surface
    assert capi.error_code(ei.value) == int(errors.ErrorCode.VERIFICATION) == 23


def test_verify_check_site_fails_closed():
    """Chaos on the detector itself (fault site verify.check): an
    unverifiable result must end in typed VerificationError, never a pass."""
    trip = _triplets()
    t = _local(trip, verify="on")
    with faults.inject("verify.check=raise"):
        with pytest.raises(errors.VerificationError):
            t.backward(_values(trip))


def test_typed_execution_error_retries_then_raises():
    """sync.fence raising on every attempt AND in the reference rung leaves
    nothing verifiable: typed VerificationError with the cause chained."""
    trip = _triplets()
    t = _local(trip, verify="on")
    with faults.inject("sync.fence=raise"):
        with pytest.raises(errors.VerificationError) as ei:
            t.backward(_values(trip))
    assert ei.value.__cause__ is not None


def test_distributed_corrupt_recovers():
    trip = _triplets()
    values = _values(trip)
    per_shard = distribute_triplets(trip, 2, DIM)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    expect = _local(trip).backward(values)
    with faults.inject("engine.execute=corrupt:1.0"):
        t = _dist(per_shard, verify="on")
        out = t.backward([v.copy() for v in vps])
        back = t.forward(scaling=ScalingType.FULL)
    assert_close(out, expect)
    for got, want in zip(back, vps):
        assert_close(got, want)
    assert _counter_sum("verify_recoveries_total") >= 2
    assert any(
        d["event"] == "verify_demoted" for d in t.report()["degradations"]
    )


def test_multiprocess_mesh_rejects_verify(monkeypatch):
    """Multi-process meshes cannot satisfy the reference rung (remote shards
    are not host-visible): verify= must fail loudly at construction."""
    from spfft_tpu.parallel import execution as pexec

    per_shard = distribute_triplets(_triplets(), 2, DIM)
    monkeypatch.setattr(pexec, "mesh_process_span", lambda mesh: 2)
    with pytest.raises(errors.InvalidParameterError):
        _dist(per_shard, verify="on")


# ---- circuit breaker ---------------------------------------------------------


def test_breaker_trips_at_k_and_short_circuits(monkeypatch):
    monkeypatch.setenv(verify.breaker.BREAKER_K_ENV, "2")
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    with faults.inject("engine.execute=corrupt:1.0"):
        t = _local(trip, verify="on")
        assert_close(t.backward(values), expect)  # episode 1
        assert_close(t.backward(values), expect)  # episode 2 -> trips
        assert verify.breaker.describe(t._engine)["state"] == "open"
        injected_before = _counter_sum("faults_injected_total")
        assert_close(t.backward(values), expect)  # short-circuit to reference
    # the open breaker skipped the primary dispatch: no new injections fired
    assert _counter_sum("faults_injected_total") == injected_before
    assert any(
        d["event"] == "verify_breaker_open" for d in t._degradations
    )
    # state is visible in obs.snapshot() and the plan card
    gauges = obs.snapshot()["gauges"]
    assert any(
        k.startswith("verify_breaker_state") and v == 1 for k, v in gauges.items()
    ), gauges
    assert _counter_sum("verify_breaker_trips_total") == 1
    card = t.report()
    assert card["verification"]["breaker"]["state"] == "open"


def test_breaker_reset_zeroes_state_gauge(monkeypatch):
    """reset() must also zero the verify_breaker_state gauge: a snapshot
    showing a tripped breaker that no longer exists would desynchronize the
    metrics view from describe()/the plan card (review finding)."""
    monkeypatch.setenv(verify.breaker.BREAKER_K_ENV, "1")
    trip = _triplets()
    t = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:1.0"):
        t.backward(_values(trip))
    gauges = obs.snapshot()["gauges"]
    assert any(
        k.startswith("verify_breaker_state") and v == 1 for k, v in gauges.items()
    )
    verify.breaker.reset()
    gauges = obs.snapshot()["gauges"]
    assert all(
        v == 0 for k, v in gauges.items() if k.startswith("verify_breaker_state")
    ), gauges


def test_breaker_half_open_probe_heals(monkeypatch):
    monkeypatch.setenv(verify.breaker.BREAKER_K_ENV, "1")
    monkeypatch.setenv(verify.breaker.BREAKER_COOLDOWN_ENV, "0")
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    t = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:1.0"):
        assert_close(t.backward(values), expect)  # trips at K=1
    assert verify.breaker.describe(t._engine)["state"] == "open"
    # cooldown 0: the next verified call probes half-open; faults are
    # disarmed, so the probe passes and the breaker closes
    assert_close(t.backward(values), expect)
    state = verify.breaker.describe(t._engine)
    assert state["state"] == "closed" and state["consecutive_failures"] == 0


def test_breaker_half_open_failure_reopens(monkeypatch):
    monkeypatch.setenv(verify.breaker.BREAKER_K_ENV, "1")
    monkeypatch.setenv(verify.breaker.BREAKER_COOLDOWN_ENV, "0")
    trip = _triplets()
    values = _values(trip)
    expect = _local(trip).backward(values)
    t = _local(trip, verify="on")
    with faults.inject("engine.execute=corrupt:1.0"):
        assert_close(t.backward(values), expect)  # trips
        assert_close(t.backward(values), expect)  # half-open probe fails
    state = verify.breaker.describe(t._engine)
    assert state["state"] == "open" and state["trips"] == 2


def test_breaker_half_open_admits_exactly_one_probe(monkeypatch):
    """Concurrent callers racing an elapsed cooldown: exactly one wins the
    half-open probe slot, the losers fail fast (allow() False, straight to
    the reference rung), and the state gauge stays consistent through the
    race and the probe's verdict."""
    import threading

    monkeypatch.setenv(verify.breaker.BREAKER_COOLDOWN_ENV, "0")
    engine = "race-engine"
    for _ in range(verify.breaker.threshold()):
        verify.breaker.record_failure(engine)
    assert verify.breaker.describe(engine)["state"] == "open"

    barrier = threading.Barrier(8)
    verdicts = [None] * 8

    def contender(slot):
        barrier.wait()
        verdicts[slot] = verify.breaker.allow(engine)

    threads = [threading.Thread(target=contender, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert sum(1 for v in verdicts if v) == 1, verdicts
    state = verify.breaker.describe(engine)
    assert state["state"] == "half_open"
    gauges = obs.snapshot()["gauges"]
    assert gauges[f'verify_breaker_state{{engine="{engine}"}}'] == 2
    # while the probe is in flight, further callers keep losing
    assert verify.breaker.allow(engine) is False
    # a failed probe reopens and a later cooldown admits exactly one again
    verify.breaker.record_failure(engine)
    assert verify.breaker.describe(engine)["state"] == "open"
    assert verify.breaker.allow(engine) is True  # cooldown 0, new probe
    # a healed probe closes and lifts the single-probe gate
    verify.breaker.record_success(engine)
    state = verify.breaker.describe(engine)
    assert state["state"] == "closed" and state["consecutive_failures"] == 0
    assert verify.breaker.allow(engine) and verify.breaker.allow(engine)
    gauges = obs.snapshot()["gauges"]
    assert gauges[f'verify_breaker_state{{engine="{engine}"}}'] == 0


def test_breaker_lost_probe_slot_self_heals(monkeypatch):
    """A probe whose carrier dies without reporting a verdict must not wedge
    the breaker in half-open forever: after the takeover interval another
    caller may claim the slot."""
    monkeypatch.setenv(verify.breaker.BREAKER_COOLDOWN_ENV, "0")
    engine = "leaky-engine"
    for _ in range(verify.breaker.threshold()):
        verify.breaker.record_failure(engine)
    assert verify.breaker.allow(engine) is True  # probe granted...
    assert verify.breaker.allow(engine) is False  # ...slot held
    # the carrier dies silently; past the takeover interval the slot frees
    from spfft_tpu.verify import breaker as breaker_mod

    real_monotonic = breaker_mod.time.monotonic
    monkeypatch.setattr(
        breaker_mod.time, "monotonic", lambda: real_monotonic() + 2.0
    )
    assert verify.breaker.allow(engine) is True
    verify.breaker.record_success(engine)
    assert verify.breaker.describe(engine)["state"] == "closed"


def test_retry_backoff_jitter_differs_across_seeds(monkeypatch):
    """The supervisor's retry backoff is jittered (faults.backoff_s):
    recorded sleep sequences differ across SPFFT_TPU_VERIFY_JITTER_SEED
    values and replay exactly for one seed — concurrent retriers of one
    failed engine must not herd on a synchronized schedule."""
    from spfft_tpu.verify import supervisor as sup_mod

    def sleeps_for(seed):
        monkeypatch.setenv(verify.VERIFY_JITTER_SEED_ENV, str(seed))
        monkeypatch.setenv(verify.VERIFY_RETRIES_ENV, "2")
        recorded = []
        monkeypatch.setattr(sup_mod.time, "sleep", recorded.append)
        trip = _triplets()
        values = _values(trip)
        t = _local(trip, verify="on")
        with faults.inject("engine.execute=corrupt:1.0"):
            t.backward(values)  # retries exhaust, reference rung recovers
        return recorded

    seq_a = sleeps_for(11)
    seq_b = sleeps_for(23)
    seq_a2 = sleeps_for(11)
    assert len(seq_a) == 2 and len(seq_b) == 2
    assert seq_a != seq_b, "jitter must decorrelate retry schedules"
    assert seq_a == seq_a2, "one seed must replay its sleep sequence exactly"
    base = verify.resolve_backoff_s()
    for i, s in enumerate(seq_a, start=1):
        lo, hi = 0.5 * base * 2 ** (i - 1), 1.5 * base * 2 ** (i - 1)
        assert lo <= s < hi, (i, s, lo, hi)


def test_backoff_s_jitter_bounds_and_determinism():
    import random

    from spfft_tpu import faults as f

    assert f.backoff_s(0.01, 1) == pytest.approx(0.01)
    assert f.backoff_s(0.01, 3) == pytest.approx(0.04)
    seq = [f.backoff_s(0.01, i, random.Random(5)) for i in range(1, 4)]
    seq2 = [f.backoff_s(0.01, i, random.Random(5)) for i in range(1, 4)]
    assert seq == seq2  # same seed, same schedule
    rng = random.Random(5)
    chained = [f.backoff_s(0.01, i, rng) for i in range(1, 4)]
    assert len(set(chained)) == 3  # one stream, distinct draws
    for i, s in enumerate(chained, start=1):
        assert 0.5 * 0.01 * 2 ** (i - 1) <= s < 1.5 * 0.01 * 2 ** (i - 1)


# ---- exposure: cards, trace, CLI surfaces ------------------------------------


def test_plan_card_verification_schema():
    trip = _triplets()
    for t in (_local(trip), _local(trip, verify="on"), _local(trip, verify="strict")):
        card = t.report()
        assert obs.validate_plan_card(card) == []
        ver = card["verification"]
        assert ver["mode"] == t._verify_mode
        assert ver["breaker"]["engine"] == t._engine
    assert _local(trip, verify="on").report()["verification"]["checks"] == [
        "dc",
        "parseval",
        "probe",
    ]


def test_verify_events_in_trace():
    from spfft_tpu.obs import trace

    trace.enable(capacity=512)
    try:
        trip = _triplets()
        with faults.inject("engine.execute=corrupt:1.0"):
            t = _local(trip, verify="on")
            t.backward(_values(trip))
        events = [e for e in trace.snapshot()["events"] if e["name"] == "verify"]
        whats = {e["args"].get("what") for e in events}
        assert {"check", "retry", "demote"} <= whats, whats
        # verify events carry the plan's run ID: card <-> trace join key
        assert any(e["run"] == t._run_id for e in events)
    finally:
        trace.disable()


def test_clone_preserves_verify_mode():
    trip = _triplets()
    t = _local(trip, verify="on")
    c = t.clone()
    assert c._verify_mode == "on" and c._verifier is not None
    assert _local(trip).clone()._verifier is None


def test_grid_create_transform_passes_verify():
    trip = _triplets()
    g = sp.Grid(DIM, DIM, DIM, DIM * DIM, ProcessingUnit.HOST, 1)
    t = g.create_transform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        indices=trip,
        verify="on",
    )
    assert t._verify_mode == "on" and t._verifier is not None
