"""spfft_tpu.obs.fleet: fleet metrics aggregation (ISSUE 16).

Contract layers:

* series keys — ``parse_series_key`` inverts the registry's key builder
  (escaping included) and raises typed on malformed blocks;
  ``host_series_key`` merges the ``host`` label in registry label order;
* merge — counters/histograms re-keyed per host and summed fleet-wide
  under ``totals`` (buckets bound-by-bound), gauges per-host only, missing
  hosts recorded with their scrape state, never silently dropped;
* scrape — ``fleet_snapshot`` skips already-lost hosts typed without
  touching the wire, stamps ``unreachable``/``malformed`` per-host verdicts
  inside one bounded ``SPFFT_TPU_FLEET_SCRAPE_S`` deadline, and counts
  every outcome in ``fleet_scrapes_total``;
* schema pin / export — ``validate_fleet`` trips on doctored documents,
  ``fleet_prometheus_text`` renders host-labeled series and deliberately
  never re-exports ``totals`` (double-counting).
"""
import json

import pytest

from spfft_tpu import obs
from spfft_tpu.errors import HostLostError, InvalidParameterError
from spfft_tpu.obs import fleet, registry, trace


@pytest.fixture(autouse=True)
def clean_registry():
    obs.clear()
    yield
    obs.clear()
    trace.disable()


def _snap_with(counter=None, gauge=None, hist=None):
    """A real registry snapshot with one series of each asked-for kind."""
    obs.clear()
    if counter:
        registry.counter(counter[0], **counter[1]).inc(counter[2])
    if gauge:
        registry.gauge(gauge[0], **gauge[1]).set(gauge[2])
    if hist:
        for v in hist[2]:
            registry.histogram(hist[0], **hist[1]).observe(v)
    snap = obs.snapshot()
    obs.clear()
    return snap


# ---- series keys -------------------------------------------------------------


def test_parse_series_key_inverts_registry_escaping():
    key = 'requests_total{tenant="a\\"b\\\\c\\nd",verb="submit"}'
    name, labels = fleet.parse_series_key(key)
    assert name == "requests_total"
    assert dict(labels) == {"tenant": 'a"b\\c\nd', "verb": "submit"}
    assert fleet.parse_series_key("plain_total") == ("plain_total", ())


def test_parse_series_key_typed_on_malformed():
    with trace.suppressed_dumps():
        for bad in ("x{unterminated", 'x{k="v}', "x{noeq}", 'x{k=bare}'):
            with pytest.raises(InvalidParameterError):
                fleet.parse_series_key(bad)


def test_host_series_key_sorts_host_with_existing_labels():
    assert (
        fleet.host_series_key('x_total{tenant="t0"}', "host1")
        == 'x_total{host="host1",tenant="t0"}'
    )
    assert fleet.host_series_key("x_total", "h") == 'x_total{host="h"}'
    # round-trips through the registry's own parser
    name, labels = fleet.parse_series_key(
        fleet.host_series_key('x_total{z="1",a="2"}', "h")
    )
    assert name == "x_total" and dict(labels)["host"] == "h"


# ---- merge -------------------------------------------------------------------


def test_merge_snapshots_rekeys_and_sums():
    a = _snap_with(
        counter=("requests_total", {"tenant": "t"}, 3),
        gauge=("queue_depth", {}, 5.0),
        hist=("serve_seconds", {}, [0.1, 0.2]),
    )
    b = _snap_with(
        counter=("requests_total", {"tenant": "t"}, 4),
        gauge=("queue_depth", {}, 7.0),
        hist=("serve_seconds", {}, [0.4]),
    )
    doc = fleet.merge_snapshots({"host0": a, "host1": b})
    assert doc["schema"] == fleet.FLEET_SCHEMA
    assert fleet.validate_fleet(doc) == []
    key = 'requests_total{host="host0",tenant="t"}'
    assert doc["counters"][key] == 3
    assert doc["counters"]['requests_total{host="host1",tenant="t"}'] == 4
    # fleet-wide totals: counters summed under the ORIGINAL key
    assert doc["totals"]["counters"]['requests_total{tenant="t"}'] == 7
    # gauges stay per-host only — a last-value has no meaningful fleet sum
    assert 'queue_depth{host="host0"}' in doc["gauges"]
    assert "queue_depth" not in doc["totals"]["counters"]
    total_h = doc["totals"]["histograms"]["serve_seconds"]
    assert total_h["count"] == 3
    assert total_h["sum"] == pytest.approx(0.7)
    assert total_h["min"] == pytest.approx(0.1)
    assert total_h["max"] == pytest.approx(0.4)
    # buckets summed bound-by-bound equal the per-host cumulative counts
    ha = a["histograms"]["serve_seconds"]["buckets"]
    hb = b["histograms"]["serve_seconds"]["buckets"]
    for bound, cum in total_h["buckets"].items():
        assert cum == ha.get(bound, 0) + hb.get(bound, 0)
    # both hosts recorded live
    assert doc["hosts"]["host0"]["state"] == "live"
    json.dumps(doc)  # document is JSON-plain


def test_merge_records_missing_hosts():
    doc = fleet.merge_snapshots(
        {"host0": _snap_with(counter=("x_total", {}, 1))},
        {"host1": {"state": "lost", "error": "host_lost"}},
    )
    assert doc["hosts"]["host1"] == {"state": "lost", "error": "host_lost"}
    assert fleet.validate_fleet(doc) == []


# ---- scrape ------------------------------------------------------------------


class _Client:
    def __init__(self, reply=None, error=None):
        self.reply = reply
        self.error = error
        self.calls = []

    def call(self, msg, timeout_s=None):
        self.calls.append((msg, timeout_s))
        if self.error is not None:
            raise self.error
        return self.reply


class _Handle:
    def __init__(self, name, client, lost=False):
        self.name = name
        self.client = client
        self.lost = lost


def _counters(prefix):
    return {
        k: v
        for k, v in obs.snapshot()["counters"].items()
        if k.startswith(prefix)
    }


def test_fleet_snapshot_merges_live_hosts_with_bounded_deadline():
    snap = _snap_with(counter=("requests_total", {}, 2))
    h0 = _Handle("host0", _Client(reply={"metrics": snap}))
    h1 = _Handle("host1", _Client(reply={"metrics": snap}))
    doc = fleet.fleet_snapshot([h0, h1], timeout_s=0.25)
    assert fleet.validate_fleet(doc) == []
    assert doc["hosts"]["host0"]["state"] == "live"
    assert doc["totals"]["counters"]["requests_total"] == 4
    # ONE metrics call per host, carrying the per-host deadline
    (msg, timeout_s), = h0.client.calls
    assert msg == {"op": "metrics"} and timeout_s == 0.25
    c = _counters("fleet_scrapes_total")
    assert c['fleet_scrapes_total{host="host0",outcome="ok"}'] == 1


def test_fleet_snapshot_default_deadline_is_the_knob():
    h = _Handle("host0", _Client(reply={"metrics": _snap_with()}))
    fleet.fleet_snapshot([h])
    (_, timeout_s), = h.client.calls
    assert timeout_s == fleet.resolve_scrape_s() == 5.0


def test_fleet_snapshot_skips_lost_host_without_touching_wire():
    lost_client = _Client(error=AssertionError("wire touched"))
    h0 = _Handle("host0", lost_client, lost=True)
    h1 = _Handle("host1", _Client(reply={"metrics": _snap_with()}))
    doc = fleet.fleet_snapshot([h0, h1])
    assert lost_client.calls == []
    entry = doc["hosts"]["host0"]
    assert entry["state"] == "lost" and entry["error"] == "host_lost"
    assert "skipped_unix" in entry
    assert doc["hosts"]["host1"]["state"] == "live"
    assert fleet.validate_fleet(doc) == []
    c = _counters("fleet_scrapes_total")
    assert c['fleet_scrapes_total{host="host0",outcome="lost"}'] == 1


def test_fleet_snapshot_stamps_unreachable_and_malformed():
    h0 = _Handle("host0", _Client(error=HostLostError("host0 died")))
    h1 = _Handle("host1", _Client(reply={"metrics": {"bogus": True}}))
    h2 = _Handle("host2", _Client(reply="not-a-dict"))
    doc = fleet.fleet_snapshot([h0, h1, h2])
    assert doc["hosts"]["host0"]["state"] == "unreachable"
    assert doc["hosts"]["host0"]["error"] == "HostLostError"
    assert doc["hosts"]["host1"]["state"] == "malformed"
    assert doc["hosts"]["host2"]["state"] == "malformed"
    # the aggregation itself still returns a valid (empty-series) document
    assert fleet.validate_fleet(doc) == []
    c = _counters("fleet_scrapes_total")
    assert c['fleet_scrapes_total{host="host0",outcome="unreachable"}'] == 1
    assert c['fleet_scrapes_total{host="host1",outcome="malformed"}'] == 1


# ---- schema pin / export -----------------------------------------------------


def test_validate_fleet_trips_on_doctored_documents():
    doc = fleet.merge_snapshots({"host0": _snap_with(counter=("x_total", {}, 1))})
    assert fleet.validate_fleet(doc) == []
    assert fleet.validate_fleet("nope") == ["fleet (not a dict)"]
    bad = dict(doc, schema="spfft_tpu.obs.fleet/999")
    assert any("schema" in f for f in fleet.validate_fleet(bad))
    bad = {k: v for k, v in doc.items() if k != "totals"}
    assert any("totals" in f for f in fleet.validate_fleet(bad))
    bad = dict(doc, hosts={"host0": {"state": "zombie", "error": None}})
    assert any("state" in f for f in fleet.validate_fleet(bad))
    # a counter series without the host label is not a fleet series
    bad = dict(doc, counters={"x_total": 1})
    assert any("host label" in f for f in fleet.validate_fleet(bad))
    bad = dict(doc, counters={"x_total{oops": 1})
    assert any("malformed series key" in f for f in fleet.validate_fleet(bad))


def test_fleet_prometheus_text_excludes_totals():
    a = _snap_with(counter=("x_total", {}, 3), hist=("h_seconds", {}, [0.5]))
    doc = fleet.merge_snapshots({"host0": a, "host1": a})
    text = fleet.fleet_prometheus_text(doc)
    assert 'x_total{host="host0"} 3' in text
    assert 'x_total{host="host1"} 3' in text
    # totals are derivable by the scraper; re-exporting them double-counts
    assert "\nx_total 6" not in text and "x_total 6" not in text
    assert 'h_seconds_bucket' in text
