"""Exchange-geometry invariants in isolation (no device execution).

The analogue of the reference testing its transpose component directly against
a self-built layout (reference: tests/mpi_tests/test_transpose.cpp:63-90):
the pack/unpack z maps and the stick<->plane slot tables must be mutually
inverse and agree across both mesh engines' constructions.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu.parameters import distribute_triplets, make_distributed_parameters
from spfft_tpu.types import TransformType
from utils import random_sparse_triplets


def make_params(num_shards=3, dims=(8, 9, 10), lz=None, seed=0):
    rng = np.random.default_rng(seed)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    per_shard = distribute_triplets(trip, num_shards, dy)
    return make_distributed_parameters(
        TransformType.C2C, dx, dy, dz, per_shard, lz
    )


@pytest.mark.parametrize("lz", [None, [5, 2, 3]])
def test_pack_unpack_z_maps_are_inverse(lz):
    p = make_params(lz=lz)
    pack = p.pack_z_map()  # (P*L,) -> global z (sentinel dim_z on padding)
    unpack = p.unpack_z_map()  # (dim_z,) -> packed slot
    # every global z has a packed slot whose pack entry points back at it
    for z in range(p.dim_z):
        assert pack[unpack[z]] == z
    # every non-sentinel packed slot round-trips
    for slot, z in enumerate(pack):
        if z < p.dim_z:
            assert unpack[z] == slot
    # slab partition covers [0, dim_z) exactly once
    zs = np.concatenate(
        [
            np.arange(int(o), int(o) + int(l))
            for l, o in zip(p.local_z_lengths, p.z_offsets)
        ]
    )
    assert sorted(zs.tolist()) == list(range(p.dim_z))


def test_stick_tables_identify_unique_planes():
    p = make_params()
    sx = p.stick_x_all.reshape(-1)
    sy = p.stick_y_all.reshape(-1)
    valid = sx < p.dim_x_freq
    slots = sy[valid].astype(np.int64) * p.dim_x_freq + sx[valid]
    # one stick per (x, y) column globally (whole-stick ownership)
    assert len(np.unique(slots)) == len(slots)
    # per-shard stick counts match the padded table's valid rows
    S = p.max_num_sticks
    per_shard_valid = valid.reshape(p.num_shards, S).sum(axis=1)
    np.testing.assert_array_equal(per_shard_valid, p.num_sticks_per_shard)


def test_engine_slot_tables_are_inverse():
    """MxuDistributedExecution's stick_yx and yx_stick must invert each other."""
    import jax

    p = make_params(num_shards=2)
    from spfft_tpu.parallel.execution_mxu import MxuDistributedExecution

    mesh = sp.make_fft_mesh(2)
    ex = MxuDistributedExecution(p, np.float64, mesh)
    S = p.max_num_sticks
    A = ex._num_x_active
    yx = np.asarray(ex._stick_yx, dtype=np.int64)  # (P*S,) compact plane slot
    inv = np.asarray(ex._yx_stick, dtype=np.int64)  # (Y*A,) global stick row
    sentinel_slot = p.dim_y * A
    sentinel_row = p.num_shards * S
    for row, slot in enumerate(yx):
        if slot != sentinel_slot:
            assert inv[slot] == row
    for slot, row in enumerate(inv):
        if row != sentinel_row:
            assert yx[row] == slot


def test_wire_dtype_rule():
    """The single-sourced wire rule (types.wire_dtype) drives every cast and
    the byte accounting; pin its full value table."""
    import ml_dtypes

    from spfft_tpu.types import ExchangeType as E, wire_dtype, wire_scalar_bytes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    for rt in (np.float32, np.float64):
        for et in (E.DEFAULT, E.BUFFERED, E.COMPACT_BUFFERED, E.UNBUFFERED):
            assert wire_dtype(et, rt) == np.dtype(rt)
        assert wire_dtype(E.BUFFERED_BF16, rt) == bf16
        assert wire_dtype(E.COMPACT_BUFFERED_BF16, rt) == bf16
    for et in (E.BUFFERED_FLOAT, E.COMPACT_BUFFERED_FLOAT):
        assert wire_dtype(et, np.float32) == np.dtype(np.float32)
        assert wire_dtype(et, np.float64) == np.dtype(np.float32)
    assert wire_scalar_bytes(E.BUFFERED_BF16, np.float32) == 2
    assert wire_scalar_bytes(E.BUFFERED_FLOAT, np.float64) == 4
    assert wire_scalar_bytes(E.UNBUFFERED, np.float64) == 8


def test_value_indices_padded_with_oob_sentinel():
    p = make_params()
    V = p.max_num_values
    for r in range(p.num_shards):
        n = int(p.num_values_per_shard[r])
        vi = np.asarray(p.value_indices[r])
        assert vi.shape == (V,)
        S, Z = p.max_num_sticks, p.dim_z
        assert (vi[:n] < S * Z).all() and (vi[:n] >= 0).all()
        assert (vi[n:] >= S * Z).all()  # padding drops on scatter
