"""Engine parity fuzz: the XLA and MXU engines must agree on random plans.

Randomized dims (odd / prime / mixed), sparsity patterns, value orders and
transform types; both local engines run the same plan and must agree to f64
accuracy, and the distributed engines must agree with the local result.
Seeded for reproducibility.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close, random_sparse_triplets


CASES = list(range(8))


@pytest.mark.parametrize("case", CASES)
def test_local_engine_parity(case):
    rng = np.random.default_rng(1000 + case)
    dims = tuple(int(rng.integers(3, 20)) for _ in range(3))
    dx, dy, dz = dims
    r2c = bool(case % 2)
    trip = random_sparse_triplets(
        rng,
        dx,
        dy,
        dz,
        stick_fraction=float(rng.uniform(0.2, 0.9)),
        z_fill=float(rng.uniform(0.3, 1.0)),
        centered=bool(rng.integers(0, 2)),
        hermitian=r2c,
    )
    ttype = TransformType.R2C if r2c else TransformType.C2C
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    outs, rounds = [], []
    for engine in ("xla", "mxu"):
        t = Transform(
            ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, engine=engine
        )
        outs.append(t.backward(values))
        rounds.append(t.forward(scaling=ScalingType.FULL))
    assert_close(outs[1], outs[0])
    assert_close(rounds[1], rounds[0])


@pytest.mark.parametrize("case", [0, 1, 2])
def test_distributed_engine_parity(case):
    rng = np.random.default_rng(2000 + case)
    dims = tuple(int(rng.integers(4, 16)) for _ in range(3))
    dx, dy, dz = dims
    shards = int(rng.choice([2, 3, 4]))
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(trip, shards, dy)
    lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(t)] for t in s]) for s in per_shard]

    local = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip
    ).backward(values)

    for engine in ("xla", "mxu"):
        t = DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            dx,
            dy,
            dz,
            per_shard,
            mesh=sp.make_fft_mesh(shards),
            engine=engine,
        )
        assert_close(t.backward(vps), local)
        back = t.forward(scaling=ScalingType.FULL)
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)
