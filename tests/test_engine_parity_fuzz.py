"""Engine parity fuzz: the XLA and MXU engines must agree on random plans.

Randomized dims (odd / prime / mixed), sparsity patterns, value orders and
transform types; both local engines run the same plan and must agree to f64
accuracy, and the distributed engines must agree with the local result.

Seeding is deterministic AND reproducible from the environment: every case's
seed is ``SPFFT_TPU_FUZZ_SEED`` (default 0) + a per-test base + the case
index, and the seed is printed at the top of each test so pytest surfaces it
with any failure's captured output — a tuner-exposed (or CI-exposed) parity
failure replays exactly with ``SPFFT_TPU_FUZZ_SEED=<offset> pytest <nodeid>``.
"""
import os

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close, random_sparse_triplets


CASES = list(range(8))

FUZZ_SEED = int(os.environ.get("SPFFT_TPU_FUZZ_SEED", "0"))


def fuzz_rng(base: int, case: int) -> np.random.Generator:
    """Per-case generator seeded ``FUZZ_SEED + base + case``; prints the
    effective seed so a failing test's captured stdout names it (see module
    docstring)."""
    seed = FUZZ_SEED + base + case
    print(f"fuzz seed = {seed} (SPFFT_TPU_FUZZ_SEED={FUZZ_SEED} + {base} + {case})")
    return np.random.default_rng(seed)


@pytest.mark.parametrize("case", CASES)
def test_local_engine_parity(case):
    rng = fuzz_rng(1000, case)
    dims = tuple(int(rng.integers(3, 20)) for _ in range(3))
    dx, dy, dz = dims
    r2c = bool(case % 2)
    trip = random_sparse_triplets(
        rng,
        dx,
        dy,
        dz,
        stick_fraction=float(rng.uniform(0.2, 0.9)),
        z_fill=float(rng.uniform(0.3, 1.0)),
        centered=bool(rng.integers(0, 2)),
        hermitian=r2c,
    )
    ttype = TransformType.R2C if r2c else TransformType.C2C
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    outs, rounds = [], []
    for engine in ("xla", "mxu"):
        t = Transform(
            ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, engine=engine
        )
        outs.append(t.backward(values))
        rounds.append(t.forward(scaling=ScalingType.FULL))
    assert_close(outs[1], outs[0])
    assert_close(rounds[1], rounds[0])


@pytest.mark.parametrize("case", [0, 1, 2])
def test_distributed_engine_parity(case):
    rng = fuzz_rng(2000, case)
    dims = tuple(int(rng.integers(4, 16)) for _ in range(3))
    dx, dy, dz = dims
    shards = int(rng.choice([2, 3, 4]))
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(trip, shards, dy)
    lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(t)] for t in s]) for s in per_shard]

    local = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip
    ).backward(values)

    for engine in ("xla", "mxu"):
        t = DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            dx,
            dy,
            dz,
            per_shard,
            mesh=sp.make_fft_mesh(shards),
            engine=engine,
        )
        assert_close(t.backward(vps), local)
        back = t.forward(scaling=ScalingType.FULL)
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)


@pytest.mark.parametrize("case", [0, 1, 2, 3])
def test_distributed_discipline_fuzz(case):
    """Random plans × random exchange disciplines (incl. wire variants) ×
    both engines × C2C/R2C must agree with the local oracle — the fuzz
    analogue of the reference's exchange-type test sweep
    (reference: tests/mpi_tests/test_transform.cpp:173-191)."""
    from spfft_tpu import ExchangeType

    rng = fuzz_rng(3000, case)
    dims = tuple(int(rng.integers(4, 14)) for _ in range(3))
    dx, dy, dz = dims
    shards = int(rng.choice([2, 4]))
    r2c = bool(case % 2)
    trip = random_sparse_triplets(
        rng, dx, dy, dz, float(rng.uniform(0.3, 0.8)), hermitian=r2c
    )
    ttype = TransformType.R2C if r2c else TransformType.C2C
    n = len(trip)
    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        freq = np.fft.fftn(real) / (dx * dy * dz)
        values = freq[trip[:, 2], trip[:, 1], trip[:, 0]]
    else:
        values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(trip, shards, dy)
    lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(t)] for t in s]) for s in per_shard]

    local = Transform(
        ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip
    ).backward(values)

    exchange = ExchangeType(
        rng.choice([
            ExchangeType.BUFFERED,
            ExchangeType.BUFFERED_FLOAT,
            ExchangeType.COMPACT_BUFFERED,
            ExchangeType.COMPACT_BUFFERED_FLOAT,
            ExchangeType.UNBUFFERED,
        ])
    )
    for engine in ("xla", "mxu"):
        t = DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz,
            [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh(shards),
            engine=engine,
            exchange_type=exchange,
        )
        out = t.backward([v.copy() for v in vps])
        # float-wire exchanges round the payload to f32: compare at that bar
        tol = (
            dict(rtol=2e-4, atol=2e-4)
            if exchange
            in (ExchangeType.BUFFERED_FLOAT, ExchangeType.COMPACT_BUFFERED_FLOAT)
            else {}
        )
        np.testing.assert_allclose(np.asarray(out), local, **(tol or dict(rtol=1e-6, atol=1e-8)))
        back = t.forward(scaling=ScalingType.FULL)
        for r, vals in enumerate(vps):
            np.testing.assert_allclose(
                np.asarray(back[r]), vals, **(tol or dict(rtol=1e-6, atol=1e-8))
            )


@pytest.mark.parametrize("case", [0, 1])
def test_pencil_mesh_fuzz(case):
    """Random plans on 2-D pencil meshes (both engines, random exchange)
    against the local oracle — fuzz for the beyond-reference decomposition."""
    from spfft_tpu import ExchangeType

    rng = fuzz_rng(4000, case)
    p1, p2 = (2, 2) if case == 0 else (2, 4)
    # pencil needs dim_z >= p1 and dim_y >= p2 slabs with content
    dx = int(rng.integers(4, 10))
    dy = int(rng.integers(p2 + 2, 14))
    dz = int(rng.integers(p1 + 2, 14))
    trip = random_sparse_triplets(rng, dx, dy, dz, float(rng.uniform(0.4, 0.9)))
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    per_shard = distribute_triplets(trip, p1 * p2, dy)
    lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(t)] for t in s]) for s in per_shard]

    local = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip
    ).backward(values)

    exchange = ExchangeType(
        rng.choice([ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED])
    )
    for engine in ("xla", "mxu"):
        t = DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
            [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh2(p1, p2),
            engine=engine,
            exchange_type=exchange,
        )
        out = t.backward([v.copy() for v in vps])
        assert_close(out, local)
        back = t.forward(scaling=ScalingType.FULL)
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)
