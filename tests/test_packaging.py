"""Installed-package consumption: the parity check for the reference's
SpFFTConfig.cmake / SpFFT.pc (reference: cmake/SpFFTConfig.cmake,
cmake/SpFFT.pc.in). Installs the native tree into a scratch prefix, then
builds the consumer project in native/tests/consumer against it via
find_package(SpFFTTPU), runs the linked binary, and validates the installed
pkg-config file."""
import os
import re
import shutil
import subprocess
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
NATIVE = ROOT / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("cmake") is None or shutil.which("g++") is None,
    reason="native toolchain not available",
)


def _run(cmd, **kw):
    return subprocess.run(cmd, check=True, capture_output=True, text=True, **kw)


@pytest.fixture(scope="module")
def installed_prefix(tmp_path_factory):
    # scratch build dir: must NOT touch a developer's native/build cache
    build = tmp_path_factory.mktemp("spfft_tpu_pkg_build")
    prefix = tmp_path_factory.mktemp("spfft_tpu_prefix")
    _run(
        ["cmake", "-S", str(NATIVE), "-B", str(build),
         "-DCMAKE_BUILD_TYPE=Release", "-DSPFFT_TPU_BUILD_TESTS=OFF",
         f"-DCMAKE_INSTALL_PREFIX={prefix}"]
    )
    _run(["cmake", "--build", str(build)])
    _run(["cmake", "--install", str(build)])
    return prefix


def _libdir(prefix: Path) -> Path:
    # GNUInstallDirs may resolve to lib or lib64 depending on the platform
    for name in ("lib", "lib64"):
        if (prefix / name / "pkgconfig" / "spfft_tpu.pc").exists():
            return prefix / name
    raise AssertionError(f"no installed libdir with spfft_tpu.pc under {prefix}")


def test_consumer_cmake_build_against_installed_tree(installed_prefix, tmp_path):
    build = tmp_path / "consumer-build"
    _run(
        ["cmake", "-S", str(NATIVE / "tests" / "consumer"), "-B", str(build),
         f"-DCMAKE_PREFIX_PATH={installed_prefix}"]
    )
    _run(["cmake", "--build", str(build)])
    libdir = str(_libdir(installed_prefix))
    inherited = os.environ.get("LD_LIBRARY_PATH", "")
    out = _run(
        [str(build / "consumer")],
        # extend, don't replace: libpython (a private dependency of the lib)
        # may only resolve through the inherited loader path
        env={
            **os.environ,
            "LD_LIBRARY_PATH": f"{libdir}:{inherited}" if inherited else libdir,
        },
    )
    assert "consumer link OK" in out.stdout


def _cmake_project_version() -> str:
    m = re.search(
        r"VERSION\s+(\d+\.\d+\.\d+)", (NATIVE / "CMakeLists.txt").read_text()
    )
    assert m, "project VERSION missing in native/CMakeLists.txt"
    return m.group(1)


def test_pkgconfig_file_installed_and_valid(installed_prefix):
    pc = _libdir(installed_prefix) / "pkgconfig" / "spfft_tpu.pc"
    assert pc.exists()
    text = pc.read_text()
    assert "-lspfft_tpu" in text
    assert f"Version: {_cmake_project_version()}" in text
    if shutil.which("pkg-config"):
        env = {**os.environ, "PKG_CONFIG_PATH": str(pc.parent)}
        cflags = _run(["pkg-config", "--cflags", "spfft_tpu"], env=env).stdout
        assert "include" in cflags
        libs = _run(["pkg-config", "--libs", "spfft_tpu"], env=env).stdout
        assert "-lspfft_tpu" in libs


def test_version_macros_match_cmake_project():
    header = (NATIVE / "include" / "spfft" / "version.h").read_text()
    version = _cmake_project_version()
    major, minor, patch = version.split(".")
    assert f"SPFFT_TPU_VERSION_MAJOR {major}" in header
    assert f"SPFFT_TPU_VERSION_MINOR {minor}" in header
    assert f"SPFFT_TPU_VERSION_PATCH {patch}" in header
    assert f'"{version}"' in header
    # the Python package must carry the same version (was comment-enforced)
    import spfft_tpu

    assert spfft_tpu.__version__ == version
    # ... and so must the pip metadata
    pyproject = (ROOT / "pyproject.toml").read_text()
    assert f'version = "{version}"' in pyproject


def test_pip_install_and_import(tmp_path):
    """`pip install .` of the Python core works and the installed copy imports
    from a neutral cwd — the Python-side parity of the reference's installed
    CMake/pkg-config consumption (reference: cmake/SpFFTConfig.cmake). Run with
    --no-deps/--no-build-isolation: the environment is zero-egress and jax is
    already present."""
    import sys

    target = tmp_path / "site"
    _run(
        [sys.executable, "-m", "pip", "install", "--no-build-isolation",
         "--no-deps", "--quiet", f"--target={target}", str(ROOT)]
    )
    assert (target / "spfft_tpu" / "__init__.py").exists()
    out = _run(
        [
            sys.executable,
            "-c",
            "import spfft_tpu, numpy as np; "
            "t = spfft_tpu.Transform("
            "    spfft_tpu.ProcessingUnit.HOST, spfft_tpu.TransformType.C2C,"
            "    4, 4, 4, indices=np.stack(np.meshgrid(*[np.arange(4)] * 3,"
            "    indexing='ij'), -1).reshape(-1, 3), dtype=np.float64); "
            "s = t.backward(np.ones(64, dtype=np.complex128)); "
            "print(spfft_tpu.__file__); print('ok', s.shape)",
        ],
        cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(target)},
    )
    assert str(target) in out.stdout
    assert "ok (4, 4, 4)" in out.stdout
