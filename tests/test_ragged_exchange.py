"""Exact-counts (ragged ppermute-chain) exchange: parallel/ragged.py.

COMPACT_BUFFERED / UNBUFFERED now send true sticks_i x planes_j blocks like the
reference's MPI_Alltoallv / Alltoallw (reference:
src/transpose/transpose_mpi_compact_buffered_host.cpp:52-106) instead of
mapping onto the padded all_to_all. These tests run the reference's
distribution edge cases (reference: tests/mpi_tests/test_transform.cpp:38-127)
through the ragged path on both engines, where padding waste would be largest —
plus the wire-format variants riding the chain.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import (
    assert_close,
    oracle_backward_c2c,
    random_sparse_triplets,
    split_values,
)

ENGINES = ["xla", "mxu"]
PU = {"xla": ProcessingUnit.HOST, "mxu": ProcessingUnit.GPU}


def build(engine, num_shards, dims, per_shard, exchange, dtype=None, **kw):
    dx, dy, dz = dims
    return DistributedTransform(
        PU[engine],
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(num_shards),
        exchange_type=exchange,
        engine=engine,
        dtype=dtype,
        **kw,
    )


@pytest.mark.parametrize("engine", ENGINES)
@pytest.mark.parametrize(
    "exchange", [ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED]
)
def test_ragged_balanced_roundtrip(engine, exchange):
    rng = np.random.default_rng(42)
    dims = (12, 11, 13)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 4, dy)
    vps = split_values(per_shard, triplets, values)
    t = build(engine, 4, dims, per_shard, exchange)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    assert_close(t.backward(vps), expected)
    # run twice (zeroing check, reference: tests/test_util/test_transform.hpp:129-131)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize("engine", ENGINES)
def test_ragged_all_sticks_on_one_shard(engine):
    """Max stick imbalance: the padded exchange would wire P x S_max x L_max;
    the ragged chain sends only shard 0's exact blocks."""
    rng = np.random.default_rng(1)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.4)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = [triplets] + [np.zeros((0, 3), dtype=np.int64)] * 3
    t = build(engine, 4, dims, per_shard, ExchangeType.COMPACT_BUFFERED)
    out = t.backward([values] + [np.zeros(0)] * 3)
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back[0], values)
    for r in range(1, 4):
        assert back[r].size == 0


@pytest.mark.parametrize("engine", ENGINES)
def test_ragged_sticks_on_one_planes_on_other(engine):
    """Zero-length slab on the stick-owning shard (L_0 = 0): exercises the
    L = 0 guards in the in-trace index math."""
    rng = np.random.default_rng(2)
    dims = (6, 6, 6)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = [triplets, np.zeros((0, 3), dtype=np.int64)]
    t = build(
        engine, 2, dims, per_shard, ExchangeType.COMPACT_BUFFERED,
        local_z_lengths=[0, dz],
    )
    out = t.backward([values, np.zeros(0)])
    assert_close(out, oracle_backward_c2c(triplets, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back[0], values)


@pytest.mark.parametrize("engine", ENGINES)
def test_ragged_uneven_planes(engine):
    """Ragged z-split (13 planes over 4 shards) through the exact-counts path."""
    rng = np.random.default_rng(3)
    dims = (8, 8, 13)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 4, dy)
    vps = split_values(per_shard, triplets, values)
    t = build(engine, 4, dims, per_shard, ExchangeType.COMPACT_BUFFERED)
    assert_close(t.backward(vps), oracle_backward_c2c(triplets, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize("engine", ENGINES)
def test_ragged_float_wire(engine):
    """COMPACT_BUFFERED_FLOAT: f64 data, f32 wire riding the ppermute chain."""
    rng = np.random.default_rng(7)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 4, dy)
    vps = split_values(per_shard, triplets, values)
    t = build(engine, 4, dims, per_shard, ExchangeType.COMPACT_BUFFERED_FLOAT)
    out = t.backward(vps)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    assert_close(out, expected, dtype=np.float32)


@pytest.mark.parametrize("engine", ENGINES)
def test_ragged_bf16_wire(engine):
    """COMPACT_BUFFERED_BF16: bf16 wire riding the ppermute chain (~1e-2 bar)."""
    rng = np.random.default_rng(11)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 4, dy)
    vps = split_values(per_shard, triplets, values)
    t = build(
        engine, 4, dims, per_shard, ExchangeType.COMPACT_BUFFERED_BF16,
        dtype=np.float32,
    )
    out = t.backward(vps)
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=3e-2 * scale)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_ragged_matches_padded_fuzz(seed):
    """Randomized equivalence: the exact-counts chain and the padded all_to_all
    move identical data, so both disciplines must produce the same transform
    (same FFT stages, only the repartition differs)."""
    rng = np.random.default_rng(seed)
    num_shards = int(rng.choice([2, 3, 5, 8]))
    dims = tuple(int(d) for d in rng.integers(4, 14, size=3))
    dx, dy, dz = dims
    triplets = random_sparse_triplets(
        rng, dx, dy, dz, float(rng.uniform(0.2, 0.8)), z_fill=float(rng.uniform(0.4, 1.0))
    )
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    weights = rng.uniform(0.1, 1.0, size=num_shards)
    per_shard = distribute_triplets(triplets, num_shards, dy, weights=weights)
    vps = split_values(per_shard, triplets, values)

    outs = {}
    for exchange in (ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED):
        t = build(
            "xla", num_shards, dims, [p.copy() for p in per_shard], exchange
        )
        outs[exchange] = (
            t.backward([v.copy() for v in vps]),
            t.forward(scaling=ScalingType.FULL),
        )
    b_pad, f_pad = outs[ExchangeType.BUFFERED]
    b_rag, f_rag = outs[ExchangeType.COMPACT_BUFFERED]
    scale = max(1.0, float(np.abs(b_pad).max()))
    np.testing.assert_allclose(b_rag, b_pad, rtol=0, atol=1e-12 * scale)
    for r in range(num_shards):
        np.testing.assert_allclose(f_rag[r], f_pad[r], rtol=0, atol=1e-12)


def test_exchange_wire_bytes_accounting():
    """Chain volume accounting under the round-5 row-granular transport: the
    per-step 2-D windows are (max rows x max cols) over ALL shard pairs of
    the step, and for P >= 2 every step faces some max-plane shard, so the
    chain volume TIES the padded one (its remaining role is the portable
    exact-rows transport; UNBUFFERED carries the byte savings — see
    test_oneshot_wire_bytes_are_exact_alltoallv_volume)."""
    rng = np.random.default_rng(6)
    dims = (8, 8, 8)
    dx, dy, dz = dims

    # balanced: every shard same stick count, uniform z split
    per_shard = [
        np.stack(
            np.meshgrid([r], np.arange(dy), np.arange(dz), indexing="ij"), -1
        ).reshape(-1, 3)
        for r in range(4)
    ]
    t_pad = build("xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.BUFFERED)
    t_rag = build("xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.COMPACT_BUFFERED)
    assert t_rag.exchange_wire_bytes() == t_pad.exchange_wire_bytes()

    # imbalanced sticks AND planes: the row-granular chain still ships
    # (max rows x max cols) windows, which tie the padded volume (every
    # step has a shard pair hitting both maxima)
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.4)
    skew = [triplets] + [np.zeros((0, 3), dtype=np.int64)] * 3
    lz = [1, 1, 1, dz - 3]
    t_pad = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.BUFFERED,
        local_z_lengths=lz,
    )
    t_rag = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.COMPACT_BUFFERED,
        local_z_lengths=lz,
    )
    assert t_rag.exchange_wire_bytes() == t_pad.exchange_wire_bytes()

    # wire-dtype variants scale the byte count, not the element count
    t_bf16 = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.COMPACT_BUFFERED_BF16,
        dtype=np.float32,
    )
    t_f32 = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.COMPACT_BUFFERED,
        dtype=np.float32,
    )
    assert t_bf16.exchange_wire_bytes() * 2 == t_f32.exchange_wire_bytes()


def test_ragged_r2c():
    """Distributed R2C through the exact-counts exchange (hermitian symmetry
    kernels downstream of the ragged unpack)."""
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, 4, dy)
    vps = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]
    for engine in ENGINES:
        t = DistributedTransform(
            PU[engine], TransformType.R2C, dx, dy, dz, [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh(4),
            exchange_type=ExchangeType.COMPACT_BUFFERED,
            engine=engine,
        )
        out = t.backward([v.copy() for v in vps])
        assert_close(out, r)
        back = t.forward(scaling=ScalingType.FULL)
        for r_, vals in enumerate(vps):
            assert_close(back[r_], vals)
