"""Stage-graph IR (spfft_tpu.ir): validation, fusion, parity, provenance.

Four contracts:

1. **Typed pre-compile validation** — unknown stage, dangling edge,
   doubly-produced edge, dtype mismatch and cycles raise
   ``InvalidParameterError`` before anything traces.
2. **Fused == staged parity fuzz** over {C2C, R2C} x {f32, f64} x
   {local, slab, pencil} x overlap {1, 4}, seeded through the
   ``SPFFT_TPU_FUZZ_SEED`` machinery (each case prints its effective seed,
   so a failure replays exactly).
3. **Dispatch counting** — the fused path issues exactly ONE compiled call
   per direction while the staged path issues one per node
   (``ir_dispatches_total{mode,direction}``).
4. **Provenance & degradation** — the plan card's schema-pinned ``ir``
   section, the OVERLAPPED graph rewrite's node structure, the
   ``ir.lower``/``ir.compile`` fallback rungs (the site-by-site invariant
   sweep lives in tests/test_faults.py), the knob surface, and the tuner's
   fused/staged/bf16-twiddle candidates.
"""
import os

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    obs,
)
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.ir import (
    NODES,
    EdgeMeta,
    StageGraph,
    compose,
    resolve_fuse,
)
from spfft_tpu.parallel.mesh import make_fft_mesh, make_fft_mesh2
from spfft_tpu.parameters import distribute_triplets
from utils import random_sparse_triplets

FUZZ_SEED = int(os.environ.get("SPFFT_TPU_FUZZ_SEED", "0"))


def fuzz_rng(base: int, case: int) -> np.random.Generator:
    seed = FUZZ_SEED + base + case
    print(f"fuzz seed = {seed} (SPFFT_TPU_FUZZ_SEED={FUZZ_SEED} + {base} + {case})")
    return np.random.default_rng(seed)


def case_id(*parts) -> int:
    """Deterministic per-parametrization case index: hash() is
    PYTHONHASHSEED-randomized across processes, which would make the printed
    fuzz seed unreplayable — crc32 of the repr is stable."""
    import zlib

    return zlib.crc32(repr(parts).encode()) % 97


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv("SPFFT_TPU_FUSE", raising=False)
    monkeypatch.delenv("SPFFT_TPU_TWIDDLE_BF16", raising=False)
    yield


# ---------------------------------------------------------------------------
# graph validation
# ---------------------------------------------------------------------------


def test_nodes_vocabulary_is_engine_subset_of_stages():
    from spfft_tpu.obs.perf import MODELED_STAGES

    assert set(NODES) == set(MODELED_STAGES)
    assert set(NODES) <= set(obs.STAGES)


def test_unknown_stage_raises_typed():
    g = StageGraph("backward")
    g.add_input("x")
    with pytest.raises(InvalidParameterError, match="unknown stage"):
        g.add("warp drive", lambda x: x, ("x",), ("y",))


def test_dangling_edge_raises_typed():
    g = StageGraph("backward")
    g.add_input("x")
    g.add("z transform", lambda x, ghost: x, ("x", "ghost"), ("y",))
    g.set_outputs(["y"])
    with pytest.raises(InvalidParameterError, match="dangling edge 'ghost'"):
        g.validate()


def test_doubly_produced_edge_raises_typed():
    g = StageGraph("backward")
    g.add_input("x")
    g.add("z transform", lambda x: x, ("x",), ("y",))
    with pytest.raises(InvalidParameterError, match="produced more than once"):
        g.add("y transform", lambda x: x, ("x",), ("y",))


def test_duplicate_node_name_raises_typed():
    g = StageGraph("backward")
    g.add_input("x")
    g.add("z transform", lambda x: x, ("x",), ("y",))
    with pytest.raises(InvalidParameterError, match="duplicate node name"):
        g.add("z transform", lambda y: y, ("y",), ("z",))


def test_dtype_mismatch_raises_before_compile():
    g = StageGraph("backward")
    g.add_input("x", dtype=np.float32, shape=(4,))
    g.add(
        "z transform", lambda x: x, ("x",), ("y",),
        out_meta={"y": EdgeMeta(np.float32, (4,))},
    )
    g.add("y transform", lambda y: y, ("y",), ("z",))
    g.set_outputs(["z"])
    g.expect_dtype("y transform", "y", np.float64)
    with pytest.raises(InvalidParameterError, match="dtype mismatch at edge 'y'"):
        g.validate()


def test_cycle_raises_typed():
    g = StageGraph("backward")
    g.add_input("x")
    g.add("z transform", lambda x, b: x, ("x", "b"), ("a",))
    g.add("y transform", lambda a: a, ("a",), ("b",))
    g.set_outputs(["b"])
    with pytest.raises(InvalidParameterError, match="cycle"):
        g.validate()


def test_missing_output_raises_typed():
    g = StageGraph("forward")
    g.add_input("x")
    g.set_outputs(["nowhere"])
    with pytest.raises(InvalidParameterError, match="produced by no node"):
        g.validate()


def test_compose_executes_in_dependency_order():
    g = StageGraph("backward")
    g.add_input("x")
    g.add("z transform", lambda x: x + 1, ("x",), ("a",))
    g.add("y transform", lambda a: a * 2, ("a",), ("b",))
    g.set_outputs(["b"])
    g.validate()
    assert compose(g)(np.float32(3)) == 8.0


def test_remove_unknown_node_raises_typed():
    g = StageGraph("backward")
    with pytest.raises(InvalidParameterError, match="no node named"):
        g.remove("ghost")


def test_fuse_env_validation(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_FUSE", "2")
    with pytest.raises(InvalidParameterError, match="SPFFT_TPU_FUSE"):
        resolve_fuse()
    monkeypatch.setenv("SPFFT_TPU_FUSE", "0")
    assert resolve_fuse() == (False, "env")
    assert resolve_fuse(True) == (True, "kwarg")
    monkeypatch.delenv("SPFFT_TPU_FUSE")
    assert resolve_fuse() == (True, "default")


def test_twiddle_bf16_env_validation(monkeypatch):
    from spfft_tpu.ops import fft as offt

    monkeypatch.setenv("SPFFT_TPU_TWIDDLE_BF16", "yes")
    with pytest.raises(InvalidParameterError, match="SPFFT_TPU_TWIDDLE_BF16"):
        offt.twiddle_bf16_enabled()
    monkeypatch.setenv("SPFFT_TPU_TWIDDLE_BF16", "1")
    assert offt.twiddle_bf16_enabled()
    # f64 plans ignore the knob: precision is part of the caller's contract
    assert np.dtype(offt.twiddle_dtype(np.float64)) == np.dtype(np.float64)


# ---------------------------------------------------------------------------
# fused vs staged parity fuzz
# ---------------------------------------------------------------------------


def _case_values(rng, trip, dims, r2c, dtype):
    dx, dy, dz = dims
    n = len(trip)
    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        freq = np.fft.fftn(real) / (dx * dy * dz)
        return freq[trip[:, 2], trip[:, 1], trip[:, 0]]
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


def _tol(dtype):
    return 2e-4 if np.dtype(dtype) == np.dtype(np.float32) else 1e-9


def _roundtrip_local(t, values):
    out = t.backward(values)
    return out, t.forward(scaling=ScalingType.FULL)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("r2c", [False, True])
@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_parity_fused_vs_staged_local(dtype, r2c, engine, monkeypatch):
    rng = fuzz_rng(1000, case_id(np.dtype(dtype).name, r2c, engine))
    dims = (int(rng.integers(6, 11)), int(rng.integers(6, 11)), int(rng.integers(6, 12)))
    trip = random_sparse_triplets(
        rng, *dims, float(rng.uniform(0.4, 0.9)), hermitian=r2c
    )
    tt = TransformType.R2C if r2c else TransformType.C2C
    values = _case_values(rng, trip, dims, r2c, dtype)

    t_f = Transform(
        ProcessingUnit.HOST, tt, *dims, indices=trip, dtype=dtype,
        engine=engine, fuse=True,
    )
    t_s = Transform(
        ProcessingUnit.HOST, tt, *dims, indices=trip, dtype=dtype,
        engine=engine, fuse=False,
    )
    assert t_f.fused and t_f._exec._ir.path == "fused"
    assert not t_s.fused and t_s._exec._ir.path == "staged"
    out_f, back_f = _roundtrip_local(t_f, values)
    out_s, back_s = _roundtrip_local(t_s, values)
    tol = _tol(dtype)
    np.testing.assert_allclose(out_f, out_s, rtol=tol, atol=tol)
    np.testing.assert_allclose(back_f, back_s, rtol=tol, atol=tol)
    if not r2c:
        # C2C only: the FULL-scaled roundtrip is the identity (the R2C
        # roundtrip PROJECTS onto hermitian-consistent spectra — Nyquist-
        # plane sticks without their conjugate partners are not reproduced;
        # see obs.perf.measure_pair_seconds)
        np.testing.assert_allclose(back_f, values, rtol=50 * tol, atol=50 * tol)


@pytest.mark.parametrize("overlap", [1, 4])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("r2c", [False, True])
def test_parity_fused_vs_staged_slab(dtype, r2c, overlap):
    rng = fuzz_rng(2000, case_id(np.dtype(dtype).name, r2c, overlap))
    dims = (int(rng.integers(6, 10)), int(rng.integers(6, 10)), int(rng.integers(8, 13)))
    trip = random_sparse_triplets(
        rng, *dims, float(rng.uniform(0.4, 0.9)), hermitian=r2c
    )
    tt = TransformType.R2C if r2c else TransformType.C2C
    values = _case_values(rng, trip, dims, r2c, dtype)
    per_shard = distribute_triplets(trip, 4, dims[1])
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    mesh = make_fft_mesh(4)

    outs = {}
    for fuse in (True, False):
        t = DistributedTransform(
            ProcessingUnit.HOST, tt, *dims, [s.copy() for s in per_shard],
            mesh=mesh, dtype=dtype, overlap=overlap,
            exchange_type=sp.ExchangeType.BUFFERED, fuse=fuse,
        )
        assert t.fused is fuse
        # engines clamp the chunk count to the per-shard stick extent, so
        # small random geometries may run fewer chunks than requested
        assert 1 <= t.overlap_chunks <= overlap
        out = t.backward([v.copy() for v in vps])
        back = t.forward(out, ScalingType.FULL)
        outs[fuse] = (out, back)
    tol = _tol(dtype)
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=tol, atol=tol)
    for bf, bs, v in zip(outs[True][1], outs[False][1], vps):
        np.testing.assert_allclose(bf, bs, rtol=tol, atol=tol)
        if not r2c:  # R2C roundtrips project (see the local parity test)
            np.testing.assert_allclose(bf, v, rtol=50 * tol, atol=50 * tol)


@pytest.mark.parametrize("overlap", [1, 4])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_parity_fused_vs_staged_pencil(dtype, overlap):
    rng = fuzz_rng(3000, case_id(np.dtype(dtype).name, overlap))
    dims = (int(rng.integers(6, 10)), int(rng.integers(6, 10)), int(rng.integers(8, 13)))
    trip = random_sparse_triplets(rng, *dims, float(rng.uniform(0.4, 0.9)))
    values = _case_values(rng, trip, dims, False, dtype)
    per_shard = distribute_triplets(
        trip, 4, dims[1], layout=(2, 2), dim_x=dims[0]
    )
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    mesh = make_fft_mesh2(2, 2)

    outs = {}
    for fuse in (True, False):
        t = DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, *dims,
            [s.copy() for s in per_shard], mesh=mesh, dtype=dtype,
            overlap=overlap, exchange_type=sp.ExchangeType.BUFFERED,
            fuse=fuse,
        )
        assert t._engine.startswith("pencil2")
        out = t.backward([v.copy() for v in vps])
        back = t.forward(out, ScalingType.FULL)
        outs[fuse] = (out, back)
    tol = _tol(dtype)
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=tol, atol=tol)
    for bf, bs, v in zip(outs[True][1], outs[False][1], vps):
        np.testing.assert_allclose(bf, bs, rtol=tol, atol=tol)
        np.testing.assert_allclose(bf, v, rtol=50 * tol, atol=50 * tol)


def test_bf16_twiddle_variant_loose_parity(monkeypatch):
    """The mixed-precision fused variant stays a correct transform at bf16
    tolerance (~3 significant digits) — the tuner may pick it, never a
    broken pipeline."""
    rng = fuzz_rng(4000, 0)
    dims = (8, 8, 8)
    trip = random_sparse_triplets(rng, *dims, 0.7)
    values = _case_values(rng, trip, dims, False, np.float32)
    base = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
        dtype=np.float32, engine="mxu",
    )
    monkeypatch.setenv("SPFFT_TPU_TWIDDLE_BF16", "1")
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
        dtype=np.float32, engine="mxu",
    )
    out_b = base.backward(values)
    out_t = t.backward(values)
    scale = max(1.0, float(np.abs(out_b).max()))
    assert np.abs(out_t - out_b).max() / scale < 3e-2
    back = t.forward(scaling=ScalingType.FULL)
    assert np.abs(back - values).max() / max(1.0, np.abs(values).max()) < 3e-2


# ---------------------------------------------------------------------------
# dispatch counting: fused = ONE executable call per direction
# ---------------------------------------------------------------------------


def _dispatch_counts():
    """(mode, direction) -> ir_dispatches_total value, from the registry
    snapshot (keys are ``name{label="value",...}`` strings)."""
    out = {}
    for key, value in obs.snapshot()["counters"].items():
        if not key.startswith("ir_dispatches_total"):
            continue
        for mode in ("fused", "staged", "legacy"):
            for direction in ("backward", "forward"):
                if f'mode="{mode}"' in key and f'direction="{direction}"' in key:
                    out[(mode, direction)] = value
    return out


def test_fused_single_dispatch_per_direction():
    rng = fuzz_rng(5000, 0)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=True,
    )
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    before = _dispatch_counts()
    t.backward(values)
    t.forward(scaling=ScalingType.FULL)
    after = _dispatch_counts()
    assert after.get(("fused", "backward"), 0) - before.get(("fused", "backward"), 0) == 1
    assert after.get(("fused", "forward"), 0) - before.get(("fused", "forward"), 0) == 1


def test_staged_dispatches_once_per_node():
    rng = fuzz_rng(5000, 1)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=False,
    )
    ir = t._exec._ir
    n_back = ir._backward.num_dispatches
    n_fwd = ir._forward[ScalingType.FULL].num_dispatches
    assert n_back >= 5 and n_fwd >= 5  # one dispatch per pipeline stage
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    before = _dispatch_counts()
    t.backward(values)
    t.forward(scaling=ScalingType.FULL)
    after = _dispatch_counts()
    assert (
        after.get(("staged", "backward"), 0)
        - before.get(("staged", "backward"), 0)
        == n_back
    )
    assert (
        after.get(("staged", "forward"), 0)
        - before.get(("staged", "forward"), 0)
        == n_fwd
    )


def test_fused_distributed_single_dispatch():
    rng = fuzz_rng(5000, 2)
    trip = random_sparse_triplets(rng, 8, 8, 10, 0.7)
    per_shard = distribute_triplets(trip, 4, 8)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 10, per_shard,
        mesh=make_fft_mesh(4), fuse=True,
    )
    values = _case_values(rng, trip, (8, 8, 10), False, np.float64)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    before = _dispatch_counts()
    out = t.backward(vps)
    t.forward(out, ScalingType.FULL)
    after = _dispatch_counts()
    assert after.get(("fused", "backward"), 0) - before.get(("fused", "backward"), 0) == 1
    assert after.get(("fused", "forward"), 0) - before.get(("fused", "forward"), 0) == 1


# ---------------------------------------------------------------------------
# provenance: plan card ir section, overlap rewrite structure, tuner axis
# ---------------------------------------------------------------------------


def test_plan_card_ir_section_schema():
    rng = fuzz_rng(6000, 0)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    card = t.report()
    assert obs.validate_plan_card(card) == []
    ir = card["ir"]
    assert ir["fused"] is True and ir["path"] == "fused"
    assert ir["requested"] in ("kwarg", "env", "default")
    for direction in ("backward", "forward"):
        stages = ir["stages"][direction]
        assert stages and all(s in NODES for s in stages)
    # the fused consuming backward donates the packed value pair (local)
    assert ir["donation"]["backward"] == ["values_re", "values_im"]
    assert ir["donation"]["forward"] == []


def test_plan_card_ir_section_staged_and_distributed():
    rng = fuzz_rng(6000, 1)
    trip = random_sparse_triplets(rng, 8, 8, 10, 0.7)
    per_shard = distribute_triplets(trip, 4, 8)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 10, per_shard,
        mesh=make_fft_mesh(4), fuse=False,
    )
    card = t.report()
    assert obs.validate_plan_card(card) == []
    assert card["ir"]["path"] == "staged" and card["ir"]["fused"] is False
    # distributed programs donate nothing (sharded staging buffers are
    # caller-visible)
    assert card["ir"]["donation"]["backward"] == []
    assert "exchange" in card["ir"]["stages"]["backward"]


def test_overlap_rewrite_splits_exchange_nodes():
    """The OVERLAPPED discipline as an IR rewrite: C chunked collectives
    carrying the overlapped labels, no bulk exchange node left, and the
    stage list still validating against the canonical vocabulary."""
    rng = fuzz_rng(6000, 2)
    trip = random_sparse_triplets(rng, 8, 8, 12, 0.8)
    per_shard = distribute_triplets(trip, 4, 8)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 12, per_shard,
        mesh=make_fft_mesh(4), overlap=3,
        exchange_type=sp.ExchangeType.BUFFERED,
    )
    assert t.overlap_chunks == 3
    stages = t.report()["ir"]["stages"]["backward"]
    assert stages.count("exchange overlapped") == 3
    assert "exchange" not in stages
    assert stages.count("z transform") == 3  # one per chunk
    bulk = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 12,
        [s.copy() for s in per_shard], mesh=make_fft_mesh(4), overlap=1,
        exchange_type=sp.ExchangeType.BUFFERED,
    )
    bstages = bulk.report()["ir"]["stages"]["backward"]
    assert bstages.count("exchange") == 1
    assert "exchange overlapped" not in bstages


def test_pencil_overlap_rewrite_splits_both_exchanges():
    rng = fuzz_rng(6000, 3)
    trip = random_sparse_triplets(rng, 8, 8, 12, 0.8)
    per_shard = distribute_triplets(trip, 4, 8, layout=(2, 2), dim_x=8)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 12, per_shard,
        mesh=make_fft_mesh2(2, 2), overlap=2,
        exchange_type=sp.ExchangeType.BUFFERED,
    )
    assert t.overlap_chunks == 2
    stages = t.report()["ir"]["stages"]["backward"]
    assert stages.count("exchange A overlapped") == 2
    assert stages.count("exchange B overlapped") == 2
    assert "exchange A" not in stages and "exchange B" not in stages


def test_fuse_env_knob_resolves_at_construction(monkeypatch):
    rng = fuzz_rng(6000, 4)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    monkeypatch.setenv("SPFFT_TPU_FUSE", "0")
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    assert not t.fused and t.report()["ir"]["requested"] == "env"
    # explicit kwarg wins over env
    t2 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=True,
    )
    assert t2.fused and t2.report()["ir"]["requested"] == "kwarg"


def test_clone_preserves_fuse_request():
    rng = fuzz_rng(6000, 5)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=False,
    )
    assert not t.clone().fused


def test_perf_report_stamps_fuse_state():
    from spfft_tpu.obs import perf

    rng = fuzz_rng(6000, 6)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    for fuse in (True, False):
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
            fuse=fuse,
        )
        rep = perf.perf_report(t, 1e-3)
        assert perf.validate_perf_report(rep) == []
        assert rep["fused"] is fuse
        total = sum(r["seconds"] for r in rep["stages"])
        assert abs(total - rep["seconds_per_pair"]) < 1e-12


def test_tuned_policy_owns_fusion_axis(tmp_path, monkeypatch):
    """fused / staged / bf16-twiddle are trial candidates under
    policy="tuned", the winner's env persists in wisdom, and a warm store
    reproduces the choice with zero trials. f32 plan: the bf16-twiddle
    candidate only exists where the knob engages (f64 drops it — see
    test_local_candidates_f64_drops_bf16_twiddle)."""
    from spfft_tpu import tuning

    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "w.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    tuning.clear_memory()
    labels = {c["label"] for c in tuning.local_candidates("cpu", np.float32)}
    assert {"xla/staged", "mxu/staged", "mxu/bf16-twiddle"} <= labels
    rng = fuzz_rng(7000, 0)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        dtype=np.float32, policy="tuned",
    )
    rec = t.report()["tuning"]
    assert rec["provenance"] == "wisdom" and rec["hit"] is False
    tried = {row["label"] for row in rec["trials"]}
    assert {"xla/staged", "mxu/staged", "mxu/bf16-twiddle"} <= tried
    # warm store: same plan, zero trials, same choice
    t2 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=trip.copy(), dtype=np.float32, policy="tuned",
    )
    rec2 = t2.report()["tuning"]
    assert rec2["hit"] is True and rec2["choice"] == rec["choice"]


def test_ir_lower_failure_degrades_to_legacy_with_parity():
    from spfft_tpu import faults

    rng = fuzz_rng(8000, 0)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    base = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    expect = base.backward(values)
    with faults.inject("ir.lower=raise"):
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip
        )
    card = t.report()
    assert card["ir"]["path"] == "legacy"
    assert any(d["event"] == "ir_lower_failed" for d in card["degradations"])
    np.testing.assert_allclose(t.backward(values), expect, rtol=1e-9, atol=1e-9)


def test_ir_compile_failure_degrades_to_staged_with_parity():
    from spfft_tpu import faults

    rng = fuzz_rng(8000, 1)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    base = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    expect = base.backward(values)
    with faults.inject("ir.compile=raise"):
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip
        )
    card = t.report()
    assert card["ir"]["path"] == "staged" and card["ir"]["fused"] is False
    assert any(d["event"] == "fuse_compile_failed" for d in card["degradations"])
    np.testing.assert_allclose(t.backward(values), expect, rtol=1e-9, atol=1e-9)


def test_fused_lazy_compile_failure_degrades_at_first_dispatch():
    """jax.jit compiles lazily, so a fused program whose XLA compile
    genuinely fails (compile-memory exhaustion on an enormous program)
    raises at the FIRST dispatch, not in init_engine_ir's try. The same
    fuse_compile_failed rung must engage there: staged re-dispatch, the
    entry on the plan card — never a failed call."""
    rng = fuzz_rng(8000, 2)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    base = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    expect = base.backward(values)
    expect_f = base.forward(expect, ScalingType.FULL)

    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    ir = t._exec._ir
    assert ir.path == "fused"

    def compile_oom(*args):
        raise RuntimeError("simulated XLA compile failure: out of memory")

    ir._backward = compile_oom
    ir._backward_consuming = compile_oom
    ir._forward = {s: compile_oom for s in ir._forward}

    out = t.backward(values)  # rung engages inside the dispatch
    np.testing.assert_allclose(out, expect, rtol=1e-9, atol=1e-9)
    card = t.report()
    assert card["ir"]["path"] == "staged" and card["ir"]["fused"] is False
    assert any(d["event"] == "fuse_compile_failed" for d in card["degradations"])
    # subsequent dispatches (both directions) run staged, no re-recording
    np.testing.assert_allclose(
        t.forward(out, ScalingType.FULL), expect_f, rtol=1e-9, atol=1e-9
    )
    events = [d["event"] for d in t.report()["degradations"]]
    assert events.count("fuse_compile_failed") == 1


def test_fused_post_success_errors_propagate():
    """The first-dispatch rung is for COMPILE failures only: once a fused
    program has succeeded, later errors (a genuine execution failure) must
    propagate to the typed_execution ladder, not silently re-route through
    the staged path."""
    rng = fuzz_rng(8000, 3)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    ir = t._exec._ir
    t.backward(values)  # fused programs compile and succeed

    def exec_fail(*args):
        raise RuntimeError("simulated execution failure after warmup")

    ir._backward = exec_fail
    ir._backward_consuming = exec_fail
    with pytest.raises(Exception, match="simulated execution failure"):
        t.backward(values)
    assert ir.path == "fused"  # no silent degradation after first success


def test_varargs_input_count_validated():
    """The varargs (local MXU operand-threading) entry validates its fixed
    input count like the plain entry: too few positionals raise typed, not
    a KeyError from a silently truncated zip."""
    from spfft_tpu.ir.compile import StagedProgram

    rng = fuzz_rng(8000, 4)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        dtype=np.float32, engine="mxu",
    )
    ir = t._exec._ir
    g = ir.graphs["backward"]
    assert getattr(g, "varargs", False), "local MXU backward threads operands"
    fn = compose(g)
    with pytest.raises(InvalidParameterError, match="expected at least"):
        fn(np.zeros(4, np.float32))  # values_im missing
    staged = StagedProgram(g, ir.spec)
    with pytest.raises(InvalidParameterError, match="expected at least"):
        staged(np.zeros(4, np.float32))


def test_local_candidates_f64_drops_bf16_twiddle():
    """SPFFT_TPU_TWIDDLE_BF16 is a no-op for f64 plans (ops/fft.twiddle_dtype
    engages for f32 only), so the tuner must not trial the mxu/bf16-twiddle
    candidate there — it would be a duplicate of the bare mxu whose noise
    win persists a misleading mixed-precision choice in wisdom."""
    from spfft_tpu import tuning

    for dt in (None, np.float32, "float32"):
        labels = {c["label"] for c in tuning.local_candidates("cpu", dt)}
        assert "mxu/bf16-twiddle" in labels, dt
    for dt in (np.float64, "float64"):
        labels = {c["label"] for c in tuning.local_candidates("cpu", dt)}
        assert "mxu/bf16-twiddle" not in labels, dt
        assert {"mxu", "mxu/staged", "xla", "xla/staged"} <= labels


def test_fuse_kwarg_validated_typed():
    """fuse= follows the same typed-validation contract as SPFFT_TPU_FUSE:
    a malformed value raises InvalidParameterError at plan construction
    (never an untyped ValueError from int() deep inside engine build), and
    out-of-range ints are refused rather than silently truthy."""
    from spfft_tpu.ir.compile import resolve_fuse

    rng = fuzz_rng(8000, 7)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    for bad in ("fast", 2, -1, 1.0):
        with pytest.raises(InvalidParameterError, match="fuse="):
            resolve_fuse(bad)
        with pytest.raises(InvalidParameterError, match="fuse="):
            Transform(
                ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
                indices=trip, fuse=bad,
            )
    for ok, want in ((True, True), (False, False), (1, True), (0, False)):
        assert resolve_fuse(ok) == (want, "kwarg")


def test_explicit_fuse_pins_tuned_fusion_axis(tmp_path, monkeypatch):
    """An explicit fuse= under policy="tuned" pins the fusion axis: the
    kwarg beats every candidate env in ir.resolve_fuse, so the */staged
    variants must not be trialed (their label and persisted env would claim
    a variant the plan never runs), trials measure the pinned state, and
    the pin is part of the wisdom key so pinned winners never answer
    tuner-owned lookups (or vice versa)."""
    from spfft_tpu import tuning

    labels = {c["label"] for c in tuning.local_candidates("cpu", np.float32,
                                                          fuse=False)}
    assert labels == {"xla", "mxu", "mxu/dense-y", "mxu/bf16-twiddle"}
    assert labels == {c["label"] for c in tuning.local_candidates(
        "cpu", np.float32, fuse=True)}

    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "w.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    tuning.clear_memory()
    rng = fuzz_rng(7000, 1)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        dtype=np.float32, policy="tuned", fuse=False,
    )
    card = t.report()
    rec = card["tuning"]
    assert rec["provenance"] == "wisdom" and rec["hit"] is False
    tried = {row["label"] for row in rec["trials"]}
    assert not any(lbl.endswith("/staged") for lbl in tried), tried
    # the plan runs what the trials measured: the pinned staged path
    assert t.fused is False and card["ir"]["path"] == "staged"
    # tuner-owned lookup of the same geometry must MISS the pinned entry
    # (distinct wisdom key) and trial the full candidate list
    t2 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=trip.copy(), dtype=np.float32, policy="tuned",
    )
    rec2 = t2.report()["tuning"]
    assert rec2["hit"] is False
    assert {"xla/staged", "mxu/staged"} <= {r["label"] for r in rec2["trials"]}
    # warm store: the pinned plan reproduces with zero trials
    t3 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=trip.copy(), dtype=np.float32, policy="tuned", fuse=False,
    )
    rec3 = t3.report()["tuning"]
    assert rec3["hit"] is True and rec3["choice"] == rec["choice"]
    assert t3.fused is False


def test_ir_typed_refusals_take_rungs_not_failed_plans(monkeypatch):
    """The IR's own typed refusals (graph validation, unregistered lowering,
    mesh-spec derivation — all InvalidParameterError) are rungs like the
    build-error classes: a lowering refusal runs legacy, a fusion refusal
    runs staged. Never a failed plan."""
    from spfft_tpu.ir import compile as ir_compile
    from spfft_tpu.ir import lower as ir_lower

    rng = fuzz_rng(8000, 5)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    values = _case_values(rng, trip, (8, 8, 8), False, np.float64)
    base = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    expect = base.backward(values)

    def refuse(*a, **k):
        raise InvalidParameterError("no lowering registered for FakeEngine")

    monkeypatch.setattr(ir_lower, "lower_engine", refuse)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    card = t.report()
    assert card["ir"]["path"] == "legacy"
    assert any(d["event"] == "ir_lower_failed" for d in card["degradations"])
    np.testing.assert_allclose(t.backward(values), expect, rtol=1e-9, atol=1e-9)
    monkeypatch.undo()

    monkeypatch.setattr(ir_compile, "build_fused", refuse)
    t2 = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    card2 = t2.report()
    assert card2["ir"]["path"] == "staged"
    assert any(d["event"] == "fuse_compile_failed" for d in card2["degradations"])
    np.testing.assert_allclose(t2.backward(values), expect, rtol=1e-9, atol=1e-9)


def test_overlap_delta_phase_tables_hoisted_once(monkeypatch):
    """Delta-rep alignment-phase tables generate ONCE per direction in the
    OVERLAPPED rewrite (one `z transform phase` producer node the chunk z
    nodes consume — the PR-7 hoist as graph structure), and the chunked
    fused/staged paths reproduce the bulk table-rep reference exactly."""
    from utils import contiguous_stick_triplets, split_values

    from spfft_tpu.ops import lanecopy

    # geometry with alignment rotations (the test_distributed_mxu delta
    # recipe — lane-misaligned contiguous sticks at a 128-deep z)
    rng = np.random.default_rng(81)
    dx, dy, dz = 6, 7, 128
    trip = contiguous_stick_triplets(rng, dx, dy, dz, r2c=False)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    mesh = make_fft_mesh(4)

    ref = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz,
        [s.copy() for s in per_shard], mesh=mesh, engine="mxu",
    )
    assert ref._exec._align_rep is not None and ref._exec._align_rep[0] == "table"
    expect = np.asarray(ref.backward([v.copy() for v in vps]))
    expect_f = ref.forward(scaling=ScalingType.FULL)

    monkeypatch.setenv(lanecopy.PHASE_TABLE_LIMIT_MB_ENV, "0")
    for fuse in (True, False):
        t = DistributedTransform(
            ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz,
            [s.copy() for s in per_shard], mesh=mesh, engine="mxu",
            overlap=3, fuse=fuse,
        )
        assert t._exec._align_rep[0] == "delta"
        g = t._exec._ir.graphs["backward"]
        names = [n.name for n in g.toposort()]
        assert names.count("z transform phase") == 1
        phase_nodes = [n for n in g.nodes if n.name == "z transform phase"]
        assert phase_nodes[0].inputs == ()
        # every chunk z node consumes the hoisted pair, none regenerates
        chunk_z = [
            n for n in g.nodes
            if n.stage == "z transform" and n.name.startswith("z transform@")
        ]
        assert len(chunk_z) == 3
        for n in chunk_z:
            assert set(phase_nodes[0].outputs) <= set(n.inputs)
        out = np.asarray(t.backward([v.copy() for v in vps]))
        np.testing.assert_allclose(out, expect, rtol=1e-12, atol=1e-12)
        back = t.forward(scaling=ScalingType.FULL)
        for bf, br in zip(back, expect_f):
            np.testing.assert_allclose(bf, br, rtol=1e-12, atol=1e-12)
