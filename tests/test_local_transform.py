"""Local (single-device) transform tests vs the dense oracle.

Parity with reference tests/local_tests/test_local_transform.cpp +
tests/test_util/test_transform.hpp: random sparse stick sets, dense-FFT oracle,
run-twice zeroing check, dimension sweep including awkward sizes.
"""
import numpy as np
import pytest

from spfft_tpu import (
    Grid,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
)
from utils import (
    assert_close,
    oracle_backward_c2c,
    oracle_forward_c2c,
    random_sparse_triplets,
)

# dim set mirrors the reference sweep {1, 2, 11, 12, 13, 100}
# (reference: tests/mpi_tests/test_transform.cpp:173-191)
DIMS = [
    (2, 2, 2),
    (4, 5, 6),
    (11, 12, 13),
    (16, 16, 16),
    (1, 13, 7),
    (1, 1, 1),
    (100, 11, 2),
]


def make_transform(dims, triplets, dtype=np.float64, ttype=TransformType.C2C):
    return Transform(
        ProcessingUnit.HOST,
        ttype,
        dims[0],
        dims[1],
        dims[2],
        indices=triplets,
        dtype=dtype,
    )


@pytest.mark.parametrize("dims", DIMS)
@pytest.mark.parametrize("centered", [False, True])
def test_c2c_backward_vs_oracle(dims, centered):
    rng = np.random.default_rng(42)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.6, 0.8, centered=centered)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    t = make_transform(dims, triplets)
    out = np.asarray(t.backward(values))
    expected = oracle_backward_c2c(triplets, values, dx, dy, dz)
    assert out.shape == (dz, dy, dx)
    assert_close(out, expected)

    # Run twice: catches stale-buffer / missing-zeroing bugs
    # (reference: tests/test_util/test_transform.hpp:129-131).
    out2 = np.asarray(t.backward(values))
    assert_close(out2, expected)


@pytest.mark.parametrize("dims", DIMS)
def test_c2c_forward_vs_oracle(dims):
    rng = np.random.default_rng(7)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    space = rng.standard_normal((dz, dy, dx)) + 1j * rng.standard_normal((dz, dy, dx))

    t = make_transform(dims, triplets)
    out = np.asarray(t.forward(space))
    assert_close(out, oracle_forward_c2c(triplets, space))

    scaled = np.asarray(t.forward(space, scaling=ScalingType.FULL))
    assert_close(scaled, oracle_forward_c2c(triplets, space, scale=1.0 / (dx * dy * dz)))


@pytest.mark.parametrize("dims", [(8, 8, 8), (11, 12, 13)])
def test_c2c_roundtrip_full_scaling(dims):
    rng = np.random.default_rng(3)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.4, 0.7)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    t = make_transform(dims, triplets)
    t.backward(values)
    # forward with full scaling over the retained space buffer restores the input
    # (reference: docs/source/details.rst:42-44).
    out = np.asarray(t.forward(scaling=ScalingType.FULL))
    assert_close(out, values)


def test_float32_backward():
    rng = np.random.default_rng(5)
    dims = (12, 10, 8)
    triplets = random_sparse_triplets(rng, *dims, 0.5)
    n = len(triplets)
    values = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)

    t = make_transform(dims, triplets, dtype=np.float32)
    out = np.asarray(t.backward(values))
    assert out.dtype == np.complex64
    assert_close(out, oracle_backward_c2c(triplets, values, *dims), dtype=np.float32)


def test_grid_create_transform_and_capacity():
    rng = np.random.default_rng(1)
    dims = (8, 8, 8)
    triplets = random_sparse_triplets(rng, *dims, 0.5)
    grid = Grid(8, 8, 8, 64, ProcessingUnit.HOST)
    t = grid.create_transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=triplets
    )
    assert t.grid is grid
    assert t.dim_x == 8 and t.local_z_length == 8 and t.local_z_offset == 0

    from spfft_tpu import InvalidParameterError

    small = Grid(4, 4, 4, 1, ProcessingUnit.HOST)
    with pytest.raises(InvalidParameterError):
        small.create_transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=triplets
        )


def test_clone_independent():
    rng = np.random.default_rng(9)
    dims = (6, 6, 6)
    triplets = random_sparse_triplets(rng, *dims, 0.5)
    n = len(triplets)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    t = make_transform(dims, triplets)
    c = t.clone()
    assert_close(np.asarray(c.backward(values)), np.asarray(t.backward(values)))


def test_accessors():
    rng = np.random.default_rng(11)
    dims = (4, 6, 8)
    triplets = random_sparse_triplets(rng, *dims, 0.5)
    t = make_transform(dims, triplets)
    assert (t.dim_x, t.dim_y, t.dim_z) == dims
    assert t.global_size == 4 * 6 * 8
    assert t.num_local_elements == len(triplets)
    assert t.transform_type == TransformType.C2C
    assert t.local_slice_size == 4 * 6 * 8


def test_local_z_length_validation():
    """An explicit positive local_z_length outside the local-slab envelope is
    rejected (reference: src/spfft/transform.cpp:51-55,
    transform_internal.cpp:45-137); the full-depth value is accepted, and 0
    means "unspecified" like the reference's serial path, which ignores the
    parameter entirely (docs/MIGRATION.md behavioral difference #7)."""
    import pytest

    from spfft_tpu.errors import InvalidParameterError

    rng = np.random.default_rng(11)
    trip = random_sparse_triplets(rng, 6, 6, 6, 0.5)
    for bad in (-1, 3, 7):
        with pytest.raises(InvalidParameterError):
            Transform(
                ProcessingUnit.HOST, TransformType.C2C, 6, 6, 6,
                indices=trip, local_z_length=bad,
            )
    for ok in (0, 6):  # 0 == unspecified (reference serial callers pass it)
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 6, 6, 6,
            indices=trip, local_z_length=ok,
        )
        assert t.dim_z == 6
        assert t.local_z_length == 6
