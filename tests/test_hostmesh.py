"""Multi-host bootstrap (`spfft_tpu.hostmesh`) + distributed-init validation.

Covers the boot half of the multi-host serving layer: typed up-front
validation of the ``jax.distributed`` coordinates
(``parallel/mesh.py:validate_distributed_args`` — a malformed value must
raise here, never fail opaquely inside a child process), worker-spawn env
propagation (every ambient ``SPFFT_TPU_*`` knob reaches the child — lockdep
arming included), wisdom warm-start from fleet bundles, and the real
2-process × N-device ``jax.distributed`` boot proof. The cluster-front /
chaos suites live in ``tests/test_cluster.py``.
"""
from __future__ import annotations

import json

import pytest

import spfft_tpu as sp
from spfft_tpu import hostmesh, tuning
from spfft_tpu.errors import (
    GenericError,
    HostExecutionError,
    InvalidParameterError,
)
from spfft_tpu.parallel.mesh import validate_distributed_args
from spfft_tpu.serve.rpc import RpcClient


# ---- init_distributed up-front validation -----------------------------------


@pytest.mark.parametrize(
    "coord,nprocs,pid",
    [
        ("localhost", 2, 0),          # no port
        (":8476", 2, 0),              # no host
        ("localhost:notaport", 2, 0),  # non-integer port
        ("localhost:0", 2, 0),        # port out of range
        ("localhost:99999", 2, 0),    # port out of range
        ("localhost:8476", 0, 0),     # num_processes < 1
        ("localhost:8476", "two", 0),  # non-integer num_processes
        ("localhost:8476", 2, -1),    # negative process_id
        ("localhost:8476", 2, 2),     # process_id >= num_processes
        ("localhost:8476", 2, "one"),  # non-integer process_id
        ("localhost:8476", None, 0),  # process_id without num_processes
    ],
)
def test_distributed_args_malformed_raise_typed(coord, nprocs, pid):
    with pytest.raises(InvalidParameterError):
        validate_distributed_args(coord, nprocs, pid)


def test_distributed_args_valid_pass():
    validate_distributed_args("localhost:8476", 2, 1)
    validate_distributed_args(None, None, None)  # TPU pods: all inferred
    validate_distributed_args("10.0.0.1:1", 1, 0)


def test_init_distributed_validates_before_initialize(monkeypatch):
    """init_distributed must refuse malformed coordinates WITHOUT touching
    jax.distributed (the opaque-in-child failure the wrapper exists to
    prevent)."""
    import jax

    called = []
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: called.append(kw),
    )
    with pytest.raises(InvalidParameterError):
        sp.init_distributed("nonsense", num_processes=2, process_id=0)
    assert called == []


# ---- child env propagation --------------------------------------------------


def test_child_env_propagates_every_ambient_knob(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_LOCKDEP", "1")
    monkeypatch.setenv("SPFFT_TPU_SERVE_QUEUE_CAP", "17")
    monkeypatch.setenv("SPFFT_TPU_FAULTS_SEED", "42")
    env = hostmesh.child_env(devices=4)
    assert env["SPFFT_TPU_LOCKDEP"] == "1"
    assert env["SPFFT_TPU_SERVE_QUEUE_CAP"] == "17"
    assert env["SPFFT_TPU_FAULTS_SEED"] == "42"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert env["JAX_PLATFORMS"]  # always pinned for the child


def test_child_env_overrides_win_and_device_flag_replaced(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_LOCKDEP", "0")
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_cpu_foo=1 --xla_force_host_platform_device_count=8",
    )
    env = hostmesh.child_env({"SPFFT_TPU_LOCKDEP": "1"}, devices=2)
    assert env["SPFFT_TPU_LOCKDEP"] == "1"
    # the parent's own device-count flag is replaced, other flags survive
    assert "--xla_cpu_foo=1" in env["XLA_FLAGS"]
    assert env["XLA_FLAGS"].count("device_count") == 1
    assert "device_count=2" in env["XLA_FLAGS"]


def test_child_env_devices_typed():
    with pytest.raises(InvalidParameterError):
        hostmesh.child_env(devices=0)


def test_child_env_never_propagates_shared_lockdep_report(monkeypatch):
    """The parent's SPFFT_TPU_LOCKDEP_REPORT must not reach children
    verbatim: every process writing ONE report path at exit means last
    writer wins and the merged cross-check silently loses the workers'
    graphs. Explicit per-host overrides (spawn_workers lockdep_dir=) still
    win."""
    monkeypatch.setenv("SPFFT_TPU_LOCKDEP", "1")
    monkeypatch.setenv("SPFFT_TPU_LOCKDEP_REPORT", "/tmp/shared.json")
    env = hostmesh.child_env()
    assert "SPFFT_TPU_LOCKDEP_REPORT" not in env
    assert env["SPFFT_TPU_LOCKDEP"] == "1"  # the arming itself propagates
    env = hostmesh.child_env({"SPFFT_TPU_LOCKDEP_REPORT": "/tmp/host0.json"})
    assert env["SPFFT_TPU_LOCKDEP_REPORT"] == "/tmp/host0.json"


def test_child_env_never_propagates_shared_trace_dump(monkeypatch):
    """The parent's SPFFT_TPU_TRACE_DUMP must not reach children verbatim
    (the lockdep-report rule): a shared dump directory interleaves every
    host's crash dumps into one pid-keyed pile nobody can attribute.
    Explicit per-host overrides still win."""
    monkeypatch.setenv("SPFFT_TPU_TRACE", "1")
    monkeypatch.setenv("SPFFT_TPU_TRACE_DUMP", "/tmp/shared-dumps")
    env = hostmesh.child_env()
    assert "SPFFT_TPU_TRACE_DUMP" not in env
    assert env["SPFFT_TPU_TRACE"] == "1"  # the arming itself propagates
    env = hostmesh.child_env({"SPFFT_TPU_TRACE_DUMP": "/tmp/dumps/host0"})
    assert env["SPFFT_TPU_TRACE_DUMP"] == "/tmp/dumps/host0"


def test_spawn_fans_out_trace_dump_per_host(tmp_path, monkeypatch):
    """A parent SPFFT_TPU_TRACE_DUMP fans out as per-host subdirectories
    (``trace.dump()`` mkdirs, so they need not pre-exist): each worker
    flushes its flight recorder into its own attributable directory."""
    monkeypatch.setenv("SPFFT_TPU_TRACE_DUMP", str(tmp_path / "dumps"))
    captured = []

    class _DeadProc:
        def poll(self):
            return 1  # exited: the readiness wait gives up immediately

        def send_signal(self, sig):
            pass

    def fake_popen(cmd, stdout=None, stderr=None, env=None, cwd=None):
        captured.append(env)
        return _DeadProc()

    monkeypatch.setattr(hostmesh.subprocess, "Popen", fake_popen)
    with pytest.raises(HostExecutionError, match="failed to become ready"):
        hostmesh.spawn_workers(2, workdir=str(tmp_path / "w"))
    assert [e.get("SPFFT_TPU_TRACE_DUMP") for e in captured] == [
        str(tmp_path / "dumps" / "host0"),
        str(tmp_path / "dumps" / "host1"),
    ]
    # an explicit env= override beats the fan-out default
    captured.clear()
    with pytest.raises(HostExecutionError):
        hostmesh.spawn_workers(
            1, workdir=str(tmp_path / "w2"),
            env={"SPFFT_TPU_TRACE_DUMP": str(tmp_path / "mine")},
        )
    assert captured[0]["SPFFT_TPU_TRACE_DUMP"] == str(tmp_path / "mine")


# ---- wisdom warm-start ------------------------------------------------------


def test_warm_start_merges_fleet_bundle(tmp_path, monkeypatch):
    donor = tuning.WisdomStore(str(tmp_path / "donor.json"))
    key = {"kind": "local", "probe": 1}
    donor.record(
        key,
        tuning.make_entry(key, {"engine": "xla"}, [{"label": "c0", "ms": 1.0}]),
    )
    bundle = tmp_path / "fleet.json"
    assert donor.export(str(bundle)) == 1
    # the booted host's own (file) store starts cold and warms from the bundle
    monkeypatch.setenv("SPFFT_TPU_WISDOM", str(tmp_path / "host.json"))
    monkeypatch.setenv(hostmesh.WISDOM_BUNDLE_ENV, str(bundle))
    assert hostmesh.warm_start() == (1, 0)
    store = tuning.WisdomStore(str(tmp_path / "host.json"))
    assert store.lookup(key)["choice"] == {"engine": "xla"}
    # idempotent: a second boot adds nothing
    assert hostmesh.warm_start() == (0, 0)


def test_warm_start_unset_is_noop(monkeypatch):
    monkeypatch.delenv(hostmesh.WISDOM_BUNDLE_ENV, raising=False)
    assert hostmesh.warm_start() == (0, 0)


def test_warm_start_corrupt_bundle_typed(tmp_path, monkeypatch):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setenv("SPFFT_TPU_WISDOM", str(tmp_path / "host.json"))
    with pytest.raises(GenericError):
        hostmesh.warm_start(str(bad))


# ---- spawn validation -------------------------------------------------------


def test_spawn_workers_typed_validation():
    with pytest.raises(InvalidParameterError):
        hostmesh.spawn_workers(0)


def test_spawn_workers_boot_failure_typed(tmp_path):
    """A worker that dies before readiness surfaces typed with its log tail
    — never a silent hang until the timeout."""
    with pytest.raises(HostExecutionError, match="failed to become ready"):
        hostmesh.spawn_workers(
            1, workdir=str(tmp_path), ready_timeout_s=20.0,
            python="/bin/false",
        )


# ---- real worker boot (subprocess; the expensive cells) ---------------------


def test_spawn_worker_ready_env_lockdep_and_clean_stop(tmp_path, monkeypatch):
    """One spawned worker: ready handshake, knob propagation observed from
    INSIDE the child, lockdep armed per-host with a report written on clean
    shutdown, and the merged report cross-checking clean against the SA011
    static graph (`analyze.py --lockdep-check` semantics)."""
    monkeypatch.setenv("SPFFT_TPU_SERVE_QUEUE_CAP", "19")
    lockdir = tmp_path / "lockdep"
    lockdir.mkdir()
    workers = hostmesh.spawn_workers(
        1, devices_per_host=1, workdir=str(tmp_path / "w"),
        lockdep_dir=str(lockdir),
    )
    try:
        w = workers[0]
        assert w.alive()
        assert w.ready["port"] > 0
        # the parent's ambient knob reached the child environment
        assert "SPFFT_TPU_SERVE_QUEUE_CAP" in w.ready["env_knobs"]
        assert "SPFFT_TPU_LOCKDEP" in w.ready["env_knobs"]
        client = RpcClient(w.address, timeout_s=10.0)
        try:
            assert client.call({"op": "ping"})["ok"] == 1
            stats = client.call({"op": "stats"})["stats"]
            # the propagated knob governed the child's service config
            assert stats["queue_capacity"] == 19
        finally:
            client.close()
    finally:
        hostmesh.stop_workers(workers)
    assert not workers[0].alive()
    # clean shutdown ran the exit hooks: the per-host lockdep report exists,
    # validates, and merge_reports over it (the N-host shape) stays sound
    report_path = lockdir / "host0.json"
    assert report_path.exists(), workers[0].log_tail()
    from spfft_tpu.analysis import lockdep

    doc = json.loads(report_path.read_text())
    assert lockdep.validate_report(doc) == []
    merged = lockdep.merge_reports([doc, doc])
    assert lockdep.validate_report(merged) == []
    # duplicate-report merge doubles counts but invents no edges/locks
    assert merged["counts"]["locks"] == doc["counts"]["locks"]
    assert merged["counts"]["edges"] == doc["counts"]["edges"]
    assert merged["cycles"] == doc["cycles"]


def test_spawn_mesh_boot_two_process_topology(tmp_path):
    """The CI boot proof: 2 worker processes join ONE jax.distributed
    multi-controller run, each with 2 virtual CPU devices — every rank must
    observe process_count=2 and the 4-device global mesh."""
    workers = hostmesh.spawn_workers(
        2, devices_per_host=2, mesh=True, workdir=str(tmp_path),
    )
    try:
        for w in workers:
            topo = w.ready["topology"]
            assert topo is not None, w.log_tail()
            assert topo["process_count"] == 2
            assert topo["process_index"] == w.host_id
            assert topo["global_devices"] == 4
            assert topo["local_devices"] == 2
    finally:
        hostmesh.stop_workers(workers)
