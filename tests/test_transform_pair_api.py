"""Transform-level device-pair API + accessor coverage.

The engine-level pair paths are covered elsewhere; this pins the Transform
wrappers: backward_pair retains the space buffer in the engine-native layout,
forward_pair reuses it, and the layout contract matches space_domain_layout.
"""
import numpy as np
import pytest

from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType
from spfft_tpu.errors import InvalidParameterError
from utils import assert_close, random_sparse_triplets


@pytest.mark.parametrize("engine,layout", [("xla", "zyx"), ("mxu", "yxz")])
def test_pair_roundtrip_and_layout(engine, layout):
    rng = np.random.default_rng(12)
    dx, dy, dz = 6, 7, 8
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip, engine=engine
    )
    assert t.space_domain_layout == layout
    n = len(trip)
    vre = rng.standard_normal(n)
    vim = rng.standard_normal(n)

    sre, sim = t.backward_pair(t._exec.put(vre), t._exec.put(vim))
    expected_shape = (dz, dy, dx) if layout == "zyx" else (dy, dx, dz)
    assert sre.shape == expected_shape and sim.shape == expected_shape

    fre, fim = t.forward_pair(ScalingType.FULL)
    assert_close(np.asarray(fre) + 1j * np.asarray(fim), vre + 1j * vim)

    # host-facing view of the same retained buffer is always (Z, Y, X)
    assert t.space_domain_data().shape == (dz, dy, dx)


def test_forward_pair_without_backward_raises():
    rng = np.random.default_rng(13)
    trip = random_sparse_triplets(rng, 4, 4, 4, 0.7)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 4, 4, 4, indices=trip)
    with pytest.raises(InvalidParameterError):
        t.forward_pair(ScalingType.NONE)


def test_space_domain_data_locations():
    rng = np.random.default_rng(15)
    dx, dy, dz = 6, 5, 8
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip, engine="mxu"
    )
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t.backward(v)
    host = t.space_domain_data(ProcessingUnit.HOST)
    assert host.shape == (dz, dy, dx)
    dev = t.space_domain_data(ProcessingUnit.GPU)  # device-resident, native layout
    dre, dim_ = dev
    assert dre.shape == (dy, dx, dz)  # MXU engine native (Y, X, Z)
    np.testing.assert_allclose(
        np.asarray(dre).transpose(2, 0, 1) + 1j * np.asarray(dim_).transpose(2, 0, 1),
        host,
        atol=1e-9,
    )


def test_combined_pu_rejected_as_data_location():
    rng = np.random.default_rng(16)
    trip = random_sparse_triplets(rng, 4, 4, 4, 0.7)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 4, 4, 4, indices=trip)
    t.backward(rng.standard_normal(len(trip)) + 0j)
    with pytest.raises(InvalidParameterError):
        t.space_domain_data(ProcessingUnit.HOST | ProcessingUnit.GPU)


def test_accessors():
    rng = np.random.default_rng(14)
    trip = random_sparse_triplets(rng, 5, 6, 7, 0.5)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 5, 6, 7, indices=trip)
    assert t.processing_unit == ProcessingUnit.HOST
    assert t.device_id == 0
    assert t.num_threads >= 1
