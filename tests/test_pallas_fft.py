"""The fused Pallas complex-matmul kernel vs the einsum reference path.

Off-TPU the kernel runs in interpret mode (the reference's GPU kernels are
likewise build-only in CI, reference: .github/workflows/ci.yml:89-130); on real
TPU hardware the same test exercises the compiled Mosaic kernel.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu.ops import fft as offt
from spfft_tpu.ops import pallas_fft


@pytest.mark.parametrize("m,k,n", [(8, 128, 128), (64, 256, 128), (40, 128, 256)])
def test_fused_matches_einsum(m, k, n):
    rng = np.random.default_rng(7)
    xr = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    xi = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    wr = jnp.asarray(rng.standard_normal((k, n)).astype(np.float32))
    wi = jnp.asarray(rng.standard_normal((k, n)).astype(np.float32))

    assert pallas_fft.supports(m, k, n, np.float32)
    yr, yi = pallas_fft.complex_matmul_fused(xr, xi, wr, wi)
    rr, ri = offt.complex_matmul(xr, xi, wr, wi, "mk,kn->mn")

    np.testing.assert_allclose(np.asarray(yr), np.asarray(rr), atol=1e-3)
    np.testing.assert_allclose(np.asarray(yi), np.asarray(ri), atol=1e-3)


def test_supports_rejects_bad_shapes():
    assert not pallas_fft.supports(7, 128, 128, np.float32)  # m % 8
    assert not pallas_fft.supports(8, 100, 128, np.float32)  # k % 128
    assert not pallas_fft.supports(8, 128, 100, np.float32)  # n % 128
    assert not pallas_fft.supports(8, 128, 128, np.float64)  # dtype
    assert not pallas_fft.supports(8, 128, 128 * 1024 * 8, np.float32)  # VMEM
