"""Runtime lockdep validator (``spfft_tpu.analysis.lockdep``).

Covers the acceptance surface of the concurrency soundness layer's runtime
half:

* wrapper recording: acquisition edges (at the attempt), RLock re-entry
  exempt, per-thread held stacks, Condition/Event waits entered with
  another lock held land in ``blocking``,
* the ``spfft_tpu.analysis.lockdep/1`` report schema + validator + cycles,
* install/uninstall restore the real ``threading`` factories; foreign
  (non-package) creations pass through unwrapped,
* cross-check semantics against the SA011 static graph: matched edges are
  explained, an edge the static model lacks is a ``stale-static`` finding,
  statically untracked locks are ``dynamic`` (explained, not findings),
* the armed end-to-end path: ``SPFFT_TPU_LOCKDEP=1`` installs at package
  import, ``SPFFT_TPU_LOCKDEP_REPORT`` dumps the report at process exit,
  and the dump cross-checks green against the real tree's static graph.

The unit tests force ``_in_package`` open so locks created HERE record;
everything is uninstalled + reset in ``finally`` — the patch is process-
global state exactly like the fault plane.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "programs"))

from analyze import load_analysis  # noqa: E402

analysis = load_analysis()
lockdep = analysis.lockdep
locks_mod = analysis.locks


@pytest.fixture
def armed(monkeypatch):
    """Install the wrappers with the package predicate forced open, and
    guarantee uninstall + reset afterwards."""
    monkeypatch.setattr(lockdep, "_in_package", lambda rel: True)
    lockdep.install()
    lockdep.reset()
    try:
        yield lockdep
    finally:
        lockdep.uninstall()
        lockdep.reset()


def test_edges_cycles_and_schema(armed):
    A = threading.Lock()
    B = threading.Lock()
    with A:
        with B:
            pass
    with B:
        with A:
            pass
    doc = armed.report()
    assert not armed.validate_report(doc), armed.validate_report(doc)
    assert doc["schema"] == "spfft_tpu.analysis.lockdep/1"
    ids = {l["id"] for l in doc["locks"]}
    assert len(ids) == 2 and all("test_lockdep" in i for i in ids)
    pairs = {(e["from"], e["to"]) for e in doc["edges"]}
    assert len(pairs) == 2  # both orders observed
    assert len(doc["cycles"]) == 1 and len(doc["cycles"][0]) == 2
    json.dumps(doc)  # JSON-plain


def test_rlock_reentry_is_not_an_edge(armed):
    R = threading.RLock()
    with R:
        with R:
            pass
    doc = armed.report()
    assert doc["edges"] == [] and doc["cycles"] == []
    assert [l["kind"] for l in doc["locks"]] == ["rlock"]


def test_same_site_instances_record_a_self_edge(armed):
    """Two per-instance locks created at ONE site (the `self._lock`
    pattern) nested inside each other are an unordered two-instance
    hazard — identity exempts only same-instance re-entry, so the nesting
    records a site-level self-edge instead of vanishing."""
    def make():
        return threading.Lock()

    a = make()
    b = make()  # same creation site as `a`
    assert a.lock_id == b.lock_id
    with a:
        with b:
            pass
    doc = armed.report()
    assert [(e["from"], e["to"]) for e in doc["edges"]] == [
        (a.lock_id, a.lock_id)
    ]
    # and the cross-check calls the hazard out, statically known or not
    chk = lockdep.crosscheck(doc, {"locks": {}, "edges": []})
    assert [f["kind"] for f in chk["findings"]] == ["same-site-nesting"]
    assert "ABBA" in chk["findings"][0]["message"]


def test_edge_recorded_at_attempt_even_when_acquire_fails(armed):
    A = threading.Lock()
    B = threading.Lock()
    B.acquire()  # so the attempt below fails (and B joins the held stack)
    with A:
        # a failed non-blocking acquire still records the ordering attempt
        # (a real deadlock must leave its edge in the report)
        assert not B.acquire(False)
    B.release()
    doc = armed.report()
    pairs = {(e["from"], e["to"]) for e in doc["edges"]}
    assert (A.lock_id, B.lock_id) in pairs  # the failed attempt's edge


def test_condition_wait_with_other_lock_held_is_blocking(armed):
    A = threading.Lock()
    cv = threading.Condition()
    with A:
        with cv:
            cv.wait(0.01)
    doc = armed.report()
    assert doc["blocking"], doc
    row = doc["blocking"][0]
    assert row["lock"] == cv.lock_id and A.lock_id in row["held"]
    # the same wait with ONLY the condition held records nothing
    armed.reset()
    with cv:
        cv.wait(0.01)
    assert armed.report()["blocking"] == []


def test_event_wait_with_lock_held_is_blocking(armed):
    A = threading.Lock()
    ev = threading.Event()
    ev.set()
    with A:
        ev.wait(0.01)
    doc = armed.report()
    assert any(r["lock"] == ev.lock_id for r in doc["blocking"])


def test_cross_thread_handoff_observed(armed):
    """Edges come from per-thread held stacks: two threads acquiring in
    opposite orders produce the cycle no single thread shows."""
    A = threading.Lock()
    B = threading.Lock()
    gate = threading.Barrier(2, timeout=10)

    def ab():
        gate.wait()
        with A:
            with B:
                pass

    def ba():
        gate.wait()
        with B:
            with A:
                pass

    t1 = threading.Thread(target=ab, daemon=True)
    t2 = threading.Thread(target=ba, daemon=True)
    t1.start()
    t2.start()
    t1.join(10)
    t2.join(10)
    doc = armed.report()
    assert len(doc["cycles"]) == 1


def test_uninstall_restores_factories():
    real = threading.Lock
    lockdep.install()
    try:
        assert threading.Lock is not real
    finally:
        lockdep.uninstall()
    assert threading.Lock is real
    # foreign creations during the armed window pass through unwrapped
    lockdep.install()
    try:
        lockdep.reset()
        lock = threading.Lock()  # tests/ is not the package: passthrough
        assert not hasattr(lock, "lock_id")
        assert lockdep.report()["locks"] == []
    finally:
        lockdep.uninstall()
        lockdep.reset()


def test_crosscheck_stale_static_and_dynamic(armed):
    A = threading.Lock()
    B = threading.Lock()
    with A:
        with B:
            pass
    doc = armed.report()
    a, b = A.lock_id, B.lock_id
    site = lambda lid: next(  # noqa: E731
        (l["file"], l["line"]) for l in doc["locks"] if l["id"] == lid
    )
    known = {
        "locks": {
            "m.py::A": {"kind": "lock", "file": site(a)[0], "line": site(a)[1]},
            "m.py::B": {"kind": "lock", "file": site(b)[0], "line": site(b)[1]},
        },
        "edges": [["m.py::A", "m.py::B"]],
    }
    chk = lockdep.crosscheck(doc, known)
    assert chk["findings"] == [] and len(chk["explained"]["static"]) == 1
    # the same runtime graph against a static model MISSING the edge: stale
    stale = dict(known, edges=[])
    chk = lockdep.crosscheck(doc, stale)
    assert [f["kind"] for f in chk["findings"]] == ["stale-static"]
    assert "static model is stale" in chk["findings"][0]["message"]
    # unknown locks are dynamic: explained, not findings
    chk = lockdep.crosscheck(doc, {"locks": {}, "edges": []})
    assert chk["findings"] == [] and len(chk["explained"]["dynamic"]) == 1


def test_static_graph_export_shape():
    static = locks_mod.static_graph(analysis.Tree(root=ROOT))
    assert static["locks"] and static["edges"]
    # the known module-level locks resolve with real definition sites
    reg = static["locks"].get("spfft_tpu/obs/registry.py::_lock")
    assert reg and reg["file"] == "spfft_tpu/obs/registry.py" and reg["line"] > 0
    assert all(len(e) == 2 for e in static["edges"])


def test_env_armed_import_and_exit_dump(tmp_path):
    """SPFFT_TPU_LOCKDEP=1 installs at package import; the report knob
    dumps at process exit; the dump validates and cross-checks green
    against the real static graph."""
    report = tmp_path / "lockdep.json"
    code = (
        "import threading, spfft_tpu\n"
        "from spfft_tpu.analysis import lockdep\n"
        "assert lockdep.installed()\n"
        "from spfft_tpu import obs\n"
        "obs.counter('transforms_total', direction='backward', engine='x').inc()\n"
        "snap = obs.snapshot()\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SPFFT_TPU_LOCKDEP="1",
        SPFFT_TPU_LOCKDEP_REPORT=str(report),
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(report.read_text())
    assert not lockdep.validate_report(doc)
    assert any(
        l["id"] == "spfft_tpu/obs/registry.py::179" or
        l["file"] == "spfft_tpu/obs/registry.py"
        for l in doc["locks"]
    ), doc["locks"]
    r = subprocess.run(
        [
            sys.executable, str(ROOT / "programs" / "analyze.py"),
            "--lockdep-check", str(report),
        ],
        capture_output=True, text=True, cwd=ROOT, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lockdep cross-check" in r.stdout


def test_unarmed_import_does_not_install():
    r = subprocess.run(
        [
            sys.executable, "-c",
            "import spfft_tpu\n"
            "from spfft_tpu.analysis import lockdep\n"
            "import threading\n"
            "assert not lockdep.installed()\n"
            "assert not hasattr(threading.Lock(), 'lock_id')\n",
        ],
        capture_output=True, text=True, cwd=ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu", SPFFT_TPU_LOCKDEP=""),
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
