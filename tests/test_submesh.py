"""Transforms over an "fft" sub-axis of a larger model mesh.

A caller embedding the FFT in a bigger SPMD program carves an ``"fft"`` axis
out of its model mesh; transforms shard over that axis and are replicated over
the remaining axes. Results must match the dedicated 1-D mesh exactly.
"""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from spfft_tpu import DistributedTransform, ProcessingUnit, ScalingType, TransformType
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close, oracle_backward_c2c, random_sparse_triplets, split_values


def make_2d_mesh(fft=2, rep=2):
    devs = np.asarray(jax.devices()[: fft * rep]).reshape(fft, rep)
    return Mesh(devs, ("fft", "rep"))


from spfft_tpu import ExchangeType


@pytest.mark.parametrize("engine", ["xla", "mxu"])
@pytest.mark.parametrize(
    "seed,weights,exchange",
    [
        (31, None, ExchangeType.BUFFERED),
        # exact-counts ppermute chain: rotations must stay on the fft axis,
        # replicated over the rest (imbalanced weights exercise the raggedness)
        (33, [3, 1], ExchangeType.COMPACT_BUFFERED),
    ],
)
def test_fft_subaxis_of_model_mesh(engine, seed, weights, exchange):
    rng = np.random.default_rng(seed)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 2, dy, weights=weights)
    vps = split_values(per_shard, trip, values)

    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=make_2d_mesh(),
        exchange_type=exchange,
        engine=engine,
    )
    expected = oracle_backward_c2c(trip, values, *dims)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_mesh_without_fft_axis_rejected():
    devs = np.asarray(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("data",))
    rng = np.random.default_rng(32)
    trip = random_sparse_triplets(rng, 4, 4, 4, 0.7)
    with pytest.raises(InvalidParameterError):
        DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, 4, 4, 4,
            distribute_triplets(trip, 2, 4), mesh=mesh,
        )
