"""The pluggable static-analysis engine (``spfft_tpu/analysis``).

Covers the acceptance surface of the analysis framework:

* every checker (SA001-SA019) trips on an in-memory positive fixture and
  stays silent on its clean negative twin,
* framework semantics: ``# noqa`` suppression, ``--only`` selection by code
  and by name, loud missing-anchor findings on rooted trees,
* the ``spfft_tpu.analysis/1`` report schema and its validator,
* the baseline round trip through the real CLI: write -> green -> doctored
  finding exits 3 -> fixed finding leaves a stale entry that also exits 3,
* the real tree runs green (zero non-baselined findings) through both
  ``programs/analyze.py`` and the ``programs/lint.py`` shim,
* the import-discipline contract: the standalone load pulls neither
  ``spfft_tpu`` nor ``jax``.

Fixtures are in-memory ``{relpath: source}`` trees (``Tree(files=...)``);
anchored checkers get minimal anchor files so the contract under test is
the checker's rule, not its anchor plumbing.
"""
from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "programs"))

from analyze import load_analysis  # noqa: E402

analysis = load_analysis()

# Fixture knob names are assembled at runtime: SA003 scans THIS file's
# source lines for SPFFT_TPU_* strings near environ/getenv reads, and the
# made-up fixture knobs must not register as unregistered-knob findings.
PFX = "SPFFT_TPU" + "_"


def run_checker(files: dict, code: str):
    return analysis.run(analysis.Tree(files=files), only=[code])


def codes(findings):
    return [f.code for f in findings]


# =============================================================================
# checkers 1-2: import hygiene
# =============================================================================


def test_sa001_duplicate_import():
    pos = {"spfft_tpu/m.py": "import os\nimport os\nos.getcwd()\n"}
    neg = {"spfft_tpu/m.py": "import os\nos.getcwd()\n"}
    assert codes(run_checker(pos, "SA001")) == ["SA001"]
    assert not run_checker(neg, "SA001")


def test_sa002_unused_import():
    pos = {"spfft_tpu/m.py": "import os\n\nX = 1\n"}
    neg = {"spfft_tpu/m.py": "import os\n\nX = os.getcwd()\n"}
    noqa = {"spfft_tpu/m.py": "import os  # noqa: F401\n\nX = 1\n"}
    assert codes(run_checker(pos, "SA002")) == ["SA002"]
    assert not run_checker(neg, "SA002")
    assert not run_checker(noqa, "SA002")


# =============================================================================
# checkers 3-9: both-ways vocabulary contracts (minimal anchors)
# =============================================================================

KNOBS_FIXTURE = (
    'def register(name, kind, default, doc=None, **kw):\n'
    '    return name\n\n'
    'register("SPFFT_TPU_GOOD", "int", 1, "a knob")\n'
)


def test_sa003_env_knob_registry():
    pos = {
        "spfft_tpu/knobs.py": KNOBS_FIXTURE,
        "spfft_tpu/m.py": '# reads SPFFT_TPU_GOOD and SPFFT_TPU_ROGUE\n',
    }
    neg = {
        "spfft_tpu/knobs.py": KNOBS_FIXTURE,
        "spfft_tpu/m.py": '# reads SPFFT_TPU_GOOD\n',
    }
    found = run_checker(pos, "SA003")
    assert codes(found) == ["SA003"] and "SPFFT_TPU_ROGUE" in found[0].message
    assert not run_checker(neg, "SA003")


STAGES_FIXTURE = 'STAGES = ("z transform",)\n'


def test_sa004_stage_scope():
    def engine(label):
        return (
            "import jax\n\n"
            "def go(x):\n"
            f'    with jax.named_scope("{label}"):\n'
            "        return x\n"
        )

    pos = {
        "spfft_tpu/obs/stages.py": STAGES_FIXTURE,
        # keep the canonical stage present as a string so the coverage
        # direction stays green; the rogue label is the defect under test
        "spfft_tpu/execution.py": engine("bogus stage") + 'S = "z transform"\n',
    }
    neg = {
        "spfft_tpu/obs/stages.py": STAGES_FIXTURE,
        "spfft_tpu/execution.py": engine("z transform"),
    }
    found = run_checker(pos, "SA004")
    assert codes(found) == ["SA004"] and "bogus stage" in found[0].message
    assert not run_checker(neg, "SA004")


def test_sa005_fault_site():
    plane = 'SITES = ("a.site",)\n'
    pos = {
        "spfft_tpu/faults/plane.py": plane,
        "spfft_tpu/m.py": 'faults.site("a.site")\nfaults.site("rogue")\n',
    }
    neg = {
        "spfft_tpu/faults/plane.py": plane,
        "spfft_tpu/m.py": 'faults.site("a.site")\n',
    }
    unthreaded = {
        "spfft_tpu/faults/plane.py": plane,
        "spfft_tpu/m.py": "X = 1\n",
    }
    found = run_checker(pos, "SA005")
    assert codes(found) == ["SA005"] and "rogue" in found[0].message
    assert not run_checker(neg, "SA005")
    # the other direction: a registered site threaded through no code path
    found = run_checker(unthreaded, "SA005")
    assert codes(found) == ["SA005"] and "a.site" in found[0].message


def test_sa006_trace_event():
    tr = 'EVENTS = ("ev",)\n'
    pos = {
        "spfft_tpu/obs/trace.py": tr,
        "spfft_tpu/m.py": 'trace.event("ev")\ntrace.event("rogue")\n',
    }
    neg = {
        "spfft_tpu/obs/trace.py": tr,
        "spfft_tpu/m.py": 'trace.event("ev")\n',
    }
    found = run_checker(pos, "SA006")
    assert codes(found) == ["SA006"] and "rogue" in found[0].message
    assert not run_checker(neg, "SA006")


def test_sa007_verify_check():
    pos = {
        "spfft_tpu/verify/checks.py": (
            'CHECKS = ("c1", "c2")\n'
            "def f():\n    pass\n\n"
            'CHECK_FNS = {"c1": f}\n'
        ),
    }
    neg = {
        "spfft_tpu/verify/checks.py": (
            'CHECKS = ("c1",)\n'
            "def f():\n    pass\n\n"
            'CHECK_FNS = {"c1": f}\n'
        ),
    }
    found = run_checker(pos, "SA007")
    assert codes(found) == ["SA007"] and "c2" in found[0].message
    assert not run_checker(neg, "SA007")


def test_sa008_perf_stage():
    base = {
        "spfft_tpu/obs/stages.py": STAGES_FIXTURE,
        "spfft_tpu/execution.py": 'S = "z transform"\n',
    }
    pos = dict(base)
    pos["spfft_tpu/obs/perf.py"] = 'MODELED_STAGES = ("z transform", "ghost")\n'
    neg = dict(base)
    neg["spfft_tpu/obs/perf.py"] = 'MODELED_STAGES = ("z transform",)\n'
    found = run_checker(pos, "SA008")
    assert codes(found) == ["SA008"] and "ghost" in found[0].message
    assert not run_checker(neg, "SA008")


def test_sa009_ir_node():
    base = {
        "spfft_tpu/obs/stages.py": STAGES_FIXTURE,
        "spfft_tpu/obs/perf.py": 'MODELED_STAGES = ("z transform",)\n',
    }
    pos = dict(base)
    pos["spfft_tpu/ir/graph.py"] = 'NODES = ("z transform", "ghost")\n'
    neg = dict(base)
    neg["spfft_tpu/ir/graph.py"] = 'NODES = ("z transform",)\n'
    found = run_checker(pos, "SA009")
    assert found and all(c == "SA009" for c in codes(found))
    assert any("ghost" in f.message for f in found)
    assert not run_checker(neg, "SA009")


# =============================================================================
# checker 10: typed-error discipline
# =============================================================================

ERRORS_FIXTURE = (
    "class GenericError(Exception):\n    pass\n\n"
    "class MyError(GenericError):\n    pass\n"
)


def test_sa010_raise_discipline():
    pos = {
        "spfft_tpu/errors.py": ERRORS_FIXTURE,
        "spfft_tpu/m.py": 'def f():\n    raise ValueError("untyped")\n',
    }
    neg = {
        "spfft_tpu/errors.py": ERRORS_FIXTURE,
        "spfft_tpu/m.py": (
            "from .errors import MyError\n\n"
            "def f():\n"
            '    raise MyError("typed")\n'
        ),
    }
    found = run_checker(pos, "SA010")
    assert codes(found) == ["SA010"] and "ValueError" in found[0].message
    assert not run_checker(neg, "SA010")


def test_sa010_broad_except():
    swallow = {
        "spfft_tpu/errors.py": ERRORS_FIXTURE,
        "spfft_tpu/m.py": (
            "def f():\n"
            "    try:\n        pass\n"
            "    except Exception:\n        pass\n"
        ),
    }
    counted = {
        "spfft_tpu/errors.py": ERRORS_FIXTURE,
        "spfft_tpu/m.py": (
            "from .errors import MyError\n\n"
            "class S:\n"
            "    def f(self):\n"
            "        try:\n            pass\n"
            "        except Exception as e:\n"
            "            self.counter.inc()\n"
            '            raise MyError(str(e))\n'
        ),
    }
    cleanup = {
        "spfft_tpu/errors.py": ERRORS_FIXTURE,
        "spfft_tpu/m.py": (
            "def f():\n"
            "    try:\n        pass\n"
            "    except BaseException:\n"
            "        release()\n"
            "        raise\n"
        ),
    }
    assert codes(run_checker(swallow, "SA010")) == ["SA010"]
    assert not run_checker(counted, "SA010")
    assert not run_checker(cleanup, "SA010")  # bare re-raise: nothing swallowed


# =============================================================================
# checker 11: lock-order analysis
# =============================================================================

LOCKS_HEADER = "import threading\nimport time\n\nA = threading.Lock()\nB = threading.Lock()\n"


def test_sa011_cycle_and_blocking():
    cycle = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def one():\n    with A:\n        with B:\n            pass\n\n"
            "def two():\n    with B:\n        with A:\n            pass\n"
        ),
    }
    sleepy = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def slow():\n    with A:\n        time.sleep(1)\n"
        ),
    }
    self_deadlock = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def again():\n    with A:\n        with A:\n            pass\n"
        ),
    }
    ordered = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def one():\n    with A:\n        with B:\n            pass\n\n"
            "def two():\n    with A:\n        with B:\n            pass\n\n"
            "def fine():\n    time.sleep(0)\n    with A:\n        pass\n"
        ),
    }
    cond_wait = {
        "spfft_tpu/m.py": (
            "import threading\n\ncv = threading.Condition()\n\n"
            "def waiter():\n    with cv:\n        cv.wait()\n"
        ),
    }
    found = run_checker(cycle, "SA011")
    assert codes(found) == ["SA011"] and "cycle" in found[0].message
    found = run_checker(sleepy, "SA011")
    assert codes(found) == ["SA011"] and "time.sleep" in found[0].message
    found = run_checker(self_deadlock, "SA011")
    assert codes(found) == ["SA011"] and "re-acquired" in found[0].message
    assert not run_checker(ordered, "SA011")
    # Condition.wait on the HELD condition releases it: exempt
    assert not run_checker(cond_wait, "SA011")


def test_sa011_transitive_effects():
    files = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def inner():\n    with B:\n        pass\n\n"
            "def outer():\n    with A:\n        inner()\n\n"
            "def reverse():\n    with B:\n        with A:\n            pass\n"
        ),
    }
    found = run_checker(files, "SA011")
    assert codes(found) == ["SA011"] and "cycle" in found[0].message


def test_sa011_multi_context_with_orders():
    """``with A, B:`` acquires in item order — the single statement must
    contribute the A->B edge (the ride-along bugfix), so an opposite
    nested acquisition elsewhere is a cycle."""
    files = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def one():\n    with A, B:\n        pass\n\n"
            "def two():\n    with B:\n        with A:\n            pass\n"
        ),
    }
    found = run_checker(files, "SA011")
    assert codes(found) == ["SA011"] and "cycle" in found[0].message
    # same order twice: no cycle
    ordered = {
        "spfft_tpu/m.py": LOCKS_HEADER + (
            "def one():\n    with A, B:\n        pass\n\n"
            "def two():\n    with A:\n        with B:\n            pass\n"
        ),
    }
    assert not run_checker(ordered, "SA011")


def test_sa011_exitstack_enter_context():
    """``stack.enter_context(lock)`` chains acquire in call order and hold
    for the rest of the body — ordered like nested ``with`` blocks."""
    cycle = {
        "spfft_tpu/m.py": "import contextlib\n" + LOCKS_HEADER + (
            "def one():\n"
            "    with contextlib.ExitStack() as es:\n"
            "        es.enter_context(A)\n"
            "        es.enter_context(B)\n\n"
            "def two():\n    with B:\n        with A:\n            pass\n"
        ),
    }
    sleepy = {
        "spfft_tpu/m.py": "import contextlib\n" + LOCKS_HEADER + (
            "def slow():\n"
            "    with contextlib.ExitStack() as es:\n"
            "        es.enter_context(A)\n"
            "        time.sleep(1)\n"
        ),
    }
    ordered = {
        "spfft_tpu/m.py": "import contextlib\n" + LOCKS_HEADER + (
            "def one():\n"
            "    with contextlib.ExitStack() as es:\n"
            "        es.enter_context(A)\n"
            "        es.enter_context(B)\n\n"
            "def two():\n    with A:\n        with B:\n            pass\n"
        ),
    }
    found = run_checker(cycle, "SA011")
    assert codes(found) == ["SA011"] and "cycle" in found[0].message
    found = run_checker(sleepy, "SA011")
    assert codes(found) == ["SA011"] and "time.sleep" in found[0].message
    assert not run_checker(ordered, "SA011")


def test_sa011_condition_wait_releases_only_its_own_lock():
    """``Condition.wait`` on the held condition is exempt ONLY when the
    condition is the whole held set — any other lock stays held across the
    unbounded wait (the ride-along fix)."""
    two_locks = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "L = threading.Lock()\ncv = threading.Condition()\n\n"
            "def waiter():\n"
            "    with L:\n"
            "        with cv:\n"
            "            cv.wait()\n"
        ),
    }
    found = run_checker(two_locks, "SA011")
    assert codes(found) == ["SA011"]
    assert "releases only its own lock" in found[0].message
    alone = {
        "spfft_tpu/m.py": (
            "import threading\n\ncv = threading.Condition()\n\n"
            "def waiter():\n    with cv:\n        cv.wait()\n"
        ),
    }
    assert not run_checker(alone, "SA011")


# =============================================================================
# checker 12: donation safety
# =============================================================================

SPEC_FIXTURE = (
    "class E:\n"
    "    def _ir_spec(self):\n"
    '        return {"kind": "local", "donate": (0, 1)}\n'
)

COMPILE_OK = (
    "import jax\n\n"
    "def build_fused(graph, spec):\n"
    '    donate = spec.get("donate")\n'
    "    return jax.jit(graph, donate_argnums=tuple(donate))\n\n"
    "class EngineIr:\n"
    "    def describe(self):\n"
    '        donated = list(self.spec["donate"])\n'
    '        return {"donation": donated}\n'
)


def _lower_fixture(second_node_inputs, outputs):
    return (
        "from .graph import StageGraph\n\n"
        "def _lower_local_x(e):\n"
        "    def backward():\n"
        '        g = StageGraph("backward")\n'
        '        g.add_input("values_re")\n'
        '        g.add_input("values_im")\n'
        '        g.add("compression", e._st_d, ("values_re", "values_im"), ("sticks",))\n'
        f'        g.add("z transform", e._st_z, {second_node_inputs}, ("z",))\n'
        f"        g.set_outputs({outputs})\n"
        "        return g\n"
        "    return backward()\n"
    )


def test_sa012_use_after_donate():
    pos = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _lower_fixture('("sticks", "values_re")', '["z"]'),
        "spfft_tpu/ir/compile.py": COMPILE_OK,
    }
    escapes = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _lower_fixture('("sticks",)', '["z", "values_im"]'),
        "spfft_tpu/ir/compile.py": COMPILE_OK,
    }
    neg = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _lower_fixture('("sticks",)', '["z"]'),
        "spfft_tpu/ir/compile.py": COMPILE_OK,
    }
    found = run_checker(pos, "SA012")
    assert codes(found) == ["SA012"]
    assert "referenced after its consuming node" in found[0].message
    found = run_checker(escapes, "SA012")
    assert codes(found) == ["SA012"] and "escapes" in found[0].message
    assert not run_checker(neg, "SA012")


def test_sa012_card_donation_map_mismatch():
    bad_compile = COMPILE_OK.replace(
        'donated = list(self.spec["donate"])',
        'donated = list(self.spec["wrongkey"])',
    )
    files = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _lower_fixture('("sticks",)', '["z"]'),
        "spfft_tpu/ir/compile.py": bad_compile,
    }
    found = run_checker(files, "SA012")
    assert codes(found) == ["SA012"] and "wrongkey" in found[0].message


def test_sa012_donation_never_applied():
    no_donate = (
        "import jax\n\n"
        "def build_fused(graph, spec):\n"
        "    return jax.jit(graph)\n"
    )
    files = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _lower_fixture('("sticks",)', '["z"]'),
        "spfft_tpu/ir/compile.py": no_donate,
    }
    found = run_checker(files, "SA012")
    assert codes(found) == ["SA012"] and "never applied" in found[0].message


# =============================================================================
# checker 13: jit purity
# =============================================================================


def test_sa013_stage_body_impurity():
    pos = {
        "spfft_tpu/m.py": (
            "import time\n\n"
            "def _st_bad(x):\n"
            "    t = time.perf_counter()\n"
            "    return x + t\n"
        ),
    }
    neg = {
        "spfft_tpu/m.py": "def _st_good(x):\n    return x + 1\n",
    }
    found = run_checker(pos, "SA013")
    assert codes(found) == ["SA013"] and "time.perf_counter" in found[0].message
    assert not run_checker(neg, "SA013")


def test_sa013_jitted_function_impurity():
    pos = {
        "spfft_tpu/m.py": (
            "import jax\nimport os\n\n"
            "def body(x):\n"
            f'    flag = os.environ.get("{PFX}X")\n'
            "    return x\n\n"
            "f = jax.jit(body)\n"
        ),
    }
    neg = {
        "spfft_tpu/m.py": (
            "import jax\nimport os\n\n"
            "def host():\n"
            f'    flag = os.environ.get("{PFX}X")\n'
            "    return flag\n\n"
            "def body(x):\n    return x\n\n"
            "f = jax.jit(body)\n"
        ),
    }
    found = run_checker(pos, "SA013")
    assert codes(found) == ["SA013"] and "os.environ" in found[0].message
    assert not run_checker(neg, "SA013")  # host-side reads are fine


def test_sa013_metric_and_trace_in_trace():
    files = {
        "spfft_tpu/m.py": (
            "def _st_bad(x):\n"
            '    obs.counter("n").inc()\n'
            '    trace.event("go")\n'
            "    return x\n"
        ),
    }
    found = run_checker(files, "SA013")
    msgs = " ".join(f.message for f in found)
    assert ".inc()" in msgs and "trace.event" in msgs


# =============================================================================
# checker 14: knob-registry read path
# =============================================================================


def test_sa014_raw_knob_reads():
    pos = {
        "spfft_tpu/m.py": (
            "import os\n\n"
            f'a = os.environ.get("{PFX}FOO")\n'
            f'b = os.environ["{PFX}BAR"]\n'
            f'c = os.getenv("{PFX}BAZ")\n'
        ),
    }
    neg = {
        "spfft_tpu/m.py": (
            "import os\n\n"
            'flags = os.environ.get("XLA_FLAGS", "")\n'
        ),
    }
    dynamic = {
        "spfft_tpu/m.py": (
            "import os\n\n"
            "def snap(keys):\n"
            "    return {k: os.environ.get(k) for k in keys}\n"
        ),
    }
    noqa = {
        "spfft_tpu/m.py": (
            "import os\n\n"
            "def snap(keys):\n"
            "    return {k: os.environ.get(k) for k in keys}  # noqa: SA014\n"
        ),
    }
    assert codes(run_checker(pos, "SA014")) == ["SA014"] * 3
    assert not run_checker(neg, "SA014")  # foreign vocabulary: allowed
    assert codes(run_checker(dynamic, "SA014")) == ["SA014"]  # conservative
    assert not run_checker(noqa, "SA014")  # documented deliberate raw path
    # knobs.py itself is the allowed read path
    in_registry = {
        "spfft_tpu/knobs.py": f'import os\nv = os.environ.get("{PFX}X")\n'
    }
    assert not run_checker(in_registry, "SA014")


# =============================================================================
# checker 15: batched/mesh donation safety
# =============================================================================

BATCH_COMPILE_OK = (
    "import jax\n\n"
    "def build_fused(graph, spec):\n"
    '    donate = spec.get("donate")\n'
    "    return jax.jit(graph, donate_argnums=tuple(donate))\n\n"
    "def build_batched(graph, spec):\n"
    '    donate = spec.get("donate")\n'
    "    return jax.jit(graph, donate_argnums=tuple(donate))\n\n"
    "class EngineIr:\n"
    "    def describe(self):\n"
    '        donated = list(self.spec["donate"])\n'
    '        return {"donation": donated}\n'
)


def _batch_lower_fixture(second_inputs, outputs, builder="_lower_slab_x"):
    return (
        "from .graph import StageGraph\n\n"
        f"def {builder}(e):\n"
        "    def backward():\n"
        '        g = StageGraph("backward")\n'
        '        g.add_input("values_re")\n'
        '        g.add_input("values_im")\n'
        '        g.batch_inputs = ("values_re", "values_im")\n'
        '        g.add("compression", e._st_d, ("values_re", "values_im"), ("sticks",))\n'
        f'        g.add("z transform", e._st_z, {second_inputs}, ("z",))\n'
        f"        g.set_outputs({outputs})\n"
        "        return g\n"
        "    return backward()\n"
    )


def test_sa015_batched_use_after_consume():
    pos = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _batch_lower_fixture(
            '("sticks", "values_im")', '["z"]'
        ),
        "spfft_tpu/ir/compile.py": BATCH_COMPILE_OK,
    }
    escapes = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _batch_lower_fixture(
            '("sticks",)', '["z", "values_re"]'
        ),
        "spfft_tpu/ir/compile.py": BATCH_COMPILE_OK,
    }
    neg = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _batch_lower_fixture('("sticks",)', '["z"]'),
        "spfft_tpu/ir/compile.py": BATCH_COMPILE_OK,
    }
    found = run_checker(pos, "SA015")
    assert codes(found) == ["SA015"]
    assert "referenced after its consuming node" in found[0].message
    found = run_checker(escapes, "SA015")
    assert codes(found) == ["SA015"] and "escapes" in found[0].message
    assert not run_checker(neg, "SA015")


def test_sa015_mesh_builders_held_to_the_same_rule():
    """A NON-local builder (slab/pencil) with a doubly-consumed batch edge
    is a finding too — SA012 only guards _lower_local_*."""
    files = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _batch_lower_fixture(
            '("sticks", "values_im")', '["z"]', builder="_lower_pencil_x"
        ),
        "spfft_tpu/ir/compile.py": BATCH_COMPILE_OK,
    }
    assert codes(run_checker(files, "SA015")) == ["SA015"]
    assert not run_checker(files, "SA012")  # local-only checker stays silent


def test_sa015_donated_position_must_be_batch_edge():
    lower = (
        "from .graph import StageGraph\n\n"
        "def _lower_local_x(e):\n"
        "    def backward():\n"
        '        g = StageGraph("backward")\n'
        '        g.add_input("values_re")\n'
        '        g.add_input("values_im")\n'
        '        g.batch_inputs = ("values_re",)\n'
        '        g.add("compression", e._st_d, ("values_re", "values_im"), ("sticks",))\n'
        '        g.set_outputs(["sticks"])\n'
        "        return g\n"
        "    return backward()\n"
    )
    files = {
        "spfft_tpu/e.py": SPEC_FIXTURE,  # donates positions (0, 1)
        "spfft_tpu/ir/lower.py": lower,
        "spfft_tpu/ir/compile.py": BATCH_COMPILE_OK,
    }
    found = run_checker(files, "SA015")
    assert codes(found) == ["SA015"]
    assert "not a declared batch_inputs edge" in found[0].message


def test_sa015_batched_jit_stopped_donating():
    no_batch_donate = BATCH_COMPILE_OK.replace(
        "def build_batched(graph, spec):\n"
        '    donate = spec.get("donate")\n'
        "    return jax.jit(graph, donate_argnums=tuple(donate))\n",
        "def build_batched(graph, spec):\n"
        "    return jax.jit(graph)\n",
    )
    files = {
        "spfft_tpu/e.py": SPEC_FIXTURE,
        "spfft_tpu/ir/lower.py": _batch_lower_fixture('("sticks",)', '["z"]'),
        "spfft_tpu/ir/compile.py": no_batch_donate,
    }
    found = run_checker(files, "SA015")
    assert codes(found) == ["SA015"]
    assert "silently stopped donating" in found[0].message


# =============================================================================
# checker 16: metrics-vocabulary discipline
# =============================================================================

METRICS_FIXTURE = (
    "METRICS = (\n"
    '    ("good_total", "counter", ("tenant",), "a counter"),\n'
    '    ("depth", "gauge", (), "a gauge"),\n'
    ")\n"
)


def test_sa016_rogue_and_dead_metrics():
    pos = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.counter("good_total", tenant=t).inc()\n'
            'obs.gauge("depth").set(1)\n'
            'obs.counter("rogue_total").inc()\n'
        ),
    }
    dead = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": 'obs.counter("good_total", tenant=t).inc()\n',
    }
    neg = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.counter("good_total", tenant=t).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    found = run_checker(pos, "SA016")
    assert codes(found) == ["SA016"] and "rogue_total" in found[0].message
    found = run_checker(dead, "SA016")
    assert codes(found) == ["SA016"] and "dead declaration" in found[0].message
    assert not run_checker(neg, "SA016")


def test_sa016_label_and_kind_mismatch():
    wrong_labels = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.counter("good_total", engine=e).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    wrong_kind = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.histogram("good_total", tenant=t).observe(1)\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    dynamic_name = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.counter(name, tenant=t).inc()\n'
            'obs.counter("good_total", tenant=t).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    found = run_checker(wrong_labels, "SA016")
    assert codes(found) == ["SA016"] and "label keys" in found[0].message
    found = run_checker(wrong_kind, "SA016")
    assert codes(found) == ["SA016"] and "declared a counter" in found[0].message
    found = run_checker(dynamic_name, "SA016")
    assert codes(found) == ["SA016"] and "literal metric name" in found[0].message


def test_sa016_starred_label_resolution():
    """``**{dict literal}`` and ``**name`` (dict-literal assigned in the
    module) resolve; an unresolvable ``**`` skips only the label check."""
    resolved = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'labels = {"tenant": "a"}\n'
            'obs.counter("good_total", **labels).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    mismatch = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            'obs.counter("good_total", **{"engine": "a"}).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    opaque = {
        "spfft_tpu/obs/metrics.py": METRICS_FIXTURE,
        "spfft_tpu/m.py": (
            "def f(kw):\n"
            '    obs.counter("good_total", **kw).inc()\n'
            'obs.gauge("depth").set(1)\n'
        ),
    }
    assert not run_checker(resolved, "SA016")
    found = run_checker(mismatch, "SA016")
    assert codes(found) == ["SA016"] and "label keys" in found[0].message
    assert not run_checker(opaque, "SA016")


# =============================================================================
# checker 17: thread-lifecycle discipline
# =============================================================================


def test_sa017_thread_daemon_or_joined():
    leaked = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "def go():\n"
            "    t = threading.Thread(target=work)\n"
            "    t.start()\n"
        ),
    }
    daemon = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "def go():\n"
            "    t = threading.Thread(target=work, daemon=True)\n"
            "    t.start()\n"
        ),
    }
    joined = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "def go():\n"
            "    t = threading.Thread(target=work)\n"
            "    t.start()\n"
            "    t.join(5.0)\n"
        ),
    }
    unbound = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "def go():\n"
            "    threading.Thread(target=work).start()\n"
        ),
    }
    found = run_checker(leaked, "SA017")
    assert codes(found) == ["SA017"] and "neither daemon" in found[0].message
    assert not run_checker(daemon, "SA017")
    assert not run_checker(joined, "SA017")
    found = run_checker(unbound, "SA017")
    assert codes(found) == ["SA017"] and "unbound" in found[0].message
    # a nested construction with the daemon assignment at outer level is
    # clean — binding collection completes before the daemon pass
    late_daemon = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "class S:\n"
            "    def go(self, restart):\n"
            "        if restart:\n"
            "            self._t = threading.Thread(target=self.work)\n"
            "        self._t.daemon = True\n"
            "        self._t.start()\n"
        ),
    }
    assert not run_checker(late_daemon, "SA017")


def test_sa017_bounded_parks():
    waits = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "cv = threading.Condition()\n\n"
            "def park():\n"
            "    with cv:\n"
            "        cv.wait()\n"
        ),
    }
    bounded = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "cv = threading.Condition()\n\n"
            "def park(timeout):\n"
            "    with cv:\n"
            "        cv.wait(timeout)\n"
        ),
    }
    join_forever = {
        "spfft_tpu/m.py": (
            "import threading\n\n"
            "def stop(worker):\n"
            "    worker.join()\n"
        ),
    }
    str_join_ok = {
        "spfft_tpu/m.py": 'def fmt(parts):\n    return ", ".join(parts)\n',
    }
    found = run_checker(waits, "SA017")
    assert codes(found) == ["SA017"] and "unbounded park" in found[0].message
    assert not run_checker(bounded, "SA017")
    found = run_checker(join_forever, "SA017")
    assert codes(found) == ["SA017"] and ".join()" in found[0].message
    assert not run_checker(str_join_ok, "SA017")
    # Queue.get: block=True / get(True) / bare get() all park unbounded;
    # get(False) and a real timeout are fine
    def queue_fixture(call):
        return {
            "spfft_tpu/m.py": (
                "import queue\n\nq = queue.Queue()\n\n"
                f"def pump():\n    return q.{call}\n"
            ),
        }

    for bad in ("get()", "get(True)", "get(block=True)"):
        found = run_checker(queue_fixture(bad), "SA017")
        assert codes(found) == ["SA017"], bad
        assert "unbounded park" in found[0].message
    for ok in ("get(False)", "get(timeout=1.0)", "get(True, 2.0)", "get_nowait()"):
        assert not run_checker(queue_fixture(ok), "SA017"), ok


# =============================================================================
# checker 18: fault-site chaos coverage
# =============================================================================

PLANE_FIXTURE = 'SITES = ("a.site", "b.site")\n'

# fixture arming tokens are assembled at runtime: SA018 scans THIS file's
# string constants for the site=kind grammar, and the made-up fixture sites
# must not register as unknown-site findings (the PFX idiom of SA003)
RAISE = "rai" + "se"
CORRUPT = "cor" + "rupt"


def test_sa018_every_site_has_a_targeted_test():
    covered = {
        "spfft_tpu/faults/plane.py": PLANE_FIXTURE,
        "tests/test_chaos.py": (
            "def test_a():\n"
            f'    with faults.inject("a.site={RAISE}"):\n'
            "        pass\n\n"
            "def test_b():\n"
            '    faults.arm({"b.site": {"kind": "nan"}})\n'
        ),
    }
    uncovered = {
        "spfft_tpu/faults/plane.py": PLANE_FIXTURE,
        "tests/test_chaos.py": (
            "def test_a():\n"
            f'    with faults.inject("a.site={RAISE}"):\n'
            "        pass\n"
        ),
    }
    assert not run_checker(covered, "SA018")
    found = run_checker(uncovered, "SA018")
    assert codes(found) == ["SA018"]
    assert "b.site" in found[0].message
    assert "no targeted chaos test" in found[0].message


def test_sa018_unknown_site_in_test_spec():
    files = {
        "spfft_tpu/faults/plane.py": PLANE_FIXTURE,
        "tests/test_chaos.py": (
            "def test_a():\n"
            f'    with faults.inject("a.site={RAISE},ghost.site={CORRUPT}:0.5"):\n'
            "        pass\n\n"
            "def test_b():\n"
            '    faults.arm({"b.site": {"kind": "nan"}})\n'
        ),
    }
    found = run_checker(files, "SA018")
    assert codes(found) == ["SA018"] and "ghost.site" in found[0].message
    # the dynamic sweep (f-strings) is not coverage and not a false positive
    sweep_only = {
        "spfft_tpu/faults/plane.py": PLANE_FIXTURE,
        "tests/test_chaos.py": (
            "def test_sweep(site_name):\n"
            '    with faults.inject(f"{site_name}=raise"):\n'
            "        pass\n"
        ),
    }
    found = run_checker(sweep_only, "SA018")
    assert len(found) == 2  # both sites uncovered: the sweep does not count


# =============================================================================
# checker 19: blocking while traced
# =============================================================================


def test_sa019_sleep_and_lock_inside_span():
    sleepy = {
        "spfft_tpu/m.py": (
            "import time\n\n"
            "def f():\n"
            '    with timing.scoped("dispatch"):\n'
            "        time.sleep(0.1)\n"
        ),
    }
    locked = {
        "spfft_tpu/m.py": (
            "import threading\n\nL = threading.Lock()\n\n"
            "def f():\n"
            '    with trace.span("phase", label="x"):\n'
            "        with L:\n"
            "            pass\n"
        ),
    }
    acquired = {
        "spfft_tpu/m.py": (
            "import threading\n\nL = threading.Lock()\n\n"
            "def f():\n"
            '    with trace.operation("execute"):\n'
            "        L.acquire()\n"
        ),
    }
    clean = {
        "spfft_tpu/m.py": (
            "import time\nimport threading\n\nL = threading.Lock()\n\n"
            "def f():\n"
            "    time.sleep(0.1)\n"
            "    with L:\n"
            "        pass\n"
            '    with timing.scoped("dispatch"):\n'
            "        g()\n"
        ),
    }
    found = run_checker(sleepy, "SA019")
    assert codes(found) == ["SA019"] and "time.sleep" in found[0].message
    assert "timing.scoped 'dispatch'" in found[0].message
    found = run_checker(locked, "SA019")
    assert codes(found) == ["SA019"] and "acquired inside" in found[0].message
    found = run_checker(acquired, "SA019")
    assert codes(found) == ["SA019"] and ".acquire()d inside" in found[0].message
    assert not run_checker(clean, "SA019")


def test_sa019_nested_defs_execute_outside_the_span():
    files = {
        "spfft_tpu/m.py": (
            "import time\n\n"
            "def f():\n"
            '    with timing.scoped("dispatch"):\n'
            "        def cb():\n"
            "            time.sleep(1)\n"
            "        return cb\n"
        ),
    }
    assert not run_checker(files, "SA019")
    # ...including a lambda nested under a compound statement in the body
    deep = {
        "spfft_tpu/m.py": (
            "import time\n\n"
            "def f(cond, cbs):\n"
            '    with timing.scoped("dispatch"):\n'
            "        if cond:\n"
            "            cbs.append(lambda: time.sleep(1))\n"
        ),
    }
    assert not run_checker(deep, "SA019")


# =============================================================================
# framework semantics
# =============================================================================


def test_noqa_suppression_codes():
    bare = {"spfft_tpu/m.py": "import os\nimport os  # noqa\nos.getcwd()\n"}
    right = {"spfft_tpu/m.py": "import os\nimport os  # noqa: SA001\nos.getcwd()\n"}
    assert not run_checker(bare, "SA001")
    assert not run_checker(right, "SA001")


def test_parallel_run_matches_serial():
    """The --jobs thread pool must produce byte-identical findings to the
    serial reference — over the real tree, every checker."""
    serial = analysis.run(analysis.Tree(root=ROOT), jobs=1)
    parallel = analysis.run(analysis.Tree(root=ROOT), jobs=4)
    assert [f.key() for f in serial] == [f.key() for f in parallel]
    assert [f.line for f in serial] == [f.line for f in parallel]


def test_list_noqa_and_orphan_detection():
    files = {
        "spfft_tpu/m.py": (
            "def f():\n"
            '    raise ValueError("x")  # noqa: SA010\n'  # live suppression
            "X = 1  # noqa: SA011\n"               # orphaned: nothing fires
            "Y = 2  # noqa: F401\n"                # foreign code: not listed
            '"""prose mentioning # noqa: SA012 is not a suppression"""\n'
        ),
    }
    tree = analysis.Tree(files=files)
    rows = analysis.list_noqa(tree)
    assert [(r["line"], r["codes"]) for r in rows] == [
        (2, ["SA010"]), (3, ["SA011"]),
    ]
    raw = analysis.run(tree, suppress=False)
    fired = {(f.code, f.file, f.line) for f in raw}
    assert ("SA010", "spfft_tpu/m.py", 2) in fired
    assert ("SA011", "spfft_tpu/m.py", 3) not in fired  # the orphan
    # the suppressed run honors the live noqa
    assert not analysis.run(tree, only=["SA010"])


def test_list_noqa_cli_trips_on_orphan(tmp_path):
    pkg = tmp_path / "spfft_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text("X = 1  # noqa: SA010\n")
    r = _analyze("--root", str(tmp_path), "--list-noqa")
    assert r.returncode == 3, r.stdout + r.stderr
    assert "ORPHANED" in r.stdout
    (pkg / "m.py").write_text('def f():\n    raise ValueError("x")  # noqa: SA010\n')
    r = _analyze("--root", str(tmp_path), "--list-noqa")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all live" in r.stdout


def test_real_tree_noqa_audit_is_clean():
    r = _analyze("--list-noqa", "-q")
    assert r.returncode == 0, r.stdout + r.stderr


def test_only_selection_and_unknown():
    files = {"spfft_tpu/m.py": "import os\n\nX = 1\n"}  # SA002 positive
    by_code = analysis.run(analysis.Tree(files=files), only=["SA002"])
    by_name = analysis.run(analysis.Tree(files=files), only=["unused-import"])
    assert codes(by_code) == codes(by_name) == ["SA002"]
    with pytest.raises(analysis.AnalysisError):
        analysis.run(analysis.Tree(files=files), only=["SA999"])


def test_missing_anchor_is_loud_on_rooted_tree(tmp_path):
    (tmp_path / "spfft_tpu").mkdir()
    (tmp_path / "spfft_tpu" / "m.py").write_text("X = 1\n")
    tree = analysis.Tree(root=tmp_path)
    found = analysis.run(tree, only=["SA005"])
    assert codes(found) == ["SA005"]
    assert "anchor file is missing" in found[0].message
    # the same absent anchor on a PARTIAL tree skips silently
    assert not run_checker({"spfft_tpu/m.py": "X = 1\n"}, "SA005")


def test_checker_registry_is_complete():
    assert [c.code for c in analysis.CHECKERS.values()] == [
        f"SA0{i:02d}" for i in range(1, 20)
    ]
    for entry in analysis.CHECKERS.values():
        assert entry.doc and entry.severity == "error"


def test_report_schema_and_validator():
    files = {"spfft_tpu/m.py": "import os\n\nX = 1\n"}
    tree = analysis.Tree(files=files)
    findings = analysis.run(tree)
    split = analysis.apply_baseline(findings, set())
    doc = analysis.report_doc(
        findings, split, root="mem", baseline_path="analysis_baseline.json"
    )
    assert doc["schema"] == "spfft_tpu.analysis/1"
    assert not analysis.validate_report(doc)
    assert doc["counts"]["new"] == len(findings) > 0
    json.dumps(doc)  # JSON-plain
    broken = dict(doc)
    del broken["counts"]
    broken["findings"] = [{"code": "SA002"}]
    missing = analysis.validate_report(broken)
    assert "counts.total" in missing and "findings[0].file" in missing


def test_apply_baseline_split_and_staleness():
    files = {"spfft_tpu/m.py": "import os\n\nX = 1\n"}
    findings = analysis.run(analysis.Tree(files=files))
    accepted = {findings[0].key(), "SA010:spfft_tpu/gone.py:fixed finding"}
    split = analysis.apply_baseline(findings, accepted)
    assert not split["new"]
    assert [f.key() for f in split["baselined"]] == [findings[0].key()]
    assert split["stale"] == ["SA010:spfft_tpu/gone.py:fixed finding"]


# =============================================================================
# the CLI: baseline round trip, real tree, shim
# =============================================================================


def _analyze(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, str(ROOT / "programs" / "analyze.py"), *args],
        capture_output=True,
        text=True,
        cwd=cwd,
    )


def test_baseline_round_trip(tmp_path):
    pkg = tmp_path / "spfft_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text('def f():\n    raise ValueError("x")\n')

    # 1. write the baseline accepting the current findings
    r = _analyze("--root", str(tmp_path), "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    baseline = json.loads((tmp_path / "analysis_baseline.json").read_text())
    assert baseline["schema"] == "spfft_tpu.analysis.baseline/1"
    assert any(e.startswith("SA010:spfft_tpu/bad.py") for e in baseline["entries"])

    # 2. re-run: green (every finding baselined)
    r = _analyze("--root", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr

    # 3. doctor a NEW finding: exit 3, reported as new in the JSON
    (pkg / "bad.py").write_text(
        'def f():\n    raise ValueError("x")\n\n'
        'def g():\n    raise TypeError("y")\n'
    )
    r = _analyze("--root", str(tmp_path), "--json", str(tmp_path / "r.json"))
    assert r.returncode == 3, r.stdout + r.stderr
    doc = json.loads((tmp_path / "r.json").read_text())
    new = [f for f in doc["findings"] if not f["baselined"]]
    assert len(new) == 1 and "TypeError" in new[0]["message"]

    # 4. fix the original finding instead: its baseline entry is now STALE
    #    and the gate trips again — a fixed finding must leave the baseline
    (pkg / "bad.py").write_text("def f():\n    return 1\n")
    r = _analyze("--root", str(tmp_path))
    assert r.returncode == 3, r.stdout + r.stderr
    assert "stale baseline entry" in r.stdout

    # 5. regenerating the baseline restores green
    r = _analyze("--root", str(tmp_path), "--write-baseline")
    assert r.returncode == 0
    r = _analyze("--root", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr


def test_subset_write_baseline_preserves_other_checkers(tmp_path):
    """--only X --write-baseline (the lint shim's shape) must replace only
    checker X's entries — another checker's accepted findings survive."""
    pkg = tmp_path / "spfft_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text('def f():\n    raise ValueError("x")\n')
    foreign = "SA011:spfft_tpu/locks.py:a lock-order finding accepted earlier"
    (tmp_path / "analysis_baseline.json").write_text(
        json.dumps(
            {
                "schema": "spfft_tpu.analysis.baseline/1",
                "generated_by": "test",
                "entries": [foreign],
            }
        )
    )
    r = _analyze("--root", str(tmp_path), "--only", "SA010", "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    baseline = json.loads((tmp_path / "analysis_baseline.json").read_text())
    assert foreign in baseline["entries"], baseline["entries"]
    assert any(e.startswith("SA010:spfft_tpu/bad.py") for e in baseline["entries"])


def test_real_tree_is_green():
    r = _analyze("--json", "-")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    doc = json.loads(r.stdout)
    assert not analysis.validate_report(doc)
    assert len(doc["checkers"]) == 19
    assert doc["counts"]["new"] == 0 and doc["counts"]["stale_baseline"] == 0


def test_lint_shim_runs_ported_checkers():
    r = subprocess.run(
        [sys.executable, str(ROOT / "programs" / "lint.py")],
        capture_output=True,
        text=True,
        cwd=ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "9 checker(s)" in r.stdout


def test_standalone_load_pulls_no_jax():
    code = (
        "import sys\n"
        f"sys.path.insert(0, {str(ROOT / 'programs')!r})\n"
        "from analyze import load_analysis\n"
        "a = load_analysis()\n"
        "assert len(a.CHECKERS) == 19\n"
        "assert 'jax' not in sys.modules, 'analysis load pulled jax'\n"
        "assert 'spfft_tpu' not in sys.modules, 'analysis load pulled spfft_tpu'\n"
        "print('standalone ok')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, cwd=ROOT
    )
    assert r.returncode == 0, r.stdout + r.stderr
