"""2-D pencil decomposition engine (parallel/pencil2.py).

The beyond-reference scaling path: space split into z-slabs x y-slabs over a
("fft", "fft2") mesh, lifting the slab engine's P <= dim_z cap to
P1 * P2 <= dim_z * dim_y. Oracle scenarios mirror the 1-D distributed tests.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import (
    assert_close,
    oracle_backward_c2c,
    oracle_forward_c2c,
    random_sparse_triplets,
    split_values,
)


def build(p1, p2, dims, per_shard, exchange=ExchangeType.DEFAULT, dtype=None,
          engine="auto"):
    dx, dy, dz = dims
    return DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh2(p1, p2),
        exchange_type=exchange,
        dtype=dtype,
        engine=engine,
    )


@pytest.mark.parametrize("p1,p2", [(2, 4), (4, 2), (1, 8), (8, 1), (2, 2)])
def test_pencil2_c2c_roundtrip(p1, p2):
    rng = np.random.default_rng(41)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, p1 * p2, dy)
    vps = split_values(per_shard, trip, values)
    t = build(p1, p2, dims, per_shard)
    assert t._engine == "pencil2"
    expected = oracle_backward_c2c(trip, values, dx, dy, dz)
    assert_close(t.backward(vps), expected)
    # run twice (zeroing check, reference: tests/test_util/test_transform.hpp:129-131)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_pencil2_beyond_slab_limit(engine):
    """P = 8 > dim_z = 2: the 1-D slab engine would idle 6 shards in space;
    the pencil split keeps every shard's slab non-trivial (z x y blocks)."""
    rng = np.random.default_rng(43)
    dims = (8, 8, 2)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.7)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 8, dy)
    vps = split_values(per_shard, trip, values)
    t = build(4, 2, dims, [p.copy() for p in per_shard], engine=engine)
    assert t._engine == ("pencil2" if engine == "xla" else "pencil2-mxu")
    assert_close(t.backward(vps), oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_pencil2_mxu_matches_xla():
    """The matmul-DFT pencil engine reproduces the jnp.fft one at the 1e-6 bar
    on an imbalanced C2C plan (wire variants: test_pencil2_wire_formats)."""
    rng = np.random.default_rng(51)
    dims = (12, 11, 13)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, z_fill=0.7)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 8, dy, weights=[5, 1, 1, 1, 1, 1, 1, 1])
    vps = split_values(per_shard, trip, values)
    outs = {}
    for engine_name in ("xla", "mxu"):
        t = build(2, 4, dims, [p.copy() for p in per_shard], engine=engine_name)
        outs[engine_name] = (
            t.backward([v.copy() for v in vps]),
            t.forward(scaling=ScalingType.FULL),
        )
    b_x, f_x = outs["xla"]
    b_m, f_m = outs["mxu"]
    scale = np.abs(b_x).max()
    np.testing.assert_allclose(b_m, b_x, rtol=0, atol=1e-6 * scale)
    for r in range(8):
        np.testing.assert_allclose(f_m[r], f_x[r], rtol=0, atol=1e-6)


def test_pencil2_mxu_r2c():
    rng = np.random.default_rng(54)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, 4, dy)
    vps = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh2(2, 2), engine="mxu",
    )
    out = t.backward([v.copy() for v in vps])
    assert_close(out, r)
    back = t.forward(scaling=ScalingType.FULL)
    for r_, vals in enumerate(vps):
        assert_close(back[r_], vals)


def test_pencil2_explicit_space_forward():
    rng = np.random.default_rng(44)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    per_shard = distribute_triplets(trip, 8, dy)
    t = build(2, 4, dims, per_shard)
    space = rng.standard_normal((dz, dy, dx)) + 1j * rng.standard_normal((dz, dy, dx))
    got = t.forward(space)
    for r, trip_r in enumerate(per_shard):
        assert_close(got[r], oracle_forward_c2c(trip_r, space))


def test_pencil2_imbalanced_sticks():
    """All sticks on one shard; empty stick sets elsewhere."""
    rng = np.random.default_rng(45)
    dims = (6, 6, 6)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = [trip] + [np.zeros((0, 3), dtype=np.int64)] * 3
    t = build(2, 2, dims, per_shard)
    out = t.backward([values] + [np.zeros(0)] * 3)
    assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back[0], values)


@pytest.mark.parametrize("engine", ["xla", "mxu"])
@pytest.mark.parametrize(
    "exchange,dtype,atol_scale",
    [
        (ExchangeType.BUFFERED_FLOAT, np.float64, 1e-4),
        (ExchangeType.BUFFERED_BF16, np.float32, 3e-2),
        (ExchangeType.COMPACT_BUFFERED, np.float64, 1e-9),
        (ExchangeType.COMPACT_BUFFERED_FLOAT, np.float64, 1e-4),
        (ExchangeType.COMPACT_BUFFERED_BF16, np.float32, 3e-2),
        (ExchangeType.UNBUFFERED, np.float64, 1e-9),
    ],
)
def test_pencil2_wire_formats(engine, exchange, dtype, atol_scale):
    rng = np.random.default_rng(46)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = build(2, 2, dims, [p.copy() for p in per_shard], exchange=exchange,
              dtype=dtype, engine=engine)
    out = t.backward(vps)
    expected = oracle_backward_c2c(trip, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=atol_scale * scale)
    assert t.exchange_wire_bytes() > 0


def test_pencil2_f32():
    rng = np.random.default_rng(47)
    dims = (16, 8, 12)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 8, dy)
    vps = split_values(per_shard, trip, values)
    t = build(2, 4, dims, per_shard, dtype=np.float32)
    assert_close(t.backward(vps), oracle_backward_c2c(trip, values, dx, dy, dz),
                 dtype=np.float32)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals, dtype=np.float32)


def test_pencil2_per_shard_layout_and_local_blocks():
    """Per-shard accessors describe the 2-D z×y split and
    space_domain_data_local returns the matching block of the global result."""
    rng = np.random.default_rng(50)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 8, dy)
    vps = split_values(per_shard, trip, values)
    t = build(2, 4, dims, per_shard)
    out = t.backward(vps)
    # z lengths tile dim_z within each y-slab row; y lengths tile dim_y
    assert sum(t.local_z_length(r) for r in range(4)) == dz  # one y-row (a=0)
    assert t.local_y_length(0) + t.local_y_length(4) == dy
    for r in range(8):
        lz, zo = t.local_z_length(r), t.local_z_offset(r)
        ly, yo = t.local_y_length(r), t.local_y_offset(r)
        assert t.local_slice_size(r) == lz * ly * dx
        blk = t.space_domain_data_local(r)
        assert blk.shape == (lz, ly, dx)
        np.testing.assert_allclose(
            blk, out[zo : zo + lz, yo : yo + ly], rtol=0, atol=1e-12
        )


@pytest.mark.parametrize("p1,p2", [(2, 4), (4, 2)])
def test_pencil2_r2c(p1, p2):
    """R2C over the 2-D pencil split: both hermitian completions are
    shard-local (the (0,0) stick pre-exchange-A; the x=0 plane post-exchange-A
    on the x-group-0 column, which holds the full y extent)."""
    rng = np.random.default_rng(48)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, p1 * p2, dy)
    vps = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh2(p1, p2),
    )
    out = t.backward([v.copy() for v in vps])
    assert out.dtype == np.float64
    assert_close(out, r)
    back = t.forward(scaling=ScalingType.FULL)
    for r_, vals in enumerate(vps):
        assert_close(back[r_], vals)
    # per-shard block accessor in r2c form
    blk = t.space_domain_data_local(1)
    lz, zo = t.local_z_length(1), t.local_z_offset(1)
    ly, yo = t.local_y_length(1), t.local_y_offset(1)
    np.testing.assert_allclose(blk, r[zo : zo + lz, yo : yo + ly], atol=1e-10)


def test_pencil2_r2c_partial_spectrum():
    """Non-redundant spherical R2C set (redundant x=0 half omitted by the
    caller; restored by the symmetry kernels)."""
    rng = np.random.default_rng(52)
    dims = (10, 8, 6)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    trip = random_sparse_triplets(rng, dx, dy, dz, 1.0, hermitian=True)
    # drop the redundant (x=0, y > dy/2) sticks the reference lets callers omit
    keep = ~((trip[:, 0] == 0) & (trip[:, 1] > dy // 2))
    trip = trip[keep]
    per_shard = distribute_triplets(trip, 4, dy)
    vps = [freq[t_[:, 2] % dz, t_[:, 1] % dy, t_[:, 0] % dx] for t_ in per_shard]
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh2(2, 2),
    )
    assert_close(t.backward(vps), r)


def test_pencil2_multi_transform_batch():
    """Pipelined batching works over pencil plans (engine-agnostic dispatch)."""
    rng = np.random.default_rng(55)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    per_shard = distribute_triplets(trip, 4, dy)
    ts = [build(2, 2, dims, [p.copy() for p in per_shard]) for _ in range(3)]
    all_vps = []
    for _ in ts:
        values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
        all_vps.append(split_values(per_shard, trip, values))
    outs = sp.multi_transform_backward(ts, all_vps)
    for vps, out in zip(all_vps, outs):
        flat = np.concatenate(vps)
        tt = np.concatenate(per_shard)
        lut = {tuple(t_): v for t_, v in zip(map(tuple, tt), flat)}
        vals = np.asarray([lut[tuple(t_)] for t_ in trip])
        assert_close(out, oracle_backward_c2c(trip, vals, dx, dy, dz))
    backs = sp.multi_transform_forward(ts, None, ScalingType.FULL)
    for vps, back in zip(all_vps, backs):
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_pencil2_exact_counts_roundtrip(engine):
    """COMPACT on the pencil mesh: full roundtrip on an imbalanced plan, and
    the exact-counts wire volume must undercut the padded discipline's (the
    Alltoallv-vs-Alltoall contrast of the reference,
    transpose_mpi_compact_buffered_host.cpp:183-200)."""
    rng = np.random.default_rng(53)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    # imbalanced: most sticks on shard 0 -> ragged counts across x-groups
    per_shard = [trip] + [np.zeros((0, 3), dtype=np.int64)] * 3
    vps = [values] + [np.zeros(0)] * 3

    compact = build(
        2, 2, dims, [p.copy() for p in per_shard],
        exchange=ExchangeType.COMPACT_BUFFERED, engine=engine,
    )
    out = compact.backward(vps)
    assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = compact.forward(scaling=ScalingType.FULL)
    assert_close(back[0], values)

    padded = build(
        2, 2, dims, [p.copy() for p in per_shard],
        exchange=ExchangeType.BUFFERED, engine=engine,
    )
    assert compact.exchange_wire_bytes() < padded.exchange_wire_bytes()


def test_pencil2_mesh_size_mismatch_rejected():
    rng = np.random.default_rng(49)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.4)
    per_shard = distribute_triplets(trip, 4, 8)
    with pytest.raises(Exception):
        build(2, 4, (8, 8, 8), per_shard)  # 4 shard lists over an 8-device mesh


@pytest.mark.parametrize("ttype", [TransformType.C2C, TransformType.R2C])
def test_pencil2_mxu_lane_alignment_rotation_path(ttype):
    """dz=128 engages the lane-alignment rotations in the pencil MXU engine
    (phase tables as shard-indexed constants): oracle + roundtrip must hold,
    R2C covering the keep_zero hermitian-stick handling."""
    from utils import contiguous_stick_triplets

    rng = np.random.default_rng(79)
    dx, dy, dz = 6, 8, 128
    r2c = ttype == TransformType.R2C
    trip = contiguous_stick_triplets(rng, dx, dy, dz, r2c=r2c)
    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        values = (np.fft.fftn(real) / (dx * dy * dz))[trip[:, 2], trip[:, 1], trip[:, 0]]
    else:
        values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.HOST, ttype, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh2(2, 2), engine="mxu",
    )
    assert t._exec._align_rep is not None, "rotations must engage at dz=128"
    out = t.backward(vps)
    if r2c:
        ref = DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz,
            [p.copy() for p in per_shard], mesh=sp.make_fft_mesh2(2, 2), engine="xla",
        )
        assert_close(out, ref.backward([v.copy() for v in vps]))
    else:
        assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_pencil2_mxu_compact_phase_rep(monkeypatch):
    """Forcing the compact ("delta") phase representation in the pencil MXU
    engine must reproduce the table form exactly — big plans embed only the
    (P, S) rotation matrix and generate each shard's tables in-trace
    (lanecopy.phase_rep_tables_at; the stacked tables overflowed the compile
    transport at 512^3-class plans)."""
    from utils import contiguous_stick_triplets

    from spfft_tpu.ops import lanecopy

    rng = np.random.default_rng(80)
    dx, dy, dz = 6, 8, 128
    trip = contiguous_stick_triplets(rng, dx, dy, dz, r2c=False)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    t_table = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh2(2, 2), engine="mxu",
    )
    assert t_table._exec._align_rep is not None
    assert t_table._exec._align_rep[0] == "table"
    out_table = t_table.backward(vps)

    monkeypatch.setenv(lanecopy.PHASE_TABLE_LIMIT_MB_ENV, "0")
    t_delta = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
        [p.copy() for p in per_shard], mesh=sp.make_fft_mesh2(2, 2), engine="mxu",
    )
    assert t_delta._exec._align_rep[0] == "delta"
    out_delta = t_delta.backward([v.copy() for v in vps])
    assert_close(out_delta, out_table)
    back = t_delta.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)
