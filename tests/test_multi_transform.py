"""Multi-transform batched execution tests.

Modeled on the reference's MPI multi-transform test — N=5 independent transforms,
backward then forward, each checked against its own single-transform result
(reference: tests/mpi_tests/test_multi_transform.cpp:1-91) — plus batches mixing
transform types, dims, scaling modes, and local+distributed plans.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    multi_transform_backward,
    multi_transform_forward,
)
from spfft_tpu.errors import InvalidParameterError


def _make_local(dim, ttype=TransformType.C2C, sparsity=0.8):
    triplets = sp.create_spherical_cutoff_triplets(
        dim, dim, dim, sparsity, hermitian_symmetry=(ttype == TransformType.R2C)
    )
    return Transform(
        ProcessingUnit.HOST, ttype, dim, dim, dim, indices=triplets
    )


def _rand_values(t, rng):
    n = t.num_local_elements
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


def test_five_transform_roundtrip():
    rng = np.random.default_rng(3)
    transforms = [_make_local(8) for _ in range(5)]
    values = [_rand_values(t, rng) for t in transforms]

    spaces = multi_transform_backward(transforms, values)
    results = multi_transform_forward(transforms, None, ScalingType.FULL)

    for t, v, s, r in zip(transforms, values, spaces, results):
        # Each batch entry must equal the single-transform result.
        solo = _make_local(8)
        np.testing.assert_allclose(solo.backward(v), s, atol=1e-10)
        np.testing.assert_allclose(r, v, atol=1e-10)


def test_mixed_dims_and_explicit_spaces():
    rng = np.random.default_rng(4)
    transforms = [_make_local(d) for d in (4, 8, 12)]
    values = [_rand_values(t, rng) for t in transforms]
    spaces = multi_transform_backward(transforms, values)
    results = multi_transform_forward(transforms, spaces, ScalingType.FULL)
    for v, r in zip(values, results):
        np.testing.assert_allclose(r, v, atol=1e-10)


def test_mixed_c2c_r2c():
    rng = np.random.default_rng(5)
    tc = _make_local(8, TransformType.C2C)
    tr = _make_local(8, TransformType.R2C)
    vc = _rand_values(tc, rng)
    # R2C frequency inputs must be hermitian-consistent: derive them from a real
    # space field via a forward transform.
    real_space = rng.standard_normal((8, 8, 8))
    vr = tr.forward(real_space, ScalingType.NONE)

    spaces = multi_transform_backward([tc, tr], [vc, vr])
    assert np.iscomplexobj(spaces[0])
    assert not np.iscomplexobj(spaces[1])
    results = multi_transform_forward([tc, tr], None, ScalingType.FULL)
    np.testing.assert_allclose(results[0], vc, atol=1e-10)
    np.testing.assert_allclose(results[1], vr, atol=1e-10)


def test_per_transform_scaling():
    rng = np.random.default_rng(6)
    transforms = [_make_local(8), _make_local(8)]
    values = [_rand_values(t, rng) for t in transforms]
    multi_transform_backward(transforms, values)
    scaled, unscaled = multi_transform_forward(
        transforms, None, [ScalingType.FULL, ScalingType.NONE]
    )
    np.testing.assert_allclose(scaled, values[0], atol=1e-10)
    np.testing.assert_allclose(unscaled, np.asarray(values[1]) * 8**3, atol=1e-8)


def test_distributed_in_batch():
    rng = np.random.default_rng(7)
    mesh = sp.make_fft_mesh(4)
    dim = 8
    dt = sp.DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dim,
        dim,
        dim,
        sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.9),
        mesh=mesh,
    )
    lt = _make_local(dim)
    dvals = [
        rng.standard_normal(dt.num_local_elements(r))
        + 1j * rng.standard_normal(dt.num_local_elements(r))
        for r in range(dt.num_shards)
    ]
    lvals = _rand_values(lt, rng)
    spaces = multi_transform_backward([dt, lt], [dvals, lvals])
    assert spaces[0].shape == (dim, dim, dim)
    dres, lres = multi_transform_forward([dt, lt], None, ScalingType.FULL)
    for r in range(dt.num_shards):
        np.testing.assert_allclose(dres[r], dvals[r], atol=1e-10)
    np.testing.assert_allclose(lres, lvals, atol=1e-10)


def test_duplicate_transform_rejected():
    t = _make_local(4)
    v = _rand_values(t, np.random.default_rng(8))
    with pytest.raises(InvalidParameterError):
        multi_transform_backward([t, t], [v, v])


def test_length_mismatch_rejected():
    t = _make_local(4)
    with pytest.raises(InvalidParameterError):
        multi_transform_backward([t], [])
    with pytest.raises(InvalidParameterError):
        multi_transform_forward([t], None, [ScalingType.FULL, ScalingType.NONE])


def test_split_phase_api_matches_one_shot():
    """The public dispatch_*/finalize_* halves (the serving layer's batch
    path) produce exactly what the one-shot functions produce — they ARE the
    one-shot functions' implementation, exposed for batch owners that
    interleave work between the phases."""
    from spfft_tpu import multi_transform as mt

    rng = np.random.default_rng(9)
    ts = [_make_local(4), _make_local(6)]
    vals = [_rand_values(t, rng) for t in ts]
    expect = multi_transform_backward(
        [t.clone() for t in ts], [v.copy() for v in vals]
    )
    pending = mt.dispatch_backward(ts, vals)
    spaces = mt.finalize_backward(ts, pending)
    for got, want in zip(spaces, expect):
        np.testing.assert_allclose(got, want, atol=1e-12)
    scalings = [ScalingType.FULL] * len(ts)
    fp = mt.dispatch_forward(ts, [None] * len(ts), scalings)
    freqs = mt.finalize_forward(ts, fp)
    for got, want in zip(freqs, vals):
        np.testing.assert_allclose(got, want, atol=1e-10)
