"""Multi-transform batched execution tests.

Modeled on the reference's MPI multi-transform test — N=5 independent transforms,
backward then forward, each checked against its own single-transform result
(reference: tests/mpi_tests/test_multi_transform.cpp:1-91) — plus batches mixing
transform types, dims, scaling modes, and local+distributed plans.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    multi_transform_backward,
    multi_transform_forward,
)
from spfft_tpu.errors import InvalidParameterError


def _make_local(dim, ttype=TransformType.C2C, sparsity=0.8):
    triplets = sp.create_spherical_cutoff_triplets(
        dim, dim, dim, sparsity, hermitian_symmetry=(ttype == TransformType.R2C)
    )
    return Transform(
        ProcessingUnit.HOST, ttype, dim, dim, dim, indices=triplets
    )


def _rand_values(t, rng):
    n = t.num_local_elements
    return rng.standard_normal(n) + 1j * rng.standard_normal(n)


def test_five_transform_roundtrip():
    rng = np.random.default_rng(3)
    transforms = [_make_local(8) for _ in range(5)]
    values = [_rand_values(t, rng) for t in transforms]

    spaces = multi_transform_backward(transforms, values)
    results = multi_transform_forward(transforms, None, ScalingType.FULL)

    for t, v, s, r in zip(transforms, values, spaces, results):
        # Each batch entry must equal the single-transform result.
        solo = _make_local(8)
        np.testing.assert_allclose(solo.backward(v), s, atol=1e-10)
        np.testing.assert_allclose(r, v, atol=1e-10)


def test_mixed_dims_and_explicit_spaces():
    rng = np.random.default_rng(4)
    transforms = [_make_local(d) for d in (4, 8, 12)]
    values = [_rand_values(t, rng) for t in transforms]
    spaces = multi_transform_backward(transforms, values)
    results = multi_transform_forward(transforms, spaces, ScalingType.FULL)
    for v, r in zip(values, results):
        np.testing.assert_allclose(r, v, atol=1e-10)


def test_mixed_c2c_r2c():
    rng = np.random.default_rng(5)
    tc = _make_local(8, TransformType.C2C)
    tr = _make_local(8, TransformType.R2C)
    vc = _rand_values(tc, rng)
    # R2C frequency inputs must be hermitian-consistent: derive them from a real
    # space field via a forward transform.
    real_space = rng.standard_normal((8, 8, 8))
    vr = tr.forward(real_space, ScalingType.NONE)

    spaces = multi_transform_backward([tc, tr], [vc, vr])
    assert np.iscomplexobj(spaces[0])
    assert not np.iscomplexobj(spaces[1])
    results = multi_transform_forward([tc, tr], None, ScalingType.FULL)
    np.testing.assert_allclose(results[0], vc, atol=1e-10)
    np.testing.assert_allclose(results[1], vr, atol=1e-10)


def test_per_transform_scaling():
    rng = np.random.default_rng(6)
    transforms = [_make_local(8), _make_local(8)]
    values = [_rand_values(t, rng) for t in transforms]
    multi_transform_backward(transforms, values)
    scaled, unscaled = multi_transform_forward(
        transforms, None, [ScalingType.FULL, ScalingType.NONE]
    )
    np.testing.assert_allclose(scaled, values[0], atol=1e-10)
    np.testing.assert_allclose(unscaled, np.asarray(values[1]) * 8**3, atol=1e-8)


def test_distributed_in_batch():
    rng = np.random.default_rng(7)
    mesh = sp.make_fft_mesh(4)
    dim = 8
    dt = sp.DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dim,
        dim,
        dim,
        sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.9),
        mesh=mesh,
    )
    lt = _make_local(dim)
    dvals = [
        rng.standard_normal(dt.num_local_elements(r))
        + 1j * rng.standard_normal(dt.num_local_elements(r))
        for r in range(dt.num_shards)
    ]
    lvals = _rand_values(lt, rng)
    spaces = multi_transform_backward([dt, lt], [dvals, lvals])
    assert spaces[0].shape == (dim, dim, dim)
    dres, lres = multi_transform_forward([dt, lt], None, ScalingType.FULL)
    for r in range(dt.num_shards):
        np.testing.assert_allclose(dres[r], dvals[r], atol=1e-10)
    np.testing.assert_allclose(lres, lvals, atol=1e-10)


def test_duplicate_transform_rejected():
    t = _make_local(4)
    v = _rand_values(t, np.random.default_rng(8))
    with pytest.raises(InvalidParameterError):
        multi_transform_backward([t, t], [v, v])


def test_length_mismatch_rejected():
    t = _make_local(4)
    with pytest.raises(InvalidParameterError):
        multi_transform_backward([t], [])
    with pytest.raises(InvalidParameterError):
        multi_transform_forward([t], None, [ScalingType.FULL, ScalingType.NONE])


FUZZ_SEED = int(__import__("os").environ.get("SPFFT_TPU_FUZZ_SEED", "0"))


def _fuzz_rng(case: int):
    """Seeded per-case generator, parity-fuzz style. The case offset is
    pinned by the test's own parametrization, so a failure replays with the
    SAME env value: ``SPFFT_TPU_FUZZ_SEED=<env> pytest <failing nodeid>``
    (the print shows the env value, not the derived stream seed — setting
    the env to the derived seed would select a different stream)."""
    seed = FUZZ_SEED + case
    print(
        f"interleaving fuzz: SPFFT_TPU_FUZZ_SEED={FUZZ_SEED} case={case} "
        f"(stream seed {seed})"
    )
    return np.random.default_rng(seed)


@pytest.mark.parametrize("case", range(4))
def test_fuzz_out_of_order_finalize(case):
    """Finalize order is free: pending split-phase results finalize in ANY
    permutation — submission order, reversed, shuffled — with identical
    results. This is the invariant the task-graph scheduler's
    completion-order finalize (spfft_tpu.sched) relies on: whichever
    transform finishes first may be fetched first."""
    from spfft_tpu import multi_transform as mt

    rng = _fuzz_rng(case)
    dims = [int(d) for d in rng.choice([4, 6, 8], size=4)]
    ts = [_make_local(d) for d in dims]
    vals = [_rand_values(t, rng) for t in ts]
    expect = [t.clone().backward(v) for t, v in zip(ts, vals)]
    pending = mt.dispatch_backward(ts, vals)
    order = rng.permutation(len(ts))
    got = {}
    for i in order:
        got[i] = ts[i]._finalize_backward(pending[i])
    for i, want in enumerate(expect):
        np.testing.assert_allclose(got[i], want, atol=1e-10)


@pytest.mark.parametrize("case", range(4))
def test_fuzz_finalize_before_dispatch_of_next_and_cross_batch(case):
    """Interleavings across batch boundaries: finalize of batch A's entries
    interleaves with dispatch of batch B (finalize-before-dispatch-of-next
    included — the degenerate window=1 schedule), in a random order drawn
    under SPFFT_TPU_FUZZ_SEED. Every result must equal its solo execution —
    dispatch and finalize of *distinct plan objects* are order-independent,
    which is exactly what lets the scheduler keep several batches in
    flight."""
    from spfft_tpu import multi_transform as mt

    rng = _fuzz_rng(10 + case)
    dims_a = [int(d) for d in rng.choice([4, 6, 8], size=3)]
    dims_b = [int(d) for d in rng.choice([4, 6, 8], size=3)]
    ts_a = [_make_local(d) for d in dims_a]
    ts_b = [_make_local(d) for d in dims_b]
    vals_a = [_rand_values(t, rng) for t in ts_a]
    vals_b = [_rand_values(t, rng) for t in ts_b]
    expect_a = [t.clone().backward(v) for t, v in zip(ts_a, vals_a)]
    expect_b = [t.clone().backward(v) for t, v in zip(ts_b, vals_b)]

    # schedule: all of A dispatched, then a fuzzed interleaving of
    # (finalize A_i) and (dispatch B_j), then B finalized in fuzzed order
    pend_a = mt.dispatch_backward(ts_a, vals_a)
    steps = [("fin_a", i) for i in range(len(ts_a))] + [
        ("disp_b", j) for j in range(len(ts_b))
    ]
    rng.shuffle(steps)
    got_a, pend_b = {}, {}
    for op, idx in steps:
        if op == "fin_a":
            got_a[idx] = ts_a[idx]._finalize_backward(pend_a[idx])
        else:
            pend_b[idx] = mt.dispatch_backward(
                [ts_b[idx]], [vals_b[idx]]
            )[0]
    got_b = {}
    for j in rng.permutation(len(ts_b)):
        got_b[j] = ts_b[j]._finalize_backward(pend_b[j])
    for i, want in enumerate(expect_a):
        np.testing.assert_allclose(got_a[i], want, atol=1e-10)
    for j, want in enumerate(expect_b):
        np.testing.assert_allclose(got_b[j], want, atol=1e-10)
    # forward halves interleave the same way (retained buffers are
    # per-object: the backward above retained each plan's space slab)
    fp_a = mt.dispatch_forward(
        ts_a, [None] * len(ts_a), [ScalingType.FULL] * len(ts_a)
    )
    fp_b = mt.dispatch_forward(
        ts_b, [None] * len(ts_b), [ScalingType.FULL] * len(ts_b)
    )
    both = [("a", i) for i in range(len(ts_a))] + [
        ("b", j) for j in range(len(ts_b))
    ]
    rng.shuffle(both)
    for which, idx in both:
        if which == "a":
            np.testing.assert_allclose(
                ts_a[idx]._finalize_forward(fp_a[idx]), vals_a[idx],
                atol=1e-10,
            )
        else:
            np.testing.assert_allclose(
                ts_b[idx]._finalize_forward(fp_b[idx]), vals_b[idx],
                atol=1e-10,
            )


def test_split_phase_api_matches_one_shot():
    """The public dispatch_*/finalize_* halves (the serving layer's batch
    path) produce exactly what the one-shot functions produce — they ARE the
    one-shot functions' implementation, exposed for batch owners that
    interleave work between the phases."""
    from spfft_tpu import multi_transform as mt

    rng = np.random.default_rng(9)
    ts = [_make_local(4), _make_local(6)]
    vals = [_rand_values(t, rng) for t in ts]
    expect = multi_transform_backward(
        [t.clone() for t in ts], [v.copy() for v in vals]
    )
    pending = mt.dispatch_backward(ts, vals)
    spaces = mt.finalize_backward(ts, pending)
    for got, want in zip(spaces, expect):
        np.testing.assert_allclose(got, want, atol=1e-12)
    scalings = [ScalingType.FULL] * len(ts)
    fp = mt.dispatch_forward(ts, [None] * len(ts), scalings)
    freqs = mt.finalize_forward(ts, fp)
    for got, want in zip(freqs, vals):
        np.testing.assert_allclose(got, want, atol=1e-10)
