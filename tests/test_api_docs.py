"""The committed API reference (docs/api) must match a fresh regeneration —
the generated-docs analogue of the reference keeping docs/source/*.rst in its
tree (reference: docs/source). A drifted page means an API change shipped
without `python programs/gen_api_docs.py`."""
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_api_reference_is_current(tmp_path):
    out = tmp_path / "api"
    subprocess.run(
        [sys.executable, str(ROOT / "programs" / "gen_api_docs.py"), str(out)],
        check=True,
        capture_output=True,
        text=True,
    )
    committed = ROOT / "docs" / "api"
    fresh = {p.name: p.read_text() for p in out.glob("*.md")}
    existing = {p.name: p.read_text() for p in committed.glob("*.md")}
    assert fresh.keys() == existing.keys(), (
        sorted(fresh.keys() ^ existing.keys()),
        "page set drifted — rerun programs/gen_api_docs.py",
    )
    stale = [name for name in fresh if fresh[name] != existing[name]]
    assert not stale, f"stale API pages {stale} — rerun programs/gen_api_docs.py"
