"""Benchmark harness smoke tests (reference: tests/programs/benchmark.cpp).

Checks the CLI produces a complete JSON report for local, R2C, multi-transform and
distributed runs on tiny grids, and that the stick-generation model matches the
reference's (x-slab cutoff, x==0 limited to the hermitian half for R2C).
"""
import importlib.util
import json
import sys
from pathlib import Path

import numpy as np

_spec = importlib.util.spec_from_file_location(
    "benchmark", Path(__file__).resolve().parent.parent / "programs" / "benchmark.py"
)
benchmark = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(benchmark)


def test_stick_model_c2c():
    trips, n_sticks = benchmark.create_benchmark_triplets(8, 8, 8, 0.5, r2c=False)
    # x < ceil(8 * 0.5) = 4, all 8 y values, all 8 z values
    assert n_sticks == 4 * 8
    assert len(trips) == n_sticks * 8
    assert trips[:, 0].max() == 3
    assert set(map(tuple, np.unique(trips[:, :2], axis=0))) == {
        (x, y) for x in range(4) for y in range(8)
    }


def test_stick_model_r2c_x0_half():
    trips, n_sticks = benchmark.create_benchmark_triplets(8, 8, 8, 1.0, r2c=True)
    # dimXFreq = 5; x==0 sticks cover only y < dimYFreq = 5 (hermitian half)
    x0_y = np.unique(trips[trips[:, 0] == 0][:, 1])
    assert list(x0_y) == [0, 1, 2, 3, 4]
    x1_y = np.unique(trips[trips[:, 0] == 1][:, 1])
    assert len(x1_y) == 8
    assert n_sticks == 5 + 4 * 8


def test_split_contiguous_even():
    trips, n_sticks = benchmark.create_benchmark_triplets(4, 4, 4, 1.0, r2c=False)
    parts = benchmark.split_contiguous(trips, n_sticks, 3, 4)
    assert sum(len(p) for p in parts) == len(trips)
    sizes = [len(p) // 4 for p in parts]
    assert max(sizes) - min(sizes) <= 1


def _run_cli(tmp_path, extra):
    out = tmp_path / "report.json"
    argv = ["-d", "8", "8", "8", "-r", "2", "-o", str(out)] + extra
    benchmark.main(argv)
    report = json.loads(out.read_text())
    assert set(report) == {"parameters", "results", "timings"}
    assert report["results"]["wall_s_per_transform_pair"] > 0
    assert report["results"]["gflops_per_pair"] > 0
    assert report["timings"]["sub"], "timing tree must not be empty"
    return report


def test_cli_local_c2c(tmp_path):
    r = _run_cli(tmp_path, ["-p", "cpu", "-s", "0.5"])
    assert r["parameters"]["transform_type"] == "c2c"


def test_cli_local_r2c_multi(tmp_path):
    r = _run_cli(tmp_path, ["-p", "cpu", "-t", "r2c", "-m", "2"])
    assert r["parameters"]["num_transforms"] == 2


def test_cli_distributed(tmp_path):
    r = _run_cli(tmp_path, ["-p", "gpu", "--shards", "4", "-e", "bufferedFloat"])
    assert r["parameters"]["shards"] == 4
    assert r["parameters"]["exchange"] == "bufferedFloat"


def test_cli_pencil2(tmp_path):
    r = _run_cli(tmp_path, ["-p", "gpu", "--mesh2", "2", "2"])
    assert r["parameters"]["mesh2"] == [2, 2]
    assert r["parameters"]["shards"] == 4
    assert r["results"]["exchange_wire_bytes"] > 0


ROOT = Path(__file__).resolve().parent.parent


def test_discipline_compare_cli(tmp_path):
    """programs/discipline_compare.py (the BUFFERED/COMPACT/UNBUFFERED
    bytes+rounds+wall-clock comparison behind BASELINE.md's table) runs and
    emits consistent rows at toy scale."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "disc.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(ROOT)}
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [
            sys.executable, str(ROOT / "programs" / "discipline_compare.py"),
            "--shards", "2", "4", "--dim", "8", "--sparsity", "0.6",
            "--repeats", "2", "--engine", "xla", "--json", str(out),
        ],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    rows = json.loads(out.read_text())["rows"]
    # 2 shard counts x (3 explicit disciplines + the DEFAULT policy A/B row)
    assert len(rows) == 8
    by = {(row["P"], row["discipline"]): row for row in rows}
    for P in (2, 4):
        assert by[(P, "BUFFERED")]["rounds"] == 1
        # chain transport on CPU: P-1 rounds (1 when P-1 == 1)
        assert by[(P, "COMPACT")]["rounds"] == P - 1
        assert by[(P, "UNBUFFERED")]["transport"] == "chain"
        assert (
            by[(P, "UNBUFFERED")]["wire_bytes"]
            <= by[(P, "COMPACT")]["wire_bytes"]
            <= by[(P, "BUFFERED")]["wire_bytes"]
        )
        # the policy row records what DEFAULT resolved to and its provenance
        default = by[(P, "DEFAULT:default")]
        assert default["resolved"] in (
            "BUFFERED", "COMPACT_BUFFERED", "UNBUFFERED",
        )
        assert default["provenance"] == "model"
        for d in ("BUFFERED", "COMPACT", "UNBUFFERED", "DEFAULT:default"):
            assert by[(P, d)]["ms_per_pair"] > 0
