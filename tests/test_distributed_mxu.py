"""Distributed MXU engine (matmul DFT + lane-copy plans) on the CPU mesh.

Same oracle scenarios as test_distributed.py but with engine="mxu" forced, so
the TPU-fast mesh pipeline (parallel/execution_mxu.py) is exercised end to end
on the virtual 8-device mesh: per-shard lax.switch value plans, the stacked-pair
all_to_all exchange, and the lane-major matmul xy stages.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import split_values, assert_close, oracle_backward_c2c, random_sparse_triplets


def make_c2c(num_shards, dims, exchange=ExchangeType.BUFFERED, dtype=None, seed=42):
    rng = np.random.default_rng(seed)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, num_shards, dy)
    vps = split_values(per_shard, triplets, values)
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(num_shards),
        exchange_type=exchange,
        engine="mxu",
        dtype=dtype,
    )
    return t, triplets, values, vps


@pytest.mark.parametrize("num_shards", [2, 4, 8])
def test_mxu_distributed_c2c(num_shards):
    dims = (12, 11, 13)
    t, triplets, values, vps = make_c2c(num_shards, dims)
    expected = oracle_backward_c2c(triplets, values, *dims)
    out = t.backward(vps)
    assert_close(out, expected)
    # run twice (zeroing check, reference: tests/test_util/test_transform.hpp:129-131)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_mxu_distributed_c2c_f32():
    dims = (16, 8, 32)
    t, triplets, values, vps = make_c2c(4, dims, dtype=np.float32)
    expected = oracle_backward_c2c(triplets, values, *dims)
    out = t.backward(vps)
    assert_close(out, expected, dtype=np.float32)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals, dtype=np.float32)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED_FLOAT, ExchangeType.COMPACT_BUFFERED_FLOAT],
)
def test_mxu_float_exchange_f64(exchange):
    """f64 data, f32 wire: accuracy bounded by the wire cast, not the transform."""
    dims = (12, 11, 13)
    t, triplets, values, vps = make_c2c(4, dims, exchange=exchange)
    expected = oracle_backward_c2c(triplets, values, *dims)
    # f32-wire accuracy, judged at the f32 bar
    assert_close(t.backward(vps), expected, dtype=np.float32)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED_BF16, ExchangeType.COMPACT_BUFFERED_BF16],
)
def test_mxu_bf16_wire_exchange(exchange):
    """*_BF16 (TPU extension): f32 data with a bfloat16 wire — the (re, im)
    stacked exchange buffer makes this a pure wire-dtype swap in the MXU engine;
    accuracy judged at the documented ~1e-2 relative bar."""
    dims = (12, 11, 13)
    t, triplets, values, vps = make_c2c(4, dims, exchange=exchange, dtype=np.float32)
    expected = oracle_backward_c2c(triplets, values, *dims)
    out = t.backward(vps)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=3e-2 * scale)
    back = t.forward(scaling=ScalingType.FULL)
    vscale = max(np.abs(values).max(), 1.0)
    for r, vals in enumerate(vps):
        np.testing.assert_allclose(back[r], vals, rtol=0, atol=3e-2 * vscale)


def test_mxu_distributed_r2c():
    rng = np.random.default_rng(5)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, 4, dy)
    vps = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]

    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.R2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        engine="mxu",
    )
    out = t.backward(vps)
    assert out.dtype == np.float64
    assert_close(out, r)
    back = t.forward(scaling=ScalingType.FULL)
    for r_, vals in enumerate(vps):
        assert_close(back[r_], vals)


def test_mxu_switch_branch_dedup():
    """Shards with identical local value layouts share one lax.switch branch
    (compile-size bound = layout diversity, not shard count)."""
    rng = np.random.default_rng(21)
    dx, dy, dz = 8, 8, 8
    # symmetric workload: shard r owns sticks x == r, all y, full z — every
    # shard's LOCAL packed order is identical, so one branch serves all 8
    per_shard = [
        np.stack(
            np.meshgrid([r], np.arange(dy), np.arange(dz), indexing="ij"), -1
        ).reshape(-1, 3)
        for r in range(8)
    ]
    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz,
        [p.copy() for p in per_shard],
        mesh=sp.make_fft_mesh(8), engine="mxu",
    )
    ex = t._exec
    assert len(ex._decompress_branches) == 1
    assert len(ex._compress_branches) == 1
    assert (ex._branch_of_shard == 0).all()
    # correctness through the deduped switch
    vps = [
        rng.standard_normal(len(p)) + 1j * rng.standard_normal(len(p))
        for p in per_shard
    ]
    triplets = np.concatenate(per_shard)
    values = np.concatenate(vps)
    assert_close(t.backward(vps), oracle_backward_c2c(triplets, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)

    # asymmetric layouts still get distinct branches
    t2, *_ = make_c2c(4, (12, 11, 13))
    assert len(t2._exec._decompress_branches) > 1


def test_mxu_ragged_z_split():
    """Non-uniform local_z_lengths exercise the pack/unpack z lane-gathers."""
    rng = np.random.default_rng(3)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 3, dy)
    vps = split_values(per_shard, triplets, values)
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(3),
        local_z_lengths=[5, 2, 3],
        engine="mxu",
    )
    expected = oracle_backward_c2c(triplets, values, *dims)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_mxu_active_x_compaction():
    """Sticks concentrated on few x rows get a small compact extent
    (rectangular matrices) in the distributed MXU engine."""
    rng = np.random.default_rng(17)
    dx, dy, dz = 64, 16, 16
    xs = np.asarray([0, 3, 50])  # 3 active x rows of 64 -> A = 8 after padding
    trip = []
    for x in xs:
        for y in range(dy):
            for z in range(dz):
                trip.append((x, y, z))
    trip = np.asarray(trip, dtype=np.int64)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        engine="mxu",
    )
    assert t._exec._num_x_active == 8  # compact, not the full 64
    expected = oracle_backward_c2c(trip, values, dx, dy, dz)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_mxu_r2c_active_x_compaction():
    """R2C on few active x rows: rectangular c2r/r2c matrix pairs."""
    rng = np.random.default_rng(19)
    dx, dy, dz = 64, 12, 10
    r = rng.standard_normal((dz, dy, dx))
    full = np.fft.fftn(r)
    xs = [0, 2, 9]  # 3 of 33 x-freq rows -> A = 8 after padding
    trip = np.asarray(
        [(x, y, z) for x in xs for y in range(dy) for z in range(dz)], dtype=np.int64
    )
    values = full[trip[:, 2], trip[:, 1], trip[:, 0]]

    # hermitian-closed masked spectrum oracle
    dense = np.zeros((dz, dy, dx), dtype=np.complex128)
    dense[trip[:, 2], trip[:, 1], trip[:, 0]] = values
    dense[(-trip[:, 2]) % dz, (-trip[:, 1]) % dy, (-trip[:, 0]) % dx] = np.conj(values)
    expected = np.fft.ifftn(dense) * (dx * dy * dz)
    assert np.abs(expected.imag).max() < 1e-9

    per_shard = distribute_triplets(trip, 3, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.R2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(3),
        engine="mxu",
    )
    assert t._exec._num_x_active == 8
    assert_close(t.backward(vps), expected.real)
    back = t.forward(scaling=ScalingType.FULL)
    for r_, vals in enumerate(vps):
        assert_close(back[r_], vals)


def test_mxu_centered_indexing():
    """Centered (negative-frequency) triplets on the distributed MXU engine."""
    rng = np.random.default_rng(21)
    dims = (12, 10, 14)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.5, centered=True)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = distribute_triplets(triplets, 4, dy)
    vps = split_values(per_shard, triplets, values)
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        engine="mxu",
    )
    expected = oracle_backward_c2c(triplets, values, *dims)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_mxu_multi_transform_batch():
    """multi_transform over distributed MXU transforms (pipelined dispatch)."""
    from spfft_tpu import multi_transform_backward, multi_transform_forward

    dims = (8, 9, 10)
    t1, trip1, vals1, vps1 = make_c2c(2, dims, seed=1)
    t2, trip2, vals2, vps2 = make_c2c(2, dims, seed=2)
    outs = multi_transform_backward([t1, t2], [vps1, vps2])
    assert_close(outs[0], oracle_backward_c2c(trip1, vals1, *dims))
    assert_close(outs[1], oracle_backward_c2c(trip2, vals2, *dims))
    backs = multi_transform_forward([t1, t2], None, ScalingType.FULL)
    for back, vps in zip(backs, (vps1, vps2)):
        for r, vals in enumerate(vps):
            assert_close(back[r], vals)


def test_mxu_all_sticks_on_one_shard():
    """Edge case from reference tests/mpi_tests/test_transform.cpp:38-127."""
    rng = np.random.default_rng(11)
    dims = (6, 7, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.7)
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    per_shard = [np.asarray(triplets), np.zeros((0, 3), dtype=np.int64)]
    vps = [values, np.zeros(0, dtype=np.complex128)]
    t = DistributedTransform(
        ProcessingUnit.GPU,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(2),
        engine="mxu",
    )
    expected = oracle_backward_c2c(triplets, values, *dims)
    assert_close(t.backward(vps), expected)
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back[0], values)
    assert back[1].size == 0


@pytest.mark.parametrize("ttype", [TransformType.C2C, TransformType.R2C])
def test_mxu_distributed_lane_alignment_rotation_path(ttype):
    """dz=128 engages the per-shard lane-alignment rotations in the mesh
    engine (sharded phase tables threaded through the shard_map): results
    must match the oracle and the roundtrip must close. R2C also covers the
    keep_zero handling of the hermitian (0, 0) stick."""
    from utils import contiguous_stick_triplets

    rng = np.random.default_rng(78)
    dx, dy, dz = 6, 7, 128
    r2c = ttype == TransformType.R2C
    trip = contiguous_stick_triplets(rng, dx, dy, dz, r2c=r2c)
    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        values = (np.fft.fftn(real) / (dx * dy * dz))[trip[:, 2], trip[:, 1], trip[:, 0]]
    else:
        values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.GPU, ttype, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh(4), engine="mxu",
    )
    assert t._exec._align_phase is not None, "rotations must engage at dz=128"
    out = t.backward(vps)
    if r2c:
        ref = DistributedTransform(
            ProcessingUnit.GPU, ttype, dx, dy, dz,
            [p.copy() for p in per_shard], mesh=sp.make_fft_mesh(4), engine="xla",
        )
        assert_close(out, ref.backward([v.copy() for v in vps]))
    else:
        assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


def test_p1_distributed_emits_no_collective():
    """A 1-shard distributed plan must compile to the same compute-only
    program shape as a local plan: the exchange specializes to the identity
    and no all-to-all reaches the HLO (the reference's 1-rank MPI transform
    likewise takes the plain compute path,
    reference: src/spfft/transform_internal.cpp:45-137)."""
    import jax

    dims = (12, 12, 12)
    t, triplets, values, vps = make_c2c(1, dims)
    ex = t._exec
    pair = ex.pad_values(vps)
    hlo = jax.jit(ex._backward_sm).lower(*pair, *ex._phase_args()).compile().as_text()
    assert "all-to-all" not in hlo
    expected = oracle_backward_c2c(triplets, values, *dims)
    out = t.backward(vps)
    assert_close(out, expected)


def test_mxu_distributed_compact_phase_rep(monkeypatch):
    """Forcing the compact phase representation in the 1-D mesh engine must
    reproduce the runtime-operand table path exactly: above the size budget
    the engine embeds only the (P, S) rotation matrix and generates each
    shard's tables in-trace (no phase operands thread the shard_map at all)."""
    from utils import contiguous_stick_triplets

    from spfft_tpu.ops import lanecopy

    rng = np.random.default_rng(81)
    dx, dy, dz = 6, 7, 128
    trip = contiguous_stick_triplets(rng, dx, dy, dz, r2c=False)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    t_table = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz, per_shard,
        mesh=sp.make_fft_mesh(4), engine="mxu",
    )
    assert t_table._exec._align_rep is not None
    assert t_table._exec._align_rep[0] == "table"
    assert t_table._exec._align_phase is not None  # staged runtime operands
    out_table = t_table.backward(vps)

    monkeypatch.setenv(lanecopy.PHASE_TABLE_LIMIT_MB_ENV, "0")
    t_delta = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz,
        [p.copy() for p in per_shard], mesh=sp.make_fft_mesh(4), engine="mxu",
    )
    assert t_delta._exec._align_rep is not None
    assert t_delta._exec._align_rep[0] == "delta"
    assert t_delta._exec._align_phase is None  # no phase operands threaded
    out_delta = t_delta.backward([v.copy() for v in vps])
    assert_close(out_delta, out_table)
    back = t_delta.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED],
)
def test_mxu_distributed_sparse_y(monkeypatch, exchange):
    """The distributed sparse-y stage (global per-slot y contraction; the
    plane slot space shrinks to the (A, Sy) table for every exchange
    discipline) must agree with the dense oracle and close the roundtrip.
    Forced on via SPFFT_TPU_SPARSE_Y=1 so the small test dims engage it."""
    import spfft_tpu as sp2

    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y", "1")
    rng = np.random.default_rng(82)
    dx, dy, dz = 12, 32, 16
    # sharp y-occupancy: few y values per x-slot
    trips = []
    for x in range(dx):
        for y in range(x % 3, dy, 5):
            trips.extend((x, y, z) for z in range(dz))
    trip = np.asarray(trips)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz, per_shard,
        mesh=sp2.make_fft_mesh(4), engine="mxu", exchange_type=exchange,
    )
    assert t._exec._sparse_y, "sparse-y must engage on this plan"
    out = t.backward(vps)
    assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED],
)
def test_mxu_distributed_sparse_y_blocked(monkeypatch, exchange):
    """The distributed blocked sparse-y stage (per-bucket y contractions over
    the EXACT global stick set; the bucket flats become the plane slot space
    every exchange discipline ships) must agree with the dense oracle and
    close the roundtrip. Forced bucket count so the small dims engage it;
    headline-class density keeps the per-slot stage off (Sy/Y > 0.6)."""
    import spfft_tpu as sp2

    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "3")
    rng = np.random.default_rng(93)
    dx = dy = dz = 32
    trip = sp2.create_spherical_cutoff_triplets(dx, dy, dz, 0.659)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz, per_shard,
        mesh=sp2.make_fft_mesh(4), engine="mxu", exchange_type=exchange,
    )
    assert not t._exec._sparse_y
    assert t._exec._sparse_y_blocked is not None, "blocked must engage"
    assert len(t._exec._sparse_y_blocked) == 3
    # the plane slot space the exchanges ship IS the (smaller) bucket flats
    assert t._exec._plane_slots < t._exec._num_x_active * dy
    out = t.backward(vps)
    assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    for r, vals in enumerate(vps):
        assert_close(back[r], vals)


@pytest.mark.parametrize(
    "exchange",
    [ExchangeType.BUFFERED, ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED],
)
def test_mxu_distributed_sparse_y_blocked_r2c(monkeypatch, exchange):
    """R2C blocked sparse-y under SPMD (round 5, VERDICT r4 item 3): the
    x == 0 plane rides as a trailing dense bucket in the bucket flats (which
    every exchange discipline ships), and its hermitian fill runs shard-local
    post-exchange. Checked against the hermitian-extension oracle across all
    three disciplines."""
    import spfft_tpu as sp2

    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "3")
    rng = np.random.default_rng(94)
    dx, dy, dz = 16, 32, 32
    r = rng.standard_normal((dz, dy, dx))
    full = np.fft.fftn(r)
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, hermitian=True)
    hx = dx // 2
    stick_set = {(int(a), int(b) % dy) for a, b in trip[:, :2]}
    trip = trip[[
        i for i, tt in enumerate(trip)
        if tt[0] != hx or (hx, (-int(tt[1])) % dy) in stick_set
    ]]
    # keep the active-x set strictly below the full half extent (the SPMD
    # engine's blocked gate needs A < Xf; at the full extent the slot
    # permutation buys nothing)
    trip = trip[trip[:, 0] != 3]
    assert (trip[:, 0] == 0).any()
    xs, ys, zs = trip[:, 0], trip[:, 1] % dy, trip[:, 2] % dz
    values = full[zs, ys, xs]
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)

    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.R2C, dx, dy, dz, per_shard,
        mesh=sp2.make_fft_mesh(4), engine="mxu", exchange_type=exchange,
    )
    blk = t._exec._sparse_y_blocked
    assert blk is not None, "R2C blocked must engage when forced"
    assert t._exec._sy_x0_bucket == len(blk) - 1

    dense = np.zeros((dz, dy, dx), dtype=np.complex128)
    dense[zs, ys, xs] = values
    dense[(-zs) % dz, (-ys) % dy, (-xs) % dx] = np.conj(values)
    expected = np.fft.ifftn(dense) * (dx * dy * dz)
    assert np.abs(expected.imag).max() < 1e-9
    out = t.backward(vps)
    assert_close(np.asarray(out), expected.real)
    back = t.forward(scaling=ScalingType.FULL)
    for rr, vals in enumerate(vps):
        assert_close(back[rr], vals)
