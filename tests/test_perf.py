"""spfft_tpu.obs.perf: schema, stage attribution, dbench CLI, regression gate.

Runs entirely on the conftest's virtual 8-device CPU mesh — the perf layer's
acceptance surface (ISSUE 6): 8-device slab AND pencil runs emit validating
``spfft_tpu.obs.perf/1`` reports whose stage seconds sum to the measured
wall time and whose exchange bytes match the plan geometry, and the
regression gate trips on a doctored baseline.
"""
import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    Transform,
    TransformType,
    obs,
)
from spfft_tpu.obs import perf

PROGRAMS = Path(__file__).resolve().parent.parent / "programs"


def load_program(name):
    spec = importlib.util.spec_from_file_location(name, PROGRAMS / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def clean_registries():
    obs.clear()
    yield
    obs.clear()
    obs.trace.disable()
    obs.trace.clear()


def small_triplets(dim=8, fraction=0.9, r2c=False):
    radius = sp.spherical_radius_for_fraction(fraction)
    return sp.create_spherical_cutoff_triplets(
        dim, dim, dim, min(radius, 1.0), hermitian_symmetry=r2c
    )


def measured_report(t, **kw):
    m = perf.measure_pair_seconds(t, chain=kw.pop("chain", 2), repeats=2)
    return perf.perf_report(t, m["seconds_per_pair"], repeats=2), m


# ---- report schema + attribution invariants ---------------------------------


def test_local_report_validates_and_sums():
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=small_triplets(), dtype=np.float32,
    )
    report, measured = measured_report(t)
    assert perf.validate_perf_report(report) == []
    assert report["kind"] == "local"
    assert report["device_count"] == 1
    assert report["mesh"] is None
    assert report["exchange_fraction"] == 0.0
    assert report["wire_bytes_per_pair"] == 0
    total = sum(row["seconds"] for row in report["stages"])
    assert total == pytest.approx(report["seconds_per_pair"], rel=1e-9)
    assert measured["roundtrip_residual"] < 1e-2
    assert len(measured["rep_seconds"]) == 2
    # the report joins the plan card on the run ID
    assert report["run_id"] == t.report()["run_id"]


@pytest.mark.parametrize("mesh_kind", ["slab", "pencil"])
def test_8device_report_validates(mesh_kind):
    trip = small_triplets()
    mesh = sp.make_fft_mesh(8) if mesh_kind == "slab" else sp.make_fft_mesh2(2, 4)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, trip,
        mesh=mesh, dtype=np.float32, engine="xla",
    )
    report, measured = measured_report(t)
    assert perf.validate_perf_report(report) == []
    assert report["device_count"] == 8
    assert report["decomposition"] == ("slab" if mesh_kind == "slab" else "pencil2")
    # stage seconds sum ~= wall time (attribution is exact by construction)
    total = sum(row["seconds"] for row in report["stages"])
    assert total == pytest.approx(report["seconds_per_pair"], rel=1e-9)
    # exchange bytes match the plan geometry (one pair = fwd + bwd)
    assert report["wire_bytes_per_pair"] == 2 * t.exchange_wire_bytes()
    stage_wire = sum(
        row["bytes"]
        for row in report["stages"]
        if row["stage"] in perf.EXCHANGE_STAGES
    )
    assert stage_wire == report["wire_bytes_per_pair"]
    assert 0.0 < report["exchange_fraction"] < 1.0
    assert measured["roundtrip_residual"] < 1e-2
    # every attributed stage is canonical
    for row in report["stages"]:
        assert row["stage"] in obs.STAGES
    if mesh_kind == "pencil":
        names = {row["stage"] for row in report["stages"]}
        assert {"exchange A", "exchange B"} <= names


def test_r2c_and_sparse_variants_stay_canonical():
    trip = small_triplets(r2c=True)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.R2C, 8, 8, 8, trip,
        mesh=sp.make_fft_mesh(4), dtype=np.float32, engine="xla",
    )
    report, measured = measured_report(t)
    assert perf.validate_perf_report(report) == []
    names = {row["stage"] for row in report["stages"]}
    assert "plane symmetry" in names
    assert measured["roundtrip_residual"] is None  # R2C roundtrip projects
    # sparse-y MXU local variant carries its disambiguated label
    tm = Transform(
        ProcessingUnit.GPU, TransformType.C2C, 8, 8, 8,
        indices=small_triplets(fraction=0.3), dtype=np.float32, engine="mxu",
    )
    rows = perf.stage_model(tm)
    y_rows = [r for r in rows if r["stage"].startswith("y transform")]
    assert len(y_rows) == 1
    assert y_rows[0]["stage"] == tm._exec._y_stage_scope()


def test_batched_report_scales_models_and_stamps_attribution():
    """``perf_report(..., batch=B)`` attributes one B-batched execution:
    every stage model and the dense-flops/wire-bytes baselines scale by B,
    ``attribution["batch"]`` records the extent, and the schema still
    validates (batch is validation-optional, the ``overlap_chunks``
    precedent)."""
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=small_triplets(), dtype=np.float32,
    )
    base = perf.perf_report(t, 0.01)
    batched = perf.perf_report(t, 0.04, batch=4)
    assert perf.validate_perf_report(batched) == []
    assert batched["attribution"]["batch"] == 4
    assert "batch" not in base["attribution"] or \
        base["attribution"]["batch"] == 1
    for b_row, row in zip(batched["stages"], base["stages"]):
        assert b_row["stage"] == row["stage"]
        assert b_row["flops"] == 4 * row["flops"]
        assert b_row["bytes"] == 4 * row["bytes"]
    assert batched["dense_flops_per_pair"] == 4 * base["dense_flops_per_pair"]
    # B transforms in 4x the wall time: per-transform GFLOP/s is unchanged
    assert batched["gflops"] == pytest.approx(base["gflops"])
    # stage seconds still sum to the measured wall time
    total = sum(row["seconds"] for row in batched["stages"])
    assert total == pytest.approx(batched["seconds_per_pair"], rel=1e-9)


def test_batched_report_invalid_extent_typed():
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        indices=small_triplets(), dtype=np.float32,
    )
    for bad in (0, -3):
        with pytest.raises(sp.InvalidParameterError, match="batch"):
            perf.perf_report(t, 0.01, batch=bad)


def test_modeled_stages_are_the_engine_subset():
    assert set(perf.MODELED_STAGES) <= set(obs.STAGES)
    assert set(obs.STAGES) - set(perf.MODELED_STAGES) == {
        "tune warmup",
        "tune trial",
    }


def test_report_feeds_registry_and_trace():
    obs.trace.enable(capacity=256)
    try:
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
            indices=small_triplets(), dtype=np.float32,
        )
        report, _ = measured_report(t)
        snap = obs.snapshot()
        assert any(
            k.startswith("perf_pair_seconds") for k in snap["histograms"]
        )
        assert any(
            k.startswith("perf_stage_seconds") for k in snap["histograms"]
        )
        assert any(k.startswith("perf_gflops") for k in snap["gauges"])
        events = [
            e for e in obs.trace.snapshot()["events"] if e["name"] == "perf"
        ]
        assert events and events[-1]["run"] == report["run_id"]
    finally:
        obs.trace.disable()


def test_attribution_balance_env_knob(monkeypatch):
    monkeypatch.setenv(perf.FLOP_PER_BYTE_ENV, "0")
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, small_triplets(),
        mesh=sp.make_fft_mesh(2), dtype=np.float32, engine="xla",
    )
    # balance 0: byte-only stages get zero weight -> zero attributed time
    report = perf.perf_report(t, 1e-3)
    assert report["attribution"]["flop_per_byte"] == 0.0
    assert report["exchange_fraction"] == 0.0
    monkeypatch.setenv(perf.FLOP_PER_BYTE_ENV, "1e9")
    report = perf.perf_report(t, 1e-3)
    # balance huge: movement dominates, exchange fraction becomes visible
    assert report["exchange_fraction"] > 0.0


# ---- dbench CLI --------------------------------------------------------------


def test_dbench_cli_writes_validating_scaling_doc(tmp_path):
    dbench = load_program("dbench")
    out = tmp_path / "scaling.json"
    rc = dbench.main([
        "--devices", "2", "--dim", "8", "--sparsity", "0.9",
        "--mesh", "slab", "--scaling", "strong", "--repeats", "1",
        "--chain", "2", "--engine", "xla", "--cpu", "-o", str(out),
    ])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert perf.validate_scaling_doc(doc) == []
    (row,) = doc["rows"]
    assert row["scaling"] == "strong"
    assert row["device_count"] == 2
    assert row["key"].startswith("strong:slab:P2:8x8x8:C2C:")
    assert row["seconds_noise"] >= 0.0


# ---- regression gate ---------------------------------------------------------


def _doc(rows):
    return {"schema": perf.SCALING_SCHEMA, "config": {}, "rows": rows}


def _row(key, gflops, noise=0.0):
    return {"key": key, "gflops": gflops, "seconds_noise": noise}


def test_perf_gate_trips_on_doctored_baseline(tmp_path, capsys):
    gate = load_program("perf_gate")
    cur = tmp_path / "cur.json"
    base = tmp_path / "base.json"
    cur.write_text(json.dumps(_doc([_row("a", 1.0), _row("b", 2.0)])))
    base.write_text(json.dumps(_doc([_row("a", 1.0), _row("b", 2.0)])))
    assert gate.main([str(cur), str(base)]) == 0
    # doctored baseline: the past claims 10x the throughput -> exit 3
    base.write_text(json.dumps(_doc([_row("a", 10.0), _row("b", 20.0)])))
    assert gate.main([str(cur), str(base)]) == 3
    assert "REGRESSION" in capsys.readouterr().out


def test_perf_gate_noise_widens_but_caps(tmp_path):
    gate = load_program("perf_gate")
    cur = tmp_path / "cur.json"
    base = tmp_path / "base.json"
    # 40% slower but the rows were measured 50% noisy: allowance widens, ok
    cur.write_text(json.dumps(_doc([_row("a", 0.6, noise=0.25)])))
    base.write_text(json.dumps(_doc([_row("a", 1.0, noise=0.25)])))
    assert gate.main([str(cur), str(base), "--tolerance", "0.1"]) == 0
    # noise cannot unbound the gate: even absurd recorded spread is capped,
    # so a 10x slide still trips
    cur.write_text(json.dumps(_doc([_row("a", 0.1, noise=5.0)])))
    base.write_text(json.dumps(_doc([_row("a", 1.0, noise=5.0)])))
    assert gate.main([str(cur), str(base), "--tolerance", "0.1"]) == 3


def test_perf_gate_guards_empty_intersection(tmp_path):
    gate = load_program("perf_gate")
    cur = tmp_path / "cur.json"
    base = tmp_path / "base.json"
    cur.write_text(json.dumps(_doc([_row("a", 1.0)])))
    base.write_text(json.dumps(_doc([_row("zzz", 1.0)])))
    # zero matched rows must not pass vacuously
    assert gate.main([str(cur), str(base)]) == 1
