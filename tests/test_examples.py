"""The shipped Python examples must run — the reference builds its examples in
CI (reference: examples/ + CMake example targets), so a bit-rotted example is
a test failure here, not a user's first impression."""
import os
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
EXAMPLES = [
    ROOT / "examples" / "example.py",
    ROOT / "examples" / "example_distributed.py",
    ROOT / "examples" / "poisson.py",
]


@pytest.mark.parametrize("script", EXAMPLES, ids=lambda p: p.name)
def test_python_example_runs(script):
    # force the portable CPU backend: the dev environment pins an accelerator
    # platform via env that a fresh subprocess may not be able to initialize.
    # PYTHONPATH points at the checkout: examples import spfft_tpu like an
    # installed package (no sys.path editing inside them; pip install . is the
    # real flow, exercised by test_packaging.py).
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(ROOT)}
    result = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert result.returncode == 0, result.stderr[-2000:]
