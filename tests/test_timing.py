"""Timing subsystem tests.

The reference has no tests for rt_graph; these cover the rebuilt tree semantics
(nesting, statistics, JSON schema) plus the integration hook points in Transform
(the "backward"/"forward"/"Execution init" scopes the reference tags in
src/spfft/transform_internal.cpp:153,255 and src/execution/execution_host.cpp:56).
"""
import json
import time

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import timing
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.timing import Timer


@pytest.fixture(autouse=True)
def _reset_global_timer():
    timing.disable()
    timing.clear()
    yield
    timing.disable()
    timing.clear()


def test_nested_tree_structure():
    t = Timer()
    with t.scoped("outer"):
        with t.scoped("inner"):
            pass
        with t.scoped("inner"):
            pass
        with t.scoped("other"):
            pass
    with t.scoped("outer"):
        pass
    res = t.process()
    assert [s.label for s in res.sub] == ["outer"]
    outer = res.sub[0]
    assert outer.count == 2
    assert [s.label for s in outer.sub] == ["inner", "other"]
    assert outer.sub[0].count == 2
    assert outer.sub[1].count == 1


def test_statistics():
    t = Timer()
    node = t._root.child("x")
    node.timings = [1.0, 2.0, 3.0, 4.0]
    res = t.process().sub[0]
    assert res.count == 4
    assert res.total == pytest.approx(10.0)
    assert res.mean == pytest.approx(2.5)
    assert res.median == pytest.approx(2.5)
    assert res.min == 1.0 and res.max == 4.0
    assert res.lower_quartile == pytest.approx(1.75)
    assert res.upper_quartile == pytest.approx(3.25)
    assert res.percentage == pytest.approx(100.0)


def test_parent_percentage():
    t = Timer()
    parent = t._root.child("p")
    parent.timings = [10.0]
    child = parent.child("c")
    child.timings = [4.0]
    res = t.process()
    assert res.sub[0].sub[0].parent_percentage == pytest.approx(40.0)


def test_mismatched_stop_raises():
    t = Timer()
    t.start("a")
    with pytest.raises(InvalidParameterError):
        t.stop("b")
    t.stop("a")
    with pytest.raises(InvalidParameterError):
        t.stop("a")


def test_timing_measures_wall_clock():
    t = Timer()
    with t.scoped("sleep"):
        time.sleep(0.01)
    res = t.process().sub[0]
    assert res.total >= 0.009


def test_json_roundtrip():
    t = Timer()
    with t.scoped("a"):
        with t.scoped("b"):
            pass
    data = json.loads(t.process().json())
    assert data["sub"][0]["label"] == "a"
    assert data["sub"][0]["sub"][0]["label"] == "b"
    for key in (
        "count", "total", "mean", "median", "min", "max",
        "lower_quartile", "upper_quartile", "percentage", "parent_percentage",
    ):
        assert key in data["sub"][0]


def test_global_disabled_is_noop():
    assert not timing.is_enabled()
    with timing.scoped("ignored"):
        pass
    assert timing.process().sub == []


def test_transform_hooks():
    timing.enable()
    dim = 8
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, 1.0)
    t = sp.Transform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, dim, dim, dim, indices=triplets
    )
    rng = np.random.default_rng(0)
    vals = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    space = t.backward(vals)
    t.forward(space, sp.ScalingType.FULL)

    res = timing.process()
    labels = [s.label for s in res.sub]
    assert "Execution init" in labels
    assert "backward" in labels
    assert "forward" in labels
    bwd = res.find("backward")
    sub_labels = [s.label for s in bwd.sub]
    assert "input staging" in sub_labels
    assert "dispatch" in sub_labels
    assert "wait" in sub_labels
    # Printable without raising.
    assert "backward" in str(res)


def test_distributed_hooks():
    timing.enable()
    dim = 8
    mesh = sp.make_fft_mesh(4)
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.9)
    t = sp.DistributedTransform(
        sp.ProcessingUnit.GPU, sp.TransformType.C2C, dim, dim, dim, triplets, mesh=mesh
    )
    rng = np.random.default_rng(1)
    values = [
        rng.standard_normal(t.num_local_elements(r))
        + 1j * rng.standard_normal(t.num_local_elements(r))
        for r in range(t.num_shards)
    ]
    space = t.backward(values)
    t.forward(space, sp.ScalingType.FULL)
    res = timing.process()
    assert res.find("backward") is not None
    assert res.find("forward") is not None
